"""Network boundary specs: the TCP front end (Alfred analog) + network
driver — the same e2e flows as the local driver, but over real sockets,
including one test with the server in a SEPARATE PROCESS.

Ref: alfred socket contract (lambdas/src/alfred/index.ts:112-405),
routerlicious-driver documentService.ts, io.spec.ts service tests.
"""

from __future__ import annotations

import contextlib
import subprocess
import sys
import time

import pytest

from fluidframework_tpu.driver import NetworkDocumentServiceFactory
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.service import LocalServer, NetworkFrontEnd


def wait_for(pred, timeout=10.0, interval=0.005):
    t0 = time.time()
    while time.time() - t0 < timeout:
        try:
            if pred():
                return True
        except (KeyError, IndexError):
            pass
        time.sleep(interval)
    return False


@contextlib.contextmanager
def front_end_process():
    """A front end in a separate OS process; yields its port."""
    proc = subprocess.Popen(
        [sys.executable, "-m", "fluidframework_tpu.service.front_end",
         "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd="/root/repo",
    )
    try:
        line = proc.stdout.readline().strip()
        assert line.startswith("LISTENING"), line
        yield int(line.rsplit(":", 1)[1])
    finally:
        proc.terminate()
        proc.wait(timeout=10)


@pytest.fixture
def front_end():
    fe = NetworkFrontEnd(LocalServer()).start_background()
    yield fe
    fe.stop()


@pytest.fixture
def loader(front_end):
    return Loader(NetworkDocumentServiceFactory("127.0.0.1", front_end.port))


def test_two_clients_converge_over_sockets(loader):
    c1 = loader.resolve("t", "doc")
    c2 = loader.resolve("t", "doc")
    s1 = c1.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    s1.insert_text(0, "hello network")
    assert wait_for(lambda: c2.runtime.get_data_store("default")
                    .get_channel("text").get_text() == "hello network")
    s2 = c2.runtime.get_data_store("default").get_channel("text")
    s2.insert_text(5, " there")
    s1.remove_text(0, 1)
    assert wait_for(lambda: s1.get_text() == s2.get_text()
                    and len(s1.get_text()) == 18)
    assert s1.get_text() == "ello there network"


def test_late_joiner_backfills_over_network(loader):
    c1 = loader.resolve("t", "doc")
    s1 = c1.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    for i in range(10):
        s1.insert_text(len(s1.get_text()), f"{i}")
    assert wait_for(lambda: s1.get_text() == "0123456789")
    # late joiner must catch up through the delta-backfill endpoint
    c2 = loader.resolve("t", "doc")
    assert wait_for(lambda: c2.runtime.get_data_store("default")
                    .get_channel("text").get_text() == "0123456789")


def test_signals_relay_unsequenced(loader):
    c1 = loader.resolve("t", "doc")
    c2 = loader.resolve("t", "doc")
    got = []
    c1.on_signal = got.append
    c2.submit_signal({"cursor": 7})
    assert wait_for(lambda: len(got) == 1)
    assert got[0].content == {"cursor": 7}
    assert got[0].client_id == c2.client_id


def test_map_and_counter_over_network(loader):
    c1 = loader.resolve("t", "doc")
    c2 = loader.resolve("t", "doc")
    ds1 = c1.runtime.create_data_store("default")
    m1 = ds1.create_channel("kv", "shared-map")
    k1 = ds1.create_channel("n", "shared-counter")
    m1.set("key", {"nested": [1, 2]})
    k1.increment(5)
    assert wait_for(lambda: c2.runtime.get_data_store("default")
                    .get_channel("kv").get("key") == {"nested": [1, 2]})
    assert wait_for(lambda: c2.runtime.get_data_store("default")
                    .get_channel("n").value == 5)


def test_oversized_message_nacked_not_sequenced(front_end, loader):
    c1 = loader.resolve("t", "doc")
    c2 = loader.resolve("t", "doc")
    s1 = c1.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    s1.insert_text(0, "ok")
    assert wait_for(lambda: c2.runtime.get_data_store("default")
                    .get_channel("text").get_text() == "ok")
    nacks = []
    c1.on_nack = nacks.append
    s1.insert_text(0, "X" * (front_end.max_message_size + 1))
    assert wait_for(lambda: len(nacks) == 1)
    assert nacks[0].code == 413
    # the oversized op never reached the sequencer
    s2 = c2.runtime.get_data_store("default").get_channel("text")
    time.sleep(0.1)
    assert s2.get_text() == "ok"


def test_summary_pipeline_over_network(loader):
    from fluidframework_tpu.runtime.summarizer import SummaryManager

    c1 = loader.resolve("t", "doc")
    sm = SummaryManager(c1, max_ops=3)
    s = c1.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    s.insert_text(0, "abcdef")
    s.remove_text(0, 2)
    assert wait_for(lambda: sm.summaries_acked >= 1)
    # fresh client boots from the network-uploaded summary + tail
    c2 = loader.resolve("t", "doc")
    assert c2._base_snapshot is not None
    assert wait_for(lambda: c2.runtime.get_data_store("default")
                    .get_channel("text").get_text() == "cdef")


def test_reconnect_rebase_over_network(loader):
    c1 = loader.resolve("t", "doc")
    c2 = loader.resolve("t", "doc")
    s1 = c1.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    s1.insert_text(0, "base")
    assert wait_for(lambda: c2.runtime.get_data_store("default")
                    .get_channel("text").get_text() == "base")
    s2 = c2.runtime.get_data_store("default").get_channel("text")
    c1.disconnect()
    s1.insert_text(0, "X")  # offline edit
    s2.insert_text(4, "Y")  # concurrent remote edit
    assert wait_for(lambda: s2.get_text() == "baseY")
    c1.reconnect()
    assert wait_for(lambda: s1.get_text() == s2.get_text() == "XbaseY")


def test_cross_process_server():
    """The real thing: server in a separate OS process, clients in this
    one, talking TCP (VERDICT r1 next-round #1 'separate processes')."""
    with front_end_process() as port:
        loader = Loader(NetworkDocumentServiceFactory("127.0.0.1", port))
        c1 = loader.resolve("t", "xdoc")
        c2 = loader.resolve("t", "xdoc")
        s1 = c1.runtime.create_data_store("default").create_channel(
            "text", "shared-string")
        s1.insert_text(0, "cross process!")
        assert wait_for(lambda: c2.runtime.get_data_store("default")
                        .get_channel("text").get_text() == "cross process!")
        s2 = c2.runtime.get_data_store("default").get_channel("text")
        s2.annotate_range(0, 5, {"bold": True})
        s2.insert_text(0, ">> ")
        assert wait_for(lambda: s1.get_text() == s2.get_text()
                        == ">> cross process!")


def test_full_dds_catalog_over_the_wire():
    """Breadth over the real socket stack: matrix, directory, counter,
    consensus queue, and undo-redo all converge across two network
    clients against a front-end process."""
    with front_end_process() as port:
        loader = Loader(NetworkDocumentServiceFactory("127.0.0.1", port))
        c1 = loader.resolve("t", "catalog")
        c2 = loader.resolve("t", "catalog")
        ds1 = c1.runtime.create_data_store("default")

        matrix = ds1.create_channel("grid", "shared-matrix")
        matrix.insert_rows(0, 2)
        matrix.insert_cols(0, 2)
        matrix.set_cell(0, 0, "a")
        matrix.set_cell(1, 1, "d")

        directory = ds1.create_channel("dir", "shared-directory")
        directory.create_subdirectory("settings").set("theme", "dark")

        counter = ds1.create_channel("clicks", "shared-counter")
        counter.increment(5)

        queue = ds1.create_channel("work", "consensus-queue")
        queue.add({"job": 1})

        def synced():
            ds2 = c2.runtime.data_stores.get("default")
            return ds2 and all(
                ch in ds2.channels
                for ch in ("grid", "dir", "clicks", "work"))
        assert wait_for(synced)
        ds2 = c2.runtime.get_data_store("default")
        assert wait_for(lambda: ds2.get_channel("grid")
                        .get_cell(1, 1) == "d")
        assert ds2.get_channel("grid").get_cell(0, 0) == "a"
        assert wait_for(lambda: ds2.get_channel("dir")
                        .get_subdirectory("settings") is not None)
        assert ds2.get_channel("dir").get_subdirectory("settings") \
            .get("theme") == "dark"
        assert wait_for(lambda: ds2.get_channel("clicks").value == 5)
        ds2.get_channel("clicks").increment(-2)
        assert wait_for(lambda: ds1.get_channel("clicks").value == 3)

        # consensus queue: exactly-once across the wire
        q2 = ds2.get_channel("work")
        assert wait_for(lambda: len(q2) == 1)
        item = q2.acquire()
        assert item is not None
        q2.complete(item)
        assert wait_for(lambda: len(ds1.get_channel("work")) == 0)


def test_json_and_binary_clients_interoperate(front_end):
    """A legacy JSON client and a binwire client share a doc: the front
    end keeps per-protocol broadcast caches and both converge (the JSON
    wire format stays frozen — tests/golden pins it)."""
    lb = Loader(NetworkDocumentServiceFactory("127.0.0.1", front_end.port,
                                              binary=True))
    lj = Loader(NetworkDocumentServiceFactory("127.0.0.1", front_end.port,
                                              binary=False))
    cb = lb.resolve("t", "mixdoc")
    cj = lj.resolve("t", "mixdoc")
    sb = cb.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    sb.insert_text(0, "from-binary")
    assert wait_for(lambda: cj.runtime.get_data_store("default")
                    .get_channel("text").get_text() == "from-binary")
    sj = cj.runtime.get_data_store("default").get_channel("text")
    sj.insert_text(0, "json:")
    assert wait_for(lambda: sb.get_text() == "json:from-binary"
                    and sj.get_text() == "json:from-binary")


def test_unpackable_message_falls_back_to_json_broadcast(front_end):
    """An op binwire cannot pack (refSeq beyond the i32 fixed field) must
    not break the broadcast: the front end falls back to a JSON ops
    frame for that batch, which binary clients also dispatch."""
    from fluidframework_tpu.protocol.messages import (
        DocumentMessage,
        MessageType,
    )

    factory = NetworkDocumentServiceFactory("127.0.0.1", front_end.port,
                                            binary=True)
    conn = factory.create_document_service(
        "t", "odd").connect_to_delta_stream()
    got = []
    conn.on_op = got.append
    big_ref = 2 ** 40  # valid per protocol (>= msn), outside binwire i32
    conn.submit([DocumentMessage(
        client_sequence_number=1, reference_sequence_number=big_ref,
        type=MessageType.OPERATION, contents={"free": "form"})])
    assert wait_for(lambda: any(
        m.client_id == conn.client_id
        and m.reference_sequence_number == big_ref for m in got))
    conn.close()


def test_snapshot_cache_second_boot_issues_no_storage_rpcs(front_end):
    """The odsp-driver lesson (odspCache.ts): re-booting an unchanged
    doc must serve version+tree from the driver cache — zero storage
    round trips — and a committed summary invalidates the entry."""
    from fluidframework_tpu.runtime.summarizer import SummaryManager

    factory = NetworkDocumentServiceFactory("127.0.0.1", front_end.port)
    loader = Loader(factory)
    c1 = loader.resolve("t", "cachedoc")
    sm = SummaryManager(c1, max_ops=10**9)
    s = c1.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    s.insert_text(0, "cache me")
    assert wait_for(lambda: c1.runtime.pending.count == 0)
    sm.summarize_now()
    assert wait_for(lambda: sm.summaries_acked == 1)

    # first boot after the summary: fetches and populates the cache
    c2 = loader.resolve("t", "cachedoc")
    assert c2._base_snapshot is not None
    assert c2.storage.rpcs > 0
    assert wait_for(lambda: c2.runtime.get_data_store("default")
                    .get_channel("text").get_text() == "cache me")

    # second boot: served ENTIRELY from the cache
    c3 = loader.resolve("t", "cachedoc")
    assert c3._base_snapshot is not None
    assert c3.storage.rpcs == 0, "cached boot issued storage RPCs"
    assert wait_for(lambda: c3.runtime.get_data_store("default")
                    .get_channel("text").get_text() == "cache me")

    # a newer summary invalidates: the NEXT boot fetches the new head
    s.insert_text(0, "fresh ")
    assert wait_for(lambda: c1.runtime.pending.count == 0)
    sm.summarize_now()
    assert wait_for(lambda: sm.summaries_acked == 2)
    assert wait_for(
        lambda: factory.snapshot_cache.stats["invalidations"] >= 1)
    c4 = loader.resolve("t", "cachedoc")
    assert c4.storage.rpcs > 0  # refetched the newer version
    assert wait_for(lambda: c4.runtime.get_data_store("default")
                    .get_channel("text").get_text() == "fresh cache me")


def test_idle_connection_survives_recv_timeout_windows(front_end):
    """A silent server is NOT a dead server: with a short recv timeout,
    an idle client's reader must ride through several timeout windows
    (probing with pings) and still deliver a push that arrives much
    later. Regression: the reader thread used to treat the recv timeout
    as EOF and die silently after 30 s of server silence — after which
    summary acks/ops pushed by the server were ignored forever (the
    round-4 full-composition failure mode)."""
    loader = Loader(NetworkDocumentServiceFactory(
        "127.0.0.1", front_end.port, timeout=1.0))
    c1 = loader.resolve("t", "idledoc")
    s1 = c1.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    s1.insert_text(0, "x")
    assert wait_for(lambda: c1.runtime.pending.count == 0)
    # idle well past 2 recv-timeout windows (the escalation budget)
    time.sleep(3.5)
    # a second client edits; the idle client must still receive it
    c2 = Loader(NetworkDocumentServiceFactory(
        "127.0.0.1", front_end.port)).resolve("t", "idledoc")
    s2 = c2.runtime.get_data_store("default").get_channel("text")
    s2.insert_text(1, "y")
    assert wait_for(lambda: s1.get_text() == "xy", timeout=20.0), \
        f"idle client missed the push: {s1.get_text()!r}"


def test_vanished_server_detected_by_ping_escalation():
    """A VANISHED peer (SIGSTOPped server: TCP keeps ACKing, no FIN
    ever) must be detected: unanswered ping probes over consecutive
    idle windows end the reader and fire on_disconnect, which is what
    lets auto-reconnect/sharded failover take over."""
    import signal as _signal

    from fluidframework_tpu.driver.network import _Transport

    proc = subprocess.Popen(
        [sys.executable, "-m", "fluidframework_tpu.service.front_end",
         "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd="/root/repo")
    try:
        line = proc.stdout.readline().strip()
        port = int(line.rsplit(":", 1)[1])
        t = _Transport("127.0.0.1", port, timeout=1.0)
        reasons = []
        t.on_disconnect = reasons.append
        # prove liveness first: a ping gets answered while running
        t.send({"t": "ping"})
        time.sleep(1.0)
        assert not reasons
        proc.send_signal(_signal.SIGSTOP)
        try:
            # ~2 idle windows + margin: reader must give up and report
            assert wait_for(lambda: reasons, timeout=15.0), \
                "vanished server never detected"
        finally:
            proc.send_signal(_signal.SIGCONT)
        t.close()
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def test_close_wakes_requester_even_with_reader_parked():
    """close() must wake a blocked requester DIRECTLY (under the
    pending cv), not by relying on the reader thread's exit path: here
    the reader is parked inside a push handler, so only the cv notify
    in close() can deliver the wakeup. Found by fluidlint's
    BLOCKING-ON-LOOP triage of request_rid (@blocking)."""
    import json as _json
    import socket as _socket
    import threading as _threading

    from fluidframework_tpu.driver.network import _Transport

    srv = _socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    def serve():
        conn, _ = srv.accept()
        # wait for the client's ready frame FIRST — pushing the stall
        # before the handler registers would drop it silently
        need = int.from_bytes(conn.recv(4), "big")
        while need > 0:
            need -= len(conn.recv(need))
        # one rid-less push to park the reader in the stall handler;
        # then silence — the requester below would wait out its full
        # timeout without the close() fix
        body = _json.dumps({"t": "stall"}).encode()
        conn.sendall(len(body).to_bytes(4, "big") + body)
        stop.wait(20.0)
        conn.close()

    stop = _threading.Event()
    server_thread = _threading.Thread(target=serve, daemon=True)
    server_thread.start()
    t = _Transport("127.0.0.1", port, timeout=30.0)
    parked = _threading.Event()
    t.on_push("stall", lambda frame: (parked.set(), stop.wait(20.0)))
    t.send({"t": "ready"})
    assert parked.wait(10.0), "reader never entered the stall handler"

    outcome = []

    def request():
        t0 = time.monotonic()
        try:
            t.request({"t": "admin_status"})
        except ConnectionError as e:
            outcome.append((time.monotonic() - t0, str(e)))

    requester = _threading.Thread(target=request, daemon=True)
    requester.start()
    time.sleep(0.3)  # let it park on the cv
    t.close()
    requester.join(timeout=5.0)
    stop.set()
    srv.close()
    assert outcome, "requester still blocked after close()"
    elapsed, message = outcome[0]
    assert elapsed < 5.0, f"woke by timeout, not by close(): {elapsed}"
    assert "closed" in message


def test_fleet_admin_fanout_does_not_stall_the_loop(tmp_path):
    """The fleet placement fan-out (per-peer admin_rpc dials with
    multi-second timeouts) must run OFF the event loop: while a slow
    fan-out is in flight, a concurrent ping on the same connection
    still turns around immediately, and the fleet reply arrives with
    its counters intact. Found by fluidlint (BLOCKING-ON-LOOP via
    peer_tier_snapshots); the fix is _ClientSession._reply_offloop."""
    import threading as _threading

    from fluidframework_tpu.driver.network import _Transport
    from fluidframework_tpu.service.front_end import ShardHost

    sh = ShardHost(str(tmp_path), 1, prefer=(0,))
    fe = NetworkFrontEnd(shard_host=sh).start_background()
    try:
        slow = 1.5

        def slow_counters(table_rec):
            time.sleep(slow)  # a peer dial timing out, in miniature
            return {"placement.fleet_probe": 7}

        fe._fleet_placement_counters = slow_counters

        t = _Transport("127.0.0.1", fe.port, timeout=10.0)
        try:
            fleet_reply = []

            def fleet():
                fleet_reply.append(
                    t.request({"t": "admin_placement", "fleet": True}))

            worker = _threading.Thread(target=fleet, daemon=True)
            t0 = time.monotonic()
            worker.start()
            time.sleep(0.2)  # fan-out is now parked in the executor
            t.request({"t": "admin_docs"})
            ping_latency = time.monotonic() - t0
            assert ping_latency < slow, \
                f"loop stalled behind the fan-out: {ping_latency:.2f}s"
            worker.join(timeout=10.0)
            assert fleet_reply, "fleet reply never arrived"
            placement = fleet_reply[0]["placement"]
            assert placement["counters"] == {"placement.fleet_probe": 7}
            # the synchronous fields rode along unharmed
            assert placement["owner"] == sh.owner_id
        finally:
            t.close()
    finally:
        fe.stop()
