"""Horizontal front-end scale-out: N gateway processes over one core
ordering process (the Redis-pub/sub Alfred topology, SURVEY §2.10).

Ref: services/src/socketIoRedisPublisher.ts (cross-instance broadcast),
lambdas-driver partition rebalance.
"""

import json
import socket
import subprocess
import sys
import time

import pytest

from fluidframework_tpu.driver.network import NetworkDocumentServiceFactory
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.service.tenants import SCOPE_READ, sign_token


def _spawn(args):
    proc = subprocess.Popen(
        [sys.executable, "-m"] + args,
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd="/root/repo")
    line = proc.stdout.readline().strip()
    assert line.startswith("LISTENING"), line
    return proc, int(line.rsplit(":", 1)[1])


def wait_for(cond, timeout=10.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(0.02)
    return False


@pytest.fixture(scope="module")
def topology():
    """One core + two gateways, all separate OS processes."""
    core, core_port = _spawn(
        ["fluidframework_tpu.service.front_end", "--port", "0"])
    gw1, p1 = _spawn(["fluidframework_tpu.service.gateway",
                      "--core-port", str(core_port)])
    gw2, p2 = _spawn(["fluidframework_tpu.service.gateway",
                      "--core-port", str(core_port)])
    try:
        yield core_port, p1, p2
    finally:
        for proc in (gw1, gw2, core):
            proc.terminate()
            proc.wait(timeout=10)


def test_clients_on_different_gateways_converge(topology):
    _, p1, p2 = topology
    l1 = Loader(NetworkDocumentServiceFactory("127.0.0.1", p1))
    l2 = Loader(NetworkDocumentServiceFactory("127.0.0.1", p2))
    c1 = l1.resolve("t", "gwdoc")
    c2 = l2.resolve("t", "gwdoc")
    s1 = c1.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    s1.insert_text(0, "across gateways")
    assert wait_for(lambda: "default" in c2.runtime.data_stores
                    and "text" in c2.runtime.get_data_store("default").channels
                    and c2.runtime.get_data_store("default")
                    .get_channel("text").get_text() == "across gateways")
    s2 = c2.runtime.get_data_store("default").get_channel("text")
    s2.insert_text(0, ">> ")
    s1.insert_text(len(s1.get_text()), " <<")
    assert wait_for(
        lambda: s1.get_text() == s2.get_text() == ">> across gateways <<")


def test_gateway_client_and_direct_core_client_interoperate(topology):
    core_port, p1, _ = topology
    lg = Loader(NetworkDocumentServiceFactory("127.0.0.1", p1))
    lc = Loader(NetworkDocumentServiceFactory("127.0.0.1", core_port))
    c1 = lg.resolve("t", "mixdoc")
    c2 = lc.resolve("t", "mixdoc")
    s1 = c1.runtime.create_data_store("default").create_channel(
        "kv", "shared-map")
    s1.set("from", "gateway")
    assert wait_for(lambda: "default" in c2.runtime.data_stores
                    and "kv" in c2.runtime.get_data_store("default").channels
                    and c2.runtime.get_data_store("default")
                    .get_channel("kv").get("from") == "gateway")
    c2.runtime.get_data_store("default").get_channel("kv").set("back", "core")
    assert wait_for(lambda: s1.get("back") == "core")


def test_storage_rpcs_pass_through_gateway(topology):
    _, p1, _ = topology
    loader = Loader(NetworkDocumentServiceFactory("127.0.0.1", p1))
    c1 = loader.resolve("t", "sumdoc")
    s = c1.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    s.insert_text(0, "summarize me")

    from fluidframework_tpu.runtime.summarizer import SummaryManager

    assert wait_for(lambda: c1.runtime.pending.count == 0)
    sm = SummaryManager(c1, max_ops=10**9)
    sm.summarize_now()
    assert wait_for(lambda: sm.summaries_acked == 1)

    # a fresh gateway client boots from the summary written through the
    # gateway's storage passthrough
    c2 = loader.resolve("t", "sumdoc")
    assert c2._base_snapshot is not None
    assert wait_for(lambda: c2.runtime.get_data_store("default")
                    .get_channel("text").get_text() == "summarize me")


def test_signals_relay_across_gateways(topology):
    _, p1, p2 = topology
    l1 = Loader(NetworkDocumentServiceFactory("127.0.0.1", p1))
    l2 = Loader(NetworkDocumentServiceFactory("127.0.0.1", p2))
    c1 = l1.resolve("t", "sigdoc")
    c2 = l2.resolve("t", "sigdoc")
    got = []
    c2.on_signal = lambda sig: got.append(sig.content)
    c1.submit_signal({"ping": 1})
    assert wait_for(lambda: got == [{"ping": 1}])


def test_shared_text_example_demo_converges():
    """The runnable developer-surface demo: server + two editor
    PROCESSES edit concurrently and render identical documents."""
    out = subprocess.run(
        [sys.executable, "-m", "examples.shared_text"],
        capture_output=True, text=True, timeout=120, cwd="/root/repo")
    assert out.returncode == 0, out.stdout + out.stderr
    assert "CONVERGED" in out.stdout
    assert "⟦verify deli ordering claim⟧" in out.stdout  # anchored comment
    assert "**Welcome**" in out.stdout  # bold annotation rendered


def test_clicker_example_demo_converges():
    """The SharedCounter example (BASELINE config 2): 4 clicker
    processes hammer one counter and the total converges."""
    out = subprocess.run(
        [sys.executable, "-m", "examples.clicker"],
        capture_output=True, text=True, timeout=120, cwd="/root/repo")
    assert out.returncode == 0, out.stdout + out.stderr
    assert "CONVERGED: 4 processes x 25 clicks = 100" in out.stdout


# --------------------------------------------------------- secured gateway

def _send_frame(sock: socket.socket, obj: dict) -> None:
    body = json.dumps(obj).encode()
    sock.sendall(len(body).to_bytes(4, "big") + body)


def _recv_frame(sock: socket.socket, timeout: float):
    """One length-prefixed frame, or None on timeout."""
    sock.settimeout(timeout)
    try:
        buf = b""
        while len(buf) < 4:
            chunk = sock.recv(4 - len(buf))
            if not chunk:
                return None
            buf += chunk
        n = int.from_bytes(buf, "big")
        body = b""
        while len(body) < n:
            chunk = sock.recv(n - len(body))
            if not chunk:
                return None
            body += chunk
        return json.loads(body.decode())
    except socket.timeout:
        return None


@pytest.fixture(scope="module")
def secured_topology():
    """Core with tenancy enforced + one gateway in front of it."""
    core, core_port = _spawn(
        ["fluidframework_tpu.service.front_end", "--port", "0",
         "--tenant", "acme:s3cret"])
    gw, p = _spawn(["fluidframework_tpu.service.gateway",
                    "--core-port", str(core_port)])
    try:
        yield p
    finally:
        for proc in (gw, core):
            proc.terminate()
            proc.wait(timeout=10)


def _signed_factory(port, **token_kwargs):
    return NetworkDocumentServiceFactory(
        "127.0.0.1", port,
        token_provider=lambda t, d: sign_token(t, d, "s3cret",
                                               **token_kwargs))


def test_rejected_gateway_connect_receives_no_broadcasts(secured_topology):
    """Auth regression: a tokenless client whose connect the core REFUSES
    must not be left subscribed to the doc's live op stream on the
    gateway (the round-3 advisor finding)."""
    p = secured_topology
    eaves = socket.create_connection(("127.0.0.1", p))
    try:
        _send_frame(eaves, {"t": "connect", "tenant": "acme",
                            "doc": "secdoc", "rid": 1})
        reply = _recv_frame(eaves, 10.0)
        assert reply is not None and reply["t"] == "error"

        # an authorized client on the SAME gateway keeps the topic live
        loader = Loader(_signed_factory(p))
        c1 = loader.resolve("acme", "secdoc")
        s1 = c1.runtime.create_data_store("default").create_channel(
            "text", "shared-string")
        s1.insert_text(0, "classified")
        assert wait_for(lambda: c1.runtime.pending.count == 0)

        # the refused socket must see NOTHING of that traffic
        leaked = _recv_frame(eaves, 1.0)
        assert leaked is None, f"tokenless client received {leaked!r}"
    finally:
        eaves.close()


def test_read_scope_token_connects_read_mode_via_gateway(secured_topology):
    """A doc:read token must get a read-mode connection through the
    gateway, exactly as at the direct door — not an outright refusal."""
    p = secured_topology
    svc = _signed_factory(p, scopes=(SCOPE_READ,)) \
        .create_document_service("acme", "readdoc")
    conn = svc.connect_to_delta_stream()
    assert conn.mode == "read"
    conn.close()


def test_gateway_reconnect_frame_releases_previous_registration(
        secured_topology):
    """A second connect frame on a live gateway socket must detach the
    first registration (old client leaves the quorum) instead of
    orphaning its core-side connection."""
    p = secured_topology
    loader = Loader(_signed_factory(p))
    observer = loader.resolve("acme", "redoc")
    token = sign_token("acme", "redoc", "s3cret")

    raw = socket.create_connection(("127.0.0.1", p))
    try:
        _send_frame(raw, {"t": "connect", "tenant": "acme", "doc": "redoc",
                          "token": token, "rid": 1,
                          "details": {"mode": "write"}})
        first = _recv_frame(raw, 10.0)
        assert first["t"] == "connected"
        a = first["clientId"]
        assert wait_for(lambda: a in observer.audience)

        _send_frame(raw, {"t": "connect", "tenant": "acme", "doc": "redoc",
                          "token": token, "rid": 2,
                          "details": {"mode": "write"}})
        second = None
        while second is None or second["t"] != "connected":
            second = _recv_frame(raw, 10.0)
            assert second is not None
        b = second["clientId"]
        assert b != a
        assert wait_for(lambda: a not in observer.audience
                        and b in observer.audience)
    finally:
        raw.close()


def test_reconnect_rebase_through_gateway(topology):
    """Offline edits rebase + resubmit across a RECONNECT whose new
    session rides the gateway backbone (fresh sid, fresh upstream
    registration)."""
    _, p1, p2 = topology
    l1 = Loader(NetworkDocumentServiceFactory("127.0.0.1", p1))
    l2 = Loader(NetworkDocumentServiceFactory("127.0.0.1", p2))
    c1 = l1.resolve("t", "rcdoc")
    c2 = l2.resolve("t", "rcdoc")
    s1 = c1.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    s1.insert_text(0, "base")
    assert wait_for(lambda: "default" in c2.runtime.data_stores
                    and "text" in c2.runtime.get_data_store("default").channels
                    and c2.runtime.get_data_store("default")
                    .get_channel("text").get_text() == "base")
    s2 = c2.runtime.get_data_store("default").get_channel("text")

    c1.disconnect()
    s1.insert_text(0, "X")   # offline edit on the gateway-1 client
    s2.insert_text(4, "Y")   # concurrent edit through gateway 2
    assert wait_for(lambda: s2.get_text() == "baseY")
    c1.reconnect()
    assert wait_for(lambda: s1.get_text() == s2.get_text() == "XbaseY")


def test_mixed_protocol_clients_through_gateway(topology):
    """A binwire client and a JSON client on the SAME gateway converge:
    the gateway byte-slices fops for the binary session and re-encodes
    JSON once for the legacy one (gateway._dispatch_upstream_binary)."""
    _, p1, _ = topology
    lb = Loader(NetworkDocumentServiceFactory("127.0.0.1", p1, binary=True))
    lj = Loader(NetworkDocumentServiceFactory("127.0.0.1", p1, binary=False))
    cb = lb.resolve("t", "gwmix")
    cj = lj.resolve("t", "gwmix")
    sb = cb.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    sb.insert_text(0, "binary")

    def synced():
        ds = cj.runtime.data_stores.get("default")
        return (ds is not None and "text" in ds.channels
                and ds.get_channel("text").get_text() == "binary")
    assert wait_for(synced)
    sj = cj.runtime.get_data_store("default").get_channel("text")
    sj.insert_text(0, "json+")
    assert wait_for(lambda: sb.get_text() == "json+binary"
                    and sj.get_text() == "json+binary")


def test_table_doc_example_demo_converges():
    """The composed example (matrix + map + string in ONE container,
    ref: table-document): two editor processes edit concurrently —
    including a row insert racing cell writes — and render identical
    tables."""
    out = subprocess.run(
        [sys.executable, "-m", "examples.table_doc"],
        capture_output=True, text=True, timeout=120, cwd="/root/repo")
    assert out.returncode == 0, out.stdout + out.stderr
    assert "CONVERGED" in out.stdout
    assert "TOTAL" in out.stdout
    assert "region" in out.stdout


def test_ping_answered_at_every_terminator(topology):
    """The liveness probe (driver/network.py recv-timeout escalation)
    must be answered at each hop a client can terminate at: the core
    itself, the native C++ gateway relay (the fixture default), and the
    pure-Python relay — a hop that relayed or dropped pings would make
    idle clients behind it false-positive as dead after two windows."""
    from fluidframework_tpu.driver.network import _Transport

    core_port, gw_native, _ = topology
    pygw, pyport = _spawn(["fluidframework_tpu.service.gateway",
                           "--core-port", str(core_port), "--python"])
    try:
        for label, port in (("core", core_port),
                            ("native-gateway", gw_native),
                            ("python-gateway", pyport)):
            t = _Transport("127.0.0.1", port, timeout=5.0)
            got = []
            t.on_push("pong", got.append)
            try:
                t.send({"t": "ping"})
                assert wait_for(lambda: got), f"no pong from {label}"
            finally:
                t.close()
    finally:
        pygw.terminate()
        pygw.wait(timeout=10)
