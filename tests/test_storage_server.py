"""Storage as its own process: commit/ref DAG, boot-from-ref, caching.

Ref: the reference's storage micro-services (gitrest object store +
historian caching proxy, services-client/src/gitManager.ts:13,
historian.ts:29) — summaries live in a git-shaped commit DAG behind a
standalone cached service; the scribe's ack advances the doc's named
ref (VERDICT r3 item 5).
"""

from __future__ import annotations

import contextlib
import subprocess
import sys
import time

from fluidframework_tpu.driver.network import NetworkDocumentServiceFactory
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.service.storage_client import (
    RemoteStorage,
    StorageConnection,
)


def wait_for(cond, timeout=20.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(0.02)
    return False


def _spawn(args):
    proc = subprocess.Popen(
        [sys.executable, "-m"] + args,
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd="/root/repo")
    line = proc.stdout.readline().strip()
    assert line.startswith("LISTENING"), line
    return proc, int(line.rsplit(":", 1)[1])


@contextlib.contextmanager
def storage_process(data_dir):
    proc, port = _spawn(["fluidframework_tpu.service.storage_server",
                         "--dir", str(data_dir)])
    try:
        yield port
    finally:
        proc.terminate()
        proc.wait(timeout=10)


@contextlib.contextmanager
def full_deployment(tmp_path):
    """Storage process + ordering core process wired to it."""
    with storage_process(tmp_path / "store") as sport:
        core, port = _spawn(["fluidframework_tpu.service.front_end",
                             "--port", "0",
                             "--storage-server", str(sport)])
        try:
            yield port, sport
        finally:
            core.terminate()
            core.wait(timeout=10)


def test_commit_dag_and_history_walk(tmp_path):
    """Direct RPC exercise: summary uploads build parent-linked commits;
    the ref advances only on commit_ref; history walks the chain."""
    with storage_process(tmp_path / "s") as port:
        conn = StorageConnection("127.0.0.1", port)
        st = RemoteStorage(conn, "t", "doc")
        v1 = st.upload_summary({"root": {"a": 1}}, None)
        assert st.get_ref() is None          # unacked: not yet a version
        assert st.get_versions() == []
        st.commit_ref(v1)
        assert st.get_ref() == v1
        v2 = st.upload_summary({"root": {"a": 2}}, v1)
        st.commit_ref(v2)
        v3 = st.upload_summary({"root": {"a": 3}}, v2)
        st.commit_ref(v3)

        commits = st.history()
        assert [c["id"] for c in commits] == [v3, v2, v1]
        assert [c["meta"]["n"] for c in commits] == [2, 1, 0]
        assert commits[0]["parents"] == [v2]
        assert commits[2]["parents"] == []
        # newest-first version listing mirrors the walk
        assert [v["id"] for v in st.get_versions(2)] == [v3, v2]
        assert st.get_snapshot_tree() == {"root": {"a": 3}}


def test_refs_survive_storage_process_restart(tmp_path):
    data = tmp_path / "s"
    with storage_process(data) as port:
        st = RemoteStorage(StorageConnection("127.0.0.1", port), "t", "d")
        v1 = st.upload_summary({"root": {"x": 1}}, None)
        st.commit_ref(v1)
    with storage_process(data) as port:
        st = RemoteStorage(StorageConnection("127.0.0.1", port), "t", "d")
        assert st.get_ref() == v1            # reflog replayed
        assert st.get_snapshot_tree() == {"root": {"x": 1}}


def test_client_boots_from_ref_through_storage_process(tmp_path):
    """End to end: client summary → scribe ack advances the ref in the
    storage PROCESS → a fresh client boots from it; blob reads hit the
    historian-role LRU."""
    from fluidframework_tpu.runtime.summarizer import SummaryManager

    with full_deployment(tmp_path) as (port, sport):
        loader = Loader(NetworkDocumentServiceFactory("127.0.0.1", port))
        c1 = loader.resolve("t", "doc")
        sm = SummaryManager(c1, max_ops=3)
        s = c1.runtime.create_data_store("default").create_channel(
            "text", "shared-string")
        s.insert_text(0, "stored remotely")
        assert wait_for(lambda: sm.summaries_acked >= 1)

        st = RemoteStorage(StorageConnection("127.0.0.1", sport),
                           "t", "doc")
        head = st.get_ref()
        assert head is not None              # scribe advanced the ref
        assert st.history()[0]["id"] == head

        c2 = loader.resolve("t", "doc")
        assert c2._base_snapshot is not None  # booted from the summary
        assert wait_for(lambda: c2.runtime.get_data_store("default")
                        .get_channel("text").get_text()
                        == "stored remotely")
        stats = st.stats()
        assert stats["hits"] > 0             # c2's boot re-read cached blobs

        # a second summary chains onto the first
        for i in range(4):
            s.insert_text(0, f"{i}")
        assert wait_for(lambda: sm.summaries_acked >= 2)
        hist = st.history()
        assert len(hist) == 2 and hist[1]["id"] == head
