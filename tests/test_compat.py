"""Cross-round format-freeze harness (VERDICT r3 item 6).

Ref: the reference's cross-version compat suite
(packages/test/end-to-end-tests/src/test/compat.spec.ts + the pinned
snapshot corpus in packages/test/snapshots): new code must keep loading
artifacts produced by older code, or ship an explicit migration.

The fixtures in tests/golden/ were generated at the round-4 freeze by
``python -m tests.golden.generate`` and are COMMITTED — these tests load
them with current code. A format change that breaks them needs a
migration plus a deliberate fixture regeneration, never a silent break.
"""

import json
import os
import shutil

import pytest

GOLDEN = os.path.join(os.path.dirname(__file__), "golden")


@pytest.fixture(scope="module")
def expected():
    with open(os.path.join(GOLDEN, "expected.json")) as fh:
        return json.load(fh)


def test_wire_frame_bytes_are_stable():
    """Framed JSON protocol: byte-exact both directions."""
    from fluidframework_tpu.service.front_end import _encode_frame

    with open(os.path.join(GOLDEN, "wire_frames.json")) as fh:
        entries = json.load(fh)
    assert len(entries) >= 8
    for e in entries:
        golden = bytes.fromhex(e["hex"])
        assert _encode_frame(e["frame"]) == golden, e["frame"]["t"]
        n = int.from_bytes(golden[:4], "big")
        assert n == len(golden) - 4
        assert json.loads(golden[4:].decode()) == e["frame"]


def test_message_serialization_is_stable():
    """encode_message/decode_message: golden bytes decode, and re-encode
    byte-identically (field order + enum spellings pinned)."""
    from fluidframework_tpu.protocol.serialization import (
        decode_message,
        encode_message,
    )

    with open(os.path.join(GOLDEN, "messages.json")) as fh:
        shapes = json.load(fh)
    assert set(shapes) == {"sequenced_op", "join", "raw", "nack"}
    for name, text in shapes.items():
        msg = decode_message(text.encode())
        assert encode_message(msg) == text.encode(), name
    op = decode_message(shapes["sequenced_op"].encode())
    assert op.sequence_number == 42 and op.client_id == "client-a"
    nack = decode_message(shapes["nack"].encode())
    assert nack.code == 429


def test_durable_log_and_blobs_boot_round3_session(tmp_path, expected):
    """A service process restarted over the golden log directory + chunk
    store restores the doc: summary head, retained tail, live edits."""
    from fluidframework_tpu.driver import LocalDocumentServiceFactory
    from fluidframework_tpu.loader import Loader
    from fluidframework_tpu.service import LocalServer
    from fluidframework_tpu.service.durable_log import DurableLog

    # copy: recovery may truncate/append; the committed fixture stays pristine
    logdir = str(tmp_path / "svclog")
    blobdir = str(tmp_path / "blobs")
    shutil.copytree(os.path.join(GOLDEN, "svclog"), logdir)
    shutil.copytree(os.path.join(GOLDEN, "blobs"), blobdir)

    server = LocalServer(log=DurableLog(logdir), storage_dir=blobdir)
    scribe = server._get_orderer("t", "doc").scribe
    assert scribe.last_summary_head == expected["summary_head"]
    assert server._get_orderer("t", "doc").deli.sequence_number \
        == expected["seq"]

    c = Loader(LocalDocumentServiceFactory(server)).resolve("t", "doc")
    assert c._base_snapshot is not None  # booted FROM the golden summary
    s = c.runtime.get_data_store("default").get_channel("text")
    assert s.get_text() == expected["text"]
    # the annotate survived the summary+boot ('olden' kept bold)
    pos_o = expected["text"].index("olden")
    assert s.client.get_properties_at(pos_o).get("bold") is True
    assert s.client.get_properties_at(0).get("bold") is None
    # and the doc is live
    s.insert_text(0, "r4 ")
    assert s.get_text() == "r4 " + expected["text"]


def test_applier_checkpoint_loads(tmp_path, expected):
    """The device-farm checkpoint (npz + json sidecar) warm-restores."""
    from fluidframework_tpu.service.tpu_applier import (
        load_applier_checkpoint,
    )

    for ext in (".npz", ".json"):
        shutil.copy(os.path.join(GOLDEN, "applier_ckpt" + ext),
                    str(tmp_path / ("applier_ckpt" + ext)))
    applier = load_applier_checkpoint(str(tmp_path / "applier_ckpt"),
                                      ops_per_dispatch=8)
    assert applier.get_text("t", "ckdoc") == expected["ckpt_text"]
    assert applier.applied_seq("t", "ckdoc") == expected["ckpt_applied_seq"]
    props = applier.get_properties_at("t", "ckdoc", 0)
    assert props.get("em") is True


def test_applier_checkpoint_loads_legacy_meta(tmp_path, expected):
    """A checkpoint written before coverage tracking (no applied_seq /
    first_seq / anchored keys) must still load — such slots restore
    unanchored and the summarizer refuses until coverage is re-proven."""
    from fluidframework_tpu.service.tpu_applier import (
        load_applier_checkpoint,
    )

    shutil.copy(os.path.join(GOLDEN, "applier_ckpt.npz"),
                str(tmp_path / "applier_ckpt.npz"))
    with open(os.path.join(GOLDEN, "applier_ckpt.json")) as fh:
        meta = json.load(fh)
    for legacy_missing in ("applied_seq", "first_seq", "anchored"):
        meta.pop(legacy_missing, None)
    with open(str(tmp_path / "applier_ckpt.json"), "w") as fh:
        json.dump(meta, fh)
    applier = load_applier_checkpoint(str(tmp_path / "applier_ckpt"),
                                      ops_per_dispatch=8)
    assert applier.get_text("t", "ckdoc") == expected["ckpt_text"]
    assert applier.applied_seq("t", "ckdoc") == 0  # unknown ⇒ refuse-safe
    assert not applier.is_anchored("t", "ckdoc")
