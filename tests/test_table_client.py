"""Networked placement plane: local-vs-remote TableClient equivalence,
the table door's zombie fence, epoch-gated snapshot coherence, and the
multi-host topology spec's derived views (ISSUE 19).

The load-bearing claim is that :class:`RemoteTableClient` changed the
TRANSPORT, not the semantics: the same claim/heartbeat/release/transfer
interleaving driven through the flock directly and through the table
door must return identical booleans, identical epoch sequences, and an
identical final table. The fuzz below asserts exactly that at seeds
0/7/42.
"""

import random
import threading
import time

import pytest

from fluidframework_tpu.service.storage_server import StorageServer
from fluidframework_tpu.service.table_client import (
    LocalTableClient,
    RemoteEpochTable,
    RemoteTableClient,
    TableDoorService,
    TableFenceError,
)
from fluidframework_tpu.service.topology import TopologySpec, multihost_spec
from fluidframework_tpu.utils.telemetry import Counters

N_PARTS = 4
OWNERS = ("a", "b", "c")


def _start_door(tmp_path, shard_name, n=N_PARTS, ttl_s=30.0):
    """A real table door on a real socket: TableDoorService riding a
    StorageServer, exactly the production deployment shape."""
    shard_dir = str(tmp_path / shard_name)
    door = TableDoorService(shard_dir, n, ttl_s=ttl_s)
    srv = StorageServer(str(tmp_path / f"{shard_name}-storage"), port=0,
                        table_door=door)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    deadline = time.monotonic() + 10.0
    while srv.port == 0 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert srv.port, "door server did not bind"
    return shard_dir, door, srv


# ------------------------------------------------- equivalence fuzz


@pytest.mark.parametrize("seed", [0, 7, 42])
def test_local_remote_equivalence_fuzz(tmp_path, seed):
    """The same randomized claim/heartbeat/transfer/release interleaving
    through the local flock and through the door produces identical
    results, identical epoch sequences, and identical final tables."""
    local = LocalTableClient(str(tmp_path / "local"), N_PARTS,
                             ttl_s=30.0, counters=Counters())
    _, _, srv = _start_door(tmp_path, "remote")
    remote = RemoteTableClient(f"127.0.0.1:{srv.port}", N_PARTS,
                               ttl_s=30.0, counters=Counters())

    rng = random.Random(seed)
    trace_a: list = []
    trace_b: list = []

    # one shared op plan replayed against both worlds
    ops = []
    for _ in range(120):
        k = rng.randrange(N_PARTS)
        o = rng.choice(OWNERS)
        ops.append((rng.choice(("claim", "heartbeat", "release",
                                "transfer", "owner_of", "epoch")),
                    k, o, rng.choice(OWNERS)))

    def run(client, trace):
        for op, k, o, o2 in ops:
            addr = f"addr-{o}"
            if op == "claim":
                ok = client.leases.try_claim(k, o, addr)
                trace.append(("claim", k, o, ok))
                if ok:
                    # what ShardHost.poll does after a claim lands
                    trace.append(("epoch",
                                  client.table.record_claim(k, o, addr)))
            elif op == "heartbeat":
                trace.append(("hb", k, o,
                              client.leases.heartbeat(k, o)))
            elif op == "release":
                if client.leases.owner_of(k) == addr:
                    client.leases.release(k, o)
                    trace.append(("release", k, o,
                                  client.table.record_release(k, o)))
            elif op == "transfer":
                ok = client.leases.transfer(k, o, o2, f"addr-{o2}")
                trace.append(("transfer", k, o, o2, ok))
                if ok:
                    trace.append(("epoch", client.table.record_claim(
                        k, o2, f"addr-{o2}")))
            elif op == "owner_of":
                trace.append(("owner_of", k, client.leases.owner_of(k)))
            elif op == "epoch":
                trace.append(("gepoch", client.table.global_epoch()))

    run(local, trace_a)
    run(remote, trace_b)

    assert trace_a == trace_b

    # final state: identical lease tables and epoch-table records
    assert local.leases.table() == remote.leases.table()
    remote.table._invalidate()  # bypass the snapshot for the final read
    rec_a, rec_b = local.table.read(), remote.table.read()
    assert rec_a["epoch"] == rec_b["epoch"]
    assert rec_a["parts"] == rec_b["parts"]
    remote.close()


# ------------------------------------------------- the door's fence


def test_zombie_ex_owner_fenced_via_remote_path(tmp_path):
    """A remote zombie whose lease was taken over gets table_reject →
    TableFenceError, counted as placement.table.stale_rejections — the
    3-layer fencing proof carries to the networked path."""
    _, _, srv = _start_door(tmp_path, "shard", ttl_s=0.6)
    ca, cb = Counters(), Counters()
    zombie = RemoteTableClient(f"127.0.0.1:{srv.port}", N_PARTS,
                               ttl_s=0.6, counters=ca)
    usurper = RemoteTableClient(f"127.0.0.1:{srv.port}", N_PARTS,
                                ttl_s=0.6, counters=cb)

    assert zombie.leases.try_claim(0, "a", "addr-a")
    assert zombie.table.record_claim(0, "a", "addr-a") >= 1
    time.sleep(0.9)  # lease expires; "a" never heartbeats again

    assert usurper.leases.try_claim(0, "b", "addr-b")  # takeover
    e2 = usurper.table.record_claim(0, "b", "addr-b")

    with pytest.raises(TableFenceError):
        zombie.table.record_claim(0, "a", "addr-a")
    assert ca.snapshot().get("placement.table.stale_rejections") == 1
    # the refused write bumped nothing and re-routed nothing
    usurper.table._invalidate()
    rec = usurper.table.read()
    assert rec["epoch"] == e2
    assert rec["parts"]["0"]["owner"] == "b"
    zombie.close()
    usurper.close()


# ------------------------------------------------- snapshot coherence


class _FakeChan:
    """A scripted door: counts calls, serves a mutable record."""

    def __init__(self):
        self.rec = {"epoch": 1, "parts": {}, "cores": {}}
        self.calls = 0

    def call(self, frame):
        assert frame["t"] == "admin_table_read"
        self.calls += 1
        return {"t": "table_rec", "rec": dict(self.rec)}


def test_remote_snapshot_epoch_gated_coherence():
    """Reads inside SNAP_TTL_S hit the snapshot; a note_epoch push for a
    NEWER epoch drops it immediately (an old snapshot can never veto a
    newer route); an older/equal push is ignored."""
    chan, c = _FakeChan(), Counters()
    table = RemoteEpochTable(chan, c)

    assert table.global_epoch() == 1
    assert table.global_epoch() == 1  # served from snapshot
    assert chan.calls == 1
    assert c.snapshot()["placement.table.cache_hits"] == 1

    table.note_epoch(1)  # stale push: snapshot stays
    assert table.global_epoch() == 1
    assert chan.calls == 1

    chan.rec["epoch"] = 5
    table.note_epoch(5)  # coherence push: snapshot dead
    assert table.global_epoch() == 5
    assert chan.calls == 2
    assert c.snapshot()["placement.table.rpc_reads"] == 2


# ------------------------------------------------- topology spec


def test_topology_unknown_keys_roundtrip_both_directions(tmp_path):
    """Forward-compat: unknown top-level spec keys survive load→save
    and save→load round trips untouched."""
    d = {"shard_dir": str(tmp_path / "s"), "n_partitions": 4,
         "cores": [{"name": "c0", "prefer": [0, 1, 2, 3]}],
         "future_knob": {"x": 1}, "operator_note": "keep me"}
    spec = TopologySpec.from_dict(d)
    assert spec.extras == {"future_knob": {"x": 1},
                           "operator_note": "keep me"}
    out = spec.to_dict()
    assert out["future_knob"] == {"x": 1}
    assert out["operator_note"] == "keep me"
    assert out["n_partitions"] == 4  # known fields still win

    # and through the file: save → load → save preserves them
    p = spec.save(str(tmp_path / "spec.json"))
    spec2 = TopologySpec.load(p)
    assert spec2.extras == spec.extras
    assert spec2.to_dict()["future_knob"] == {"x": 1}


def test_doctor_multihost_anomaly_trio(tmp_path):
    """The doctor's multi-host triage: an unreachable host group (every
    core a host id advertises failed capture), a cross-host epoch
    regression (a later epoch.bump with a LOWER epoch for the same
    part), and remote-table writes rejected by the door's fence."""
    import json

    from tools.doctor import diagnose

    bundle = tmp_path / "bundle"
    c0 = bundle / "cores" / "core0"
    c0.mkdir(parents=True)
    for owner in ("core2", "core3"):
        (bundle / "cores" / owner).mkdir()
    (bundle / "manifest.json").write_text(json.dumps({"cores": {
        "core0": {"addr": "127.0.0.1:7000", "journal_armed": True},
        "core2": {"addr": "10.0.0.2:7000",
                  "error": "connection refused"},
        "core3": {"addr": "10.0.0.2:7001", "error": "timed out"},
    }}))
    (bundle / "placement.json").write_text(json.dumps({
        "parts": {"0": {"owner": "core0", "addr": "127.0.0.1:7000",
                        "epoch": 5}},
        "cores": {
            "core0": {"addr": "127.0.0.1:7000", "state": "active",
                      "host": "h0"},
            "core2": {"addr": "10.0.0.2:7000", "state": "active",
                      "host": "h1"},
            "core3": {"addr": "10.0.0.2:7001", "state": "active",
                      "host": "h1"},
        }}))
    (c0 / "scrape.prom").write_text(
        "fluid_placement_table_stale_rejections 2\n")

    def bump(seq, ts, core, epoch, part):
        return {"id": f"{core}:{seq}", "seq": seq, "ts": ts,
                "core": core, "epoch": epoch, "kind": "epoch.bump",
                "cause": None, "labels": {"part": part,
                                          "change": "claim"}}

    (c0 / "journal.jsonl").write_text("\n".join(json.dumps(e) for e in [
        bump(1, 100.0, "core0", 5, 0),
        bump(2, 101.0, "core2", 3, 0),  # later wall-clock, LOWER epoch
        bump(3, 102.0, "core0", 6, 1),  # other part: healthy
    ]) + "\n")

    rep = diagnose(str(bundle))
    assert any("host group h1" in a and "unreachable" in a
               for a in rep["anomalies"])
    assert any("epoch regressed e3 on core2 after e5 on core0" in a
               for a in rep["anomalies"])
    assert any("2 remote-table write(s) rejected" in a
               for a in rep["anomalies"])
    # the healthy host group and part raise nothing extra: exactly the
    # trio plus one capture-error row per dead core
    assert not any("host group h0" in a for a in rep["anomalies"])
    assert len(rep["anomalies"]) == 5


def test_multihost_spec_derived_views(tmp_path):
    """Host-group derivations: disjoint working dirs for remote groups,
    same-dir for the placement host, remote leaf gateways wired to the
    table door instead of the shard dir."""
    shard = str(tmp_path / "fleet")
    spec = multihost_spec(shard, n_hosts=2, cores_per_host=2,
                          n_partitions=8)
    spec.table_server = "127.0.0.1:9999"

    assert spec.placement_host_id() == "h0"
    assert spec.core_host(0) == "h0" and spec.core_host(3) == "h1"
    assert spec.core_dir(0) == shard  # placement host: canonical dir
    assert spec.core_dir(3) == f"{shard}-host-h1"  # remote: disjoint
    assert spec.host_dir("h1") != spec.host_dir("h0")
    assert spec.claim_policy == "prefer"

    gw_ports: dict = {}
    core_ports = {i: 7000 + i for i in range(4)}
    for i, g in enumerate(spec.gateways):
        argv = spec.gateway_argv(i, core_ports, gw_ports)
        if spec.gateway_host(i) == "h1":
            assert "--table-server" in argv and "--shard-dir" not in argv
            assert "--host-id" in argv
        else:
            assert "--shard-dir" in argv

    # remote group without a table door is a hard config error, not a
    # silent fall-back onto the placement host's files
    spec.table_server = None
    bad = next(i for i in range(len(spec.gateways))
               if spec.gateway_host(i) == "h1")
    with pytest.raises(RuntimeError):
        spec.gateway_argv(bad, core_ports, gw_ports)
