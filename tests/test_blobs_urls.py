"""Attachment blobs (blobManager.ts) + URL resolution (url resolvers):
binary payloads ride storage, handles ride ops; fluid:// URLs bootstrap
the whole client stack.
"""

import subprocess
import sys

import pytest

from fluidframework_tpu.driver import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.loader.blob_manager import BlobHandle
from fluidframework_tpu.loader.url_resolver import open_url, resolve_url
from fluidframework_tpu.service import LocalServer


@pytest.fixture
def server():
    return LocalServer()


@pytest.fixture
def loader(server):
    return Loader(LocalDocumentServiceFactory(server))


def test_blob_payloads_ride_storage_not_ops(server, loader):
    c1 = loader.resolve("t", "doc")
    c2 = loader.resolve("t", "doc")
    kv1 = c1.runtime.create_data_store("default").create_channel(
        "kv", "shared-map")
    payload = b"\x89PNG" + bytes(range(256)) * 200  # > the 16KB op cap
    handle = c1.blob_manager.create_blob(payload, mime="image/png")
    kv1.set("logo", handle.to_value())

    kv2 = c2.runtime.get_data_store("default").get_channel("kv")
    got = BlobHandle.from_value(kv2.get("logo"))
    assert got is not None and got.mime == "image/png"
    assert c2.blob_manager.get_blob(got) == payload
    # the op stream never carried the payload
    for m in server.get_deltas("t", "doc", 0, 10**9):
        assert b"PNG" not in str(m.contents).encode()


def test_identical_content_dedupes(server, loader):
    c = loader.resolve("t", "doc")
    h1 = c.blob_manager.create_blob(b"same bytes")
    h2 = c.blob_manager.create_blob(b"same bytes")
    assert h1.blob_id == h2.blob_id  # content addressing


def test_resolve_url_parses_and_rejects():
    r = resolve_url("fluid://127.0.0.1:7070/acme/design-doc")
    assert (r.host, r.port, r.tenant_id, r.document_id) == \
        ("127.0.0.1", 7070, "acme", "design-doc")
    for bad in ("http://x:1/t/d", "fluid://x:1/only-tenant",
                "fluid://noport/t/d"):
        with pytest.raises(ValueError):
            resolve_url(bad)


def test_open_url_boots_a_live_container():
    proc = subprocess.Popen(
        [sys.executable, "-m", "fluidframework_tpu.service.front_end",
         "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd="/root/repo")
    try:
        line = proc.stdout.readline().strip()
        port = int(line.rsplit(":", 1)[1])
        c1 = open_url(f"fluid://127.0.0.1:{port}/t/urldoc")
        s1 = c1.runtime.create_data_store("default").create_channel(
            "text", "shared-string")
        s1.insert_text(0, "via url")
        c2 = open_url(f"fluid://127.0.0.1:{port}/t/urldoc")

        import time

        t0 = time.time()
        while time.time() - t0 < 10:
            ds = c2.runtime.data_stores.get("default")
            if ds and "text" in ds.channels and \
                    ds.get_channel("text").get_text() == "via url":
                break
            time.sleep(0.02)
        assert c2.runtime.get_data_store("default") \
            .get_channel("text").get_text() == "via url"
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def test_summary_block_dirty_write_disqualifies_handle_reuse(server, loader):
    from fluidframework_tpu.runtime.summarizer import SummaryManager

    c1 = loader.resolve("t", "doc")
    ds = c1.runtime.create_data_store("default")
    ds.create_channel("text", "shared-string").insert_text(0, "x")
    block = ds.create_channel("meta", "shared-summary-block")
    block.set("build", 41)
    sm = SummaryManager(c1, max_ops=10**9)
    sm.summarize_now()
    sm.summarize_now()  # nothing changed: block rides as a handle
    reused = server.storage_stats["handles_reused"]
    assert reused >= 1

    block.set("build", 42)  # local-only write, no op
    sm.summarize_now()
    c2 = loader.resolve("t", "doc")
    # the new value traveled via the summary alone
    assert c2.runtime.get_data_store("default") \
        .get_channel("meta").get("build") == 42
