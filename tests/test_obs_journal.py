"""Control-plane audit journal + fleet observability (ISSUE 14).

Five planes under test:

- the :class:`~fluidframework_tpu.obs.journal.Journal` codec — fuzzed
  labels survive a write/read round trip, torn tails and garbage lines
  are skipped, rotation keeps the tail and a restart recovers the seq
  from the file (ids are never reused);
- cause links — ``causal_chain`` walks root-first, terminates on
  opaque causes, and cuts cycles;
- the fleet merge — ``(epoch, ts, core, seq)`` ordering keeps
  cross-core causality correct under deliberate wall-clock skew;
- the metrics history ring — retired buckets survive past the live
  window, the horizon prunes, and ``window_history`` label-filters;
- the appended hop taxonomy e2e — ``relay_to_relay`` from a real
  2-level relay tree (core ← mid ← leaf, separate processes) and
  ``stage_to_execute`` from the applier backchannel fold, plus the
  full forced-migration journal chain on in-proc shard hosts.
"""

from __future__ import annotations

import json
import os
import random
import socket
import string
import subprocess
import sys
import time
import types

import pytest

from fluidframework_tpu.obs import get_registry, parse_prometheus
from fluidframework_tpu.obs.journal import (
    KINDS,
    Journal,
    arm_journal,
    causal_chain,
    filter_entries,
    get_journal,
    merge_entries,
    read_journal,
    reset_journal,
)
from fluidframework_tpu.obs.metrics import MetricsRegistry, WindowedSeries
from fluidframework_tpu.protocol import binwire
from fluidframework_tpu.service import LocalServer, NetworkFrontEnd
from fluidframework_tpu.service.front_end import ShardHost
from fluidframework_tpu.service.placement_plane import MigrationEngine
from fluidframework_tpu.utils.telemetry import (
    HOP_ACK,
    HOP_ADMIT,
    HOP_DELI,
    HOP_EXECUTE,
    HOP_FANOUT,
    HOP_RELAY,
    HOP_SHED,
    HOP_STAGE,
    HOP_SUBMIT,
    count_unknown_hops,
    hop_pairs,
)
from tests.test_columnar import _rand_cols_ops


def wait_for(pred, timeout: float = 20.0) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.005)
    return bool(pred())


# ------------------------------------------------------------ codec basics


def test_emit_roundtrip_disarmed_noop_and_kind_guard(tmp_path):
    path = str(tmp_path / "j" / "core.jsonl")
    jr = Journal(path, core="c0", epoch_fn=lambda: 7)
    eid = jr.emit("core.start", owner="c0", shards=2)
    assert eid == "c0:1"
    e2 = jr.emit("lease.claim", cause=eid, part=3)
    assert e2 == "c0:2"
    entries = read_journal(path)
    assert [e["id"] for e in entries] == ["c0:1", "c0:2"]
    assert entries[0]["kind"] == "core.start"
    assert entries[0]["epoch"] == 7
    assert entries[0]["labels"] == {"owner": "c0", "shards": 2}
    assert entries[1]["cause"] == eid
    # an undeclared kind must explode at emit time on an ARMED journal
    with pytest.raises(ValueError):
        jr.emit("migration.sealed", part=3)
    jr.close()
    # disarmed: emit is a no-op returning None (the bench A/B contract)
    off = Journal()
    assert not off.armed
    assert off.emit("core.start") is None
    assert off.emit("not.even.a.kind") is None  # no validation when free


def test_codec_fuzz_and_torn_tail(tmp_path):
    """200 fuzzed entries round-trip; garbage and a torn final line are
    skipped without poisoning the earlier reads."""
    rng = random.Random(14)
    path = str(tmp_path / "fuzz.jsonl")
    jr = Journal(path, core="fz")
    kinds = sorted(KINDS)
    emitted = []
    for i in range(200):
        labels = {
            "s": "".join(rng.choices(string.printable, k=rng.randrange(20))),
            "u": "μ→漢 \x00" * rng.randrange(3),
            "n": rng.choice([None, rng.random(), rng.randrange(1 << 40)]),
            "nest": {"a": [1, {"b": rng.random()}]},
        }
        kind = rng.choice(kinds)
        emitted.append((kind, jr.emit(kind, **labels), labels))
    jr.close()
    # torn tail: a crash mid-write leaves a partial line; plus junk
    with open(path, "a", encoding="utf-8", errors="surrogateescape") as f:
        f.write("not json at all\n")
        f.write('{"noise": true}\n')      # wrong shape (no kind)
        f.write('[1, 2, 3]\n')            # not an object
        f.write('{"id":"fz:999","seq":999,"kind":"core.st')  # torn
    entries = read_journal(path)
    assert len(entries) == 200
    for (kind, eid, labels), e in zip(emitted, entries):
        assert e["kind"] == kind and e["id"] == eid
        assert e["labels"]["nest"] == labels["nest"]
    assert [e["seq"] for e in entries] == list(range(1, 201))


def test_rotation_and_seq_recovery_across_restart(tmp_path):
    """Rotation keeps one prior generation; a re-armed journal recovers
    the seq from the tail so restarted cores never reuse ids."""
    path = str(tmp_path / "rot.jsonl")
    jr = Journal(path, core="r0", max_bytes=2048)
    n = 0
    while not os.path.exists(path + ".1") and n < 500:
        n += 1
        jr.emit("epoch.bump", epoch=n, part=n % 4)
    assert os.path.exists(path + ".1"), "rotation never happened"
    for _ in range(5):  # land entries in the fresh generation too
        n += 1
        jr.emit("epoch.bump", epoch=n)
    jr.close()
    entries = read_journal(path)  # rotated generation first
    seqs = [e["seq"] for e in entries]
    assert seqs == sorted(seqs)
    assert seqs[-1] == n
    # restart: a new instance on the same path continues the id space
    jr2 = Journal(path, core="r0")
    assert jr2.seq == n
    assert jr2.emit("core.recover", owner="r0") == f"r0:{n + 1}"
    jr2.close()


def test_tail_filters_kind_prefix_doc_and_part(tmp_path):
    path = str(tmp_path / "t.jsonl")
    jr = Journal(path, core="t0")
    jr.emit("migration.seal", part=1, doc="d1")
    jr.emit("migration.commit", part=1)
    jr.emit("lease.claim", part=2)
    jr.emit("summary.commit", doc="d1", tenant="t")
    assert [e["kind"] for e in jr.tail(kind="migration.")] == [
        "migration.seal", "migration.commit"]
    assert [e["kind"] for e in jr.tail(part=2)] == ["lease.claim"]
    assert [e["kind"] for e in jr.tail(doc="d1")] == [
        "migration.seal", "summary.commit"]
    assert len(jr.tail(n=2)) == 2
    jr.close()
    # the same filters over raw entry lists (the admin --fleet path)
    entries = read_journal(path)
    assert len(filter_entries(entries, kind="migration.", part=1)) == 2


# ------------------------------------------------------------- cause links


def _entry(core, seq, kind, cause=None, epoch=None, ts=0.0, **labels):
    return {"id": f"{core}:{seq}", "seq": seq, "ts": ts, "core": core,
            "epoch": epoch, "kind": kind, "cause": cause, "labels": labels}


def test_causal_chain_root_first_opaque_and_cycles():
    entries = [
        _entry("a", 1, "operator.command"),
        _entry("a", 2, "migration.seal", cause="a:1"),
        _entry("b", 1, "migration.adopt", cause="a:2"),
        _entry("a", 3, "migration.commit", cause="b:1"),
        # opaque cause (a flight-dump path) terminates the walk cleanly
        _entry("a", 4, "flight.dump", cause="/var/dumps/x.json"),
        # a cause cycle must not hang the walker
        _entry("c", 1, "lease.claim", cause="c:2"),
        _entry("c", 2, "lease.release", cause="c:1"),
    ]
    chain = causal_chain(entries, "a:3")
    assert [e["id"] for e in chain] == ["a:1", "a:2", "b:1", "a:3"]
    assert [e["id"] for e in causal_chain(entries, "a:4")] == ["a:4"]
    cyc = causal_chain(entries, "c:1")
    assert {e["id"] for e in cyc} == {"c:1", "c:2"}  # visited once each
    assert causal_chain(entries, "nope:1") == []


def test_fleet_merge_epoch_leads_wall_clock_skew():
    """core A's wall clock runs 100 s AHEAD of core B's. The shared
    epoch must still order the cross-core handoff correctly — ts only
    breaks ties within an epoch."""
    core_a = [  # skewed fast: big ts, SMALL epochs
        _entry("a", 1, "migration.seal", epoch=5, ts=1100.0),
        _entry("a", 2, "migration.commit", epoch=7, ts=1101.0),
    ]
    core_b = [  # adopt happened between, on the slow clock
        _entry("b", 1, "migration.adopt", epoch=6, ts=1000.5),
        _entry("b", 2, "epoch.bump", epoch=6, ts=1000.9),
    ]
    merged = merge_entries([core_a, core_b])
    assert [e["id"] for e in merged] == ["a:1", "b:1", "b:2", "a:2"]
    # entries with no epoch (unbound journal) sort before any epoch
    merged2 = merge_entries([[_entry("c", 1, "core.start", ts=999.0)],
                             core_b])
    assert merged2[0]["id"] == "c:1"


# ------------------------------------------------------- history retention


def test_windowed_series_history_retirement_and_horizon():
    ws = WindowedSeries(window_s=10.0, buckets=5, history_s=60.0,
                        history_res_s=10.0)  # width 2 s, 6 slots
    ws.observe(5.0, now=5.0)
    ws.observe(7.0, now=5.5)       # same bucket
    pts = ws.history(now=6.0)      # live bucket visible immediately
    assert len(pts) == 1
    assert pts[0]["count"] == 2 and pts[0]["sum"] == 12.0
    assert pts[0]["max"] == 7.0 and pts[0]["t"] == 0.0
    # ring wrap retires the bucket into its history slot — the values
    # survive far past the 10 s live window
    ws.observe(1.0, now=25.0)      # reuses ring index 2 → retire
    pts = ws.history(now=25.0)
    assert [p["t"] for p in pts] == [0.0, 20.0]
    assert pts[0]["count"] == 2    # the retired blip, intact
    # the horizon prunes: 60 s later neither old slot is readable
    ws.observe(2.0, now=99.0)
    pts = ws.history(now=99.0)
    assert [p["t"] for p in pts] == [90.0]


def test_registry_window_history_names_and_label_filter():
    reg = MetricsRegistry()
    reg.observe_windowed("obs.hop.window_ms", 3.0, now=50.0,
                         pair="relay_to_relay")
    reg.observe_windowed("obs.hop.window_ms", 9.0, now=50.0,
                         pair="stage_to_execute")
    reg.observe_windowed("net.batch.window_ms", 1.0, now=50.0)
    hist = reg.window_history(now=50.0)
    assert set(hist) == {"obs.hop.window_ms", "net.batch.window_ms"}
    only = reg.window_history("obs.hop.window_ms", now=50.0,
                              pair="stage_to_execute")
    assert list(only) == ["obs.hop.window_ms"]
    (row,) = only["obs.hop.window_ms"]
    assert row["labels"] == {"pair": "stage_to_execute"}
    assert row["points"][0]["sum"] == 9.0


# ----------------------------------------------- appended hop ids (6/7/8)


def test_hop_pairs_full_pipeline_with_new_ids():
    """shed/stage/execute slot into pipeline order, and repeated relay
    stamps become relay_to_relay legs in arrival order."""
    hops = [(HOP_SHED, 1.0), (HOP_SUBMIT, 2.0), (HOP_RELAY, 3.0),
            (HOP_RELAY, 4.5), (HOP_ADMIT, 5.0), (HOP_DELI, 6.0),
            (HOP_STAGE, 7.0), (HOP_EXECUTE, 8.5), (HOP_FANOUT, 9.0),
            (HOP_ACK, 9.5)]
    pairs = hop_pairs(hops)
    assert pairs == [
        ("shed_to_submit", 1000.0), ("submit_to_relay", 1000.0),
        ("relay_to_relay", 1500.0), ("relay_to_admit", 500.0),
        ("admit_to_deli", 1000.0), ("deli_to_stage", 1000.0),
        ("stage_to_execute", 1500.0), ("execute_to_fanout", 500.0),
        ("fanout_to_ack", 500.0)]
    # out-of-taxonomy ids are ignored by pairs but counted for the
    # obs.trace.unknown_hops surface
    skewed = hops + [(42, 3.3), (99, 1.1)]
    assert hop_pairs(skewed) == pairs
    assert count_unknown_hops(skewed) == 2
    assert count_unknown_hops(hops) == 0


def test_stage_to_execute_folds_from_applier_backchannel():
    """An applier stage's wave stamps ride the 'applied' backchannel
    record and land in THIS core's registry as stage_to_execute."""
    fe = NetworkFrontEnd(LocalServer())
    t0 = time.time()
    rec = {"kind": "applied", "tenant": "t", "doc": "bdoc",
           "applied_seq": 4, "wave_hops": [t0, t0 + 0.012]}
    fe._on_backchannel_record(types.SimpleNamespace(value=rec))
    assert fe.applier_status[("t", "bdoc")] == 4
    series = parse_prometheus(get_registry().scrape())
    pairs = {dict(k).get("pair")
             for k in series.get("fluid_obs_hop_ms_count", {})}
    assert "stage_to_execute" in pairs


# ------------------------------------------------- relay-tree hoptail e2e


def _spawn(args):
    proc = subprocess.Popen(
        [sys.executable, "-m"] + args,
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd="/root/repo")
    line = proc.stdout.readline().strip()
    assert line.startswith("LISTENING"), line
    return proc, int(line.rsplit(":", 1)[1])


@pytest.fixture(scope="module")
def tree():
    """core ← mid gateway ← leaf gateway, separate OS processes — the
    2-level relay tree of the read-fanout plane."""
    core, core_port = _spawn(
        ["fluidframework_tpu.service.front_end", "--port", "0"])
    mid, p_mid = _spawn(["fluidframework_tpu.service.gateway",
                         "--core-port", str(core_port), "--python"])
    leaf, p_leaf = _spawn(["fluidframework_tpu.service.gateway",
                           "--upstream-gateway", f"127.0.0.1:{p_mid}"])
    try:
        yield core_port, p_mid, p_leaf
    finally:
        for proc in (leaf, mid, core):
            proc.terminate()
            proc.wait(timeout=10)


def _frame(obj: dict) -> bytes:
    body = json.dumps(obj, separators=(",", ":")).encode()
    return len(body).to_bytes(4, "big") + body


def _bin_client(port: int, doc: str):
    s = socket.create_connection(("127.0.0.1", port), timeout=10)
    s.sendall(_frame({"t": "connect", "tenant": "t", "doc": doc,
                      "rid": 1, "bin": 1}))
    buf = [b""]

    def read_frame():
        while True:
            b = buf[0]
            if len(b) >= 4:
                n = int.from_bytes(b[:4], "big")
                if len(b) >= 4 + n:
                    buf[0] = b[4 + n:]
                    return b[4:4 + n]
            chunk = s.recv(65536)
            if not chunk:
                raise ConnectionError("closed")
            buf[0] += chunk
    while binwire.is_binary(read_frame()):
        pass  # drain until the JSON connect reply
    return s, read_frame


def test_relay_to_relay_pair_through_two_tier_tree(tree):
    """A sampled columnar submit climbing leaf → mid → core collects
    one HOP_RELAY stamp per tier; the broadcast hoptail therefore
    yields a nonzero relay_to_relay leg (the relay-depth witness)."""
    _, _, p_leaf = tree
    body = binwire.encode_submit_columns(_rand_cols_ops(random.Random(8), 5))
    t_submit = time.time()
    body = binwire.append_hop(body, HOP_SUBMIT, t_submit)
    s, read = _bin_client(p_leaf, "doc-tree-hops")
    s.sendall(binwire.frame(body))
    while True:
        f = read()
        if binwire.is_binary(f) and f[1] in (binwire.FT_COLS_OPS,
                                             binwire.FT_COLS_FOPS):
            break
    s.close()
    hops = binwire.read_hoptail(f)
    ids = [h for h, _ in hops]
    assert ids.count(HOP_RELAY) == 2  # one stamp per gateway tier
    assert ids[:3] == [HOP_SUBMIT, HOP_RELAY, HOP_RELAY]
    assert {HOP_ADMIT, HOP_DELI, HOP_FANOUT} <= set(ids)
    ts = [t for _, t in hops]
    assert ts == sorted(ts) and ts[0] == t_submit
    pairs = dict(hop_pairs(hops))
    assert "relay_to_relay" in pairs
    assert pairs["relay_to_relay"] >= 0.0
    assert "submit_to_relay" in pairs and "relay_to_admit" in pairs


# -------------------------------------------- forced-migration chain e2e


def _host(shard_dir, prefer=()) -> ShardHost:
    h = ShardHost(str(shard_dir), 2, prefer=prefer, ttl_s=30.0)
    h.address = f"inproc/{h.owner_id}"
    h.poll()
    return h


def test_forced_migration_emits_linked_chain(tmp_path):
    """seal → fence → checkpoint → adopt → epoch bump → commit, every
    link present and causally connected back to the operator command —
    the same chain ``admin journal --fleet`` renders after net_smoke's
    forced migration."""
    path = str(tmp_path / "journal" / "core-test.jsonl")
    arm_journal(path, core="core-test")
    try:
        src = _host(tmp_path, prefer=(0, 1))
        tgt = _host(tmp_path)
        try:
            eng = MigrationEngine(src)
            op_id = get_journal().emit(
                "operator.command", command="admin_migrate_part",
                part=0, target=tgt.address)
            res = eng.migrate(
                0, tgt.address, cause=op_id,
                adopt=lambda k, addr: MigrationEngine(tgt).adopt(
                    k, src.owner_id, cause=eng._adopt_cause))
            assert res["target"] == tgt.address
        finally:
            for h in (src, tgt):
                for srv in list(h.servers.values()):
                    srv.log.close()
        entries = read_journal(path)
        commit = [e for e in entries
                  if e["kind"] == "migration.commit"][-1]
        chain = causal_chain(entries, commit["id"])
        assert [e["kind"] for e in chain] == [
            "operator.command", "migration.seal", "migration.fence",
            "migration.checkpoint", "migration.adopt",
            "migration.commit"]
        assert chain[0]["id"] == op_id
        # the adoption's epoch bump hangs off the adopt entry (a side
        # branch of the same chain), and the commit recorded the epoch
        adopt_id = chain[4]["id"]
        bump = [e for e in entries if e["kind"] == "epoch.bump"
                and e["cause"] == adopt_id]
        assert len(bump) == 1
        assert commit["epoch"] == bump[0]["epoch"]
        # the startup claims linked too: every lease.claim's id causes
        # one epoch.bump (the poll() path)
        claims = {e["id"] for e in entries if e["kind"] == "lease.claim"}
        assert claims
        claim_bumps = {e["cause"] for e in entries
                       if e["kind"] == "epoch.bump"
                       and e["cause"] in claims}
        assert claim_bumps == claims
    finally:
        reset_journal()
