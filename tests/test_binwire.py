"""Binary wire codec: roundtrip equality against the canonical objects.

The codec's contract is exact roundtrip — decode(encode(msgs)) must equal
the input messages field-for-field, whether an op takes the packed chanop
fast path or the generic JSON fallback (protocol/binwire.py)."""

import random

from fluidframework_tpu.protocol import binwire
from fluidframework_tpu.protocol.messages import (
    DocumentMessage,
    MessageType,
    SequencedDocumentMessage,
    TraceHop,
)


def _chanop(op):
    return {"kind": "chanop", "address": "default",
            "contents": {"address": "text", "contents": op}}


def _rand_doc_msg(rng: random.Random, cseq: int) -> DocumentMessage:
    r = rng.random()
    if r < 0.3:
        contents = _chanop({"type": 0, "pos": rng.randrange(1000),
                            "text": "abcd"[: 1 + rng.randrange(4)]})
    elif r < 0.5:
        a = rng.randrange(1000)
        contents = _chanop({"type": 1, "start": a, "end": a + 1 + rng.randrange(8)})
    elif r < 0.65:
        a = rng.randrange(1000)
        contents = _chanop({"type": 2, "start": a, "end": a + 2,
                            "props": {"k": rng.randrange(4)}})
    elif r < 0.8:
        # generic: non-chanop payload
        contents = {"kind": "attach", "blob": "x" * rng.randrange(20)}
    else:
        contents = None
    msg = DocumentMessage(
        client_sequence_number=cseq,
        reference_sequence_number=rng.randrange(500),
        type=MessageType.OPERATION if r < 0.9 else MessageType.NOOP,
        contents=contents,
        metadata={"batch": True} if rng.random() < 0.1 else None,
    )
    if rng.random() < 0.5:
        msg.traces.append(TraceHop(service="client", action="submit",
                                   timestamp=rng.random() * 1e9))
    return msg


def test_submit_roundtrip_fuzz():
    rng = random.Random(7)
    for trial in range(50):
        ops = [_rand_doc_msg(rng, i + 1) for i in range(rng.randrange(1, 40))]
        body = binwire.encode_submit(ops)
        assert binwire.is_binary(body)
        sid, out = binwire.decode_submit(body)
        assert sid is None
        assert out == ops


def test_fsubmit_roundtrip_and_rewrite():
    rng = random.Random(8)
    ops = [_rand_doc_msg(rng, i + 1) for i in range(10)]
    plain = binwire.encode_submit(ops)
    direct = binwire.encode_submit(ops, sid=1234)
    # the gateway's zero-decode rewrite produces the identical frame
    assert binwire.submit_to_fsubmit(plain, 1234) == direct
    sid, out = binwire.decode_submit(direct)
    assert sid == 1234
    assert out == ops


def _rand_seq_msg(rng: random.Random, seq: int) -> SequencedDocumentMessage:
    base = _rand_doc_msg(rng, rng.randrange(100))
    return SequencedDocumentMessage(
        client_id=None if rng.random() < 0.1 else f"client-{rng.randrange(4)}",
        sequence_number=seq,
        minimum_sequence_number=max(0, seq - rng.randrange(10)),
        client_sequence_number=base.client_sequence_number,
        reference_sequence_number=base.reference_sequence_number,
        type=base.type,
        contents=base.contents,
        metadata=base.metadata,
        origin="other-cluster" if rng.random() < 0.05 else None,
        timestamp=rng.random() * 1e9,
        traces=[TraceHop(service="deli", action="sequence",
                         timestamp=rng.random() * 1e9)],
    )


def test_ops_roundtrip_fuzz():
    rng = random.Random(9)
    for trial in range(50):
        msgs = [_rand_seq_msg(rng, s + 1)
                for s in range(rng.randrange(1, 40))]
        body = binwire.encode_ops(msgs)
        topic, out = binwire.decode_ops(body)
        assert topic is None
        assert out == msgs


def test_fops_roundtrip_and_strip():
    rng = random.Random(10)
    msgs = [_rand_seq_msg(rng, s + 1) for s in range(12)]
    body = binwire.encode_ops(msgs, topic="op/t/doc-1")
    topic, client_body = binwire.fops_strip_topic(body)
    assert topic == "op/t/doc-1"
    # the stripped body IS the direct-encoded ops frame
    assert client_body == binwire.encode_ops(msgs)
    t2, out = binwire.decode_ops(body)
    assert t2 == "op/t/doc-1"
    assert out == msgs


def test_sentinel_fields():
    """System messages carry -1 cseq/rseq and a None client id."""
    msg = SequencedDocumentMessage(
        client_id=None, sequence_number=5, minimum_sequence_number=3,
        client_sequence_number=-1, reference_sequence_number=-1,
        type=MessageType.CLIENT_JOIN, contents={"clientId": "c1"},
        timestamp=123.5)
    _, out = binwire.decode_ops(binwire.encode_ops([msg]))
    assert out == [msg]


def test_spliced_encode_equals_full_encode():
    """encode_ops_spliced (payload bytes reused from the submit frame)
    must decode to exactly what encode_ops produces for the deli
    fast-lane shape: contents objects shared with the submit decode."""
    rng = random.Random(11)
    for trial in range(20):
        ops = [_rand_doc_msg(rng, i + 1) for i in range(rng.randrange(1, 24))]
        for op in ops:
            if op.contents is None:  # splice keys by contents identity
                op.contents = {"x": 1}
        body = binwire.encode_submit(ops)
        _, decoded, spans, blob, npool = binwire.decode_submit(
            body, with_spans=True)
        msgs = [
            SequencedDocumentMessage(
                client_id="client-1", sequence_number=100 + i,
                minimum_sequence_number=90 + i,
                client_sequence_number=op.client_sequence_number,
                reference_sequence_number=op.reference_sequence_number,
                type=op.type, contents=op.contents, metadata=op.metadata,
                timestamp=12.5,
                traces=list(op.traces) + [TraceHop(
                    service="deli", action="sequence", timestamp=13.0)])
            for i, op in enumerate(decoded)
        ]
        spliced = binwire.encode_ops_spliced(msgs, spans, blob, npool)
        assert spliced is not None
        _, out = binwire.decode_ops(spliced)
        _, ref = binwire.decode_ops(binwire.encode_ops(msgs))
        assert out == ref == msgs
        # fops variant strips back to the identical ops body
        fops = binwire.encode_ops_spliced(msgs, spans, blob, npool,
                                          topic="t/doc")
        topic, stripped = binwire.fops_strip_topic(fops)
        assert topic == "t/doc"
        _, out2 = binwire.decode_ops(stripped)
        assert out2 == msgs
    # unknown contents → None (caller falls back)
    foreign = SequencedDocumentMessage(
        client_id="c", sequence_number=1, minimum_sequence_number=1,
        client_sequence_number=1, reference_sequence_number=0,
        type=MessageType.OPERATION, contents={"other": True}, timestamp=1.0)
    assert binwire.encode_ops_spliced([foreign], spans, blob, npool) is None


def test_scan_ops_matches_decode():
    """scan_ops must agree with the full decode on identity fields and
    visible-length deltas."""
    rng = random.Random(12)
    for trial in range(20):
        msgs = [_rand_seq_msg(rng, s + 1) for s in range(rng.randrange(1, 30))]
        body = binwire.encode_ops(msgs)
        scanned = list(binwire.scan_ops(body))
        assert len(scanned) == len(msgs)
        for m, (cid, seq, cseq, deli_ts, delta) in zip(msgs, scanned):
            assert cid == m.client_id
            assert seq == m.sequence_number
            assert cseq == m.client_sequence_number
            expect_deli = None
            for t in m.traces:
                if t.service == "deli":
                    expect_deli = t.timestamp
            assert deli_ts == expect_deli
            # only fast-path records carry a delta: the generic JSON
            # payload (non-OPERATION type, metadata, origin) scans as 0
            fast = (m.type is MessageType.OPERATION
                    and m.metadata is None and m.origin is None)
            env = m.contents if isinstance(m.contents, dict) else {}
            op = (env.get("contents") or {}).get("contents") \
                if fast and env.get("kind") == "chanop" else None
            if op and op.get("type") == 0:
                assert delta == len(op["text"].encode())
            elif op and op.get("type") == 1:
                assert delta == op["start"] - op["end"]
            else:
                assert delta == 0
