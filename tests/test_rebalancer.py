"""Self-driving placement: the pure bin-packing planner (determinism,
hysteresis math under a frozen clock, drain/join semantics), the epoch
table's elastic membership section, the windowed heat plumbing, and the
Rebalancer daemon's tick loop over real in-proc migrations.

Ref: lambdas-driver/kafka-service/partitionManager.ts is the reference's
consumer-group rebalance analog; the planner and its hysteresis gates
are ours (service/rebalancer.py, ARCHITECTURE.md "Self-driving
placement").
"""

import random

import pytest

from fluidframework_tpu.obs import (
    get_registry,
    reset_registry,
    sum_counter_snapshots,
)
from fluidframework_tpu.service.front_end import ShardHost
from fluidframework_tpu.service.placement_plane import (
    CORE_ACTIVE,
    CORE_DRAINED,
    CORE_DRAINING,
    EpochTable,
    MigrationEngine,
)
from fluidframework_tpu.service.rebalancer import (
    HEAT_OPS,
    PartHeat,
    Rebalancer,
    plan_rebalance,
    read_local_heat,
)
from fluidframework_tpu.utils.telemetry import Counters


def _cores(*owners, draining=()):
    return {o: {"addr": f"addr-{o}",
                "state": CORE_DRAINING if o in draining else CORE_ACTIVE}
            for o in owners}


def _plan(heat, owners, cores, last_moved=None, now=100.0, **kw):
    kw.setdefault("dwell_s", 10.0)
    kw.setdefault("budget", 2)
    kw.setdefault("improvement", 0.25)
    return plan_rebalance(heat, owners, cores, last_moved or {}, now, **kw)


# ------------------------------------------------------------- planner


def test_balanced_load_is_a_noop():
    heat = {k: PartHeat(ops=10.0) for k in range(4)}
    owners = {0: "a", 1: "a", 2: "b", 3: "b"}
    plan = _plan(heat, owners, _cores("a", "b"))
    assert plan.moves == ()
    assert plan.suppressed_hysteresis == 0
    assert plan.spread_before == plan.spread_after == 0.0


def test_hotspot_moves_to_coldest_core():
    heat = {0: PartHeat(ops=90.0), 1: PartHeat(ops=10.0),
            2: PartHeat(ops=10.0), 3: PartHeat(ops=10.0)}
    owners = {k: "a" for k in range(4)}
    plan = _plan(heat, owners, _cores("a", "b", "c"))
    assert plan.moves
    assert all(m.src == "a" for m in plan.moves)
    assert plan.spread_after < plan.spread_before
    # the viral part goes to A core, not back and forth
    dsts = {m.dst for m in plan.moves}
    assert dsts <= {"b", "c"}


def test_deterministic_under_permuted_input():
    rng = random.Random(7)
    heat = {k: PartHeat(ops=float(rng.randrange(1, 100)),
                        bytes=float(rng.randrange(0, 4096)))
            for k in range(16)}
    owners = {k: "abc"[k % 3] for k in range(16)}
    cores = _cores("a", "b", "c", "d")
    last_moved = {3: 95.0, 7: 10.0}
    baseline = _plan(heat, owners, cores, last_moved)
    for seed in range(8):
        r = random.Random(seed)

        def shuffled(d):
            items = list(d.items())
            r.shuffle(items)
            return dict(items)

        plan = _plan(shuffled(heat), shuffled(owners), shuffled(cores),
                     shuffled(last_moved))
        assert plan == baseline


def test_dwell_suppresses_then_releases_frozen_clock():
    heat = {0: PartHeat(ops=60.0), 1: PartHeat(ops=30.0)}
    owners = {0: "a", 1: "a"}
    cores = _cores("a", "b")
    # both parts moved at t=95; at t=100 their 10 s dwell still holds
    held = _plan(heat, owners, cores,
                 last_moved={0: 95.0, 1: 95.0}, now=100.0)
    assert held.moves == ()
    assert held.suppressed_hysteresis == 2
    # the same input 10 s later: dwell expired, the move is planned
    released = _plan(heat, owners, cores,
                     last_moved={0: 95.0, 1: 95.0}, now=105.1)
    assert [m.k for m in released.moves] == [0]
    assert released.suppressed_hysteresis == 0


def test_budget_caps_moves_and_counts_the_overflow():
    heat = {k: PartHeat(ops=50.0) for k in range(6)}
    owners = {k: "a" for k in range(6)}
    plan = _plan(heat, owners, _cores("a", "b"), budget=1)
    assert len(plan.moves) == 1
    assert plan.suppressed_budget == 1
    # with room, the same input plans more moves
    assert len(_plan(heat, owners, _cores("a", "b"), budget=3).moves) > 1


def test_improvement_threshold_and_slo_urgency():
    # gap of ~30% of mean: under a 50% threshold nothing moves...
    heat = {0: PartHeat(ops=40.0), 1: PartHeat(ops=5.0),
            2: PartHeat(ops=30.0), 3: PartHeat(ops=3.0)}
    owners = {0: "a", 1: "a", 2: "b", 3: "b"}
    cores = _cores("a", "b")
    calm = _plan(heat, owners, cores, improvement=0.5)
    assert calm.moves == ()
    # ...but an SLO burn halves the threshold and the move happens
    hot = _plan(heat, owners, cores, improvement=0.5, slo_hot=True)
    assert [m.k for m in hot.moves] == [1]


def test_join_absorbs_load_onto_cold_core():
    heat = {k: PartHeat(ops=20.0) for k in range(4)}
    owners = {0: "a", 1: "a", 2: "b", 3: "b"}
    # core c just registered: owns nothing, maximally cold
    plan = _plan(heat, owners, _cores("a", "b", "c"))
    assert plan.moves
    assert all(m.dst == "c" for m in plan.moves)
    assert plan.spread_after < plan.spread_before


def test_drain_empties_core_ignoring_dwell_and_threshold():
    # cold partitions and freshly-moved partitions still evacuate
    heat = {0: PartHeat(ops=0.0), 1: PartHeat(ops=1.0)}
    owners = {0: "b", 1: "b"}
    cores = _cores("a", "b", draining=("b",))
    plan = _plan(heat, owners, cores, last_moved={0: 99.9, 1: 99.9},
                 now=100.0, budget=4)
    assert sorted(m.k for m in plan.moves) == [0, 1]
    assert all(m.src == "b" and m.dst == "a" for m in plan.moves)
    # hottest part leaves first
    assert plan.moves[0].k == 1


def test_only_source_restricts_and_unlisted_cores_untouched():
    heat = {0: PartHeat(ops=90.0), 1: PartHeat(ops=90.0),
            2: PartHeat(ops=1.0)}
    owners = {0: "a", 1: "ghost", 2: "b"}
    cores = _cores("a", "b")  # "ghost" unreachable / unregistered
    plan = _plan(heat, owners, cores, only_source="b")
    assert plan.moves == ()  # b is the coldest; nothing to give
    plan = _plan(heat, owners, cores, only_source="a")
    assert all(m.src == "a" for m in plan.moves)
    assert all(m.k != 1 for m in plan.moves)  # ghost's part never planned


def test_no_move_that_overshoots_the_gap():
    # moving the only hot part would just swap the imbalance: refuse
    heat = {0: PartHeat(ops=100.0)}
    owners = {0: "a"}
    plan = _plan(heat, owners, _cores("a", "b"))
    assert plan.moves == ()


# --------------------------------------------------- elastic membership


def test_core_membership_records_without_epoch_bumps(tmp_path):
    table = EpochTable(str(tmp_path / "placement"),
                       counters=Counters("placement"))
    e0 = table.global_epoch()
    table.record_core("a", "addr-a")
    table.record_core("b", "addr-b")
    assert table.global_epoch() == e0  # membership never fences
    assert set(table.cores()) == {"a", "b"}
    assert table.core_state("a") == CORE_ACTIVE
    # drain survives re-registration (the host's poll keeps advertising)
    assert table.set_core_state("a", CORE_DRAINING)
    table.record_core("a", "addr-a2")
    assert table.core_state("a") == CORE_DRAINING
    assert table.cores()["a"]["addr"] == "addr-a2"
    # unknown owner is a refusal, not a silent pending mark
    assert not table.set_core_state("nobody", CORE_DRAINING)
    table.remove_core("a")
    assert table.core_state("a") is None


def test_draining_host_stops_claiming(tmp_path):
    host = ShardHost(str(tmp_path), 2, prefer=(0, 1), ttl_s=30.0)
    host.address = f"inproc/{host.owner_id}"
    host.poll()
    assert sorted(host.servers) == [0, 1]
    assert host.table.core_state(host.owner_id) == CORE_ACTIVE
    host.table.set_core_state(host.owner_id, CORE_DRAINING)
    host.poll()
    assert host.draining
    # release everything; a draining host must not re-claim
    host.release_all()
    host.poll()
    assert host.servers == {}
    for s in ():
        s.log.close()


# ----------------------------------------------------------- heat read


def test_windowed_heat_read_is_exact(monkeypatch):
    reset_registry()
    try:
        reg = get_registry()
        for _ in range(50):
            reg.observe_windowed(HEAT_OPS, 2.0, now=1000.0, part="0")
        reg.observe_windowed(HEAT_OPS, 7.0, now=1000.0, part="1")
        heat = read_local_heat([0, 1, 2], now=1000.0, registry=reg)
        # exact sums (no reservoir sampling loss), folded to rates
        assert heat[0].ops == pytest.approx(100.0 / 10.0)
        assert heat[1].ops == pytest.approx(7.0 / 10.0)
        assert heat[2].ops == 0.0  # owned-but-cold still present
    finally:
        reset_registry()


def test_sum_counter_snapshots_fleet_totals():
    total = sum_counter_snapshots([
        {"placement.rebalance.ticks": 5,
         "placement.rebalance.migrations_issued": 1},
        {"placement.rebalance.ticks": 7},
        {},
    ])
    assert total == {"placement.rebalance.ticks": 12,
                     "placement.rebalance.migrations_issued": 1}


# ------------------------------------------------------- daemon ticks


def _two_hosts(tmp_path, n=2):
    a = ShardHost(str(tmp_path), n, prefer=range(n), ttl_s=30.0)
    a.address = f"inproc/{a.owner_id}"
    a.poll()
    b = ShardHost(str(tmp_path), n, ttl_s=30.0)
    b.address = f"inproc/{b.owner_id}"
    b.poll()
    return a, b


def _rebalancer_for(src, tgt, heat_by_part, pc, **kw):
    eng_src = MigrationEngine(src, counters=pc)
    eng_tgt = MigrationEngine(tgt, counters=pc)

    def heat_reader(owners, cores, now):
        heat = {k: heat_by_part.get(k, PartHeat()) for k in owners}
        return heat, set(cores)

    def actuate(k, target_addr):
        eng_src.migrate(
            k, target_addr,
            adopt=lambda k, addr: eng_tgt.adopt(k, src.owner_id))

    kw.setdefault("dwell_s", 10.0)
    kw.setdefault("budget", 1)
    kw.setdefault("improvement", 0.25)
    return Rebalancer(src, eng_src, heat_reader=heat_reader,
                      actuate=actuate, counters=pc, **kw)


def test_tick_migrates_hot_partition_for_real(tmp_path):
    pc = Counters("placement")
    a, b = _two_hosts(tmp_path)
    reb = _rebalancer_for(a, b, {0: PartHeat(ops=90.0),
                                 1: PartHeat(ops=10.0)}, pc)
    plan = reb.tick(now=100.0)
    assert [m.k for m in plan.moves] == [0]
    assert 0 in b.servers and 0 not in a.servers
    assert pc.snapshot()["placement.rebalance.migrations_issued"] == 1
    assert pc.snapshot()["placement.rebalance.ticks"] == 1
    # the next tick sees the move it just made: dwell holds part 0
    plan2 = reb.tick(now=101.0)
    assert plan2.moves == ()
    assert reb.flap_count() == 0
    for h in (a, b):
        for s in h.servers.values():
            s.log.close()


def test_tick_drains_and_marks_drained(tmp_path):
    pc = Counters("placement")
    a, b = _two_hosts(tmp_path)
    a.table.set_core_state(a.owner_id, CORE_DRAINING)
    a.poll()
    assert a.draining
    reb = _rebalancer_for(a, b, {0: PartHeat(ops=5.0),
                                 1: PartHeat(ops=5.0)}, pc, budget=2)
    reb.tick(now=100.0)
    assert a.servers == {}
    assert sorted(b.servers) == [0, 1]
    reb.tick(now=101.0)  # the empty tick flips the membership state
    assert a.table.core_state(a.owner_id) == CORE_DRAINED
    st = reb.status()
    assert st["draining"] and st["drained"]
    for s in b.servers.values():
        s.log.close()


def test_dwell_clock_follows_peer_epoch_bumps(tmp_path):
    """A move issued by ANOTHER core shows up as an epoch bump; this
    core's dwell clock must honor it without any gossip."""
    pc = Counters("placement")
    a, b = _two_hosts(tmp_path)
    hb = {0: PartHeat(), 1: PartHeat()}
    reb = _rebalancer_for(a, b, hb, pc)
    assert reb.tick(now=50.0).moves == ()  # cold baseline: epochs noted
    # both parts round-trip a→b→a by EXTERNAL decision (a peer's moves,
    # as this core sees them: pure epoch bumps in the shared table)
    eng_a = MigrationEngine(a, counters=pc)
    eng_b = MigrationEngine(b, counters=pc)
    for k in (0, 1):
        eng_a.migrate(k, b.address,
                      adopt=lambda k, addr: eng_b.adopt(k, a.owner_id))
        eng_b.migrate(k, a.address,
                      adopt=lambda k, addr: eng_a.adopt(k, b.owner_id))
    # the load turns hot AFTER those moves: profitable but dwell-held
    hb[0] = PartHeat(ops=60.0)
    hb[1] = PartHeat(ops=30.0)
    plan = reb.tick(now=51.0)
    assert plan.moves == ()
    assert plan.suppressed_hysteresis == 2
    # dwell expiry releases the move
    assert [m.k for m in reb.tick(now=70.0).moves] == [0]
    for h in (a, b):
        for s in h.servers.values():
            s.log.close()
