"""Incremental-summary producer: recursive SummaryTree emit with
per-channel handle reuse, chunked merge-tree snapshots, byte reduction
vs full upload, and boot from the incremental chain (local + network).

Ref: ContainerRuntime.summarize (containerRuntime.ts:1424), per-channel
reuse decisions (channel contexts), ISummaryHandle (protocol-definitions
summary.ts), chunked emit (merge-tree snapshotV1.ts:87).
"""

import pytest

from fluidframework_tpu.driver import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.runtime.summarizer import SummaryManager
from fluidframework_tpu.service import LocalServer


@pytest.fixture
def server():
    return LocalServer()


@pytest.fixture
def loader(server):
    return Loader(LocalDocumentServiceFactory(server))


def boot_with_channels(loader):
    c1 = loader.resolve("t", "doc")
    ds = c1.runtime.create_data_store("default")
    text = ds.create_channel("text", "shared-string")
    kv = ds.create_channel("kv", "shared-map")
    text.insert_text(0, "hello world")
    kv.set("a", 1)
    return c1, text, kv


def test_second_summary_reuses_unchanged_channels(server, loader):
    c1, text, kv = boot_with_channels(loader)
    sm = SummaryManager(c1, max_ops=10**9)  # manual attempts only
    h1 = sm.summarize_now()
    assert sm.summaries_acked == 1 and sm.last_acked_handle == h1
    assert server.storage_stats["handles_reused"] == 0  # first is full

    # touch ONLY the text channel; the map must ride as a handle
    text.insert_text(0, ">> ")
    blobs_before = server.storage_stats["blobs_written"]
    h2 = sm.summarize_now()
    assert sm.summaries_acked == 2 and sm.last_acked_handle == h2
    assert server.storage_stats["handles_reused"] >= 1

    # third cycle with NOTHING changed: every channel is a handle
    reused_before = server.storage_stats["handles_reused"]
    sm.summarize_now()
    assert sm.summaries_acked == 3
    assert server.storage_stats["handles_reused"] >= reused_before + 2

    # a fresh client boots from the incremental chain
    c2 = loader.resolve("t", "doc")
    ds2 = c2.runtime.get_data_store("default")
    assert ds2.get_channel("text").get_text() == ">> hello world"
    assert ds2.get_channel("kv").get("a") == 1
    assert blobs_before > 0


def test_incremental_upload_writes_fewer_bytes(server, loader):
    """The incremental upload's new-blob bytes must be well under the
    full-tree bytes when only one small channel changed."""
    c1 = loader.resolve("t", "doc")
    ds = c1.runtime.create_data_store("default")
    text = ds.create_channel("text", "shared-string")
    kv = ds.create_channel("kv", "shared-map")
    for i in range(40):
        text.insert_text(0, f"paragraph {i} of substantial content. ")
    sm = SummaryManager(c1, max_ops=10**9)

    before = server.storage_stats["blobs_written"]
    sm.summarize_now()
    full_blobs = server.storage_stats["blobs_written"] - before

    kv.set("tiny", 1)  # only the map changes
    before = server.storage_stats["blobs_written"]
    sm.summarize_now()
    incr_blobs = server.storage_stats["blobs_written"] - before
    # the big text channel (multiple chunk blobs) was NOT re-uploaded
    assert incr_blobs < full_blobs


def test_chunked_mergetree_summary_round_trips(server, loader):
    """A string with > SUMMARY_CHUNK_SEGMENTS segments emits a chunked
    subtree, and a fresh client reassembles it correctly."""
    c1 = loader.resolve("t", "doc")
    ds = c1.runtime.create_data_store("default")
    text = ds.create_channel("text", "shared-string")
    text.SUMMARY_CHUNK_SEGMENTS = 8  # force chunking at test scale
    for i in range(30):
        text.insert_text(len(text.get_text()) // 2, f"[{i}]")
    text.annotate_range(0, 5, {"bold": True})
    sm = SummaryManager(c1, max_ops=10**9)
    before = server.storage_stats["blobs_written"]
    sm.summarize_now()
    # header + several chunks, not one monolith
    assert server.storage_stats["blobs_written"] - before > 3

    c2 = loader.resolve("t", "doc")
    s2 = c2.runtime.get_data_store("default").get_channel("text")
    assert s2.get_text() == text.get_text()
    assert s2.client.get_properties_at(0).get("bold") is True
    # and the loaded replica stays live
    s2.insert_text(0, "x")
    assert text.get_text() == s2.get_text()


def test_incremental_chain_over_network_driver():
    """Summaries upload as wire-encoded trees through the TCP storage RPC
    and a fresh network client boots from the chain."""
    import subprocess
    import sys

    from fluidframework_tpu.driver.network import NetworkDocumentServiceFactory

    proc = subprocess.Popen(
        [sys.executable, "-m", "fluidframework_tpu.service.front_end",
         "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd="/root/repo",
    )
    try:
        line = proc.stdout.readline().strip()
        port = int(line.rsplit(":", 1)[1])
        loader = Loader(NetworkDocumentServiceFactory("127.0.0.1", port))
        c1 = loader.resolve("t", "netsumdoc")
        ds = c1.runtime.create_data_store("default")
        text = ds.create_channel("text", "shared-string")
        kv = ds.create_channel("kv", "shared-map")
        text.insert_text(0, "over the wire")
        kv.set("k", "v")

        import time

        def wait_for(cond, timeout=10.0):
            t0 = time.time()
            while time.time() - t0 < timeout:
                if cond():
                    return True
                time.sleep(0.02)
            return False

        sm = SummaryManager(c1, max_ops=10**9)
        assert wait_for(lambda: c1.runtime.pending.count == 0)
        sm.summarize_now()
        assert wait_for(lambda: sm.summaries_acked == 1)
        text.insert_text(0, "!! ")
        assert wait_for(lambda: c1.runtime.pending.count == 0)
        sm.summarize_now()  # kv rides as a handle through the wire codec
        assert wait_for(lambda: sm.summaries_acked == 2)

        c2 = loader.resolve("t", "netsumdoc")
        ds2 = c2.runtime.get_data_store("default")
        assert ds2.get_channel("text").get_text() == "!! over the wire"
        assert ds2.get_channel("kv").get("k") == "v"
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def test_handle_reuse_survives_summarizer_restart(server, loader):
    """A summarizer that BOOTED from a summary (cold channels, no op
    traffic) must still reach handle reuse once its own first summary is
    acked — loaded channels carry the boot snapshot's capture seq."""
    c1, text, kv = boot_with_channels(loader)
    sm1 = SummaryManager(c1, max_ops=10**9)
    sm1.summarize_now()
    c1.close()

    c2 = loader.resolve("t", "doc")  # boots from the acked summary
    assert c2._base_snapshot is not None
    sm2 = SummaryManager(c2, max_ops=10**9)
    # first post-boot summary: capture seq of the head is unknown to this
    # manager, so it uploads full — and gets acked
    sm2.summarize_now()
    assert sm2.summaries_acked == 1
    reused_before = server.storage_stats["handles_reused"]
    # second summary with nothing touched: every channel rides as handle
    sm2.summarize_now()
    assert sm2.summaries_acked == 2
    assert server.storage_stats["handles_reused"] >= reused_before + 2
