"""End-to-end: real client stack (loader→runtime→DDS) against the real
service lambdas in one process — the local-driver test backbone.

Ref: packages/test/end-to-end-tests (sharedStringEndToEndTests.spec.ts,
mapEndToEndTests.spec.ts, opsOnReconnect.spec.ts, container.spec.ts) over
LocalDeltaConnectionServer (SURVEY §4).
"""

import pytest

from fluidframework_tpu.driver import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.service import LocalServer


@pytest.fixture
def server():
    return LocalServer()


@pytest.fixture
def loader(server):
    return Loader(LocalDocumentServiceFactory(server))


def boot_two(loader, doc="doc"):
    c1 = loader.resolve("t", doc)
    c2 = loader.resolve("t", doc)
    return c1, c2


def test_shared_string_two_clients_converge(loader):
    c1, c2 = boot_two(loader)
    ds1 = c1.runtime.create_data_store("default")
    s1 = ds1.create_channel("text", "shared-string")
    s1.insert_text(0, "hello world")

    # c2 received the attach ops and materialized the channel
    s2 = c2.runtime.get_data_store("default").get_channel("text")
    assert s2.get_text() == "hello world"

    s2.insert_text(5, ", brave")
    s1.remove_text(0, 5)
    s1.insert_text(0, "HELLO")
    assert s1.get_text() == s2.get_text() == "HELLO, brave world"


def test_shared_string_concurrent_inserts_deterministic(server, loader):
    # pause delivery to force true concurrency, then drain
    server._auto_drain = False
    c1, c2 = boot_two(loader)
    server.drain()
    ds1 = c1.runtime.create_data_store("default")
    server.drain()
    s1 = ds1.create_channel("text", "shared-string")
    server.drain()
    s1.insert_text(0, "base")
    server.drain()
    s2 = c2.runtime.get_data_store("default").get_channel("text")
    # concurrent edits at the same position
    s1.insert_text(0, "AA")
    s2.insert_text(0, "BB")
    server.drain()
    assert s1.get_text() == s2.get_text()
    assert sorted([s1.get_text().count("AA"), s1.get_text().count("BB")]) == [1, 1]


def test_shared_map_converges_and_pending_local_wins(server, loader):
    c1, c2 = boot_two(loader)
    m1 = c1.runtime.create_data_store("default").create_channel("kv", "shared-map")
    m2 = c2.runtime.get_data_store("default").get_channel("kv")
    m1.set("a", 1)
    assert m2.get("a") == 1

    server._auto_drain = False
    m1.set("x", "from-1")
    m2.set("x", "from-2")
    server.drain()
    # both sequenced; the later one in the total order wins everywhere
    assert m1.get("x") == m2.get("x") == "from-2"


def test_shared_map_remote_clear_preserves_pending(server, loader):
    c1, c2 = boot_two(loader)
    m1 = c1.runtime.create_data_store("default").create_channel("kv", "shared-map")
    m2 = c2.runtime.get_data_store("default").get_channel("kv")
    m1.set("a", 1)
    server._auto_drain = False
    m2.clear()
    m1.set("b", 2)  # in flight when the clear lands
    server.drain()
    assert m1.get("a") is None and m2.get("a") is None
    assert m1.get("b") == m2.get("b") == 2


def test_late_joiner_catches_up_from_op_history(loader):
    c1 = loader.resolve("t", "doc")
    s1 = c1.runtime.create_data_store("default").create_channel("text", "shared-string")
    s1.insert_text(0, "written before client 2 existed")
    c2 = loader.resolve("t", "doc")
    s2 = c2.runtime.get_data_store("default").get_channel("text")
    assert s2.get_text() == "written before client 2 existed"
    assert c2.existing


def test_reconnect_resubmits_pending_string_ops(server, loader):
    c1, c2 = boot_two(loader)
    s1 = c1.runtime.create_data_store("default").create_channel("text", "shared-string")
    s1.insert_text(0, "shared ")
    s2 = c2.runtime.get_data_store("default").get_channel("text")

    c1.disconnect()
    s1.insert_text(len(s1.get_text()), "offline-edit")  # buffered, not sent
    s2.insert_text(0, "remote ")  # sequenced while c1 is away
    assert "offline-edit" not in s2.get_text()
    c1.reconnect()
    assert s1.get_text() == s2.get_text() == "remote shared offline-edit"


def test_reconnect_resubmits_pending_map_ops(server, loader):
    c1, c2 = boot_two(loader)
    m1 = c1.runtime.create_data_store("default").create_channel("kv", "shared-map")
    m2 = c2.runtime.get_data_store("default").get_channel("kv")
    c1.disconnect()
    m1.set("offline", True)
    m2.set("online", True)
    c1.reconnect()
    for m in (m1, m2):
        assert m.get("offline") is True and m.get("online") is True


def test_ops_in_flight_at_disconnect_are_not_duplicated(server, loader):
    # op reaches the server, client drops BEFORE seeing the ack, reconnects:
    # catch-up must ack it as our own (old client id), not re-apply or
    # resubmit it (the double-apply hazard SURVEY §5.3 reconnect rebase)
    server._auto_drain = False
    c1, c2 = boot_two(loader)
    server.drain()
    s1 = c1.runtime.create_data_store("default").create_channel("text", "shared-string")
    server.drain()
    s1.insert_text(0, "x")
    # the op is queued server-side; sequence it but do NOT deliver yet:
    # c1 drops first
    c1.disconnect()
    server.drain()
    c1.reconnect()
    server.drain()
    s2 = c2.runtime.get_data_store("default").get_channel("text")
    assert s1.get_text() == s2.get_text() == "x"


def test_channel_created_offline_attaches_on_reconnect(server, loader):
    c1, c2 = boot_two(loader)
    ds1 = c1.runtime.create_data_store("default")
    c1.disconnect()
    kv = ds1.create_channel("kv2", "shared-map")  # attach op is pending
    kv.set("a", 1)
    c1.reconnect()
    m2 = c2.runtime.get_data_store("default").get_channel("kv2")
    assert m2.get("a") == 1


def test_reconnect_before_inflight_op_is_sequenced(server, loader):
    # op still QUEUED (unsequenced) server-side when the client reconnects:
    # the old copy sequences before our new join, the replay fence must
    # ack it instead of resubmitting a duplicate
    server._auto_drain = False
    c1, c2 = boot_two(loader)
    server.drain()
    s1 = c1.runtime.create_data_store("default").create_channel("text", "shared-string")
    server.drain()
    s1.insert_text(0, "x")  # queued in the raw log, NOT sequenced yet
    c1.disconnect()
    c1.reconnect()
    server.drain()  # sequences: old insert, leave, join — then replay runs
    s2 = c2.runtime.get_data_store("default").get_channel("text")
    assert s1.get_text() == s2.get_text() == "x"
    assert c1.runtime.pending.count == 0


def test_quorum_membership_tracks_joins_and_leaves(loader):
    c1, c2 = boot_two(loader)
    assert set(c1.audience) == {c1.client_id, c2.client_id}
    c2.close()
    assert set(c1.audience) == {c1.client_id}


def test_quorum_proposal_commits_via_msn(loader):
    c1, c2 = boot_two(loader)
    c1.propose("code", "pkg@1.0")
    # proposal commits once msn passes its seq: both clients must speak
    c1.runtime.create_data_store("a")
    c2.runtime.create_data_store("b")
    c1.runtime.create_data_store("c")
    c2.runtime.create_data_store("d")
    assert c1.quorum.get("code") == "pkg@1.0"
    assert c2.quorum.get("code") == "pkg@1.0"


def test_boot_from_snapshot_plus_tail(server, loader):
    from fluidframework_tpu.runtime.summarizer import SummaryManager

    c1 = loader.resolve("t", "doc")
    sm = SummaryManager(c1, max_ops=10_000)
    s1 = c1.runtime.create_data_store("default").create_channel("text", "shared-string")
    s1.insert_text(0, "summarized")
    sm.summarize_now()  # upload + SUMMARIZE op + scribe ack
    # more ops after the summary → the tail
    s1.insert_text(0, "tail ")

    c3 = loader.resolve("t", "doc")
    s3 = c3.runtime.get_data_store("default").get_channel("text")
    assert s3.get_text() == "tail summarized"
    assert c3.existing
    # and the booted replica is live: new edits converge both ways
    s3.insert_text(0, "c3 ")
    s1.insert_text(len(s1.get_text()), " end")
    assert s1.get_text() == s3.get_text() == "c3 tail summarized end"


def test_signals_between_containers(loader):
    c1, c2 = boot_two(loader)
    got = []
    c2.on_signal = lambda sig: got.append((sig.client_id, sig.content))
    c1.submit_signal({"presence": "typing"})
    assert got == [(c1.client_id, {"presence": "typing"})]
