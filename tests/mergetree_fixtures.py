"""Shared merge-tree test fixtures.

Mirrors the reference's test harness (SURVEY.md §4): TestClient +
TestServer (testServer.ts) — a fake ordering service that assigns sequence
numbers while preserving each client's FIFO submit order, delivering every
sequenced message to all clients (including the author, as its ack).
"""

from __future__ import annotations

import random
from collections import deque

from fluidframework_tpu.mergetree import MergeTreeClient, op_to_wire, op_from_wire
from fluidframework_tpu.protocol import MessageType, SequencedDocumentMessage


class FarmClient:
    """A MergeTreeClient plus its outbound queue of unsequenced ops."""

    def __init__(self, name: str):
        self.client = MergeTreeClient(name)
        self.name = name
        self.client_seq = 0
        self.outbound: deque[dict] = deque()

    def submit(self, op) -> None:
        self.client_seq += 1
        self.outbound.append(
            {
                "clientSeq": self.client_seq,
                "refSeq": self.client.tree.current_seq,
                "contents": op_to_wire(op),
            }
        )

    # convenience local-op helpers that auto-submit
    def insert(self, pos: int, text: str, props=None):
        self.submit(self.client.insert_text_local(pos, text, props))

    def remove(self, start: int, end: int):
        self.submit(self.client.remove_range_local(start, end))

    def annotate(self, start: int, end: int, props: dict):
        self.submit(self.client.annotate_range_local(start, end, props))

    def text(self) -> str:
        return self.client.get_text()

    def rich_text(self):
        """(char, frozen props) sequence — convergence must include props."""
        out = []
        view = self.client.local_view()
        for seg in self.client.tree.segments:
            if seg.visible_in(view):
                if seg.is_marker:
                    out.append(("￼", tuple(sorted(seg.props.items()))))
                else:
                    p = tuple(sorted(seg.props.items()))
                    out.extend((ch, p) for ch in seg.text)
        return out


class FarmServer:
    """Fake sequencer: random cross-client interleaving, per-client FIFO,
    deli-style msn = min of connected clients' last reference seq."""

    def __init__(self, clients: list[FarmClient], rng: random.Random):
        self.clients = clients
        self.rng = rng
        self.seq = 0
        self.client_ref = {c.name: 0 for c in clients}

    def pending_count(self) -> int:
        return sum(len(c.outbound) for c in self.clients)

    def sequence_one(self) -> bool:
        ready = [c for c in self.clients if c.outbound]
        if not ready:
            return False
        sender = self.rng.choice(ready)
        raw = sender.outbound.popleft()
        self.seq += 1
        self.client_ref[sender.name] = max(
            self.client_ref[sender.name], raw["refSeq"]
        )
        msn = min(self.client_ref.values())
        msg = SequencedDocumentMessage(
            client_id=sender.name,
            sequence_number=self.seq,
            minimum_sequence_number=msn,
            client_sequence_number=raw["clientSeq"],
            reference_sequence_number=raw["refSeq"],
            type=MessageType.OPERATION,
            contents=raw["contents"],
        )
        for c in self.clients:
            c.client.apply_msg(msg)
        return True

    def sequence_all(self) -> None:
        while self.sequence_one():
            pass


def assert_converged(clients: list[FarmClient], context: str = "") -> None:
    base = clients[0]
    for other in clients[1:]:
        if base.rich_text() != other.rich_text():
            lines = [f"DIVERGENCE {context}"]
            for c in clients:
                lines.append(f"  {c.name}: {c.text()!r}")
                for seg in c.client.tree.segments:
                    lines.append(f"    {seg!r}")
            raise AssertionError("\n".join(lines))


def random_op(fc: FarmClient, rng: random.Random, allow_annotate: bool = True) -> None:
    """One random local op, weighted toward inserts so docs grow."""
    n = fc.client.get_length()
    roll = rng.random()
    if n == 0 or roll < 0.55:
        pos = rng.randint(0, n)
        text = "".join(rng.choice("abcdefgh") for _ in range(rng.randint(1, 4)))
        props = None
        if allow_annotate and rng.random() < 0.2:  # insert-with-props
            props = {"k": rng.randint(0, 3)}
        fc.insert(pos, text, props)
    elif roll < 0.85 or not allow_annotate:
        start = rng.randint(0, n - 1)
        end = rng.randint(start + 1, min(n, start + 5))
        fc.remove(start, end)
    elif roll < 0.95:
        start = rng.randint(0, n - 1)
        end = rng.randint(start + 1, min(n, start + 6))
        fc.annotate(start, end, {"k": rng.randint(0, 3)})
    else:  # key deletion
        start = rng.randint(0, n - 1)
        end = rng.randint(start + 1, min(n, start + 6))
        fc.annotate(start, end, {"k": None})
