"""Socket-tier batching specs: ingress coalescing, drain-batched
serving with dirty-shard flushing, and encode-once fan-out — the three
amortization points ARCHITECTURE.md "Socket-tier batching" describes,
plus the satellite contracts that ride the same PR (placement lease
races, durable-log binary-path key matching)."""

from __future__ import annotations

import json
import socket
import threading
import time

import pytest

from fluidframework_tpu.driver import NetworkDocumentServiceFactory
from fluidframework_tpu.protocol.messages import (
    DocumentMessage,
    MessageType,
)
from fluidframework_tpu.protocol.serialization import message_to_dict
from fluidframework_tpu.service import LocalServer, NetworkFrontEnd
from fluidframework_tpu.service.durable_log import DurableLog


def wait_for(pred, timeout=10.0, interval=0.005):
    t0 = time.time()
    while time.time() - t0 < timeout:
        try:
            if pred():
                return True
        except (KeyError, IndexError):
            pass
        time.sleep(interval)
    return False


@pytest.fixture
def front_end():
    fe = NetworkFrontEnd(LocalServer()).start_background()
    yield fe
    fe.stop()


def _op(cseq, contents, ref_seq=0):
    return DocumentMessage(
        client_sequence_number=cseq, reference_sequence_number=ref_seq,
        type=MessageType.OPERATION, contents=contents)


def _own_ops(seen, cid):
    return [m for m in seen
            if m.client_id == cid and m.type == MessageType.OPERATION]


# ------------------------------------------------- driver coalescing

def test_driver_coalescer_preserves_order_and_reduces_frames(front_end):
    """A rapid burst through a forced coalescing window must arrive as
    fewer frames than ops, in submit order, with every op sequenced."""
    factory = NetworkDocumentServiceFactory("127.0.0.1", front_end.port)
    conn = factory.create_document_service(
        "t", "coal").connect_to_delta_stream()
    conn.coalesce_window = 0.002
    seen = []
    conn.on_op = seen.append
    n = 120
    for i in range(n):
        conn.submit([_op(i + 1, {"i": i})])
    assert wait_for(lambda: len(_own_ops(seen, conn.client_id)) >= n)
    mine = _own_ops(seen, conn.client_id)
    assert [m.client_sequence_number for m in mine] == list(range(1, n + 1))
    assert [m.contents["i"] for m in mine] == list(range(n))
    snap = factory.counters.snapshot()
    assert snap.get("driver.submit.coalesced", 0) > 0
    assert 0 < snap["driver.submit.frames"] < snap["driver.submit.ops"]
    conn.close()


def test_driver_close_drains_pending_coalesced_ops(front_end):
    """Ops buffered in the coalescer when close() is called must still
    reach the server — handing an op to submit() is a delivery promise,
    window or no window."""
    factory = NetworkDocumentServiceFactory("127.0.0.1", front_end.port)
    conn = factory.create_document_service(
        "t", "drain").connect_to_delta_stream()
    watcher = factory.create_document_service(
        "t", "drain").connect_to_delta_stream()
    seen = []
    watcher.on_op = seen.append
    conn.coalesce_window = 5.0  # far longer than the test: close must flush
    conn.submit([_op(1, {"last": "words"})])
    cid = conn.client_id
    conn.close()
    assert wait_for(lambda: len(_own_ops(seen, cid)) >= 1)
    assert _own_ops(seen, cid)[0].contents == {"last": "words"}
    watcher.close()


# ------------------------------------------- encode-once fan-out cache

def test_fanout_cache_never_serves_stale_frame_across_docs(front_end):
    """Two docs with aligned sequence numbers: the one-entry fan-out
    cache must never hand doc B's subscribers a frame encoded for doc A
    (the cache key includes the doc, not just (seq, len))."""
    factory = NetworkDocumentServiceFactory("127.0.0.1", front_end.port)
    subs = {}
    seen = {}
    for doc in ("doc-a", "doc-b"):
        seen[doc] = []
        subs[doc] = [factory.create_document_service(
            "t", doc).connect_to_delta_stream() for _ in range(2)]
        for c in subs[doc]:
            c.on_op = seen[doc].append
    writers = {doc: subs[doc][0] for doc in subs}
    # both docs are at the same seq position now (two joins each);
    # alternating submits keep (first_seq, len) colliding across docs
    for i in range(5):
        for doc in ("doc-a", "doc-b"):
            writers[doc].submit([_op(i + 1, {"from": doc, "i": i})])

    def got_all():
        # each op reaches BOTH subscribers of its doc
        return all(
            len(_own_ops(seen[doc], writers[doc].client_id)) >= 10
            for doc in subs)
    assert wait_for(got_all)
    for doc in subs:
        for m in seen[doc]:
            if m.type == MessageType.OPERATION:
                assert m.contents["from"] == doc, \
                    f"doc {doc} subscriber got a frame for " \
                    f"{m.contents['from']}: stale fan-out cache"
    assert front_end.counters.snapshot().get("net.fanout.cache_hits",
                                             0) > 0
    for doc in subs:
        for c in subs[doc]:
            c.close()


# -------------------------- drain-batched serving + dirty-shard flush

def test_drain_batch_flush_keeps_appends_visible_before_ack(tmp_path):
    """The batched flush must run BEFORE the batch's replies drain: once
    a client observes its op sequenced, a readonly consumer process (the
    stage-poll role) must already see the append — flush elision may
    skip clean batches, never reorder ack past append."""
    log_dir = str(tmp_path / "log")
    front = NetworkFrontEnd(
        LocalServer(log=DurableLog(log_dir))).start_background()
    try:
        factory = NetworkDocumentServiceFactory("127.0.0.1", front.port)
        conn = factory.create_document_service(
            "t", "doc").connect_to_delta_stream()
        seen = []
        conn.on_op = seen.append
        ro = DurableLog(log_dir, readonly=True)
        base = ro.refresh_topic("deltas/t/doc")
        n = 3
        for i in range(n):
            conn.submit([_op(i + 1, {"i": i})])
        assert wait_for(lambda: len(_own_ops(seen, conn.client_id)) >= n)
        # no retry loop here, deliberately: the acks above are the fence
        assert ro.refresh_topic("deltas/t/doc") >= base + n
        ro.close()
        conn.close()
    finally:
        front.stop()


def test_ping_only_batch_elides_the_flush(tmp_path):
    front = NetworkFrontEnd(
        LocalServer(log=DurableLog(str(tmp_path / "log"))))
    front.start_background()
    try:
        s = socket.create_connection(("127.0.0.1", front.port),
                                     timeout=10)
        _send(s, {"t": "ping"})
        assert _read_until(s, lambda f: f.get("t") == "pong")
        # the pong is written DURING batch handling, the counters land
        # right after it — poll rather than racing the loop thread
        assert wait_for(
            lambda: front.counters.snapshot().get("net.flush.elided",
                                                  0) > 0)
        assert front.counters.snapshot().get("net.flush.performed",
                                             0) == 0
        s.close()
    finally:
        front.stop()


# ------------------------------------------------- raw-socket ingress

def _send(s, obj):
    body = json.dumps(obj, separators=(",", ":")).encode()
    s.sendall(len(body).to_bytes(4, "big") + body)


def _read_until(s, pred, timeout=10.0):
    s.settimeout(timeout)
    buf = b""
    hits = []
    while True:
        while len(buf) >= 4:
            n = int.from_bytes(buf[:4], "big")
            if len(buf) < 4 + n:
                break
            frame, buf = json.loads(buf[4:4 + n].decode()), buf[4 + n:]
            hits.append(frame)
            if pred(frame):
                return hits
        chunk = s.recv(65536)
        if not chunk:
            return None
        buf += chunk


def test_ingress_burst_is_coalesced_and_fully_acked(front_end):
    """Many frames landing in one TCP wave must be served as one batch
    (net.ingress.coalesced rises) with no frame dropped: every submit
    still comes back sequenced."""
    s = socket.create_connection(("127.0.0.1", front_end.port),
                                 timeout=10)
    _send(s, {"t": "connect", "tenant": "t", "doc": "burst", "rid": 1})
    hits = _read_until(s, lambda f: f.get("rid") == 1)
    cid = hits[-1]["clientId"]
    n = 12
    body = b""
    for i in range(n):
        m = json.dumps(
            {"t": "submit", "ops": [message_to_dict(_op(i + 1, {"i": i}))]},
            separators=(",", ":")).encode()
        body += len(m).to_bytes(4, "big") + m
    before = front_end.counters.snapshot().get("net.ingress.coalesced", 0)
    s.sendall(body)  # ONE wave: the drain loop must slurp all 12

    acked = []

    def saw_all(frame):
        if frame.get("t") == "ops":
            for m in frame["msgs"]:
                if m.get("client_id", m.get("clientId")) == cid:
                    acked.append(m)
        return len(acked) >= n
    assert _read_until(s, saw_all) is not None
    # acks are written during handling, the batch counters right after:
    # poll instead of racing the loop thread
    assert wait_for(
        lambda: front_end.counters.snapshot().get(
            "net.ingress.coalesced", 0) > before)
    s.close()


def test_admin_counters_rpc_exposes_batching_counters(front_end):
    s = socket.create_connection(("127.0.0.1", front_end.port),
                                 timeout=10)
    _send(s, {"t": "ping"})
    assert _read_until(s, lambda f: f.get("t") == "pong")
    _send(s, {"t": "admin_counters", "rid": 9})
    hits = _read_until(s, lambda f: f.get("rid") == 9)
    counters = hits[-1]["counters"]
    assert counters.get("net.ingress.frames", 0) > 0
    assert all(isinstance(v, int) for v in counters.values())
    s.close()


# --------------------------------------- durable log binary key match

def _mini_batch():
    import numpy as np

    from fluidframework_tpu.service.array_batch import (
        ArrayBoxcar,
        SequencedArrayBatch,
    )

    box = ArrayBoxcar(
        tenant_id="t", document_id="doc", client_id="c1",
        ds_id="default", channel_id="text",
        kind=np.zeros(1, np.int8),
        a=np.zeros(1, np.int32), b=np.zeros(1, np.int32),
        cseq=np.ones(1, np.int32), rseq=np.zeros(1, np.int32),
        text="hi", text_off=np.array([0, 2], np.int32),
        props=None, timestamp=1.0)
    return SequencedArrayBatch(
        boxcar=box, base_seq=7, msns=np.array([3], np.int64),
        timestamp=1.0)


def test_durable_log_binary_path_requires_exact_record_shape():
    """_encode_binary's decoder reconstructs tenant/doc FROM the boxcar,
    so only the exact deltas-record shape may take the binary path; any
    renamed/extra key or divergent routing field must fall back to JSON
    (returning None) rather than silently rewrite the record."""
    from fluidframework_tpu.service.durable_log import (
        _decode_value,
        _encode_binary,
    )

    batch = _mini_batch()
    exact = {"tenant_id": "t", "document_id": "doc", "abatch": batch}
    data = _encode_binary(exact)
    assert data is not None
    back = _decode_value(data)
    assert back["tenant_id"] == "t" and back["document_id"] == "doc"
    assert back["abatch"].base_seq == 7
    assert list(back["abatch"].msns) == [3]
    assert back["abatch"].boxcar.text == "hi"

    renamed = {"tenant": "t", "document_id": "doc", "abatch": batch}
    assert _encode_binary(renamed) is None
    extra = dict(exact, route="elsewhere")
    assert _encode_binary(extra) is None
    divergent = dict(exact, tenant_id="other")
    assert _encode_binary(divergent) is None


# ------------------------------------------- placement lease interleave

def test_stalled_ex_owner_heartbeat_loses_to_takeover(tmp_path):
    """A's heartbeat resuming AFTER B's takeover must observe B's lease
    under the claim flock and report the loss — never utime B's file
    back to life (the two-writer window)."""
    from fluidframework_tpu.service.placement import PlacementDir

    pd = PlacementDir(str(tmp_path / "pl"), 1, ttl_s=0.3)
    assert pd.try_claim(0, "A", "addr-a")
    time.sleep(0.4)  # A stalls past the ttl
    assert pd.try_claim(0, "B", "addr-b")
    # the stalled ex-owner wakes up mid-life of B's lease
    assert pd.heartbeat(0, "A") is False
    pd.release(0, "A")  # a stale release must not unlink B's lease
    assert pd.owner_of(0) == "addr-b"
    assert pd.heartbeat(0, "B") is True


def test_heartbeat_and_takeover_interleave_under_the_same_lock(tmp_path):
    """Force the race: A's heartbeat reads its lease, then stalls inside
    the critical section while B tries to take over. With the flock
    shared with try_claim, B must block until A's utime lands — the
    interleave read-stale/replace/utime-over-it is impossible, so
    exactly one of them owns the lease afterwards."""
    from fluidframework_tpu.service import placement as pl

    pd = pl.PlacementDir(str(tmp_path / "pl"), 1, ttl_s=0.25)
    assert pd.try_claim(0, "A", "addr-a")
    time.sleep(0.3)  # lease is stale: both a heartbeat and a takeover
    #                  are now plausible next moves

    in_read = threading.Event()
    real_read = pd._read

    def slow_read(k):
        rec = real_read(k)
        in_read.set()
        time.sleep(0.2)  # hold the flock with a stale view in hand
        return rec

    results = {}

    def hb():
        pd._read = slow_read
        try:
            results["a_keeps"] = pd.heartbeat(0, "A")
        finally:
            pd._read = real_read

    t = threading.Thread(target=hb)
    t.start()
    assert in_read.wait(5.0)
    pd2 = pl.PlacementDir(str(tmp_path / "pl"), 1, ttl_s=0.25)
    results["b_wins"] = pd2.try_claim(0, "B", "addr-b")
    t.join(10.0)
    assert not t.is_alive()
    # serialized outcomes only: either A's utime landed first (lease
    # fresh again → B refused) or B replaced the stale lease before A's
    # heartbeat entered (→ A told to stop). Both True = split brain.
    assert not (results["a_keeps"] and results["b_wins"])
    assert results["a_keeps"] or results["b_wins"]
    owner = pd._read(0)["owner"]
    assert owner == ("A" if results["a_keeps"] else "B")
