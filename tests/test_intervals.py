"""Interval collection specs: sliding endpoints, convergence, concurrent
edits, reconnect rebase, snapshot boot.

Ref: dds/sequence interval tests (intervalCollection.ts semantics) —
"local references must slide correctly — subtle" (SURVEY §7.7).
"""

import pytest

from fluidframework_tpu.driver import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.service import LocalServer


@pytest.fixture
def server():
    return LocalServer()


@pytest.fixture
def loader(server):
    return Loader(LocalDocumentServiceFactory(server))


def string_pair(loader):
    c1 = loader.resolve("t", "doc")
    c2 = loader.resolve("t", "doc")
    s1 = c1.runtime.create_data_store("default").create_channel("text", "shared-string")
    s1.insert_text(0, "0123456789")
    s2 = c2.runtime.get_data_store("default").get_channel("text")
    return c1, c2, s1, s2


def test_interval_replicates_and_slides(server, loader):
    c1, c2, s1, s2 = string_pair(loader)
    ivals1 = s1.get_interval_collection("highlights")
    ival = ivals1.add(2, 5, {"color": "yellow"})
    ivals2 = s2.get_interval_collection("highlights")
    assert len(ivals2) == 1
    remote = ivals2.get(ival.id)
    assert ivals2.position(remote) == (2, 5)
    assert remote.properties == {"color": "yellow"}

    # text inserted before the interval slides it right, on both replicas
    s2.insert_text(0, "ab")
    assert ivals1.position(ival) == (4, 7)
    assert ivals2.position(remote) == (4, 7)
    # remove spanning the start: endpoint slides to the nearest survivor
    s1.remove_text(3, 6)
    assert ivals1.position(ival) == ivals2.position(remote)


def test_interval_delete_and_change(server, loader):
    c1, c2, s1, s2 = string_pair(loader)
    ivals1 = s1.get_interval_collection("x")
    a = ivals1.add(1, 3)
    b = ivals1.add(5, 8)
    ivals2 = s2.get_interval_collection("x")
    assert len(ivals2) == 2
    ivals2.delete(a.id)
    assert len(ivals1) == 1 and ivals1.get(a.id) is None
    ivals1.change(b.id, start=0, end=9, props={"tag": "wide"})
    rb = ivals2.get(b.id)
    assert ivals2.position(rb) == (0, 9)
    assert rb.properties == {"tag": "wide"}


def test_interval_concurrent_change_local_wins(server, loader):
    c1, c2, s1, s2 = string_pair(loader)
    i1 = s1.get_interval_collection("x")
    ival = i1.add(2, 4)
    i2 = s2.get_interval_collection("x")
    server._auto_drain = False
    i1.change(ival.id, start=0)
    i2.change(ival.id, start=6)  # later in total order → wins
    server.drain()
    assert i1.position(i1.get(ival.id)) == i2.position(i2.get(ival.id))
    assert i1.position(i1.get(ival.id))[0] == 6


def test_interval_anchors_at_author_perspective(server, loader):
    c1, c2, s1, s2 = string_pair(loader)
    i1 = s1.get_interval_collection("x")
    i2 = s2.get_interval_collection("x")
    server._auto_drain = False
    s1.insert_text(0, "XYZ")  # shifts everything right by 3 (unseen by c2)
    i2.add(4, 6)  # c2 means chars '4'..'6' of "0123456789"
    server.drain()
    # both replicas agree AND the interval covers what c2 meant
    ival1 = next(iter(i1))
    ival2 = next(iter(i2))
    assert i1.position(ival1) == i2.position(ival2) == (7, 9)


def test_interval_overlapping_query(server, loader):
    c1, c2, s1, s2 = string_pair(loader)
    ic = s1.get_interval_collection("x")
    a = ic.add(0, 2)
    b = ic.add(5, 8)
    hits = ic.find_overlapping(1, 4)
    assert [i.id for i in hits] == [a.id]
    hits = ic.find_overlapping(0, 9)
    assert {i.id for i in hits} == {a.id, b.id}


def test_interval_reconnect_resubmits_with_rebased_positions(server, loader):
    c1, c2, s1, s2 = string_pair(loader)
    i1 = s1.get_interval_collection("x")
    i2 = s2.get_interval_collection("x")
    c1.disconnect()
    ival = i1.add(3, 5)  # pending while offline
    s2.insert_text(0, "PRE-")  # remote shift lands first
    c1.reconnect()
    assert len(i2) == 1
    r = i2.get(ival.id)
    assert i2.position(r) == i1.position(ival) == (7, 9)


def test_intervals_survive_summary_boot(server, loader):
    from fluidframework_tpu.runtime.summarizer import SummaryManager

    c1, c2, s1, s2 = string_pair(loader)
    sm = SummaryManager(c1, max_ops=10_000)
    ic = s1.get_interval_collection("marks")
    ival = ic.add(2, 6, {"kind": "comment"})
    sm.summarize_now()

    c3 = loader.resolve("t", "doc")
    s3 = c3.runtime.get_data_store("default").get_channel("text")
    i3 = s3.get_interval_collection("marks")
    assert len(i3) == 1
    r = i3.get(ival.id)
    assert i3.position(r) == (2, 6)
    assert r.properties == {"kind": "comment"}
    # and live: slides with post-boot edits
    s3.insert_text(0, "zz")
    assert i3.position(r) == (4, 8)
