"""Kernel == oracle on fuzzed sequenced op streams.

The TPU-build analog of the reference's PartialSequenceLengths.options.verify
(partialLengths.ts:63): every kernel state is cross-checked against the
scalar oracle — per-character stamps AND properties, plus
perspective-visible texts at random past (refSeq, client) views. Annotate
ops run on the device path (one per-key LWW table write per op), not via
host escalation.

Runs on CPU (conftest pins JAX_PLATFORMS=cpu); the same jitted code runs on
TPU in bench.py.
"""

from __future__ import annotations

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fluidframework_tpu.mergetree import MergeTreeClient, Perspective
from fluidframework_tpu.ops import (
    DocState,
    TextArena,
    apply_op,
    decode_state,
    encode_tree,
    make_op,
    OP_INSERT,
    OP_REMOVE,
)
from fluidframework_tpu.ops.apply import (
    NO_VAL,
    OP_ANNOTATE,
    apply_ops_scan,
    compact,
)
from fluidframework_tpu.ops.doc_state import FLAG_MARKER, PropTable
from fluidframework_tpu.protocol import MessageType, SequencedDocumentMessage
from tests.mergetree_fixtures import FarmClient, FarmServer, random_op


def norm_chars(tree, min_seq, view):
    """Per-char (char?, norm insert stamp, remove stamp, props) tuples.

    Stamps at or below min_seq are equivalence-classed to 0 (always visible /
    removed in every reachable perspective) so oracle-side zamboni merging
    doesn't produce spurious diffs.
    """
    out = []
    for seg in tree.segments:
        if not seg.visible_in(view):
            continue
        ins = (0, -2) if seg.ins_seq <= min_seq else (seg.ins_seq, seg.ins_client)
        rem = None
        if seg.rem_seq is not None:
            rem = seg.rem_seq
        props = tuple(sorted(seg.props.items()))
        body = "￼" if seg.is_marker else seg.text
        for ch in body:
            out.append((ch, ins, rem, props))
    return out


_jit_apply = jax.jit(apply_op)
_jit_compact = jax.jit(compact)
_jit_scan = jax.jit(apply_ops_scan)


class KernelDoc:
    """Host driver for a single kernel doc: arena + prop table + jitted
    apply — the single-doc twin of TpuDocumentApplier's staging."""

    def __init__(self, max_slots=256):
        self.state = DocState.empty(max_slots)
        self.arena = TextArena()
        self.props = PropTable()
        self._apply = _jit_apply
        self._compact = _jit_compact

    def vectorize(self, msg, intern):
        c = msg.contents
        common = dict(
            seq=msg.sequence_number,
            ref_seq=msg.reference_sequence_number,
            client=intern(msg.client_id),
            msn=msg.minimum_sequence_number,
        )
        def annotates(start, end, props):
            return [
                make_op(
                    OP_ANNOTATE, pos=start, end=end,
                    key=self.props.intern_key(k),
                    val=NO_VAL if v is None else self.props.intern_val(v),
                    **common,
                )
                for k, v in props.items()
            ]

        if c["type"] == 0:  # insert (+ optional props on the new segment)
            if c.get("text") is None:  # marker
                start = self.arena.append("￼")
                vecs = [make_op(OP_INSERT, pos=c["pos"], text_len=1,
                                text_start=start, flags=FLAG_MARKER, **common)]
                tlen = 1
            else:
                text = c["text"]
                start = self.arena.append(text)
                vecs = [make_op(OP_INSERT, pos=c["pos"], text_len=len(text),
                                text_start=start, **common)]
                tlen = len(text)
            vecs.extend(annotates(c["pos"], c["pos"] + tlen, c.get("props") or {}))
            return vecs
        if c["type"] == 1:  # remove
            return [make_op(OP_REMOVE, pos=c["start"], end=c["end"], **common)]
        if c["type"] == 2:  # annotate: one device op per key
            return annotates(c["start"], c["end"], c["props"])
        return []

    def apply_wire(self, msg, intern):
        for op in self.vectorize(msg, intern):
            self.state = self._apply(self.state, jnp.asarray(op))

    def compact_to(self, min_seq):
        self.state = self._compact(self.state, jnp.asarray(min_seq, jnp.int32))


def run_stream(seed, n_clients=3, rounds=8, compact_every=0, allow_annotate=True):
    """Drive a farm, feed the sequenced stream to oracle server replica AND
    kernel, compare after every round."""
    rng = random.Random(seed)
    clients = [FarmClient(f"c{i}") for i in range(n_clients)]
    server = FarmServer(clients, rng)

    oracle = MergeTreeClient("__server__")
    kernel = KernelDoc()
    stream: list[SequencedDocumentMessage] = []
    escalations: list[int] = []

    for rnd in range(rounds):
        for fc in clients:
            for _ in range(rng.randint(1, 3)):
                random_op(fc, rng, allow_annotate=allow_annotate)
        while True:
            ready = [c for c in clients if c.outbound]
            if not ready:
                break
            sender = rng.choice(ready)
            raw = sender.outbound.popleft()
            server.seq += 1
            server.client_ref[sender.name] = max(
                server.client_ref[sender.name], raw["refSeq"]
            )
            msn = min(server.client_ref.values())
            msg = SequencedDocumentMessage(
                client_id=sender.name,
                sequence_number=server.seq,
                minimum_sequence_number=msn,
                client_sequence_number=raw["clientSeq"],
                reference_sequence_number=raw["refSeq"],
                type=MessageType.OPERATION,
                contents=raw["contents"],
            )
            for c in clients:
                c.client.apply_msg(msg)
            oracle.apply_msg(msg)
            kernel.apply_wire(msg, oracle.intern)
            stream.append(msg)
        if compact_every and rnd % compact_every == compact_every - 1:
            kernel.compact_to(oracle.tree.min_seq)

        # Host-escalation protocol (production behavior): a doc whose state
        # exceeds the kernel's fixed bounds (3+ concurrent removers of one
        # segment, slot capacity, or prop-table capacity) is flagged,
        # replayed host-side on the oracle, and re-uploaded once its state
        # encodes cleanly again.
        if bool(kernel.state.overflow):
            escalations.append(rnd)
            arena = TextArena()
            st = encode_tree(oracle.tree, arena, kernel.state.max_slots,
                             prop_table=kernel.props)
            if not bool(st.overflow):
                kernel.state, kernel.arena = st, arena
        if not bool(kernel.state.overflow):
            compare(oracle, kernel, stream, rng, f"seed={seed} round={rnd}")
    assert not bool(kernel.state.overflow), "doc never de-escalated"
    return oracle, kernel, stream


def compare(oracle, kernel, stream, rng, ctx):
    ktree = decode_state(kernel.state, kernel.arena, kernel.props)
    min_seq = oracle.tree.min_seq
    # 1) current server view: text + per-char stamps + props
    cur = Perspective(oracle.tree.current_seq, 10**7)
    o_chars = norm_chars(oracle.tree, min_seq, cur)
    k_chars = norm_chars(ktree, min_seq, cur)
    assert o_chars == k_chars, (
        f"{ctx}: char/stamp mismatch\noracle: {o_chars[:40]}\nkernel: {k_chars[:40]}"
    )
    # 2) random past perspectives (only refSeq ≥ minSeq are reachable)
    for _ in range(5):
        ref = rng.randint(min_seq, oracle.tree.current_seq)
        client = rng.choice(list(oracle._ids.values()) + [10**7])
        view = Perspective(ref, client)
        o_text = oracle.tree.get_text(view)
        k_text = ktree.get_text(view)
        assert o_text == k_text, f"{ctx}: past view ({ref},{client}) diverged"


@pytest.mark.parametrize("seed", range(6))
def test_kernel_matches_oracle(seed):
    run_stream(seed, n_clients=3, rounds=8, allow_annotate=False)


@pytest.mark.parametrize("seed", range(6))
def test_kernel_matches_oracle_with_annotate(seed):
    run_stream(200 + seed, n_clients=3, rounds=8, allow_annotate=True)


@pytest.mark.parametrize("seed", range(3))
def test_kernel_matches_oracle_with_compaction(seed):
    run_stream(100 + seed, n_clients=4, rounds=8, compact_every=2)


def test_annotate_lww_and_delete_on_device():
    """Deterministic annotate semantics: per-key LWW in seq order, None
    deletes, splits copy props to both halves."""
    doc = KernelDoc(max_slots=32)
    intern = lambda cid: {"a": 0, "b": 1}[cid]

    def msg(seq, contents, client="a", ref=None):
        return SequencedDocumentMessage(
            client_id=client, sequence_number=seq,
            minimum_sequence_number=0, client_sequence_number=seq,
            reference_sequence_number=seq - 1 if ref is None else ref,
            type=MessageType.OPERATION, contents=contents)

    doc.apply_wire(msg(1, {"type": 0, "pos": 0, "text": "hello world"}), intern)
    doc.apply_wire(msg(2, {"type": 2, "start": 0, "end": 5,
                           "props": {"bold": True, "size": 12}}), intern)
    # later write to same key wins
    doc.apply_wire(msg(3, {"type": 2, "start": 0, "end": 3,
                           "props": {"bold": False}}, client="b"), intern)
    # delete a key
    doc.apply_wire(msg(4, {"type": 2, "start": 0, "end": 2,
                           "props": {"size": None}}), intern)
    # insert inside an annotated run: both halves keep props
    doc.apply_wire(msg(5, {"type": 0, "pos": 4, "text": "XY"}), intern)

    tree = decode_state(doc.state, doc.arena, doc.props)
    view = Perspective(10**6, 10**7)
    assert tree.get_text(view) == "hellXYo world"
    chars = norm_chars(tree, 0, view)
    props_at = [dict(c[3]) for c in chars]
    assert props_at[0] == {"bold": False}          # deleted size, b's bold
    assert props_at[2] == {"bold": False, "size": 12}
    assert props_at[3] == {"bold": True, "size": 12}
    assert props_at[4] == {}                        # inserted X
    assert props_at[6] == {"bold": True, "size": 12}  # tail half of 'o'
    assert props_at[8] == {}                        # 'w' never annotated
    assert not bool(doc.state.overflow)


def test_prop_table_capacity_overflow_flags():
    """A slot needing a (P+1)th distinct key flags overflow for host
    escalation instead of silently dropping the annotate."""
    doc = KernelDoc(max_slots=16)
    P = int(doc.state.prop_key.shape[-1])
    intern = lambda cid: 0
    doc.apply_wire(SequencedDocumentMessage(
        client_id="a", sequence_number=1, minimum_sequence_number=0,
        client_sequence_number=1, reference_sequence_number=0,
        type=MessageType.OPERATION,
        contents={"type": 0, "pos": 0, "text": "x"}), intern)
    for k in range(P + 1):
        doc.apply_wire(SequencedDocumentMessage(
            client_id="a", sequence_number=2 + k, minimum_sequence_number=0,
            client_sequence_number=2 + k, reference_sequence_number=1 + k,
            type=MessageType.OPERATION,
            contents={"type": 2, "start": 0, "end": 1,
                      "props": {f"key{k}": k}}), intern)
    assert bool(doc.state.overflow)


def test_user_text_marker_glyph_roundtrips():
    """User text containing U+FFFC must NOT be classified as a marker —
    marker-ness is the out-of-band flags bit (round-1 VERDICT weak #5)."""
    doc = KernelDoc(max_slots=16)
    intern = lambda cid: 0
    doc.apply_wire(SequencedDocumentMessage(
        client_id="a", sequence_number=1, minimum_sequence_number=0,
        client_sequence_number=1, reference_sequence_number=0,
        type=MessageType.OPERATION,
        contents={"type": 0, "pos": 0, "text": "a￼b"}), intern)
    # and a REAL marker next to it
    doc.apply_wire(SequencedDocumentMessage(
        client_id="a", sequence_number=2, minimum_sequence_number=0,
        client_sequence_number=2, reference_sequence_number=1,
        type=MessageType.OPERATION,
        contents={"type": 0, "pos": 3, "text": None, "marker": {"refType": 1}}),
        intern)
    tree = decode_state(doc.state, doc.arena, doc.props)
    segs = [s for s in tree.segments]
    assert [s.is_marker for s in segs] == [False, True]
    assert segs[0].text == "a￼b"


def test_device_zamboni_runs_at_wave_msn():
    """With msn riding each op, compaction inside the step drops tombstones
    the collaboration window has passed — slot count stays bounded under
    insert/remove churn (round-1 VERDICT weak #1)."""
    from fluidframework_tpu.ops.apply import wave_min_seq
    from fluidframework_tpu.ops.opgen import generate_doc_ops

    @jax.jit
    def step(state, ops):
        state = apply_ops_scan(state, ops)
        return compact(state, wave_min_seq(ops))

    rng_np = np.random.default_rng(3)
    ops, _, _ = generate_doc_ops(
        rng_np, 512, remove_fraction=0.48, max_insert=4, msn_lag=8)
    state = DocState.empty(256)
    K = 16
    counts = []
    for i in range(0, 512, K):
        state = step(state, jnp.asarray(ops[i : i + K]))
        counts.append(int(state.count))
    assert not bool(state.overflow)
    # without zamboni this stream overflows 256 slots; with it the count
    # stays well clear of capacity
    assert max(counts) < 200, max(counts)


def test_kernel_scan_batch_matches_single_op_path():
    """K-op lax.scan dispatch == sequential single-op dispatch."""
    rng = random.Random(7)
    clients = [FarmClient(f"c{i}") for i in range(3)]
    server = FarmServer(clients, rng)
    oracle = MergeTreeClient("__server__")
    msgs = []
    for fc in clients:
        for _ in range(6):
            random_op(fc, rng, allow_annotate=True)
    # sequence all, collecting messages
    while True:
        ready = [c for c in clients if c.outbound]
        if not ready:
            break
        sender = rng.choice(ready)
        raw = sender.outbound.popleft()
        server.seq += 1
        msg = SequencedDocumentMessage(
            client_id=sender.name,
            sequence_number=server.seq,
            minimum_sequence_number=0,
            client_sequence_number=raw["clientSeq"],
            reference_sequence_number=raw["refSeq"],
            type=MessageType.OPERATION,
            contents=raw["contents"],
        )
        for c in clients:
            c.client.apply_msg(msg)
        msgs.append(msg)

    single = KernelDoc()
    ops = []
    for m in msgs:
        for op in single.vectorize(m, oracle.intern):
            ops.append(op)
            single.state = _jit_apply(single.state, jnp.asarray(op))

    scanned = _jit_scan(DocState.empty(256), jnp.asarray(np.stack(ops)))
    for f in ("length", "text_start", "flags", "ins_seq", "ins_client",
              "rem_seq", "prop_key", "prop_val", "count"):
        np.testing.assert_array_equal(
            np.asarray(getattr(scanned, f)), np.asarray(getattr(single.state, f)), f
        )
