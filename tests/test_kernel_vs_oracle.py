"""Kernel == oracle on fuzzed sequenced op streams.

The TPU-build analog of the reference's PartialSequenceLengths.options.verify
(partialLengths.ts:63): every kernel state is cross-checked against the
scalar oracle — per-character stamps and perspective-visible texts at
random past (refSeq, client) views.

Runs on CPU (conftest pins JAX_PLATFORMS=cpu); the same jitted code runs on
TPU in bench.py.
"""

from __future__ import annotations

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fluidframework_tpu.mergetree import MergeTreeClient, Perspective
from fluidframework_tpu.ops import (
    DocState,
    TextArena,
    apply_op,
    decode_state,
    encode_tree,
    make_op,
    OP_INSERT,
    OP_REMOVE,
)
from fluidframework_tpu.ops.apply import apply_ops_scan, compact
from fluidframework_tpu.protocol import MessageType, SequencedDocumentMessage
from tests.mergetree_fixtures import FarmClient, FarmServer, random_op


def norm_chars(tree, min_seq, view):
    """Per-char (char?, norm insert stamp, norm remove stamp) for comparison.

    Stamps at or below min_seq are equivalence-classed to 0 (always visible /
    removed in every reachable perspective) so oracle-side zamboni merging
    doesn't produce spurious diffs.
    """
    out = []
    for seg in tree.segments:
        if not seg.visible_in(view):
            continue
        ins = (0, -2) if seg.ins_seq <= min_seq else (seg.ins_seq, seg.ins_client)
        rem = None
        if seg.rem_seq is not None:
            rem = seg.rem_seq
        body = "￼" if seg.is_marker else seg.text
        for ch in body:
            out.append((ch, ins, rem))
    return out


_jit_apply = jax.jit(apply_op)
_jit_compact = jax.jit(compact)
_jit_scan = jax.jit(apply_ops_scan)


class KernelDoc:
    """Host driver for a single kernel doc: arena + jitted apply."""

    def __init__(self, max_slots=256):
        self.state = DocState.empty(max_slots)
        self.arena = TextArena()
        self._apply = _jit_apply
        self._compact = _jit_compact

    def apply_wire(self, msg, intern):
        c = msg.contents
        client = intern(msg.client_id)
        if c["type"] == 0:  # insert
            text = c.get("text")
            if text is None:
                text = "￼"  # marker placeholder
            start = self.arena.append(text)
            op = make_op(
                OP_INSERT,
                pos=c["pos"],
                seq=msg.sequence_number,
                ref_seq=msg.reference_sequence_number,
                client=client,
                text_len=len(text),
                text_start=start,
            )
        elif c["type"] == 1:  # remove
            op = make_op(
                OP_REMOVE,
                pos=c["start"],
                end=c["end"],
                seq=msg.sequence_number,
                ref_seq=msg.reference_sequence_number,
                client=client,
            )
        else:
            return
        self.state = self._apply(self.state, jnp.asarray(op))

    def compact_to(self, min_seq):
        self.state = self._compact(self.state, jnp.asarray(min_seq, jnp.int32))


def run_stream(seed, n_clients=3, rounds=8, compact_every=0):
    """Drive a farm, feed the sequenced stream to oracle server replica AND
    kernel, compare after every round."""
    rng = random.Random(seed)
    clients = [FarmClient(f"c{i}") for i in range(n_clients)]
    server = FarmServer(clients, rng)

    oracle = MergeTreeClient("__server__")
    kernel = KernelDoc()
    stream: list[SequencedDocumentMessage] = []
    escalations: list[int] = []

    for rnd in range(rounds):
        for fc in clients:
            for _ in range(rng.randint(1, 3)):
                random_op(fc, rng, allow_annotate=False)
        while True:
            ready = [c for c in clients if c.outbound]
            if not ready:
                break
            sender = rng.choice(ready)
            raw = sender.outbound.popleft()
            server.seq += 1
            server.client_ref[sender.name] = max(
                server.client_ref[sender.name], raw["refSeq"]
            )
            msn = min(server.client_ref.values())
            msg = SequencedDocumentMessage(
                client_id=sender.name,
                sequence_number=server.seq,
                minimum_sequence_number=msn,
                client_sequence_number=raw["clientSeq"],
                reference_sequence_number=raw["refSeq"],
                type=MessageType.OPERATION,
                contents=raw["contents"],
            )
            for c in clients:
                c.client.apply_msg(msg)
            oracle.apply_msg(msg)
            kernel.apply_wire(msg, oracle.intern)
            stream.append(msg)
        if compact_every and rnd % compact_every == compact_every - 1:
            kernel.compact_to(oracle.tree.min_seq)

        # Host-escalation protocol (production behavior): a doc whose state
        # exceeds the kernel's fixed bounds (3+ concurrent removers of one
        # segment, or slot capacity) is flagged, replayed host-side on the
        # oracle, and re-uploaded once its state encodes cleanly again.
        if bool(kernel.state.overflow):
            escalations.append(rnd)
            arena = TextArena()
            st = encode_tree(oracle.tree, arena, kernel.state.max_slots)
            if not bool(st.overflow):
                kernel.state, kernel.arena = st, arena
        if not bool(kernel.state.overflow):
            compare(oracle, kernel, stream, rng, f"seed={seed} round={rnd}")
    assert not bool(kernel.state.overflow), "doc never de-escalated"
    return oracle, kernel, stream


def compare(oracle, kernel, stream, rng, ctx):
    ktree = decode_state(kernel.state, kernel.arena)
    min_seq = oracle.tree.min_seq
    # 1) current server view: text + per-char stamps
    cur = Perspective(oracle.tree.current_seq, 10**7)
    o_chars = norm_chars(oracle.tree, min_seq, cur)
    k_chars = norm_chars(ktree, min_seq, cur)
    assert o_chars == k_chars, (
        f"{ctx}: char/stamp mismatch\noracle: {o_chars[:40]}\nkernel: {k_chars[:40]}"
    )
    # 2) random past perspectives (only refSeq ≥ minSeq are reachable)
    for _ in range(5):
        ref = rng.randint(min_seq, oracle.tree.current_seq)
        client = rng.choice(list(oracle._ids.values()) + [10**7])
        view = Perspective(ref, client)
        o_text = oracle.tree.get_text(view)
        k_text = ktree.get_text(view)
        assert o_text == k_text, f"{ctx}: past view ({ref},{client}) diverged"


@pytest.mark.parametrize("seed", range(6))
def test_kernel_matches_oracle(seed):
    run_stream(seed, n_clients=3, rounds=8)


@pytest.mark.parametrize("seed", range(3))
def test_kernel_matches_oracle_with_compaction(seed):
    run_stream(100 + seed, n_clients=4, rounds=8, compact_every=2)


def test_kernel_scan_batch_matches_single_op_path():
    """K-op lax.scan dispatch == sequential single-op dispatch."""
    rng = random.Random(7)
    clients = [FarmClient(f"c{i}") for i in range(3)]
    server = FarmServer(clients, rng)
    oracle = MergeTreeClient("__server__")
    msgs = []
    for fc in clients:
        for _ in range(6):
            random_op(fc, rng, allow_annotate=False)
    # sequence all, collecting messages
    while True:
        ready = [c for c in clients if c.outbound]
        if not ready:
            break
        sender = rng.choice(ready)
        raw = sender.outbound.popleft()
        server.seq += 1
        msg = SequencedDocumentMessage(
            client_id=sender.name,
            sequence_number=server.seq,
            minimum_sequence_number=0,
            client_sequence_number=raw["clientSeq"],
            reference_sequence_number=raw["refSeq"],
            type=MessageType.OPERATION,
            contents=raw["contents"],
        )
        for c in clients:
            c.client.apply_msg(msg)
        msgs.append(msg)

    single = KernelDoc()
    ops = []
    for m in msgs:
        c = m.contents
        client = oracle.intern(m.client_id)
        if c["type"] == 0:
            text = c.get("text") or "￼"
            start = single.arena.append(text)
            ops.append(
                make_op(
                    OP_INSERT,
                    pos=c["pos"],
                    seq=m.sequence_number,
                    ref_seq=m.reference_sequence_number,
                    client=client,
                    text_len=len(text),
                    text_start=start,
                )
            )
        else:
            ops.append(
                make_op(
                    OP_REMOVE,
                    pos=c["start"],
                    end=c["end"],
                    seq=m.sequence_number,
                    ref_seq=m.reference_sequence_number,
                    client=client,
                )
            )
        single.state = _jit_apply(single.state, jnp.asarray(ops[-1]))

    scanned = _jit_scan(DocState.empty(256), jnp.asarray(np.stack(ops)))
    for f in ("length", "text_start", "ins_seq", "ins_client", "rem_seq", "count"):
        np.testing.assert_array_equal(
            np.asarray(getattr(scanned, f)), np.asarray(getattr(single.state, f)), f
        )
