"""Framework extras: DI synthesis, request routing, last-edited tracker
(ref: packages/framework/synthesize, request-handler,
last-edited-experimental).
"""

import pytest

from fluidframework_tpu.driver import LocalDocumentServiceFactory
from fluidframework_tpu.framework.last_edited import LastEditedTracker
from fluidframework_tpu.framework.request_handler import RequestRouter
from fluidframework_tpu.framework.synthesize import DependencyContainer
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.service import LocalServer


@pytest.fixture
def loader():
    return Loader(LocalDocumentServiceFactory(LocalServer()))


def test_dependency_container_required_optional_and_scopes():
    host = DependencyContainer()
    host.register("logger", "host-logger")
    built = []
    host.register_factory("expensive", lambda: built.append(1) or "svc")
    child = DependencyContainer(parent=host)
    child.register("config", {"x": 1})

    deps = child.synthesize(required=("logger", "config"),
                            optional=("missing", "expensive"))
    assert deps["logger"] == "host-logger"
    assert deps["config"] == {"x": 1}
    assert deps["missing"] is None
    assert deps["expensive"] == "svc"
    child.resolve("expensive")
    assert built == [1]  # factory ran once (cached)
    with pytest.raises(KeyError):
        child.synthesize(required=("nope",))


def test_request_router_walks_the_object_graph(loader):
    c = loader.resolve("t", "doc")
    ds = c.runtime.create_data_store("default")
    text = ds.create_channel("text", "shared-string")
    router = RequestRouter(c)
    assert router.request("/") is c.runtime
    assert router.request("/default") is ds
    assert router.request("/default/text") is text
    with pytest.raises(KeyError):
        router.request("/nope/where")
    # custom handlers compose in front
    router.add_handler(
        lambda parts, cont: "CUSTOM" if parts[:1] == ["_debug"] else None)
    assert router.request("/_debug/state") == "CUSTOM"
    assert router.request("/default/text") is text  # default still works


def test_last_edited_converges_and_names_the_editor(loader):
    c1 = loader.resolve("t", "doc")
    c2 = loader.resolve("t", "doc")
    ds = c1.runtime.create_data_store("default")
    text = ds.create_channel("text", "shared-string")
    t1 = LastEditedTracker(c1)
    t2 = LastEditedTracker(c2)

    s2 = c2.runtime.get_data_store("default").get_channel("text")
    s2.insert_text(0, "bob was here")
    assert t1.last_edited is not None
    assert t1.last_edited["clientId"] == c2.client_id
    assert t1.last_edited == t2.last_edited  # convergent record

    text.insert_text(0, "alice later: ")
    assert t2.last_edited["clientId"] == c1.client_id
