"""Summary pipeline specs: client summarizer election + heuristics,
scribe validation + ack/nack through the total order, boot from acked
summaries, checkpoint/restart of scribe state.

Ref: §3.4 call stack (summaryManager → generateSummary → scribe
writeClientSummary → summaryAck) and summarizer unit/e2e coverage.
"""

import pytest

from fluidframework_tpu.driver import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.protocol.messages import MessageType
from fluidframework_tpu.runtime.summarizer import SummaryManager
from fluidframework_tpu.service import LocalServer


@pytest.fixture
def server():
    return LocalServer()


@pytest.fixture
def loader(server):
    return Loader(LocalDocumentServiceFactory(server))


def test_oldest_member_is_elected(loader):
    c1 = loader.resolve("t", "doc")
    c2 = loader.resolve("t", "doc")
    sm1, sm2 = SummaryManager(c1), SummaryManager(c2)
    assert sm1.elected_summarizer == c1.client_id
    assert sm1.is_summarizer and not sm2.is_summarizer
    c1.close()
    # remaining oldest takes over
    assert sm2.elected_summarizer == c2.client_id
    assert sm2.is_summarizer


def test_summary_acked_and_used_for_boot(loader):
    c1 = loader.resolve("t", "doc")
    sm = SummaryManager(c1, max_ops=3)
    s = c1.runtime.create_data_store("default").create_channel("text", "shared-string")
    s.insert_text(0, "abcdef")
    s.remove_text(0, 2)
    assert sm.summaries_acked >= 1  # heuristics fired and scribe acked
    assert sm.last_acked_handle is not None

    # a fresh client boots from the acked summary version + tail
    c2 = loader.resolve("t", "doc")
    assert c2._base_snapshot is not None  # actually booted from a summary
    s2 = c2.runtime.get_data_store("default").get_channel("text")
    assert s2.get_text() == "cdef"
    s2.insert_text(0, "x")
    assert s.get_text() == "xcdef"


def test_summary_chain_parents_link(loader):
    c1 = loader.resolve("t", "doc")
    sm = SummaryManager(c1, max_ops=2)
    s = c1.runtime.create_data_store("default").create_channel("kv", "shared-map")
    for i in range(8):
        s.set(f"k{i}", i)
    assert sm.summaries_acked >= 2
    versions = c1.storage.get_versions(10)
    assert len(versions) >= 2


def test_stale_parent_summary_nacked(loader):
    c1 = loader.resolve("t", "doc")
    sm1 = SummaryManager(c1, max_ops=10_000)  # manual control
    s = c1.runtime.create_data_store("default").create_channel("kv", "shared-map")
    s.set("a", 1)
    sm1.summarize_now()
    assert sm1.summaries_acked == 1
    # second summary lying about its parent → scribe nack
    sm1.last_acked_handle = None  # fake a stale head
    nacked_handle = sm1.summarize_now()
    assert sm1.summaries_nacked == 1
    # the rejected version must not be served for boot
    versions = c1.storage.get_versions(10)
    assert nacked_handle is not None
    assert all(v["id"] != nacked_handle for v in versions)


def test_summarizer_defers_with_pending_ops(server, loader):
    c1 = loader.resolve("t", "doc")
    sm = SummaryManager(c1, max_ops=10_000)  # manual control
    s = c1.runtime.create_data_store("default").create_channel("kv", "shared-map")
    server._auto_drain = False
    s.set("a", 1)  # pending, unacked
    with pytest.raises(RuntimeError):
        sm.summarize_now()
    server.drain()
    sm.summarize_now()
    server.drain()  # deliver the summarize op + scribe's ack
    assert sm.summaries_acked == 1


def test_scribe_restart_keeps_summary_head(server, loader):
    c1 = loader.resolve("t", "doc")
    sm = SummaryManager(c1, max_ops=10_000)
    s = c1.runtime.create_data_store("default").create_channel("kv", "shared-map")
    s.set("a", 1)
    sm.summarize_now()
    assert sm.summaries_acked == 1
    server.restart_orderer("t", "doc")
    # the restarted scribe must remember the head: a proper child summary
    # acks, a stale-parent one nacks
    s.set("b", 2)
    sm.summarize_now()
    assert sm.summaries_acked == 2
    assert sm.summaries_nacked == 0


def test_non_summarizer_client_never_summarizes(loader):
    c1 = loader.resolve("t", "doc")
    c2 = loader.resolve("t", "doc")
    sm1 = SummaryManager(c1, max_ops=2)
    sm2 = SummaryManager(c2, max_ops=2)
    kv2 = c2.runtime.create_data_store("default").create_channel("kv", "shared-map")
    for i in range(6):
        kv2.set(f"k{i}", i)
    assert sm2.summaries_acked == 0  # c2 is not elected
    assert sm1.summaries_acked >= 1  # c1 is, and summarizes c2's ops


def test_late_elected_summarizer_continues_chain(loader):
    # a manager attached after boot must seed its head from storage, or
    # its first proposal (parent=None) would nack-loop forever
    c1 = loader.resolve("t", "doc")
    sm1 = SummaryManager(c1, max_ops=10_000)
    kv = c1.runtime.create_data_store("default").create_channel("kv", "shared-map")
    kv.set("a", 1)
    sm1.summarize_now()
    assert sm1.summaries_acked == 1

    c2 = loader.resolve("t", "doc")
    sm2 = SummaryManager(c2, max_ops=10_000)
    assert sm2.last_acked_handle == sm1.last_acked_handle
    c1.close()  # c2 becomes the elected summarizer
    kv2 = c2.runtime.get_data_store("default").get_channel("kv")
    kv2.set("b", 2)
    sm2.summarize_now()
    assert sm2.summaries_acked == 1 and sm2.summaries_nacked == 0


def test_future_head_summary_nacked(loader):
    # a summary claiming to cover seqs beyond the stream must be rejected
    # or booting clients would resume past real ops and drop them
    c1 = loader.resolve("t", "doc")
    sm = SummaryManager(c1, max_ops=10_000)
    kv = c1.runtime.create_data_store("default").create_channel("kv", "shared-map")
    kv.set("a", 1)
    summary = {"protocol": c1.protocol.snapshot(),
               "runtime": c1.runtime.snapshot(),
               "sequence_number": 999}  # lie
    handle = c1.storage.upload_summary(summary, parent=None)
    c1.delta_manager.submit(
        MessageType.SUMMARIZE, {"handle": handle, "parent": None, "head": 999})
    assert c1.storage.get_versions(10) == []  # nothing committed
    sm.summarize_now()  # an honest summary still goes through
    assert sm.summaries_acked == 1


def test_boot_from_summary_sequence_numbers_align(loader):
    # protocol gap check: booting client must resume at exactly the
    # summary's sequence number with no gap or dup
    c1 = loader.resolve("t", "doc")
    sm = SummaryManager(c1, max_ops=10_000)
    st = c1.runtime.create_data_store("default").create_channel("text", "shared-string")
    st.insert_text(0, "hello")
    sm.summarize_now()
    c2 = loader.resolve("t", "doc")
    st2 = c2.runtime.get_data_store("default").get_channel("text")
    st2.insert_text(5, "!")
    assert st.get_text() == st2.get_text() == "hello!"
    assert c2.protocol.sequence_number == c2.delta_manager.last_processed_seq


def test_deli_crash_replay_does_not_spuriously_nack_acked_summary(server, loader):
    """Deli crash-replay re-appends already-sequenced records at NEW topic
    offsets; scribe must not re-run _handle_summarize for the duplicate
    summarize (it would nack: parent no longer matches head). ADVICE r1."""
    c1 = loader.resolve("t", "doc")
    sm = SummaryManager(c1, max_ops=2)
    s = c1.runtime.create_data_store("default").create_channel("text", "shared-string")
    s.insert_text(0, "hello")
    s.insert_text(5, " world")
    assert sm.summaries_acked >= 1
    acked_before = sm.summaries_acked
    head_before = server._orderers["t/doc"]._db  # keep db alive across restart

    nack_count_before = sum(
        1 for m in server.get_deltas("t", "doc", 0, 10**6)
        if m.type == MessageType.SUMMARY_NACK)

    # crash the orderer without checkpointing: deli replays the raw topic
    # and re-emits every sequenced record at new deltas-topic offsets
    server._orderers.pop("t/doc").close()
    server._get_orderer("t", "doc")
    server.drain()

    nack_count_after = sum(
        1 for m in server.get_deltas("t", "doc", 0, 10**6)
        if m.type == MessageType.SUMMARY_NACK)
    assert nack_count_after == nack_count_before
    assert sm.summaries_acked == acked_before


def test_scribe_skips_duplicate_summarize_at_new_offset():
    """Unit-level: a live scribe that sees the same sequenced summarize
    again at a NEW topic offset (deli crash-replay) must not re-validate
    it — re-running would nack because the head already advanced."""
    from fluidframework_tpu.protocol.messages import (
        DocumentMessage, SequencedDocumentMessage)
    from fluidframework_tpu.service.core import (
        InMemoryDb, QueuedMessage, summary_versions_collection)
    from fluidframework_tpu.service.scribe import ScribeLambda

    db = InMemoryDb()
    db.upsert(summary_versions_collection("t", "d"), "h1",
              {"id": "h1", "parent": None, "acked": False})
    sent = []
    scribe = ScribeLambda("t", "d", db, send_to_deli=sent.append)

    summarize = SequencedDocumentMessage(
        client_id="c1", sequence_number=1, minimum_sequence_number=0,
        client_sequence_number=1, reference_sequence_number=0,
        type=MessageType.SUMMARIZE,
        contents={"handle": "h1", "parent": None, "head": 1})
    scribe.handler(QueuedMessage(topic="deltas/t/d", partition=0, offset=0, value={"message": summarize}))
    assert [m.operation.type for m in sent] == [MessageType.SUMMARY_ACK]
    assert scribe.last_summary_head == "h1"

    # deli replay appended the same record again at offset 1
    scribe.handler(QueuedMessage(topic="deltas/t/d", partition=0, offset=1, value={"message": summarize}))
    assert [m.operation.type for m in sent] == [MessageType.SUMMARY_ACK]
    assert scribe.last_summary_head == "h1"


def test_log_truncates_behind_acked_summaries():
    """Retention: ops an acked summary covers truncate from scriptorium
    (minus the configured margin); fresh clients still boot correctly
    from summary + retained tail."""
    from fluidframework_tpu.config import Config
    from fluidframework_tpu.service import LocalServer

    srv = LocalServer(config=Config().with_overrides(log_retention_ops=5))
    loader = Loader(LocalDocumentServiceFactory(srv))
    c1 = loader.resolve("t", "doc")
    sm = SummaryManager(c1, max_ops=10**9)
    s = c1.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    for i in range(30):
        s.insert_text(0, f"{i % 10}")
    sm.summarize_now()
    assert sm.summaries_acked == 1

    orderer = srv._get_orderer("t", "doc")
    base = orderer.scriptorium.retained_base("t", "doc")
    assert base > 0  # prefix dropped
    # the margin holds: at least the last 5 pre-summary ops are retained
    head = orderer.deli.sequence_number
    assert head - base >= 5
    # a fetch reaching below the base fails LOUDLY (a silent gap would
    # stall the caller forever); from the base upward it serves normally
    from fluidframework_tpu.service.scriptorium import LogTruncatedError

    with pytest.raises(LogTruncatedError):
        srv.get_deltas("t", "doc", 0, 10**9)
    assert all(m.sequence_number > base
               for m in srv.get_deltas("t", "doc", base, 10**9))

    # fresh boots use the summary + retained tail and stay live
    c2 = loader.resolve("t", "doc")
    s2 = c2.runtime.get_data_store("default").get_channel("text")
    assert s2.get_text() == s.get_text()
    s2.insert_text(0, "x")
    assert s.get_text() == s2.get_text()

    # a second cycle truncates further
    for i in range(10):
        s.insert_text(0, "y")
    sm.summarize_now()
    assert orderer.scriptorium.retained_base("t", "doc") > base
