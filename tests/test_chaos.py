"""Chaos plane + invariant monitor: determinism, seam behavior, and the
soak's self-tests (a broken monitor or disabled recovery MUST fail).

The quick campaigns here run in-process (phase A) and stay in the tier-1
set; the multi-seed and socket (phase B) soaks are marked ``slow``.
"""

import json

import pytest

from fluidframework_tpu.chaos import (
    FaultPlane,
    InvariantMonitor,
    InvariantViolation,
    SimulatedCrash,
    doc_fingerprint,
)
from fluidframework_tpu.chaos.hooks import install
from fluidframework_tpu.chaos.soak import run_soak
from fluidframework_tpu.protocol.messages import (
    MessageType,
    SequencedDocumentMessage,
)
from fluidframework_tpu.utils import Counters

pytestmark = pytest.mark.chaos


# ------------------------------------------------------------ fault plane


def _count_fires(seed, n=200):
    plane = FaultPlane(seed)
    plane.rule("net.send", "drop", p=0.1)
    plane.rule("log.append", "torn", every=7, times=3)
    fired = []
    for i in range(n):
        fired.append((plane("net.send", size=i), plane("log.append")))
    return fired, plane.injected


def test_plane_same_seed_same_schedule():
    a_fired, a_ledger = _count_fires(123)
    b_fired, b_ledger = _count_fires(123)
    assert a_fired == b_fired
    assert a_ledger == b_ledger
    c_fired, _ = _count_fires(124)
    assert a_fired != c_fired  # the seed actually matters


def test_plane_rule_budget_and_at():
    plane = FaultPlane(0)
    plane.rule("x", "boom", at=3)  # times defaults to 1
    hits = [plane("x") for _ in range(10)]
    assert hits == [None, None, "boom"] + [None] * 7


def test_plane_when_predicate_filters_context():
    plane = FaultPlane(0)
    plane.rule("net.send", "drop", every=1,
               when=lambda ctx: ctx.get("kind") == "submit")
    assert plane("net.send", kind="ping") is None
    assert plane("net.send", kind="submit") == "drop"


def test_plane_crash_directive_raises():
    plane = FaultPlane(0)
    plane.rule("stage.pre_checkpoint", "crash", at=1)
    with pytest.raises(SimulatedCrash):
        plane("stage.pre_checkpoint")


def test_plane_disarm_is_total():
    plane = FaultPlane(0)
    plane.rule("x", "boom", every=1)
    plane.disarm()
    assert all(plane("x") is None for _ in range(5))
    plane.arm()
    assert plane("x") == "boom"


def test_plane_ledger_classifies_boundaries():
    plane = FaultPlane(0)
    plane.rule("net.send", "drop", at=1)
    plane.rule("log.append", "torn", at=1)
    plane.rule("applier.ingest", "escalate_host", at=1)
    plane("net.send")
    plane("log.append")
    plane("applier.ingest")
    by_class = plane.injected_by_class()
    assert by_class == {"network": 1, "log": 1, "device": 1}


# -------------------------------------------------------------- monitor


def _seq(seq, msn=0, cid="c1", cseq=1, mtype=MessageType.OPERATION,
         contents=None):
    if contents is None:
        contents = ({"clientId": cid}
                    if mtype in (MessageType.CLIENT_JOIN,
                                 MessageType.CLIENT_LEAVE) else {})
    return SequencedDocumentMessage(
        client_id=cid, sequence_number=seq, minimum_sequence_number=msn,
        client_sequence_number=cseq, reference_sequence_number=0,
        type=mtype, contents=contents)


def test_monitor_catches_msn_regression():
    mon = InvariantMonitor()
    mon.observe(_seq(1, msn=0, mtype=MessageType.CLIENT_JOIN))
    mon.observe(_seq(2, msn=1))
    mon.observe(_seq(3, msn=0, cseq=2))  # msn went backwards
    with pytest.raises(InvariantViolation, match="msn decreased"):
        mon.check()


def test_monitor_catches_msn_above_seq():
    mon = InvariantMonitor()
    mon.observe(_seq(1, msn=5, mtype=MessageType.CLIENT_JOIN))
    with pytest.raises(InvariantViolation, match="msn 5 > seq 1"):
        mon.check()


def test_monitor_dedupes_replayed_seq_but_flags_when_broken():
    strict = InvariantMonitor(dedupe=False)
    lax = InvariantMonitor()
    for m in (_seq(1, mtype=MessageType.CLIENT_JOIN), _seq(2),
              _seq(2), _seq(3, cseq=2)):
        strict.observe(m)
        lax.observe(m)
    lax.check()  # redelivery absorbed
    assert lax.redelivered == 1
    with pytest.raises(InvariantViolation,
                       match="seq not strictly increasing"):
        strict.check()


def test_monitor_catches_clientseq_gap_without_nack():
    mon = InvariantMonitor()
    mon.observe(_seq(1, mtype=MessageType.CLIENT_JOIN))
    mon.observe(_seq(2, cseq=1))
    mon.observe(_seq(3, cseq=4))  # skipped 2 and 3, never nacked
    with pytest.raises(InvariantViolation, match="clientSeq gap"):
        mon.check()


def test_monitor_catches_op_from_unjoined_client():
    mon = InvariantMonitor()
    mon.observe(_seq(1, cid="ghost"))
    with pytest.raises(InvariantViolation, match="non-joined"):
        mon.check()


def test_monitor_catches_duplicate_join():
    mon = InvariantMonitor()
    mon.observe(_seq(1, mtype=MessageType.CLIENT_JOIN))
    mon.observe(_seq(2, mtype=MessageType.CLIENT_JOIN))
    with pytest.raises(InvariantViolation, match="duplicate join"):
        mon.check()


def test_monitor_submit_lifecycle_and_quiescence():
    mon = InvariantMonitor()
    mon.note_submit("c1", 1)
    mon.note_submit("c1", 2)
    mon.observe(_seq(1, mtype=MessageType.CLIENT_JOIN))
    mon.observe(_seq(2, cseq=1))
    # cseq 2 neither acked nor nacked → quiescence must fail
    with pytest.raises(InvariantViolation, match="neither acked"):
        mon.check_quiescent({"a": "f1", "b": "f1"})


def test_monitor_quiescence_catches_divergent_fingerprints():
    mon = InvariantMonitor()
    with pytest.raises(InvariantViolation, match="diverged"):
        mon.check_quiescent({"a": doc_fingerprint("ab", [{}, {}]),
                             "b": doc_fingerprint("ba", [{}, {}])})


def test_doc_fingerprint_covers_props():
    assert doc_fingerprint("ab", [{}, {}]) \
        != doc_fingerprint("ab", [{"k": 1}, {}])


# ----------------------------------------------------- seams (disarmed)


def test_seams_disarmed_by_default():
    """No chaos import, no chaos behavior: every seam class attr is None
    until hooks.install arms it."""
    from fluidframework_tpu.driver import network
    from fluidframework_tpu.service.broadcaster import BroadcasterLambda
    from fluidframework_tpu.service.local_log import OrderedLogBase
    from fluidframework_tpu.service.partitions import Partition
    from fluidframework_tpu.service.stage_runner import _StageHostBase
    from fluidframework_tpu.service.tpu_applier import TpuDocumentApplier

    assert OrderedLogBase.fault_plane is None
    assert BroadcasterLambda.fault_plane is None
    assert TpuDocumentApplier.fault_plane is None
    assert _StageHostBase.fault_plane is None
    assert Partition.fault_plane is None
    assert network.FRAME_FAULT_HOOK is None


def test_install_arms_and_uninstall_restores():
    from fluidframework_tpu.service.broadcaster import BroadcasterLambda
    from fluidframework_tpu.service.local_server import LocalServer

    server = LocalServer()
    plane = FaultPlane(0)
    uninstall = install(plane, server=server)
    assert server.log.fault_plane is plane
    assert BroadcasterLambda.fault_plane is plane
    uninstall()
    assert server.log.fault_plane is None
    assert BroadcasterLambda.fault_plane is None


def test_torn_append_drops_the_record():
    from fluidframework_tpu.service.local_log import LocalLog

    log = LocalLog()
    plane = FaultPlane(0, Counters())
    plane.rule("log.append", "torn", at=2)
    log.fault_plane = plane
    seen = []
    log.subscribe("t", lambda m: seen.append(m.value))
    log.append("t", "a")
    log.append("t", "b")  # torn: never stored
    log.append("t", "c")
    log.drain()
    assert seen == ["a", "c"]


def test_duplicate_append_stores_twice():
    from fluidframework_tpu.service.local_log import LocalLog

    log = LocalLog()
    plane = FaultPlane(0)
    plane.rule("log.append", "dup", at=1)
    log.fault_plane = plane
    log.append("t", "a")
    assert log.length("t") == 2


def test_rewind_redelivers_to_subscribers():
    from fluidframework_tpu.service.local_log import LocalLog

    log = LocalLog()
    seen = []
    log.subscribe("t", lambda m: seen.append(m.value))
    log.append("t", "a")
    log.drain()
    log.rewind_subscribers("t", 1)
    log.drain()
    assert seen == ["a", "a"]


def test_partition_checkpoint_crash_leaves_partial_progress():
    """A crash between two docs' checkpoints: the first doc's pipeline
    checkpointed, the second didn't — exactly the window raw-log replay
    has to cover."""
    from fluidframework_tpu.service.broadcaster import PubSub
    from fluidframework_tpu.service.core import InMemoryDb
    from fluidframework_tpu.service.local_log import LocalLog
    from fluidframework_tpu.service.partitions import Partition

    log, db, pubsub = LocalLog(), InMemoryDb(), PubSub()
    part = Partition(0, log, db, pubsub)
    part.orderer("t", "d1")
    part.orderer("t", "d2")
    plane = FaultPlane(0)
    plane.rule("partition.checkpoint", "crash", at=2)
    Partition.fault_plane = plane
    try:
        with pytest.raises(SimulatedCrash):
            part.checkpoint()
    finally:
        Partition.fault_plane = None


# ------------------------------------------------------------- the soak


def test_soak_quick_phase_a_holds_invariants():
    out = run_soak(seed=0, quick=True, phases="a")
    assert out["observed"] > 10
    assert out["coverage"]  # at least one boundary class hit
    assert out["counters"]["chaos.faults.injected"] >= 5
    # the injected orderer crash must have dumped the flight recorder,
    # and the dump's tail must carry pre-crash telemetry
    assert out["flight_dump"] is not None
    with open(out["flight_dump"], encoding="utf-8") as f:
        lines = f.read().splitlines()
    assert json.loads(lines[0])["flight"] == "orderer_crash"
    kinds = {json.loads(ln).get("kind") for ln in lines[1:]}
    assert "event" in kinds


def test_soak_fails_when_monitor_dedupe_broken():
    with pytest.raises(InvariantViolation):
        run_soak(seed=0, quick=True, phases="a", break_dedupe=True)


def test_soak_fails_when_recovery_disabled():
    with pytest.raises(InvariantViolation):
        run_soak(seed=0, quick=True, phases="a", no_recover=True)


@pytest.mark.slow
def test_soak_full_campaign_both_phases():
    out = run_soak(seed=0)
    assert set(out["coverage"]) == {"network", "log", "fanout", "stage",
                                    "device", "snapshot"}


@pytest.mark.slow
@pytest.mark.parametrize("seed", [7, 42])
def test_soak_other_seeds(seed):
    out = run_soak(seed=seed, quick=True, phases="a")
    assert out["observed"] > 10
