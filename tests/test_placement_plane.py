"""Placement control plane: epoch table ordering, deli's stale-epoch
fence, migration equivalence, the double-owner race, and the driver's
transparent redirect-retry lane during a live migration.

Ref: memory-orderer/src/reservationManager.ts is the lease analog; the
epoch-numbered routing table and the seal → fence → checkpoint →
atomic-handoff protocol are ours (service/placement_plane.py,
ARCHITECTURE.md "Placement & migration").
"""

import random
import threading
import time

import pytest

from fluidframework_tpu.chaos.migrate import (
    MigrateClient,
    _doc_for_partition,
    _log_fingerprint,
)
from fluidframework_tpu.chaos.monitor import InvariantMonitor
from fluidframework_tpu.chaos.soak import _replica_fingerprint
from fluidframework_tpu.obs import tier_snapshot
from fluidframework_tpu.protocol.messages import (
    DocumentMessage,
    MessageType,
)
from fluidframework_tpu.service.front_end import ShardHost
from fluidframework_tpu.service.placement import PlacementDir
from fluidframework_tpu.service.placement_plane import (
    EpochTable,
    MigrationEngine,
    RoutingCache,
)
from fluidframework_tpu.service.stage_runner import doc_partition
from fluidframework_tpu.utils.telemetry import Counters

TENANT = "chaos"


def wait_for(pred, timeout: float = 20.0) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.005)
    return bool(pred())


def _op(cseq: int, ref_seq: int = 0) -> DocumentMessage:
    return DocumentMessage(
        client_sequence_number=cseq, reference_sequence_number=ref_seq,
        type=MessageType.OPERATION, contents={"i": cseq})


def _host(shard_dir, prefer=(), n=2, ttl_s=30.0) -> ShardHost:
    h = ShardHost(str(shard_dir), n, prefer=prefer, ttl_s=ttl_s)
    h.address = f"inproc/{h.owner_id}"
    h.poll()
    return h


def _close(*hosts) -> None:
    for h in hosts:
        for s in list(h.servers.values()):
            s.log.close()


# ----------------------------------------------------------- epoch table


def test_epoch_monotonicity_and_cache_ordering(tmp_path):
    """Every ownership change bumps the global epoch; a routing cache
    holding epoch E refuses any push older than E, in any order."""
    table = EpochTable(str(tmp_path / "placement"))
    e1 = table.record_claim(0, "a", "addr-a")
    e2 = table.record_claim(1, "a", "addr-a")
    e3 = table.record_claim(0, "b", "addr-b")  # migration adoption
    assert e1 < e2 < e3
    assert table.epoch_of(0) == e3 and table.addr_of(0) == "addr-b"
    e4 = table.record_release(1, "a")
    assert e4 > e3 and table.addr_of(1) is None
    # release by a non-owner is a no-op (no bump, no route change)
    assert table.record_release(0, "a") is None
    assert table.global_epoch() == e4

    cache = RoutingCache(PlacementDir(str(tmp_path / "placement"), 2, 1.0),
                         table)
    assert cache.resolve(0) == "addr-b"
    # a delayed push about yesterday's owner cannot clobber the route
    assert cache.note_epoch(0, "addr-a", e1) is False
    assert cache.resolve(0) == "addr-b"
    assert cache.note_epoch(0, "addr-c", e4 + 1) is True
    assert cache.resolve(0) == "addr-c"
    # invalidation drops the address but keeps the epoch floor
    cache.invalidate(0)
    assert cache.note_epoch(0, "addr-a", e1) is False
    assert cache.note_epoch(0, "addr-d", e4 + 2) is True
    assert cache.resolve(0) == "addr-d"


# ------------------------------------------------------ stale-epoch fence


def test_stale_epoch_submit_refused(tmp_path):
    """Deli's admission refuses a record whose partition epoch is older
    than the table's: nacked with the CURRENT epoch, nothing sequenced,
    offset consumed — the ex-owner can never extend the log."""
    sh = _host(tmp_path, prefer=(0, 1))
    try:
        k = doc_partition("t1", "doc-x", 2)
        server = sh.servers[k]
        conn = server.connect("t1", "doc-x")
        nacks = []
        conn.on_nack = nacks.append
        conn.submit([_op(1)])
        server.drain()
        assert not nacks
        seq_before = server.doc_sequence_numbers()["t1/doc-x"]
        assert seq_before >= 2  # join + the op

        before = tier_snapshot("placement").get(
            "placement.epoch.stale_nacks", 0)
        # another core adopts the partition behind this host's back;
        # the once-per-poll table refresh arms the fence
        current = sh.table.record_claim(k, "other-core", "inproc/other")
        sh.table_epochs = sh.table.part_epochs()
        conn.submit([_op(2)])
        server.drain()

        assert len(nacks) == 1
        nack = nacks[0]
        assert nack.code == 410
        assert f"epoch {current}" in nack.message
        assert nack.operation.client_sequence_number == 2
        assert server.doc_sequence_numbers()["t1/doc-x"] == seq_before
        assert tier_snapshot("placement").get(
            "placement.epoch.stale_nacks", 0) == before + 1
    finally:
        _close(sh)


# ------------------------------------------------- migration equivalence


def _edit_stream(tmp_path, migrate_rounds):
    """Seeded two-client edit stream over partition 0, migrated between
    two cores at the given rounds. Returns the converged text."""
    a = _host(tmp_path, prefer=(0, 1))
    b = _host(tmp_path)
    hosts = [a, b]
    doc = _doc_for_partition(0, 2)
    counters = Counters()
    monitor = InvariantMonitor(counters)

    def owner():
        for h in hosts:
            s = h.servers.get(0)
            if s is not None and not s.sealed:
                return s
        return None

    def drain_all():
        for h in hosts:
            for s in list(h.servers.values()):
                s.drain()

    clients = [MigrateClient(doc, owner, monitor, counters,
                             random.Random(77 + i)) for i in range(2)]
    try:
        for c in clients:
            assert c.connect()
        drain_all()
        for rnd in range(30):
            for c in clients:
                if c.conn is None or c.severed:
                    assert c.reconnect()
            drain_all()
            for c in clients:
                c.edit(2)
            drain_all()
            if rnd in migrate_rounds:
                src = next(h for h in hosts if 0 in h.servers)
                tgt = next(h for h in hosts if h is not src)
                res = MigrationEngine(src).migrate(
                    0, tgt.address,
                    adopt=lambda k, addr, s=src, t=tgt:
                    MigrationEngine(t).adopt(k, s.owner_id))
                assert res["target"] == tgt.address
                # the real deployment drops the partition's sessions on
                # the flip; sever so the next round rejoins the target
                for c in clients:
                    c.sever()
        for _ in range(10):
            drain_all()
            if all(c.settled for c in clients):
                break
            for c in clients:
                if not c.settled:
                    c.reconnect()
        drain_all()
        for c in clients:
            c.catch_up()
        final = owner()
        # offline replay: the whole multi-owner history from offset 0
        monitor.attach(final.log, f"deltas/{TENANT}/{doc}")
        final.drain()
        fps = {i: _replica_fingerprint(c.replica)
               for i, c in enumerate(clients)}
        fps["oracle"] = _log_fingerprint(final, doc)
        assert len(set(fps.values())) == 1, fps
        monitor.check_quiescent({str(k): v for k, v in fps.items()})
        return clients[0].replica.get_text()
    finally:
        _close(*hosts)


def test_migration_equivalence_fuzz(tmp_path):
    """The same seeded edit stream produces the SAME document whether
    the partition stayed put or migrated A→B and back mid-stream: the
    target resumes from the checkpoint + idempotent raw-log tail with
    nothing lost, duplicated, or reordered."""
    migrated = _edit_stream(tmp_path / "migrated", {9, 19})
    control = _edit_stream(tmp_path / "control", set())
    assert migrated == control
    assert len(control) > 20


# ------------------------------------------------------ double-owner race


def test_double_owner_race_exactly_one_sequences(tmp_path):
    """Two cores race to adopt the same partition: the flocked lease
    transfer admits exactly one, and the dispossessed ex-owner's next
    submit is refused by the epoch fence — never sequenced twice."""
    a = _host(tmp_path, prefer=(0,), n=1)
    b = _host(tmp_path, n=1)
    c = _host(tmp_path, n=1)
    try:
        conn = a.servers[0].connect("t1", "doc-r")
        conn.submit([_op(1)])
        a.servers[0].drain()

        winners, barrier = [], threading.Barrier(2)

        def race(host):
            barrier.wait()
            try:
                winners.append((host, MigrationEngine(host).adopt(
                    0, a.owner_id)))
            except RuntimeError:
                pass  # lost the transfer race

        threads = [threading.Thread(target=race, args=(h,)) for h in (b, c)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(winners) == 1
        winner = winners[0][0]
        loser = b if winner is c else c
        assert 0 in winner.servers and 0 not in loser.servers

        # the winner sequences; the ex-owner's fence refuses
        wconn = winner.servers[0].connect("t1", "doc-r")
        wconn.submit([_op(1)])
        winner.servers[0].drain()
        nacks = []
        conn.on_nack = nacks.append
        a.table_epochs = a.table.part_epochs()  # ex-owner's poll refresh
        conn.submit([_op(2)])
        a.servers[0].drain()
        assert len(nacks) == 1 and nacks[0].code == 410
        # the refused op is NOT in the authoritative log: only the
        # winner's server advanced past the fence point
        assert (winner.servers[0].doc_sequence_numbers()["t1/doc-r"]
                > a.servers[0].doc_sequence_numbers()["t1/doc-r"])
    finally:
        _close(a, b, c)


# ------------------------------------------- driver redirect retry order


def test_driver_redirect_retry_preserves_cseq_order(tmp_path):
    """Submits hitting a sealed partition bounce with a retryable
    redirect; the driver parks them on the shed-retry lane and resubmits
    transparently AFTER the flip — every op acked exactly once, in
    client-sequence order, with no app-visible nack."""
    from fluidframework_tpu.driver.network import (
        NetworkDocumentServiceFactory,
    )
    from fluidframework_tpu.service.front_end import NetworkFrontEnd
    from fluidframework_tpu.service.local_server import LocalServer

    front = NetworkFrontEnd(LocalServer()).start_background()
    factory = NetworkDocumentServiceFactory("127.0.0.1", front.port)
    try:
        conn = factory.create_document_service(
            "t", "doc-m").connect_to_delta_stream()
        acked, hard = {}, []
        conn.on_op = lambda m: (
            m.client_id == conn.client_id
            and acked.__setitem__(m.client_sequence_number,
                                  m.sequence_number))
        conn.on_nack = hard.append

        conn.submit([_op(1), _op(2)])
        assert wait_for(lambda: len(acked) == 2)

        placement_redirects = tier_snapshot("placement").get(
            "placement.submits.redirected", 0)
        front.server.seal()
        snap = factory.counters.snapshot
        shed_before = snap().get("driver.submit.shed_retries", 0)
        conn.submit([_op(c) for c in range(3, 13)])
        assert wait_for(
            lambda: snap().get("driver.submit.shed_retries", 0)
            > shed_before)
        assert len(acked) == 2  # nothing sequenced through the seal
        assert tier_snapshot("placement").get(
            "placement.submits.redirected", 0) > placement_redirects

        front.server.unseal()
        assert wait_for(lambda: len(acked) == 12, timeout=30.0)
        assert not hard, f"hard nack leaked: {hard[0]}"
        # the retry lane preserved submission order across the flip
        seqs = [acked[cs] for cs in range(3, 13)]
        assert seqs == sorted(seqs)
        conn.close()
    finally:
        front.stop()


# ---------------------------------------------------- campaign smoke run


def test_chaos_migrate_quick_campaign():
    """The chaos migration campaign's own verdict machinery, quick
    variant: one source-crash recovery + one clean migration, replayed
    through the invariant monitor."""
    from fluidframework_tpu.chaos.migrate import run_campaign

    result = run_campaign(11, Counters(), quick=True)
    assert result["recoveries"] == 1
    assert result["placement"]["placement.migration.committed"] >= 1
    assert result["sequenced"]["doc0"] > 20
