"""Boxcar fast lane ≡ scalar lane: fuzzed equivalence for the deli-tpu path.

The batched ticketing in service/deli.py (_ticket_boxcar) must emit
byte-identical sequenced messages and nacks to feeding the same ops one at
a time through the scalar reference (_ticket) — including under fault
injection (dups, gaps, stale refs, unjoined clients, interleaved
joins/leaves). Ref: the reference asserts the same property implicitly by
running the identical deli code on boxcar-unwrapped messages
(services-core/src/messages.ts IBoxcarMessage, deli/lambda.ts:171).
"""

from __future__ import annotations

import random

from fluidframework_tpu.protocol.messages import DocumentMessage, MessageType
from fluidframework_tpu.service.core import QueuedMessage
from fluidframework_tpu.service.deli import (
    DeliLambda,
    RawBoxcar,
    RawMessage,
)


class Capture:
    def __init__(self):
        self.sequenced = []
        self.nacks = []

    def send(self, msg):
        self.sequenced.append(msg)

    def send_batch(self, msgs):
        self.sequenced.extend(msgs)

    def nack(self, client_id, nack):
        self.nacks.append((client_id, nack))


def make_deli(cap, batch: bool):
    return DeliLambda(
        "t",
        "d",
        send_sequenced=cap.send,
        send_nack=cap.nack,
        clock=lambda: 1000.0,
        send_sequenced_batch=cap.send_batch if batch else None,
    )


def feed(deli, records, as_boxcars: bool):
    offset = 0
    for rec in records:
        if as_boxcars or not isinstance(rec, RawBoxcar):
            deli.handler(QueuedMessage(offset, "raw", 0, rec))
            offset += 1
        else:
            for op in rec.ops:
                deli.handler(
                    QueuedMessage(
                        offset,
                        "raw",
                        0,
                        RawMessage(rec.tenant_id, rec.document_id,
                                   rec.client_id, op, rec.timestamp),
                    )
                )
                offset += 1


def msg_key(m):
    return (
        m.client_id,
        m.sequence_number,
        m.minimum_sequence_number,
        m.client_sequence_number,
        m.reference_sequence_number,
        m.type,
        repr(m.contents),
        m.timestamp,
        [(t.service, t.action, t.timestamp) for t in m.traces],
    )


def join(client_id, ts=1000.0):
    return RawMessage(
        "t", "d", None,
        DocumentMessage(-1, -1, MessageType.CLIENT_JOIN,
                        {"clientId": client_id}),
        timestamp=ts,
    )


def leave(client_id, ts=1000.0):
    return RawMessage(
        "t", "d", None,
        DocumentMessage(-1, -1, MessageType.CLIENT_LEAVE,
                        {"clientId": client_id}),
        timestamp=ts,
    )


def run_both(records):
    cap_s, cap_b = Capture(), Capture()
    feed(make_deli(cap_s, batch=False), records, as_boxcars=False)
    deli_b = make_deli(cap_b, batch=True)
    feed(deli_b, records, as_boxcars=True)
    assert [msg_key(m) for m in cap_b.sequenced] == [
        msg_key(m) for m in cap_s.sequenced
    ]
    assert [(c, n.message) for c, n in cap_b.nacks] == [
        (c, n.message) for c, n in cap_s.nacks
    ]
    return deli_b


def test_boxcar_happy_path_is_fast_and_identical():
    records = [join("a"), join("b")]
    ops_a = [DocumentMessage(i + 1, 2, MessageType.OPERATION, {"n": i})
             for i in range(5)]
    ops_b = [DocumentMessage(i + 1, 2, MessageType.OPERATION, {"n": 100 + i})
             for i in range(3)]
    records.append(RawBoxcar("t", "d", "a", ops_a, timestamp=1001.0))
    records.append(RawBoxcar("t", "d", "b", ops_b, timestamp=1002.0))
    deli = run_both(records)
    assert deli.boxcars_fast == 2
    assert deli.boxcars_fallback == 0


def test_boxcar_msn_tracks_growing_refseq_within_boxcar():
    records = [join("a"), join("b")]
    # client b's refs grow inside one boxcar; msn must move per op
    ops = [DocumentMessage(i + 1, 2 + i, MessageType.OPERATION, {})
           for i in range(4)]
    records.append(RawBoxcar("t", "d", "b", ops, timestamp=1003.0))
    run_both(records)


def test_boxcar_fallbacks_match_scalar():
    # dup (replayed boxcar), gap, unjoined client, stale ref, mixed types
    records = [join("a"), join("b")]
    ops = [DocumentMessage(i + 1, 2, MessageType.OPERATION, {}) for i in range(3)]
    box = RawBoxcar("t", "d", "a", ops, timestamp=1001.0)
    records.append(box)
    records.append(box)  # full dup: every op skipped
    records.append(  # gap: clientSeq jumps
        RawBoxcar("t", "d", "a",
                  [DocumentMessage(9, 3, MessageType.OPERATION, {})], 1002.0))
    records.append(  # unjoined client
        RawBoxcar("t", "d", "ghost",
                  [DocumentMessage(1, 0, MessageType.OPERATION, {})], 1002.5))
    records.append(  # noop mixed into a boxcar → scalar lane
        RawBoxcar("t", "d", "b", [
            DocumentMessage(1, 3, MessageType.OPERATION, {}),
            DocumentMessage(2, 3, MessageType.NOOP, None),
        ], 1003.0))
    deli = run_both(records)
    assert deli.boxcars_fallback >= 4


def test_boxcar_fuzz_equivalence():
    rng = random.Random(7)
    clients = ["a", "b", "c"]
    records = [join(c) for c in clients]
    state = {c: {"cseq": 0, "ref": 0} for c in clients}
    head = 3  # seqs from the joins

    for _ in range(200):
        roll = rng.random()
        c = rng.choice(clients)
        if roll < 0.08:
            records.append(leave(c))
            records.append(join(c))
            state[c] = {"cseq": 0, "ref": head}
            head += 2
        elif roll < 0.16:
            # adversarial: dup or gap or stale-ref boxcar
            kind = rng.choice(["dup", "gap", "stale"])
            if kind == "dup":
                cseq = max(1, state[c]["cseq"])  # already used
            elif kind == "gap":
                cseq = state[c]["cseq"] + 5
            else:
                cseq = state[c]["cseq"] + 1
            ref = -5 if kind == "stale" else state[c]["ref"]
            records.append(
                RawBoxcar("t", "d", c,
                          [DocumentMessage(cseq, ref, MessageType.OPERATION,
                                           {"adv": kind})], 1000.0))
            # dup/gap/stale ops never advance the mirrored client state
        else:
            n = rng.randint(1, 6)
            ops = []
            ref = state[c]["ref"]
            for _ in range(n):
                state[c]["cseq"] += 1
                if rng.random() < 0.3:
                    ref += rng.randint(0, 2)  # growing refs inside boxcar
                ops.append(DocumentMessage(state[c]["cseq"], ref,
                                           MessageType.OPERATION,
                                           {"r": rng.randint(0, 99)}))
            state[c]["ref"] = ref
            head += n
            records.append(RawBoxcar("t", "d", c, ops, timestamp=1000.0))
        # refs must stay resolvable: creep them up toward recent seqs
        for cc in clients:
            state[cc]["ref"] += rng.randint(0, 1)

    deli = run_both(records)
    assert deli.boxcars_fast > 10  # the fuzz exercised the fast lane


def test_boxcar_checkpoint_restart_equivalence():
    records = [join("a"), join("b")]
    for r in range(4):
        ops = [DocumentMessage(r * 3 + i + 1, 2, MessageType.OPERATION, {"r": r})
               for i in range(3)]
        records.append(RawBoxcar("t", "d", "a", ops, timestamp=1001.0 + r))

    cap1 = Capture()
    deli1 = make_deli(cap1, batch=True)
    feed(deli1, records, as_boxcars=True)
    cp = deli1.checkpoint()

    # replay the whole log against the checkpointed state: all skipped
    cap2 = Capture()
    deli2 = DeliLambda(
        "t", "d", send_sequenced=cap2.send, send_nack=cap2.nack,
        checkpoint=cp, clock=lambda: 1000.0,
        send_sequenced_batch=cap2.send_batch)
    feed(deli2, records, as_boxcars=True)
    assert cap2.sequenced == []
    assert deli2.sequence_number == deli1.sequence_number

    # crash replay WITHOUT checkpoint: re-feeding everything must dedupe
    # through the scalar fallback (same head, no new messages)
    cap3 = Capture()
    deli3 = make_deli(cap3, batch=True)
    feed(deli3, records + records, as_boxcars=True)
    assert [msg_key(m) for m in cap3.sequenced] == [
        msg_key(m) for m in cap1.sequenced
    ]
