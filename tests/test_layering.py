"""Layer-check: enforce the package import DAG.

Thin wrapper: the layer table (``ALLOWED``) and the AST import walk
live in ``tools/fluidlint/layers.py`` — the single source of truth
shared by this test, ``python -m tools.fluidlint`` (pass 1), and the
generated ``PACKAGES.md``. This test only asserts the checker comes
back clean, so the DAG cannot drift between CI and the lint tool.

Ref: tools/build-tools/src/layerCheck — the reference CI fails any
build whose packages import across the declared layer boundaries
(README.md:54-56, docs/PACKAGES.md is the generated layer list).
"""

from __future__ import annotations

import os

from tools.fluidlint import layers

ROOT = os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "fluidframework_tpu"))

#: Re-exported for anything that imported the table from here.
ALLOWED = layers.ALLOWED


def test_layer_dag():
    violations = layers.check_layers(root=ROOT)
    assert not violations, (
        "layering violations (see ALLOWED in tools/fluidlint/layers.py):"
        "\n  " + "\n  ".join(str(v) for v in violations))


def test_every_subpackage_is_classified():
    """A new subpackage must be placed in the layer map explicitly."""
    violations = layers.check_classified(root=ROOT)
    assert not violations, "\n".join(str(v) for v in violations)


def test_mergetree_never_imports_service():
    """The canonical violation the reference's layer-check exists to stop
    (CRDT core depending on the service) stays impossible."""
    for pkg, path in layers.package_files(ROOT, layers.ALLOWED):
        if pkg == "mergetree":
            deps = {d for d, _, _ in layers.sibling_imports(path, ROOT)}
            assert "service" not in deps, path


def test_packages_md_is_fresh():
    """The checked-in PACKAGES.md matches what the table generates."""
    violations = layers.check_packages_md()
    assert not violations, "\n".join(str(v) for v in violations)
