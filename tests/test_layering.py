"""Layer-check: enforce the package import DAG.

Ref: tools/build-tools/src/layerCheck — the reference CI fails any build
whose packages import across the declared layer boundaries
(README.md:54-56, docs/PACKAGES.md is the generated layer list). Here the
same guarantee is one AST pass over the tree: each subpackage may import
only from the layers at or below it.

Layering (bottom → top), mirroring SURVEY §1's layer map:

    utils                (L1 base utils / telemetry)
    protocol             (L0 defs + L2 shared consensus kernel)
    mergetree            (L6 CRDT core)
    ops, parallel        (TPU kernels / sharding over the mergetree model)
    dds                  (L6 DDS catalog)
    runtime              (L5)
    loader               (L4; the loader imports DRIVER interfaces)
    driver               (L3 — may bind to service for the local driver)
    framework            (L7)
    service              (S-layers: its own branch; may use protocol,
                          utils, mergetree-adjacent kernels, driver wire
                          helpers — but never runtime/loader/framework)
    replay, native       (tools / bindings)
"""

from __future__ import annotations

import ast
import os

import pytest

ROOT = os.path.join(os.path.dirname(__file__), "..", "fluidframework_tpu")

#: subpackage → the set of sibling subpackages it may import from.
#: An import of a package not in its set is a layering violation.
ALLOWED = {
    "utils": set(),
    "protocol": {"utils"},
    "mergetree": {"protocol", "utils"},
    "ops": {"mergetree", "protocol", "utils"},
    "parallel": {"ops", "mergetree", "protocol", "utils"},
    "dds": {"mergetree", "ops", "protocol", "utils"},
    "runtime": {"dds", "mergetree", "ops", "protocol", "utils"},
    "loader": {"runtime", "dds", "mergetree", "protocol", "utils",
               "driver"},
    # drivers bind the loader contracts to a service; the local driver
    # reaches into service (the reference's local-driver does the same —
    # localDocumentService.ts binds straight to LocalDeltaConnectionServer)
    "driver": {"protocol", "utils", "service", "mergetree"},
    "framework": {"loader", "runtime", "dds", "mergetree", "protocol",
                  "utils"},
    # the service branch: protocol + utils + the TPU kernel stack; the
    # wire helpers live in driver (shared transport), NEVER runtime/loader
    "service": {"protocol", "utils", "ops", "parallel", "mergetree",
                "driver", "native"},
    "native": {"utils"},
    "replay": {"loader", "driver", "runtime", "dds", "protocol", "utils",
               "service", "mergetree"},
}


def _imports_of(path: str) -> set[str]:
    """Sibling fluidframework_tpu subpackages imported by this module."""
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    depth_from_root = os.path.relpath(
        path, ROOT).count(os.sep)  # 0 = top-level module
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.level == 0:
                mod = node.module or ""
                if mod.startswith("fluidframework_tpu."):
                    out.add(mod.split(".")[1])
            else:
                # relative: level 1 inside pkg/x.py = same package;
                # level 2 = the framework root (..sibling)
                if node.level == depth_from_root + 1 and node.module:
                    out.add(node.module.split(".")[0])
                elif node.level > depth_from_root + 1:
                    out.add("<outside-package>")
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.startswith("fluidframework_tpu."):
                    out.add(alias.name.split(".")[1])
    return out


def _package_files():
    for pkg in sorted(ALLOWED):
        pkg_dir = os.path.join(ROOT, pkg)
        if not os.path.isdir(pkg_dir):
            continue
        for dirpath, _, files in os.walk(pkg_dir):
            for fn in sorted(files):
                if fn.endswith(".py"):
                    yield pkg, os.path.join(dirpath, fn)


def test_layer_dag():
    violations = []
    for pkg, path in _package_files():
        allowed = ALLOWED[pkg] | {pkg}
        for dep in _imports_of(path):
            # only sibling SUBPACKAGES are layered; top-level modules
            # (config.py — the cross-cutting unified registry) are free
            if dep not in ALLOWED:
                continue
            if dep not in allowed:
                rel = os.path.relpath(path, ROOT)
                violations.append(f"{rel}: {pkg} -> {dep}")
    assert not violations, (
        "layering violations (see ALLOWED in this file):\n  "
        + "\n  ".join(violations))


def test_every_subpackage_is_classified():
    """A new subpackage must be placed in the layer map explicitly."""
    found = {d for d in os.listdir(ROOT)
             if os.path.isdir(os.path.join(ROOT, d))
             and not d.startswith("__")}
    unclassified = found - set(ALLOWED)
    assert not unclassified, (
        f"subpackages missing from the layer map: {sorted(unclassified)}")


def test_mergetree_never_imports_service():
    """The canonical violation the reference's layer-check exists to stop
    (CRDT core depending on the service) stays impossible."""
    for pkg, path in _package_files():
        if pkg == "mergetree":
            assert "service" not in _imports_of(path), path
