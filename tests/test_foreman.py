"""Foreman task broker: assignment, heartbeat expiry, reassignment on
worker death, stale-completion rejection (ref: lambdas/src/foreman).
"""

from fluidframework_tpu.service.foreman import Foreman


def mk(clock):
    return Foreman(clock=lambda: clock[0], worker_timeout=10.0)


def test_tasks_spread_least_loaded_and_complete():
    clock = [0.0]
    f = mk(clock)
    got = {"a": [], "b": []}
    f.register_worker("a", lambda t: got["a"].append(t))
    f.register_worker("b", lambda t: got["b"].append(t))
    for i in range(6):
        f.enqueue(f"t{i}", {"n": i})
    assert len(got["a"]) == 3 and len(got["b"]) == 3
    for t in got["a"] + got["b"]:
        worker = "a" if t in got["a"] else "b"
        assert f.complete(worker, t["task_id"], t["payload"]["n"] * 2)
    assert f.pending_count() == 0
    assert f.result("t4") == 8


def test_dead_worker_tasks_reassign_and_stale_completion_refused():
    clock = [0.0]
    f = mk(clock)
    got = {"a": [], "b": []}
    f.register_worker("a", lambda t: got["a"].append(t))
    f.enqueue("job", {"x": 1})
    assert len(got["a"]) == 1  # only worker gets it

    clock[0] = 5.0
    f.register_worker("b", lambda t: got["b"].append(t))
    clock[0] = 20.0
    f.heartbeat("b")
    f.check_workers()  # a silent past timeout → dropped, job requeued
    assert f.reassignments == 1
    assert len(got["b"]) == 1 and got["b"][0]["attempt"] == 2

    # the zombie's late result must NOT overwrite the live attempt
    assert not f.complete("a", "job", "stale result")
    assert f.result("job") is None
    assert f.complete("b", "job", "fresh result")
    assert f.result("job") == "fresh result"


def test_tasks_queue_until_a_worker_exists():
    clock = [0.0]
    f = mk(clock)
    f.enqueue("early", {"k": 1})
    assert f.pending_count() == 1
    got = []
    f.register_worker("late", got.append)
    assert [t["task_id"] for t in got] == ["early"]
