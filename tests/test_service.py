"""Service pipeline tests: deli sequencing, nacks, idle expiry, restart
from checkpoint, broadcast fan-out, scriptorium backfill, signals.

Ref test strategy: routerlicious src/test/alfred/io.spec.ts (socket
contract), lambdas-driver partition checkpoint tests, local-server
localDeltaConnectionServer.spec.ts (SURVEY §4).
"""

from fluidframework_tpu.protocol.messages import DocumentMessage, MessageType
from fluidframework_tpu.service import LocalServer


class FakeClock:
    def __init__(self):
        self.now = 1000.0

    def __call__(self):
        return self.now


def op(csn, rsn, contents=None):
    return DocumentMessage(
        client_sequence_number=csn,
        reference_sequence_number=rsn,
        type=MessageType.OPERATION,
        contents=contents,
    )


def make_client(server, tenant="t", doc="d"):
    conn = server.connect(tenant, doc)
    received, nacks, signals = [], [], []
    conn.on_op = received.append
    conn.on_nack = nacks.append
    conn.on_signal = signals.append
    return conn, received, nacks, signals


def test_join_assigns_sequence_and_broadcasts():
    server = LocalServer()
    c1, r1, _, _ = make_client(server)
    c2, r2, _, _ = make_client(server)
    # both clients see both joins (c1 sees its own join + c2's)
    assert [m.type for m in r1] == [MessageType.CLIENT_JOIN] * 2
    assert [m.sequence_number for m in r1] == [1, 2]
    # c2 connected after join 1 was sequenced; it only sees join 2 live
    assert [m.sequence_number for m in r2] == [2]
    assert c2.initial_sequence_number == 1


def test_ops_are_totally_ordered_and_fanned_out():
    server = LocalServer()
    c1, r1, _, _ = make_client(server)
    c2, r2, _, _ = make_client(server)
    c1.submit([op(1, 2, {"x": 1})])
    c2.submit([op(1, 2, {"x": 2})])
    ops1 = [m for m in r1 if m.type == MessageType.OPERATION]
    ops2 = [m for m in r2 if m.type == MessageType.OPERATION]
    assert [m.sequence_number for m in ops1] == [3, 4]
    assert [(m.client_id, m.contents) for m in ops1] == [
        (c1.client_id, {"x": 1}),
        (c2.client_id, {"x": 2}),
    ]
    # identical streams on every client
    assert [(m.sequence_number, m.client_id) for m in ops1] == [
        (m.sequence_number, m.client_id) for m in ops2
    ]


def test_msn_is_min_ref_seq_over_clients():
    server = LocalServer()
    c1, r1, _, _ = make_client(server)
    c2, _, _, _ = make_client(server)
    c1.submit([op(1, 2)])
    c2.submit([op(1, 3)])
    ops = [m for m in r1 if m.type == MessageType.OPERATION]
    assert ops[-1].minimum_sequence_number == 2  # min(2, 3)
    # after c1 leaves, msn advances to c2's refSeq
    c1.disconnect()
    c2.submit([op(2, 3)])
    server.drain()
    deltas = server.get_deltas("t", "d", 0, 100)
    assert deltas[-1].minimum_sequence_number == 3


def test_duplicate_clientseq_ignored_gap_nacked():
    server = LocalServer()
    c1, r1, nacks, _ = make_client(server)
    c1.submit([op(1, 1)])
    c1.submit([op(1, 1)])  # duplicate: silently dropped
    ops = [m for m in r1 if m.type == MessageType.OPERATION]
    assert len(ops) == 1
    c1.submit([op(5, 1)])  # gap: nacked
    assert len(nacks) == 1
    assert "gap" in nacks[0].message


def test_stale_refseq_nacked():
    server = LocalServer()
    c1, _, nacks1, _ = make_client(server)
    c2, _, _, _ = make_client(server)
    c1.submit([op(1, 2)])
    c2.submit([op(1, 2)])
    # both clients' refSeq floor is 2 now; a refSeq below it is nacked
    c1.submit([op(2, 1)])
    assert len(nacks1) == 1
    assert "below msn" in nacks1[0].message


def test_expired_client_submission_nacked():
    # a client evicted by idle expiry (socket still open) gets nacked on
    # its next submit and must reconnect (ref: deli nack on unknown client)
    clock = FakeClock()
    server = LocalServer(clock=clock, client_timeout=60.0)
    c1, _, nacks, _ = make_client(server)
    c2, _, _, _ = make_client(server)
    clock.now += 120
    c2.submit([op(1, 1)])  # keeps c2 alive at +120
    server.expire_idle_clients()  # evicts c1
    c1.submit([op(1, 1)])
    assert len(nacks) == 1
    assert "not connected" in nacks[0].message


def test_idle_client_expiry_advances_msn():
    clock = FakeClock()
    server = LocalServer(clock=clock, client_timeout=60.0)
    c1, r1, _, _ = make_client(server)
    c2, _, _, _ = make_client(server)
    c1.submit([op(1, 1)])
    clock.now += 120  # c1 goes idle; c2 stays active via its op below
    c2.submit([op(1, 2)])
    server.expire_idle_clients()
    deltas = server.get_deltas("t", "d", 0, 100)
    leaves = [m for m in deltas if m.type == MessageType.CLIENT_LEAVE]
    assert [m.contents["clientId"] for m in leaves] == [c1.client_id]
    # with c1 gone the msn is no longer pinned at its refSeq of 1
    assert deltas[-1].minimum_sequence_number == 2


def test_idle_expiry_only_hits_stale_clients():
    clock = FakeClock()
    server = LocalServer(clock=clock, client_timeout=60.0)
    c1, _, _, _ = make_client(server)
    c2, _, _, _ = make_client(server)
    c1.submit([op(1, 1)])
    clock.now += 120
    c2.submit([op(1, 2)])  # c2 active at +120
    clock.now += 10
    server.expire_idle_clients()
    deltas = server.get_deltas("t", "d", 0, 100)
    leaves = [m for m in deltas if m.type == MessageType.CLIENT_LEAVE]
    assert [m.contents["clientId"] for m in leaves] == [c1.client_id]


def test_scriptorium_backfill_window():
    server = LocalServer()
    c1, _, _, _ = make_client(server)
    for i in range(5):
        c1.submit([op(i + 1, 1, {"i": i})])
    deltas = server.get_deltas("t", "d", 2, 5)  # exclusive bounds
    assert [m.sequence_number for m in deltas] == [3, 4]


def test_deli_restart_from_checkpoint_resumes_sequencing():
    server = LocalServer()
    c1, r1, _, _ = make_client(server)
    c1.submit([op(1, 1)])
    seq_before = server._orderers["t/d"].deli.sequence_number
    seen_before = len(r1)

    server.restart_orderer("t", "d")
    orderer2 = server._orderers["t/d"]
    assert orderer2.deli.sequence_number == seq_before
    assert c1.client_id in orderer2.deli.clients
    # replay of already-ticketed raw messages is skipped by log offset,
    # and the new broadcaster must not re-deliver history to live clients
    before = server.log.length(orderer2.deltas_topic)
    server.drain()
    assert server.log.length(orderer2.deltas_topic) == before
    assert len(r1) == seen_before
    # new ops continue the sequence with no gap or dup, delivered once
    c1.submit([op(2, 2)])
    deltas = server.get_deltas("t", "d", 0, 100)
    seqs = [m.sequence_number for m in deltas]
    assert seqs == list(range(1, len(seqs) + 1))
    assert len(r1) == seen_before + 1


def test_signals_relayed_unsequenced():
    server = LocalServer()
    c1, _, _, s1 = make_client(server)
    c2, _, _, s2 = make_client(server)
    c1.submit_signal({"cursor": 7})
    assert [s.content for s in s1] == [{"cursor": 7}]
    assert [s.content for s in s2] == [{"cursor": 7}]
    assert s1[0].client_id == c1.client_id
    # signals never hit the op log
    assert server.get_deltas("t", "d", 0, 100)[-1].type == MessageType.CLIENT_JOIN


def test_manual_drain_controls_interleaving():
    server = LocalServer(auto_drain=False)
    c1 = server.connect("t", "d")
    received = []
    c1.on_op = received.append
    assert received == []  # nothing delivered yet
    server.drain()
    assert [m.type for m in received] == [MessageType.CLIENT_JOIN]
    c1.submit([op(1, 1, {"a": 1})])
    c1.submit([op(2, 1, {"a": 2})])
    assert len(received) == 1
    server.drain()
    assert [m.contents for m in received[1:]] == [{"a": 1}, {"a": 2}]


def test_independent_documents_have_independent_orders():
    server = LocalServer()
    ca = server.connect("t", "docA")
    cb = server.connect("t", "docB")
    ra, rb = [], []
    ca.on_op = ra.append
    cb.on_op = rb.append
    ca.submit([op(1, 1)])
    cb.submit([op(1, 1)])
    assert server.get_deltas("t", "docA", 0, 100)[-1].sequence_number == 2
    assert server.get_deltas("t", "docB", 0, 100)[-1].sequence_number == 2
    assert all(m.sequence_number <= 2 for m in ra)


def test_idle_eviction_rides_raw_log_for_deterministic_replay():
    """Idle-eviction leaves must be raw-log records so a crash after the
    eviction replays into identical sequence numbers (ADVICE r1, deli.py)."""
    clock = FakeClock()
    server = LocalServer(clock=clock, client_timeout=60.0)
    c1, _, _, _ = make_client(server)
    c2, _, _, _ = make_client(server)
    c1.submit([op(1, 1)])
    clock.now += 120
    c2.submit([op(1, 1)])
    server.expire_idle_clients()  # evicts c1 via the raw topic

    orderer = server._orderers["t/d"]
    seq_after_evict = orderer.deli.sequence_number
    deltas_before = [
        (m.sequence_number, m.type, m.client_id)
        for m in server.get_deltas("t", "d", 0, 10**6)
    ]
    # the leave is in the raw log... (client submits ride as boxcars,
    # server-originated records as single RawMessages)
    raw_types = []
    for i in range(orderer._log.length(orderer.raw_topic)):
        rec = orderer._log.read(orderer.raw_topic, i)
        if hasattr(rec, "ops"):
            raw_types.extend(o.type for o in rec.ops)
        else:
            raw_types.append(rec.operation.type)
    assert MessageType.CLIENT_LEAVE in raw_types

    # ...so an UN-checkpointed restart (crash: no orderer.checkpoint())
    # replays the raw topic into the SAME ticketing: same head seq, no
    # duplicate/new records
    server._orderers.pop("t/d").close()
    orderer2 = server._get_orderer("t", "d")
    server.drain()
    assert orderer2.deli.sequence_number == seq_after_evict
    deltas_after = [
        (m.sequence_number, m.type, m.client_id)
        for m in server.get_deltas("t", "d", 0, 10**6)
    ]
    assert deltas_after == deltas_before
    assert c1.client_id not in orderer2.deli.clients


def test_copier_archives_raw_traffic_including_rejected():
    """Copier (lambdas/src/copier): the raw archive keeps what deli
    NACKED too — the sequenced log only shows accepted traffic."""
    from fluidframework_tpu.protocol.messages import (
        DocumentMessage,
        MessageType,
    )
    from fluidframework_tpu.service import LocalServer
    from fluidframework_tpu.service.copier import CopierLambda

    server = LocalServer()
    copier = CopierLambda(server.db)
    conn = server.connect("t", "doc")
    # subscribe the copier to the doc's raw topic like any other lambda
    orderer = server._get_orderer("t", "doc")
    server.log.subscribe(orderer.raw_topic, copier.handler, from_offset=0)

    conn.submit([DocumentMessage(
        client_sequence_number=1, reference_sequence_number=0,
        type=MessageType.OPERATION, contents={"good": 1})])
    nacks = []
    conn.on_nack = lambda n: nacks.append(n)
    conn.submit([DocumentMessage(
        client_sequence_number=9, reference_sequence_number=0,  # gap
        type=MessageType.OPERATION, contents={"bad": 1})])
    assert nacks  # deli refused it

    rows = copier.archive("t", "doc")
    kinds = [(r["kind"], r.get("clientSeq") or
              (r["ops"][0]["clientSeq"] if r.get("ops") else None))
             for r in rows]
    # join (raw) + accepted boxcar + the NACKED boxcar are all archived
    assert ("raw", -1) in kinds
    assert ("boxcar", 1) in kinds
    assert ("boxcar", 9) in kinds  # the rejected submission is auditable
    assert copier.copied == len(rows)


def test_noop_heartbeats_consolidate_out_of_the_stream():
    """Client noops move the sender's refSeq (and thus the msn) without
    occupying sequence numbers (ref: deli noop consolidation)."""
    from fluidframework_tpu.protocol.messages import (
        DocumentMessage,
        MessageType,
    )
    from fluidframework_tpu.service import LocalServer

    server = LocalServer()
    w = server.connect("t", "doc")
    idle = server.connect("t", "doc")
    deli = server._get_orderer("t", "doc").deli
    for i in range(1, 4):
        w.submit([DocumentMessage(
            client_sequence_number=i, reference_sequence_number=i,
            type=MessageType.OPERATION, contents={"i": i})])
    before = deli.sequence_number
    pinned = deli._min_ref_seq()
    assert pinned < before  # the idle client pins the msn below the head

    # the FLOOR-MOVING noop sequences (one message makes the msn
    # visible, so quorum proposals can commit)
    idle.submit([DocumentMessage(
        client_sequence_number=1, reference_sequence_number=before,
        type=MessageType.NOOP)])
    assert deli.sequence_number == before + 1
    assert deli._min_ref_seq() > pinned  # the floor moved

    # a REDUNDANT heartbeat from the same client (floor unchanged)
    # consolidates away
    idle.submit([DocumentMessage(
        client_sequence_number=2, reference_sequence_number=before,
        type=MessageType.NOOP)])
    assert deli.sequence_number == before + 1  # nothing sequenced
    assert deli.noops_consolidated == 1

    # the clientSeq the swallowed noop consumed does not read as a gap
    idle.submit([DocumentMessage(
        client_sequence_number=3, reference_sequence_number=before,
        type=MessageType.OPERATION, contents={"after": 1})])
    assert deli.sequence_number == before + 2
    log = server.get_deltas("t", "doc", 0, 10**9)
    assert [m.type.value for m in log].count("noop") == 1
