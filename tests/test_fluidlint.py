"""fluidlint self-tests: each pass catches its fixture violation and
comes back clean on the clean twin (and on the real tree).

Fixtures live in tests/fixtures/fluidlint/ — a deliberate layering
violation, a deliberately gather-ful kernel, and an int16-promotion
bug. The hygiene/layer walkers skip fixtures/ directories, so the bad
fixtures never pollute the real-tree run.
"""

from __future__ import annotations

import importlib.util
import os
import subprocess
import sys

import jax.numpy as jnp
import numpy as np

from fluidframework_tpu.utils.contracts import (
    kernel_contract,
    register_kernel_contract,
)
from tools.fluidlint import (
    hygiene,
    jaxpr_check,
    journal_check,
    layers,
    metrics_check,
    storage_check,
    wire_check,
)

HERE = os.path.dirname(__file__)
FIX = os.path.join(HERE, "fixtures", "fluidlint")
REPO = os.path.abspath(os.path.join(HERE, ".."))

BAD_TREE = os.path.join(FIX, "layering_bad", "fluidframework_tpu")
CLEAN_TREE = os.path.join(FIX, "layering_clean", "fluidframework_tpu")


# ---------------------------------------------------------------- layers

def test_layering_violation_caught():
    vs = layers.check_layers(root=BAD_TREE, repo_root=FIX)
    assert len(vs) == 1, [str(v) for v in vs]
    v = vs[0]
    assert "'utils' may not import 'protocol'" in v.message
    assert v.path.endswith("utils/leaky.py")
    assert v.line > 0
    assert "protocol" in v.suggestion  # names the layers it IS legal from


def test_layering_clean_fixture_passes():
    assert layers.check_layers(root=CLEAN_TREE, repo_root=FIX) == []


def test_unclassified_subpackage_caught(tmp_path):
    root = tmp_path / "fluidframework_tpu"
    (root / "rogue").mkdir(parents=True)
    (root / "rogue" / "__init__.py").write_text("")
    vs = layers.check_classified(root=str(root), repo_root=str(tmp_path))
    assert len(vs) == 1 and "'rogue'" in vs[0].message


def test_emit_packages_md_is_deterministic():
    a = layers.emit_packages_md(repo_root=REPO)
    b = layers.emit_packages_md(repo_root=REPO)
    assert a == b
    assert "GENERATED" in a
    # every classified layer appears as a section
    for pkg in layers.ALLOWED:
        assert f"## {pkg}" in a


def test_stale_packages_md_caught(tmp_path):
    md = tmp_path / "PACKAGES.md"
    md.write_text("# PACKAGES\n\nstale by hand-editing\n")
    vs = layers.check_packages_md(md_path=str(md), repo_root=REPO)
    assert len(vs) == 1 and "stale" in vs[0].message


# ----------------------------------------------------------------- jaxpr

def _fixture_kernels():
    spec = importlib.util.spec_from_file_location(
        "fluidlint_fixture_kernels", os.path.join(FIX, "kernels.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _example_gather():
    return ((jnp.arange(12.0).reshape(3, 4), jnp.array([0, 2, 1])), {})


def _example_int16():
    return ((jnp.zeros((3, 4), jnp.int16), jnp.zeros((3, 2), jnp.int32)),
            {})


def test_gatherful_kernel_caught():
    mod = _fixture_kernels()
    reg: dict = {}
    kernel_contract("fixture.gatherful", example=_example_gather,
                    no_gather=True, registry=reg)(mod.gatherful_kernel)
    vs = jaxpr_check.check_kernels(registry=reg, required=())
    assert len(vs) == 1, [str(v) for v in vs]
    assert "gather" in vs[0].message and "no_gather" in vs[0].message


def test_clean_kernel_passes():
    mod = _fixture_kernels()
    reg: dict = {}
    kernel_contract("fixture.clean", example=_example_gather,
                    no_gather=True, no_scatter=True, single_jit=True,
                    registry=reg)(mod.clean_kernel)
    assert jaxpr_check.check_kernels(registry=reg, required=()) == []


def test_int16_promotion_caught():
    mod = _fixture_kernels()
    reg: dict = {}
    kernel_contract("fixture.int16_promoting", example=_example_int16,
                    no_int16_arithmetic=True,
                    registry=reg)(mod.int16_promoting_kernel)
    vs = jaxpr_check.check_kernels(registry=reg, required=())
    assert len(vs) == 1, [str(v) for v in vs]
    assert "int16" in vs[0].message


def test_int16_clean_passes():
    mod = _fixture_kernels()
    reg: dict = {}
    kernel_contract("fixture.int16_clean", example=_example_int16,
                    no_int16_arithmetic=True,
                    registry=reg)(mod.int16_clean_kernel)
    assert jaxpr_check.check_kernels(registry=reg, required=()) == []


def test_missing_required_registration_flagged():
    vs = jaxpr_check.check_kernels(registry={},
                                   required=("ops.apply_ops_batch",))
    assert len(vs) == 1 and "not registered" in vs[0].message


def test_real_registry_covers_required_kernels():
    reg = jaxpr_check.load_registry()
    for name in jaxpr_check.REQUIRED_KERNELS:
        assert name in reg, f"{name} lost its contract registration"


def test_batched_apply_jaxpr_is_gather_free():
    """The acceptance-criterion check, as a direct assertion: the
    registered batched-apply kernel's jaxpr has NO gather/scatter."""
    reg = jaxpr_check.load_registry()
    contract = reg["ops.apply_ops_batch"]
    fn, example = contract.build()
    args, kwargs = example()
    closed = jaxpr_check._trace(fn, args, kwargs)
    counts = jaxpr_check.primitive_counts(closed.jaxpr)
    assert counts.get("gather", 0) == 0, counts
    assert not any(p.startswith("scatter") for p in counts), counts


def test_packed_sharded_step_contract_holds():
    """The multi-chip fast lane's contract, run directly: the registered
    parallel.sharded_step_packed kernel must satisfy all its declared
    checks (scatter-free, bounded gathers, no silent int16 promotion,
    single compile) — and must actually be in REQUIRED_KERNELS so a
    future deregistration can't slip through."""
    assert "parallel.sharded_step_packed" in jaxpr_check.REQUIRED_KERNELS
    reg = jaxpr_check.load_registry()
    vs = [v for v in jaxpr_check.check_kernels(registry=reg, required=())
          if "sharded_step_packed" in str(v)]
    assert vs == [], [str(v) for v in vs]


def test_pallas_packed_contracts_hold():
    """The default-on Pallas lane (applier.kernel=pallas): both the
    dense and mesh selections must satisfy every declared invariant —
    the checker walks INTO the pallas_call jaxpr — and must be pinned in
    REQUIRED_KERNELS so a future deregistration can't slip through."""
    names = ("service.dense_step_packed_pallas",
             "parallel.sharded_step_packed_pallas")
    for name in names:
        assert name in jaxpr_check.REQUIRED_KERNELS, name
    reg = jaxpr_check.load_registry()
    sub = {n: reg[n] for n in names}
    vs = jaxpr_check.check_kernels(registry=sub, required=())
    assert vs == [], [str(v) for v in vs]


def test_pallas_contract_regression_fails_lint():
    """A contract REGRESSION in the Pallas lane must fail the lint, not
    pass silently: wrap the real registered kernel with int16 arithmetic
    smuggled in ahead of the explicit widen and assert the checker flags
    it under the same declared invariants."""
    reg = jaxpr_check.load_registry()
    good = reg["service.dense_step_packed_pallas"]

    def regressed_build():
        fn, example = good.build()

        def regressed(state, wave16, bases):
            return fn(state, wave16 * jnp.int16(2), bases)

        return regressed, example

    sub: dict = {}
    register_kernel_contract(
        "fixture.pallas_regressed", build=regressed_build,
        no_int16_arithmetic=True, registry=sub)
    vs = jaxpr_check.check_kernels(registry=sub, required=())
    assert len(vs) == 1 and "int16" in vs[0].message, \
        [str(v) for v in vs]


# ------------------------------------------------------------------ wire

def test_wire_bad_fixture_caught():
    vs = wire_check.check_wire(
        paths=(os.path.join(FIX, "wire_bad.py"),), repo_root=FIX)
    msgs = [v.message for v in vs]
    assert any("not explicitly big-endian" in m for m in msgs), msgs
    assert any("non-fixed-width" in m for m in msgs), msgs
    assert any("arithmetic on int16 array 'wave16'" in m
               for m in msgs), msgs
    assert any("in-place arithmetic on int16 array 'w'" in m
               for m in msgs), msgs


def test_wire_clean_fixture_passes():
    assert wire_check.check_wire(
        paths=(os.path.join(FIX, "wire_clean.py"),), repo_root=FIX) == []


def test_wire_real_tree_clean():
    assert wire_check.check_wire(repo_root=REPO) == []


# --------------------------------------------------------------- hygiene

def test_hygiene_catches_all_three(tmp_path):
    p = tmp_path / "sloppy.py"
    p.write_text(
        "import jax.numpy as jnp\n"
        "ZEROS = jnp.zeros(4)\n"
        "def f(x=[]):\n"
        "    try:\n"
        "        return x\n"
        "    except:\n"
        "        return None\n")
    vs = hygiene.check_file(str(p), repo_root=str(tmp_path),
                            import_silent=True)
    msgs = [v.message for v in vs]
    assert any("bare `except:`" in m for m in msgs), msgs
    assert any("mutable default" in m for m in msgs), msgs
    assert any("import time" in m for m in msgs), msgs


def test_hygiene_real_tree_clean():
    assert hygiene.check_hygiene(repo_root=REPO) == []


# --------------------------------------------------------------- storage

def _storage_tree(tmp_path, durable_log_src, shim=True):
    """A minimal fake repo tree shaped like the real one."""
    svc = tmp_path / "fluidframework_tpu" / "service"
    svc.mkdir(parents=True)
    (svc / "durable_log.py").write_text(durable_log_src)
    if shim:
        (svc / "log_compat.py").write_text("import json\n")
    return str(tmp_path)


def test_storage_json_ban_caught(tmp_path):
    root = _storage_tree(
        tmp_path,
        "import json\n"
        "def enc(v):\n"
        "    return json.dumps(v).encode()\n")
    vs = storage_check.check_storage(repo_root=root)
    msgs = [v.message for v in vs]
    assert any("json import in a storage hot-path module" in m
               for m in msgs), msgs
    assert any("json.dumps on the storage hot path" in m
               for m in msgs), msgs
    assert all("log_compat" not in v.path for v in vs)  # shim exempt


def test_storage_missing_shim_caught(tmp_path):
    root = _storage_tree(tmp_path, "x = 1\n", shim=False)
    vs = storage_check.check_storage(repo_root=root)
    assert any("shim module is missing" in v.message for v in vs)


def test_storage_undeclared_metric_caught(tmp_path):
    root = _storage_tree(
        tmp_path,
        "def f(c):\n"
        "    c.inc('storage.segment.append')\n")  # typo: missing 's'
    vs = storage_check.check_storage(repo_root=root)
    assert any('undeclared storage metric "storage.segment.append"'
               in v.message for v in vs), [v.message for v in vs]


def test_storage_real_tree_clean():
    assert storage_check.check_storage(repo_root=REPO) == []


def test_snapcols_json_ban_caught(tmp_path):
    proto = tmp_path / "fluidframework_tpu" / "protocol"
    proto.mkdir(parents=True)
    (proto / "snapcols.py").write_text(
        "import json\n"
        "def enc(v):\n"
        "    return json.dumps(v).encode()\n")
    svc = tmp_path / "fluidframework_tpu" / "service"
    svc.mkdir(parents=True)
    (svc / "log_compat.py").write_text("import json\n")
    vs = storage_check.check_storage(repo_root=str(tmp_path))
    assert any(v.path.endswith("snapcols.py")
               and "json import" in v.message for v in vs), \
        [str(v) for v in vs]


def test_snapshot_metric_undeclared_caught(tmp_path):
    root = _storage_tree(
        tmp_path,
        "def f(c):\n"
        "    c.inc('storage.snapshot.reencodes')\n")  # not a member
    vs = storage_check.check_storage(repo_root=root)
    assert any('undeclared storage metric "storage.snapshot.reencodes"'
               in v.message for v in vs), [v.message for v in vs]


# --------------------------------------------------------------- metrics

def _metrics_file(tmp_path, src):
    pkg = tmp_path / "fluidframework_tpu"
    pkg.mkdir()
    path = pkg / "mod.py"
    path.write_text(src)
    return str(path)


def test_boot_family_lock_caught(tmp_path):
    path = _metrics_file(
        tmp_path,
        "def f(c):\n"
        "    c.inc('boot.snapshot.fellback')\n")  # not a member
    vs = metrics_check.check_file(path, repo_root=str(tmp_path))
    assert len(vs) == 1 and 'locked "boot.*" family' in vs[0].message, \
        [str(v) for v in vs]
    assert "boot.snapshot.fallback" in vs[0].message  # names the members


def test_snapshot_family_lock_caught(tmp_path):
    path = _metrics_file(
        tmp_path,
        "def f(c):\n"
        "    c.inc('storage.snapshot.reencoded')\n")
    vs = metrics_check.check_file(path, repo_root=str(tmp_path))
    assert len(vs) == 1 \
        and 'locked "storage.snapshot.*" family' in vs[0].message, \
        [str(v) for v in vs]


def test_placement_family_lock_caught(tmp_path):
    path = _metrics_file(
        tmp_path,
        "def f(c):\n"
        "    c.inc('placement.migration.commited')\n")  # typo'd member
    vs = metrics_check.check_file(path, repo_root=str(tmp_path))
    assert len(vs) == 1 and 'locked "placement.*" family' in vs[0].message, \
        [str(v) for v in vs]
    assert "placement.migration.committed" in vs[0].message


def test_rebalance_family_lock_caught(tmp_path):
    path = _metrics_file(
        tmp_path,
        "def f(c):\n"
        "    c.inc('placement.rebalance.migrations')\n")  # not a member
    vs = metrics_check.check_file(path, repo_root=str(tmp_path))
    assert len(vs) == 1 and 'locked "placement.*" family' in vs[0].message, \
        [str(v) for v in vs]
    assert "placement.rebalance.migrations_issued" in vs[0].message


def test_heat_family_members_pass(tmp_path):
    # the rebalancer's locked heat/decision names are legal as written
    path = _metrics_file(
        tmp_path,
        "def f(c):\n"
        "    c.inc('placement.heat.ops')\n"
        "    c.inc('placement.heat.bytes')\n"
        "    c.inc('placement.rebalance.ticks')\n"
        "    c.inc('placement.rebalance.plans')\n"
        "    c.inc('placement.rebalance.suppressed_hysteresis')\n"
        "    c.inc('placement.rebalance.suppressed_budget')\n")
    vs = metrics_check.check_file(path, repo_root=str(tmp_path))
    assert vs == [], [str(v) for v in vs]


def test_applier_family_lock_caught(tmp_path):
    path = _metrics_file(
        tmp_path,
        "def f(c):\n"
        "    c.inc('applier.stage.secs')\n")  # typo'd member
    vs = metrics_check.check_file(path, repo_root=str(tmp_path))
    assert len(vs) == 1 and 'locked "applier.*" family' in vs[0].message, \
        [str(v) for v in vs]
    assert "applier.stage.seconds" in vs[0].message


def test_fanout_family_lock_caught(tmp_path):
    path = _metrics_file(
        tmp_path,
        "def f(c):\n"
        "    c.inc('fanout.relay.reencodes')\n")  # typo'd member
    vs = metrics_check.check_file(path, repo_root=str(tmp_path))
    assert len(vs) == 1 and 'locked "fanout.*" family' in vs[0].message, \
        [str(v) for v in vs]
    assert "fanout.relay.encodes" in vs[0].message


def test_presence_family_lock_caught(tmp_path):
    path = _metrics_file(
        tmp_path,
        "def f(c):\n"
        "    c.inc('presence.lane.coalesces')\n")  # not a member
    vs = metrics_check.check_file(path, repo_root=str(tmp_path))
    assert len(vs) == 1 and 'locked "presence.*" family' in vs[0].message, \
        [str(v) for v in vs]
    assert "presence.lane.coalesced" in vs[0].message


def test_readonly_family_lock_caught(tmp_path):
    path = _metrics_file(
        tmp_path,
        "def f(c):\n"
        "    c.inc('session.readonly.opens')\n")
    vs = metrics_check.check_file(path, repo_root=str(tmp_path))
    assert len(vs) == 1 \
        and 'locked "session.readonly.*" family' in vs[0].message, \
        [str(v) for v in vs]


def test_fanout_prefix_does_not_lock_net_fanout(tmp_path):
    # the front end's encode-once cache counters live under
    # "net.fanout.*" — the "fanout." lock must not swallow them
    path = _metrics_file(
        tmp_path,
        "def f(c):\n"
        "    c.inc('net.fanout.encodes')\n"
        "    c.inc('net.fanout.cache_hits')\n")
    assert metrics_check.check_file(path, repo_root=str(tmp_path)) == []


def test_boot_family_members_pass(tmp_path):
    path = _metrics_file(
        tmp_path,
        "def f(c):\n"
        "    c.inc('boot.snapshot.used')\n"
        "    c.inc('boot.backfill.bounded')\n"
        "    c.inc('storage.snapshot.served')\n"
        "    c.inc('placement.epoch.bumps')\n"
        "    c.inc('applier.stage.overlap_ratio')\n"
        "    c.inc('fanout.relay.splices')\n"
        "    c.inc('presence.lane.coalesced')\n"
        "    c.inc('session.readonly.connects')\n")
    assert metrics_check.check_file(path, repo_root=str(tmp_path)) == []


def test_metrics_real_tree_clean():
    assert metrics_check.check_metrics(repo_root=REPO) == []


def test_journal_family_lock_caught(tmp_path):
    path = _metrics_file(
        tmp_path,
        "def f(c):\n"
        "    c.inc('obs.journal.writes')\n")  # not a member
    vs = metrics_check.check_file(path, repo_root=str(tmp_path))
    assert len(vs) == 1 \
        and 'locked "obs.journal.*" family' in vs[0].message, \
        [str(v) for v in vs]
    assert "obs.journal.entries" in vs[0].message


def test_health_family_lock_caught(tmp_path):
    # the health plane's probe/engine names are an operator contract
    # (dashboards + Fleet.wait_healthy key on them): the family locks
    path = _metrics_file(
        tmp_path,
        "def f(c):\n"
        "    c.inc('health.probe.errors')\n")  # not a member
    vs = metrics_check.check_file(path, repo_root=str(tmp_path))
    assert len(vs) == 1 and 'locked "health.*" family' in vs[0].message, \
        [str(v) for v in vs]
    assert "health.probe.failures" in vs[0].message


def test_health_family_members_pass(tmp_path):
    path = _metrics_file(
        tmp_path,
        "def f(c):\n"
        "    c.inc('health.probe.ms')\n"
        "    c.inc('health.probe.failures')\n"
        "    c.inc('health.engine.state')\n")
    assert metrics_check.check_file(path, repo_root=str(tmp_path)) == []


# ---------------------------------------------------------- journal-kind

def _journal_tree(tmp_path, mod_src, kinds_src=None):
    """A fake repo with an obs/journal.py KINDS table and one module."""
    pkg = tmp_path / "fluidframework_tpu"
    obs = pkg / "obs"
    obs.mkdir(parents=True)
    (obs / "journal.py").write_text(
        kinds_src if kinds_src is not None else
        'KINDS = {"epoch.bump": "x", "migration.seal": "x",\n'
        '         "core.start": "x", "core.recover": "x"}\n')
    path = pkg / "mod.py"
    path.write_text(mod_src)
    return str(path)


def test_journal_undeclared_kind_caught(tmp_path):
    path = _journal_tree(
        tmp_path,
        "def f(jr):\n"
        "    jr.emit('migration.sealed', part=1)\n")  # typo'd kind
    kinds = journal_check.load_kinds(str(tmp_path))
    vs = journal_check.check_file(path, kinds, repo_root=str(tmp_path))
    assert len(vs) == 1 and "migration.sealed" in vs[0].message, \
        [str(v) for v in vs]


def test_journal_kind_kwarg_and_ifexp_checked(tmp_path):
    # kind= keyword and both arms of a conditional are all literals
    path = _journal_tree(
        tmp_path,
        "def f(jr, n):\n"
        "    jr.emit(kind='lease.claim')\n"  # undeclared in the fake table
        "    jr.emit('core.recover' if n else 'core.stop')\n")
    kinds = journal_check.load_kinds(str(tmp_path))
    vs = journal_check.check_file(path, kinds, repo_root=str(tmp_path))
    msgs = [v.message for v in vs]
    assert len(vs) == 2, msgs
    assert any("lease.claim" in m for m in msgs)
    assert any("core.stop" in m for m in msgs)


def test_journal_declared_kinds_and_dict_emits_pass(tmp_path):
    path = _journal_tree(
        tmp_path,
        "def f(jr, stage):\n"
        "    jr.emit('epoch.bump', part=0)\n"
        "    jr.emit('migration.seal', cause=None)\n"
        "    stage.emit({'kind': 'applied'})\n"  # backchannel: out of scope
        "    jr.emit(computed_kind())\n")  # computed: out of scope
    kinds = journal_check.load_kinds(str(tmp_path))
    assert journal_check.check_file(path, kinds,
                                    repo_root=str(tmp_path)) == []


def test_journal_nonliteral_kinds_table_caught(tmp_path):
    _journal_tree(tmp_path, "x = 1\n",
                  kinds_src="KINDS = dict(make_kinds())\n")
    vs = journal_check.check_journal_kinds(repo_root=str(tmp_path))
    assert len(vs) == 1 and "pure dict literal" in vs[0].message, \
        [str(v) for v in vs]


def test_journal_real_tree_clean():
    assert journal_check.check_journal_kinds(repo_root=REPO) == []


def test_health_journal_kinds_declared():
    # the HealthEngine's transition/probe entries are declared in the
    # real KINDS table (the journal-kind pass enforces emit sites;
    # this pins the declarations themselves against deletion)
    kinds = journal_check.load_kinds(REPO)
    assert "health.state" in kinds
    assert "health.probe" in kinds
    assert "flight.dump" in kinds  # the critical-transition evidence


# ------------------------------------------------------------------- CLI

def _run_cli(*argv, cwd=REPO):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, "-m", "tools.fluidlint", *argv],
        cwd=cwd, env=env, capture_output=True, text=True)


def test_cli_clean_on_real_tree_fast_passes():
    # layers + wire + hygiene; the jaxpr pass is covered in-process above
    r = _run_cli("--pass", "layers", "--pass", "wire", "--pass", "hygiene")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "clean" in r.stdout


def test_cli_exits_nonzero_on_violation():
    bad_root = os.path.join(FIX, "layering_bad")
    r = _run_cli("--pass", "layers", "--repo-root", bad_root)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "'utils' may not import 'protocol'" in r.stdout


# -------------------------------------------------- frame-id registry

def test_frame_registry_real_tree_clean():
    # every FT_* id unique, every id paired with both codec halves in
    # registries.FT_CODECS, no stale manifest entries
    assert wire_check.check_frame_registry(repo_root=REPO) == []


def test_frame_registry_seeded_violations():
    msgs = [v.message for v in wire_check.check_frame_registry(
        repo_root=os.path.join(FIX, "wire_registry"))]
    joined = "\n".join(msgs)
    # a reused wire id is version skew baked into one binary
    assert ("frame id 1 is assigned to both FT_SUBMIT and FT_OPS"
            in joined)
    # a frame id with no (encoder, decoder) manifest entry
    assert "FT_BOGUS has no (encoder, decoder) entry" in joined
