"""Snapshot fast-boot plane: columnar snapcols summaries, encode-once
FT_COLS_SNAP serving, O(snapshot+Δ) late-joiner catch-up, and the
retention/summary coupling that keeps a booting client's backfill base
retained.

Ref: odsp-driver snapshot-first boot + routerlicious summary serving;
merge-tree SnapshotV1 (snapshotV1.ts:87) for the chunked snapshot shape.
"""

from __future__ import annotations

import hashlib
import json
import random
import time

import pytest

from fluidframework_tpu.chaos import doc_fingerprint
from fluidframework_tpu.driver import (
    LocalDocumentServiceFactory,
    NetworkDocumentServiceFactory,
)
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.mergetree import MergeTreeClient
from fluidframework_tpu.protocol import binwire, snapcols
from fluidframework_tpu.service import LocalServer, NetworkFrontEnd
from fluidframework_tpu.service.service_summarizer import (
    HostReplicaSource,
    ServiceSummarizer,
)

from tests.mergetree_fixtures import FarmClient, FarmServer, random_op


def wait_for(pred, timeout=10.0, interval=0.005):
    t0 = time.time()
    while time.time() - t0 < timeout:
        try:
            if pred():
                return True
        except (KeyError, IndexError):
            pass
        time.sleep(interval)
    return False


def string_fingerprint(s) -> str:
    text = s.get_text()
    props = [s.client.get_properties_at(i) or {} for i in range(len(text))]
    return doc_fingerprint(text, props)


def make_doc(loader, tenant, doc, n_ops=40):
    c = loader.resolve(tenant, doc)
    s = c.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    for i in range(n_ops):
        s.insert_text(0, f"w{i} ")
    s.annotate_range(0, 3, {"bold": True})
    return c, s


def summarize(server, tenant, doc):
    svc = ServiceSummarizer(server, HostReplicaSource(server))
    version = svc.summarize_doc(tenant, doc)
    assert version is not None
    return svc, version


# =====================================================================
# snapcols codec: fuzz round-trip vs the JSON twin
# =====================================================================

def test_snapcols_fuzz_round_trips_vs_json_twin():
    """Random collaborative histories: the columnar encoding must decode
    to a merge-tree snapshot byte-identical (as canonical JSON) to the
    original, across chunk boundaries and partial collab windows."""
    for seed in range(6):
        rng = random.Random(seed)
        clients = [FarmClient(f"c{i}") for i in range(3)]
        farm = FarmServer(clients, rng)
        for _ in range(rng.randint(30, 120)):
            random_op(rng.choice(clients), rng)
            if rng.random() < 0.4:
                farm.sequence_one()
        farm.sequence_all()
        snap = clients[0].client.snapshot()

        chunks = snapcols.encode_snapshot_chunks(snap, segs_per_chunk=7)
        decoded = snapcols.decode_snapshot_chunks(
            chunks, snap["minSeq"], snap["seq"])
        assert json.dumps(decoded, sort_keys=True) \
            == json.dumps(snap, sort_keys=True), f"seed {seed}"

        # and a replica LOADED from the decoded form fingerprints equal
        a = MergeTreeClient.load("a", snap)
        b = MergeTreeClient.load("b", decoded)
        assert a.get_text() == b.get_text()


def test_snapcols_chunking_is_prefix_stable_under_append():
    """The canonical snapshot coalesces a quiet doc into ONE growing
    text run — the text-split chunker must still leave every leading
    chunk byte-identical after an append, so the content-addressed
    store dedupes across summary generations."""
    from fluidframework_tpu.mergetree import op_to_wire
    from fluidframework_tpu.protocol import (
        MessageType,
        SequencedDocumentMessage,
    )

    c = MergeTreeClient("w")
    snap1 = None
    for i in range(160):
        op = c.insert_text_local(c.get_length(), f"s{i} ")
        m = SequencedDocumentMessage(
            client_id="w", sequence_number=i + 1,
            minimum_sequence_number=i + 1, client_sequence_number=i + 1,
            reference_sequence_number=i, type=MessageType.OPERATION,
            contents=op_to_wire(op))
        c.apply_msg(m, local=True)
        if i == 150:
            snap1 = c.snapshot()
    snap2 = c.snapshot()
    # the whole doc coalesced into one canonical run in BOTH generations
    assert len(snap1["segments"]) == 1 and len(snap2["segments"]) == 1

    enc = lambda s: snapcols.encode_snapshot_chunks(  # noqa: E731
        s, segs_per_chunk=4, text_split=64)
    chunks1, chunks2 = enc(snap1), enc(snap2)
    h = lambda b: hashlib.sha256(b).hexdigest()  # noqa: E731
    assert len(chunks1) >= 3
    # every chunk but the trailing one survives the append byte-identical
    assert [h(b) for b in chunks1[:-1]] == [h(b) for b in chunks2[:len(chunks1) - 1]]
    assert h(chunks1[-1]) != h(chunks2[len(chunks1) - 1])
    # and both generations still decode to their exact snapshots
    for snap, chunks in ((snap1, chunks1), (snap2, chunks2)):
        decoded = snapcols.decode_snapshot_chunks(
            chunks, snap["minSeq"], snap["seq"])
        assert json.dumps(decoded, sort_keys=True) \
            == json.dumps(snap, sort_keys=True)


# =====================================================================
# boot equivalence: snapshot+Δ vs replay-from-0 (local + network lanes)
# =====================================================================

def test_local_boot_equivalence_snapshot_vs_replay():
    server = LocalServer()
    loader = Loader(LocalDocumentServiceFactory(server))
    # replay twin boots BEFORE any summary exists: pure from-0 replay
    replay = loader.resolve("t", "doc")
    c1, s1 = make_doc(loader, "t", "doc")
    svc, _ = summarize(server, "t", "doc")
    assert svc.summaries_written == 1
    # ops AFTER the summary: the snapshot boot must splice the Δ tail
    s1.insert_text(0, "post-summary ")

    booted = loader.resolve("t", "doc")
    assert booted._base_snapshot is not None  # snapshot+Δ path
    assert replay._base_snapshot is None      # replay-from-0 path
    sb = booted.runtime.get_data_store("default").get_channel("text")
    sr = replay.runtime.get_data_store("default").get_channel("text")
    assert string_fingerprint(sb) == string_fingerprint(sr) \
        == string_fingerprint(s1)
    # the snapshot-booted replica stays live
    sb.insert_text(0, "live ")
    assert s1.get_text() == sb.get_text()


def test_incremental_summarizer_dedupes_unchanged_chunks():
    """Generation 2 of a mostly-unchanged doc re-uploads only the tail
    chunk — storage.snapshot.chunks_reused counts the dedupe."""
    server = LocalServer()
    loader = Loader(LocalDocumentServiceFactory(server))
    c1 = loader.resolve("t", "doc")
    s1 = c1.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    for i in range(120):
        s1.insert_text(len(s1.get_text()), f"w{i} ")

    svc = ServiceSummarizer(server, HostReplicaSource(server),
                            segs_per_chunk=4, text_split=64)
    assert svc.summarize_doc("t", "doc") is not None
    written1 = svc.counters.snapshot().get("storage.snapshot.chunks_written")
    assert written1 >= 2  # several text pieces → multiple chunks

    # append-only delta: the leading chunk is byte-identical in gen 2
    s1.insert_text(len(s1.get_text()), "tail ")
    assert svc.summarize_doc("t", "doc") is not None
    assert svc.counters.snapshot() \
        .get("storage.snapshot.chunks_reused", 0) >= 1
    # and the doc still boots correctly from gen 2
    c2 = loader.resolve("t", "doc")
    s2 = c2.runtime.get_data_store("default").get_channel("text")
    assert string_fingerprint(s2) == string_fingerprint(s1)


@pytest.fixture
def front_end():
    fe = NetworkFrontEnd(LocalServer()).start_background()
    yield fe
    fe.stop()


def test_network_snapshot_boot_counters_and_equivalence(front_end):
    server = front_end.server
    factory = NetworkDocumentServiceFactory("127.0.0.1", front_end.port)
    loader = Loader(factory)
    c1, s1 = make_doc(loader, "t", "doc", n_ops=60)
    assert wait_for(lambda: c1.runtime.pending.count == 0)
    summarize(server, "t", "doc")
    s1.insert_text(0, "tail ")
    assert wait_for(lambda: c1.runtime.pending.count == 0)

    # fresh factory = cold client cache: the boot must ride FT_COLS_SNAP
    f2 = NetworkDocumentServiceFactory("127.0.0.1", front_end.port)
    c2 = Loader(f2).resolve("t", "doc")
    s2 = c2.runtime.get_data_store("default").get_channel("text")
    assert wait_for(lambda: s2.get_text() == s1.get_text())
    assert string_fingerprint(s2) == string_fingerprint(s1)
    got = f2.counters.snapshot()
    assert got.get("boot.snapshot.used") == 1
    assert got.get("boot.chunks.fetched", 0) >= 1
    # booted at the summary seq → the delta catch-up was the BOUNDED tail
    assert got.get("boot.backfill.bounded") == 1
    assert not got.get("boot.snapshot.fallback")

    srv = front_end.counters.snapshot()
    assert srv.get("storage.snapshot.encodes") == 1
    assert srv.get("storage.snapshot.served", 0) >= 1


def admin_rpc(port, frame, timeout=30.0):
    import socket

    with socket.create_connection(("127.0.0.1", port),
                                  timeout=timeout) as s:
        body = json.dumps(dict(frame, rid=1)).encode()
        s.sendall(len(body).to_bytes(4, "big") + body)
        buf = b""
        while True:
            while len(buf) < 4:
                buf += s.recv(4096)
            n = int.from_bytes(buf[:4], "big")
            while len(buf) < 4 + n:
                buf += s.recv(4096)
            reply, buf = json.loads(buf[4:4 + n].decode()), buf[4 + n:]
            if reply.get("rid") == 1:
                return reply


def test_admin_summarize_rpc_commits_a_bootable_summary(front_end):
    """The operator door onto the summarizer: one RPC commits a snapcols
    summary a cold joiner then boots through — and an unknown doc is
    refused, not born as an empty summary."""
    factory = NetworkDocumentServiceFactory("127.0.0.1", front_end.port)
    c1, s1 = make_doc(Loader(factory), "t", "doc", n_ops=40)
    assert wait_for(lambda: c1.runtime.pending.count == 0)

    reply = admin_rpc(front_end.port,
                      {"t": "admin_summarize", "tenant": "t", "doc": "doc"})
    assert reply.get("version")
    # the reply only lands after commit: a joiner can boot immediately
    f2 = NetworkDocumentServiceFactory("127.0.0.1", front_end.port)
    c2 = Loader(f2).resolve("t", "doc")
    s2 = c2.runtime.get_data_store("default").get_channel("text")
    assert wait_for(lambda: s2.get_text() == s1.get_text())
    assert f2.counters.snapshot().get("boot.snapshot.used") == 1

    err = admin_rpc(front_end.port,
                    {"t": "admin_summarize", "tenant": "t", "doc": "nope"})
    assert err.get("t") == "error" and "unknown doc" in err["message"]
    # the refusal must not have created the doc server-side
    assert "t/nope" not in front_end.server._orderers


def test_encode_once_across_joiner_burst(front_end):
    """N joiners from N cold caches: the server frames each chunk exactly
    once per summary version — byte-identical splices for everyone."""
    server = front_end.server
    factory = NetworkDocumentServiceFactory("127.0.0.1", front_end.port)
    loader = Loader(factory)
    c1, s1 = make_doc(loader, "t", "burst", n_ops=80)
    assert wait_for(lambda: c1.runtime.pending.count == 0)
    summarize(server, "t", "burst")

    joiners = []
    for _ in range(4):
        f = NetworkDocumentServiceFactory("127.0.0.1", front_end.port)
        joiners.append((f, Loader(f).resolve("t", "burst")))
    for f, c in joiners:
        s = c.runtime.get_data_store("default").get_channel("text")
        assert wait_for(lambda: s.get_text() == s1.get_text())
        assert f.counters.snapshot().get("boot.snapshot.used") == 1

    srv = front_end.counters.snapshot()
    assert srv.get("storage.snapshot.encodes") == 1, \
        "per-join re-encodes must be zero"
    assert srv.get("storage.snapshot.served") == 4
    assert srv.get("storage.snapshot.cache_hits") == 3
    # nobody fell back to the legacy whole-tree JSON shim
    assert not srv.get("storage.snapshot.legacy_tree")


def test_client_chunk_cache_skips_refetch(front_end):
    """A factory that already holds the chunks (content-addressed) boots
    a second container without refetching them — the ``have`` list lets
    the server skip the push entirely."""
    server = front_end.server
    factory = NetworkDocumentServiceFactory("127.0.0.1", front_end.port)
    loader = Loader(factory)
    c1, s1 = make_doc(loader, "t", "doc", n_ops=50)
    assert wait_for(lambda: c1.runtime.pending.count == 0)
    summarize(server, "t", "doc")

    c2 = loader.resolve("t", "doc")
    got = factory.counters.snapshot()
    fetched_once = got.get("boot.chunks.fetched", 0)
    assert got.get("boot.snapshot.used") == 1 and fetched_once >= 1

    # drop the version ENTRY but keep the chunks (invalidate's contract)
    factory.snapshot_cache.invalidate("t", "doc")
    c3 = loader.resolve("t", "doc")
    got = factory.counters.snapshot()
    assert got.get("boot.snapshot.used") == 2
    assert got.get("boot.chunks.cached", 0) >= 1
    assert got.get("boot.chunks.fetched") == fetched_once  # no refetch
    assert factory.snapshot_cache.chunk_stats["hits"] >= 1
    s3 = c3.runtime.get_data_store("default").get_channel("text")
    assert wait_for(lambda: s3.get_text() == s1.get_text())


def test_legacy_summary_at_head_uses_tree_shim(front_end):
    """A doc whose head summary predates snapcols boots through the
    legacy JSON tree RPC — counted on the deprecation counter, with the
    columnar attempt recorded as a fallback."""
    from fluidframework_tpu.runtime.summarizer import SummaryManager

    factory = NetworkDocumentServiceFactory("127.0.0.1", front_end.port)
    loader = Loader(factory)
    c1, s1 = make_doc(loader, "t", "old", n_ops=20)
    assert wait_for(lambda: c1.runtime.pending.count == 0)
    sm = SummaryManager(c1, max_ops=10**9)
    sm.summarize_now()
    assert wait_for(lambda: sm.summaries_acked == 1)

    f2 = NetworkDocumentServiceFactory("127.0.0.1", front_end.port)
    c2 = Loader(f2).resolve("t", "old")
    s2 = c2.runtime.get_data_store("default").get_channel("text")
    assert wait_for(lambda: s2.get_text() == s1.get_text())
    got = f2.counters.snapshot()
    assert got.get("boot.snapshot.fallback") == 1
    assert not got.get("boot.snapshot.used")
    assert front_end.counters.snapshot().get(
        "storage.snapshot.legacy_tree", 0) >= 1


# =====================================================================
# torn / missing chunk → verified fallback (the chaos seam's unit twin)
# =====================================================================

def corrupt_cached_frame(front, tenant, doc, body_fn):
    vid, framed, root = front._snap_cache[(tenant, doc)]
    h0 = root["chunks"][0]
    framed = dict(framed)
    framed[h0] = binwire.frame(body_fn(h0))
    front._snap_cache[(tenant, doc)] = (vid, framed, root)


@pytest.mark.parametrize("mode", ["torn", "missing"])
def test_corrupt_chunk_falls_back_and_converges(front_end, mode):
    """A torn frame (bytes ≠ hash) or a frame for the wrong hash must be
    DETECTED client-side (sha256 verify) and heal through the legacy
    path — counted, never silently booted from garbage."""
    server = front_end.server
    factory = NetworkDocumentServiceFactory("127.0.0.1", front_end.port)
    loader = Loader(factory)
    c1, s1 = make_doc(loader, "t", "doc", n_ops=50)
    assert wait_for(lambda: c1.runtime.pending.count == 0)
    summarize(server, "t", "doc")

    loader.resolve("t", "doc")  # primes the serving cache
    if mode == "torn":
        corrupt_cached_frame(
            front_end, "t", "doc",
            lambda h: binwire.snap_chunk_body(0, h, b"torn bytes"))
    else:
        corrupt_cached_frame(
            front_end, "t", "doc",
            lambda h: binwire.snap_chunk_body(0, "0" * 64, b"x"))

    f2 = NetworkDocumentServiceFactory("127.0.0.1", front_end.port)
    c2 = Loader(f2).resolve("t", "doc")
    got = f2.counters.snapshot()
    assert got.get("boot.snapshot.fallback") == 1
    assert not got.get("boot.snapshot.used")
    s2 = c2.runtime.get_data_store("default").get_channel("text")
    assert wait_for(lambda: s2.get_text() == s1.get_text())
    assert string_fingerprint(s2) == string_fingerprint(s1)


# =====================================================================
# retention/summary coupling: the mid-trim joiner race, both sides
# =====================================================================

def test_retention_clamped_to_acked_boot_seq_local():
    """Retention must never trim past the seq a joiner's boot version
    covers, and a truncation error must carry the snapshot-backed base."""
    from fluidframework_tpu.config import Config
    from fluidframework_tpu.service.scriptorium import LogTruncatedError

    server = LocalServer(config=Config().with_overrides(log_retention_ops=0))
    loader = Loader(LocalDocumentServiceFactory(server))
    c1, s1 = make_doc(loader, "t", "doc", n_ops=30)

    # no acked summary yet → nothing may be trimmed, any joiner replays
    orderer = server._get_orderer("t", "doc")
    orderer.apply_retention(orderer.deli.sequence_number)
    assert orderer.scriptorium.retained_base("t", "doc") == 0
    assert orderer.acked_boot_seq() is None

    svc, _ = summarize(server, "t", "doc")
    boot_seq = orderer.acked_boot_seq()
    assert boot_seq is not None and boot_seq > 0
    base = orderer.scriptorium.retained_base("t", "doc")
    assert 0 < base <= boot_seq  # trimmed, but never past the boot seq
    s1.insert_text(0, "after ")

    # a from-0 backfill is now unservable — but the error names the
    # snapshot seq that heals it
    with pytest.raises(LogTruncatedError) as ei:
        server.get_deltas("t", "doc", 0, orderer.deli.sequence_number + 1)
    assert ei.value.snapshot_seq == boot_seq
    # …while a joiner (snapshot+Δ boot) is never stranded
    c2 = loader.resolve("t", "doc")
    s2 = c2.runtime.get_data_store("default").get_channel("text")
    assert s2.get_text() == s1.get_text()

    # even a RE-summarize of an older capture seq cannot un-retain:
    # the clamp takes min(capture, boot)
    orderer.apply_retention(boot_seq - 5)
    assert orderer.scriptorium.retained_base("t", "doc") <= boot_seq


def test_stale_cache_reanchors_over_sockets():
    """The mid-trim race over real sockets: a joiner booting from a
    SUPERSEDED cached snapshot hits log_truncated on its backfill, and
    must re-anchor onto the newer summary instead of failing."""
    from fluidframework_tpu.config import Config

    server = LocalServer(config=Config().with_overrides(log_retention_ops=0))
    fe = NetworkFrontEnd(server).start_background()
    try:
        factory = NetworkDocumentServiceFactory("127.0.0.1", fe.port)
        loader = Loader(factory)
        c1, s1 = make_doc(loader, "t", "doc", n_ops=30)
        assert wait_for(lambda: c1.runtime.pending.count == 0)
        svc = ServiceSummarizer(server, HostReplicaSource(server))
        assert svc.summarize_doc("t", "doc") is not None

        # boot once to capture the (soon stale) cache entry
        loader.resolve("t", "doc")
        stale = factory.snapshot_cache.get("t", "doc")
        assert stale is not None

        # a second generation + trim: ops below the new boot seq vanish
        for i in range(20):
            s1.insert_text(0, f"gen2-{i} ")
        assert wait_for(lambda: c1.runtime.pending.count == 0)
        assert svc.summarize_doc("t", "doc") is not None
        orderer = server._get_orderer("t", "doc")
        assert orderer.scriptorium.retained_base("t", "doc") \
            > stale["tree"]["sequence_number"]

        # resurrect the stale entry (the race: a boot that read the
        # cache just before the summary ack invalidated it)
        factory.snapshot_cache.put(
            "t", "doc", stale["version"], stale["tree"])
        c3 = loader.resolve("t", "doc")
        s3 = c3.runtime.get_data_store("default").get_channel("text")
        assert wait_for(lambda: s3.get_text() == s1.get_text())
        assert factory.counters.snapshot() \
            .get("boot.snapshot.reanchor") == 1
    finally:
        fe.stop()
