"""Mesh lane = fast lane: the doc-sharded applier must match the local
dense lane op-for-op across 1/2/4/8-shard meshes — through compaction
waves, overflow escalation, the chaos force_wide lane, the async worker
with min-wave hold-off, and checkpoint warm restart — while its wave
staging stays proportional to ACTIVE shards (never O(max_docs)) and its
the drained live device buffer count stays flat across waves.

conftest.py forces 8 virtual CPU devices, so every mesh geometry here
runs on real (virtual) multi-device shardings.
"""

import types

import jax
import numpy as np
import pytest

from fluidframework_tpu.driver import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.ops.apply import OP_FIELDS
from fluidframework_tpu.parallel.mesh import make_mesh
from fluidframework_tpu.parallel.sharded_apply import doc_sharding
from fluidframework_tpu.service import LocalServer
from fluidframework_tpu.service.tpu_applier import (
    TpuDocumentApplier,
    channel_stream,
    load_applier_checkpoint,
    save_applier_checkpoint,
)

SEEDS = (0, 7, 42)
DOCS = [f"doc{i}" for i in range(8)]


def _build_soup(seed):
    """Seeded op soup over 8 docs through the real client stack:
    inserts, removes (so zamboni compaction runs), annotates."""
    server = LocalServer()
    loader = Loader(LocalDocumentServiceFactory(server))
    rng = np.random.default_rng(seed)
    strings = {}
    for d in DOCS:
        c = loader.resolve("t", d)
        strings[d] = c.runtime.create_data_store(
            "default").create_channel("text", "shared-string")
    for _ in range(160):
        d = DOCS[rng.integers(0, len(DOCS))]
        s = strings[d]
        n = len(s.get_text())
        r = rng.random()
        if n > 4 and r < 0.30:
            a = int(rng.integers(0, n - 1))
            b = int(rng.integers(a + 1, min(n, a + 6) + 1))
            s.remove_text(a, b)
        elif n > 2 and r < 0.40:
            a = int(rng.integers(0, n - 1))
            s.annotate_range(a, a + 1, {"k": int(rng.integers(0, 5))})
        else:
            s.insert_text(int(rng.integers(0, n + 1)),
                          f"[{rng.integers(0, 100)}]")
    return server, {d: strings[d].get_text() for d in DOCS}


@pytest.fixture(scope="module")
def soup():
    return {seed: _build_soup(seed) for seed in SEEDS}


def _feed(applier, server, doc):
    for msg in channel_stream(server, "t", doc, "default", "text"):
        applier.ingest("t", doc, msg, msg.contents)


def _feed_all(applier, server):
    for d in DOCS:
        _feed(applier, server, d)
    applier.finalize()


@pytest.mark.parametrize("n_shards", [1, 2, 4, 8])
@pytest.mark.parametrize("seed", SEEDS)
def test_mesh_matches_local_fuzz(soup, seed, n_shards):
    server, texts = soup[seed]
    local = TpuDocumentApplier(max_docs=16, max_slots=256,
                               ops_per_dispatch=8)
    meshed = TpuDocumentApplier(max_docs=16, max_slots=256,
                                ops_per_dispatch=8,
                                mesh=make_mesh(n_shards, seg_shards=1))
    for applier in (local, meshed):
        _feed_all(applier, server)
    for d in DOCS:
        assert meshed.get_text("t", d) == texts[d], (seed, n_shards, d)
        assert meshed.get_text("t", d) == local.get_text("t", d)
    assert meshed.host_escalations == 0
    assert local.host_escalations == 0
    # every mesh dispatch rode the per-shard staging lane
    assert meshed.mesh_waves == meshed.dispatches > 0


@pytest.mark.parametrize("seed", SEEDS)
def test_mesh_overflow_escalation_matches(soup, seed):
    """A slot budget far below the soup's live segment count forces the
    overflow → host-escalation flip on the mesh path; escalated docs must
    still converge to the oracle text."""
    server, texts = soup[seed]
    applier = TpuDocumentApplier(max_docs=8, max_slots=8,
                                 ops_per_dispatch=8,
                                 mesh=make_mesh(4, seg_shards=1))
    applier.set_replay_source(
        lambda t, d: channel_stream(server, t, d, "default", "text"))
    _feed_all(applier, server)
    assert applier.host_escalations > 0
    for d in DOCS:
        assert applier.get_text("t", d) == texts[d], (seed, d)


def test_mesh_force_wide_lane_matches(soup):
    """The chaos force_wide seam must route mesh waves down the int32
    wide sharded lane and still converge."""
    server, texts = soup[0]
    applier = TpuDocumentApplier(max_docs=16, max_slots=256,
                                 ops_per_dispatch=8,
                                 mesh=make_mesh(2, seg_shards=1))
    applier.fault_plane = lambda point, **kw: (
        "force_wide" if point == "applier.dispatch" else None)
    _feed_all(applier, server)
    for d in DOCS:
        assert applier.get_text("t", d) == texts[d], d
    assert applier.mesh_waves == applier.dispatches > 0


@pytest.mark.parametrize("seed", SEEDS)
def test_mesh_async_min_wave_matches(soup, seed):
    """Async + min-wave parity: the mesh path rides the same worker
    thread and min_wave_ops hold-off as the local path."""
    server, texts = soup[seed]
    applier = TpuDocumentApplier(max_docs=16, max_slots=256,
                                 ops_per_dispatch=8,
                                 mesh=make_mesh(4, seg_shards=1),
                                 async_dispatch=True, min_wave_ops=16)
    try:
        _feed_all(applier, server)
        for d in DOCS:
            assert applier.get_text("t", d) == texts[d], (seed, d)
        assert applier.host_escalations == 0
    finally:
        applier.close()


def test_mesh_staging_bytes_scale_with_active_shards(soup):
    """The tentpole's O(max_docs) → O(active shards) claim, counter-
    asserted: one active doc stages exactly one shard's compact buffers
    per wave, far below the dense global wave."""
    server, _texts = soup[0]
    K = 8
    applier = TpuDocumentApplier(max_docs=64, max_slots=64,
                                 ops_per_dispatch=K,
                                 mesh=make_mesh(8, seg_shards=1))
    _feed(applier, server, DOCS[0])
    applier.finalize()
    sps = applier.placement.slots_per_shard
    per_shard = sps * K * OP_FIELDS * 2 + sps * 2 * 4  # wave16 + bases
    assert applier.mesh_waves > 0
    assert applier.mesh_active_shards == applier.mesh_waves  # 1 per wave
    assert applier.mesh_staged_bytes == applier.mesh_waves * per_shard
    dense_wave = 64 * K * OP_FIELDS * 4  # the pre-refactor global array
    assert per_shard * 8 <= dense_wave  # even all-active stays under int32 dense


def _msg(seq, msn):
    return types.SimpleNamespace(sequence_number=seq,
                                 reference_sequence_number=max(seq - 1, 0),
                                 minimum_sequence_number=msn,
                                 client_id="c0")


def test_mesh_donation_live_buffers_flat():
    """Device-buffer regression (satellite): across 100 mesh waves the
    drained live buffer count must stay flat — a leak in the per-wave
    assembly path grows it monotonically. Counting happens behind a
    fence: the overlap pipeline legitimately keeps in-flight waves (and
    their staged inputs) alive until the device drains, and on
    non-donating backends the superseded state lives until the step
    completes."""
    applier = TpuDocumentApplier(max_docs=8, max_slots=32,
                                 ops_per_dispatch=4,
                                 mesh=make_mesh(4, seg_shards=1))
    seq = 0
    baseline = None
    for wave in range(100):
        for i in range(4):
            doc = f"d{i}"
            seq += 1
            msn = max(seq - 4, 0)
            applier.ingest("t", doc, _msg(seq, msn),
                           {"type": 0, "pos": 0, "text": "x"})
            seq += 1
            applier.ingest("t", doc, _msg(seq, max(seq - 4, 0)),
                           {"type": 1, "start": 0, "end": 1})
        applier.flush()
        if wave == 9:
            # caches are warm by now (jit, zero shards, bases buffers);
            # fence so in-flight waves don't inflate the baseline
            np.asarray(applier.state.count)
            baseline = len(jax.live_arrays())
    np.asarray(applier.state.count)
    assert applier.mesh_waves >= 100
    assert len(jax.live_arrays()) <= baseline + 2
    assert not np.asarray(applier.state.overflow).any()


def test_mesh_checkpoint_restore_resharded(tmp_path, soup):
    """Warm restart of a mesh applier: the restored state must come back
    COMMITTED per P('docs') (the zero-relayout invariant survives the
    checkpoint cycle), and a shard-count mismatch must refuse loudly."""
    server, texts = soup[0]
    mesh = make_mesh(2, seg_shards=1)
    a = TpuDocumentApplier(max_docs=8, max_slots=128, ops_per_dispatch=8,
                           mesh=mesh)
    _feed_all(a, server)
    save_applier_checkpoint(a, str(tmp_path / "ck"))

    b = load_applier_checkpoint(str(tmp_path / "ck"), mesh=mesh)
    assert b.state.length.sharding == doc_sharding(mesh)
    for d in DOCS:
        assert b.get_text("t", d) == texts[d], d

    with pytest.raises(ValueError):
        load_applier_checkpoint(str(tmp_path / "ck"),
                                mesh=make_mesh(4, seg_shards=1))
