"""DDS catalog end-to-end specs: cell, counter, directory, consensus
register/queue, ink, summary block, matrix.

Ref test model: packages/test/end-to-end-tests one spec file per DDS
(SURVEY §4), run against the in-proc service.
"""

import pytest

from fluidframework_tpu.driver import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.service import LocalServer


@pytest.fixture
def server():
    return LocalServer()


@pytest.fixture
def loader(server):
    return Loader(LocalDocumentServiceFactory(server))


def pair(loader, channel_type, doc="doc", name="ch"):
    c1 = loader.resolve("t", doc)
    c2 = loader.resolve("t", doc)
    d1 = c1.runtime.create_data_store("default").create_channel(name, channel_type)
    d2 = c2.runtime.get_data_store("default").get_channel(name)
    return c1, c2, d1, d2


# ----------------------------------------------------------------- cell

def test_cell_lww_and_pending_mask(server, loader):
    c1, c2, a, b = pair(loader, "shared-cell")
    a.set(1)
    assert b.get() == 1
    server._auto_drain = False
    b.set(2)
    a.set(3)  # later in total order → wins everywhere
    server.drain()
    assert a.get() == b.get() == 3
    a.delete()
    server.drain()
    assert b.empty


# -------------------------------------------------------------- counter

def test_counter_commutative_increments(server, loader):
    c1, c2, a, b = pair(loader, "shared-counter")
    server._auto_drain = False
    a.increment(5)
    b.increment(-2)
    a.increment(1)
    server.drain()
    assert a.value == b.value == 4


def test_counter_offline_reconnect(server, loader):
    c1, c2, a, b = pair(loader, "shared-counter")
    c1.disconnect()
    a.increment(10)
    b.increment(1)
    c1.reconnect()
    assert a.value == b.value == 11


# ------------------------------------------------------------ directory

def test_directory_subdirs_and_values(server, loader):
    c1, c2, a, b = pair(loader, "shared-directory")
    a.set("rootKey", 1)
    sub = a.create_subdirectory("sub")
    sub.set("x", "deep")
    nested = sub.create_subdirectory("nested")
    nested.set("y", [1, 2])
    assert b.get("rootKey") == 1
    assert b.get_working_directory("/sub").get("x") == "deep"
    assert b.get_working_directory("/sub/nested").get("y") == [1, 2]
    b.get_working_directory("/sub").delete("x")
    assert a.get_working_directory("/sub").get("x") is None


def test_directory_pending_local_wins(server, loader):
    c1, c2, a, b = pair(loader, "shared-directory")
    sub_a = a.create_subdirectory("s")
    server.drain()
    sub_b = b.get_subdirectory("s")
    server._auto_drain = False
    sub_b.set("k", "b-val")
    sub_a.set("k", "a-val")  # later in order → wins
    server.drain()
    assert sub_a.get("k") == sub_b.get("k") == "a-val"


def test_directory_concurrent_delete_recreate_converges(server, loader):
    c1, c2, a, b = pair(loader, "shared-directory")
    a.create_subdirectory("x")
    server.drain()
    server._auto_drain = False
    b.delete_subdirectory("x")
    b.create_subdirectory("x")
    a.delete_subdirectory("x")  # sequenced last → wins
    server.drain()
    assert a.get_subdirectory("x") is None
    assert b.get_subdirectory("x") is None


def test_directory_delete_parent_vs_create_child_converges(server, loader):
    c1, c2, a, b = pair(loader, "shared-directory")
    a.create_subdirectory("p")
    server.drain()
    server._auto_drain = False
    b.delete_subdirectory("p")  # sequenced first
    a.get_subdirectory("p").create_subdirectory("c")
    server.drain()
    # the delete killed the subtree; the interior create must not resurrect
    assert (a.get_working_directory("/p/c") is None) == (
        b.get_working_directory("/p/c") is None)
    assert (a.get_subdirectory("p") is None) == (b.get_subdirectory("p") is None)


def test_directory_recreate_masks_interior_remote_ops(server, loader):
    c1, c2, a, b = pair(loader, "shared-directory")
    a.create_subdirectory("x")
    server.drain()
    server._auto_drain = False
    a.delete_subdirectory("x")
    a.create_subdirectory("x")  # fresh empty node, both ops in flight
    b.get_subdirectory("x").set("k", 5)  # sequenced between them
    server.drain()
    # a's recreate is last: the subtree is empty on BOTH replicas
    assert a.get_subdirectory("x").get("k") == b.get_subdirectory("x").get("k")


# ---------------------------------------------------- consensus register

def test_register_atomic_first_write_wins(server, loader):
    c1, c2, a, b = pair(loader, "consensus-register-collection")
    server._auto_drain = False
    a.write("leader", c1.client_id)
    b.write("leader", c2.client_id)
    server.drain()
    # both versions coexist (neither writer had seen the other)
    assert set(a.read_versions("leader")) == {c1.client_id, c2.client_id}
    # atomic read = first sequenced = consensus winner, same on both
    assert a.read("leader") == b.read("leader") == c1.client_id
    assert a.read("leader", "lww") == c2.client_id
    # a later write that has seen both supersedes them
    a.write("leader", "final")
    server.drain()
    assert b.read_versions("leader") == ["final"]


# ------------------------------------------------------- consensus queue

def test_queue_exactly_once_acquire(server, loader):
    c1, c2, a, b = pair(loader, "consensus-queue")
    a.add("job1")
    a.add("job2")
    server._auto_drain = False
    a.acquire()
    b.acquire()
    server.drain()
    held_a, held_b = a.holding(c1.client_id), b.holding(c2.client_id)
    # each job handed to exactly one client, consistently on both replicas
    assert len(held_a) == 1 and len(held_b) == 1
    assert {held_a[0][1], held_b[0][1]} == {"job1", "job2"}
    assert a.holding(c1.client_id) == b.holding(c1.client_id)
    # complete removes durably
    item_id = held_a[0][0]
    a.complete(item_id)
    server.drain()
    assert b.holding(c1.client_id) == []


def test_queue_release_requeues(server, loader):
    c1, c2, a, b = pair(loader, "consensus-queue")
    a.add("job")
    a.acquire()
    item_id = a.holding()[0][0]
    a.release(item_id)
    assert len(a) == len(b) == 1
    b.acquire()
    assert b.holding()[0][1] == "job"


def test_queue_holder_leave_requeues(server, loader):
    c1, c2, a, b = pair(loader, "consensus-queue")
    a.add("orphan")
    a.acquire()
    assert len(b) == 0
    c1.close()  # leave is sequenced; b sees the requeue
    assert len(b) == 1
    b.acquire()
    assert b.holding()[0][1] == "orphan"


# ------------------------------------------------------------------- ink

def test_ink_strokes_converge(server, loader):
    c1, c2, a, b = pair(loader, "ink")
    sid = a.create_stroke({"color": "red", "thickness": 2})
    a.append_point(sid, 0.0, 0.0)
    a.append_point(sid, 1.0, 1.5)
    sid2 = b.create_stroke({"color": "blue"})
    b.append_point(sid2, 5.0, 5.0)
    for ink in (a, b):
        strokes = ink.get_strokes()
        assert len(strokes) == 2
        assert ink.get_stroke(sid)["points"] == [
            {"x": 0.0, "y": 0.0}, {"x": 1.0, "y": 1.5}]
        assert ink.get_stroke(sid2)["pen"] == {"color": "blue"}


def test_ink_stroke_order_converges(server, loader):
    c1, c2, a, b = pair(loader, "ink")
    server._auto_drain = False
    s1 = a.create_stroke({"n": 1})
    s2 = b.create_stroke({"n": 2})
    server.drain()
    assert [s["id"] for s in a.get_strokes()] == [s["id"] for s in b.get_strokes()]
    assert a.snapshot()["order"] == b.snapshot()["order"]


def test_ink_snapshot_is_acked_state_only(server, loader):
    c1, c2, a, b = pair(loader, "ink")
    sid = a.create_stroke({})
    a.append_point(sid, 0, 0)
    server._auto_drain = False
    b.append_point(sid, 9, 9)  # remote point sequenced before a's pending
    server.drain()
    a.append_point(sid, 1, 1)  # pending, unsequenced
    snap = a.snapshot()
    # acked: both sequenced points, no pending one
    assert snap["strokes"][sid]["points"] == [
        {"x": 0, "y": 0}, {"x": 9, "y": 9}]
    # live view still shows the optimistic point at the end
    assert a.get_stroke(sid)["points"][-1] == {"x": 1, "y": 1}


# ---------------------------------------------------------------- matrix

def test_matrix_shape_and_cells(server, loader):
    c1, c2, a, b = pair(loader, "shared-matrix")
    a.insert_rows(0, 2)
    a.insert_cols(0, 3)
    a.set_cell(0, 0, "tl")
    a.set_cell(1, 2, "br")
    assert (b.row_count, b.col_count) == (2, 3)
    assert b.get_cell(0, 0) == "tl" and b.get_cell(1, 2) == "br"
    assert a.to_lists() == b.to_lists()


def test_matrix_concurrent_row_insert_keeps_cells_aligned(server, loader):
    c1, c2, a, b = pair(loader, "shared-matrix")
    a.insert_rows(0, 2)
    a.insert_cols(0, 2)
    a.set_cell(1, 1, "anchor")
    server._auto_drain = False
    # b inserts a row ABOVE the anchor while a writes to it by position
    b.insert_rows(0, 1)
    a.set_cell(1, 1, "updated")
    server.drain()
    # the anchor row slid to index 2; the positional write still hit it
    assert a.to_lists() == b.to_lists()
    assert a.get_cell(2, 1) == "updated"
    assert (a.row_count, a.col_count) == (3, 2)


def test_matrix_concurrent_cell_write_lww(server, loader):
    c1, c2, a, b = pair(loader, "shared-matrix")
    a.insert_rows(0, 1)
    a.insert_cols(0, 1)
    server._auto_drain = False
    a.set_cell(0, 0, "from-a")
    b.set_cell(0, 0, "from-b")
    server.drain()
    assert a.get_cell(0, 0) == b.get_cell(0, 0) == "from-b"


def test_matrix_remove_rows(server, loader):
    c1, c2, a, b = pair(loader, "shared-matrix")
    a.insert_rows(0, 3)
    a.insert_cols(0, 1)
    for r in range(3):
        a.set_cell(r, 0, f"r{r}")
    a.remove_rows(1, 1)
    assert b.row_count == 2
    assert [b.get_cell(r, 0) for r in range(2)] == ["r0", "r2"]
    assert a.to_lists() == b.to_lists()


def test_matrix_offline_edits_rebase(server, loader):
    c1, c2, a, b = pair(loader, "shared-matrix")
    a.insert_rows(0, 1)
    a.insert_cols(0, 1)
    a.set_cell(0, 0, "base")
    c1.disconnect()
    a.insert_rows(1, 1)
    a.set_cell(1, 0, "offline")
    b.insert_rows(0, 1)  # lands before reconnect
    c1.reconnect()
    assert a.to_lists() == b.to_lists()
    assert a.get_cell(2, 0) == "offline"  # slid down by b's insert


def test_matrix_snapshot_boot(server, loader):
    from fluidframework_tpu.runtime.summarizer import SummaryManager

    c1 = loader.resolve("t", "doc")
    sm = SummaryManager(c1, max_ops=10_000)
    m = c1.runtime.create_data_store("default").create_channel("m", "shared-matrix")
    m.insert_rows(0, 2)
    m.insert_cols(0, 2)
    m.set_cell(0, 1, 42)
    sm.summarize_now()
    c3 = loader.resolve("t", "doc")
    m3 = c3.runtime.get_data_store("default").get_channel("m")
    assert m3.get_cell(0, 1) == 42
    m3.set_cell(1, 1, "post-boot")
    assert m.get_cell(1, 1) == "post-boot"


def test_matrix_removed_rows_purge_cell_storage(server, loader):
    c1, c2, a, b = pair(loader, "shared-matrix")
    a.insert_cols(0, 1)
    for round_ in range(5):
        a.insert_rows(0, 2)
        a.set_cell(0, 0, f"v{round_}")
        a.set_cell(1, 0, f"w{round_}")
        a.remove_rows(0, 2)
    assert a.row_count == b.row_count == 0
    # the sparse store must not accumulate dead cells on either replica
    assert len(a._cells) == 0
    assert len(b._cells) == 0
    assert a.snapshot()["cells"] == []


# -------------------------------------------------------- summary block

def test_summary_block_travels_via_snapshot_only(server, loader):
    from fluidframework_tpu.runtime.summarizer import SummaryManager

    c1 = loader.resolve("t", "doc")
    sm = SummaryManager(c1, max_ops=10_000)
    sb = c1.runtime.create_data_store("default").create_channel(
        "sb", "shared-summary-block")
    sb.set("stats", {"count": 7})
    sm.summarize_now()
    c2 = loader.resolve("t", "doc")
    sb2 = c2.runtime.get_data_store("default").get_channel("sb")
    assert sb2.get("stats") == {"count": 7}


def test_queue_multi_release_preserves_fifo(server, loader):
    """Released items re-add at the BACK in release order (ADVICE r1; ref
    ConsensusOrderedCollection re-adds to the back, not the head)."""
    c1, c2, a, b = pair(loader, "consensus-queue")
    for v in ["w1", "w2", "w3"]:
        a.add(v)
    a.acquire()
    a.acquire()
    held = [iid for iid, _ in a.holding()]
    assert [v for _, v in a.holding()] == ["w1", "w2"]
    for iid in held:
        a.release(iid)
    # w3 was never acquired; released w1, w2 queue BEHIND it, in order
    assert a.peek_values() == b.peek_values() == ["w3", "w1", "w2"]


def test_queue_holder_leave_requeues_at_back(server, loader):
    c1, c2, a, b = pair(loader, "consensus-queue")
    for v in ["w1", "w2"]:
        a.add(v)
    b.acquire()
    assert [v for _, v in b.holding()] == ["w1"]
    c2.disconnect()  # holder leaves → its items requeue deterministically
    assert a.peek_values() == ["w2", "w1"]


def test_matrix_1kx1k_eight_clients_concurrent(server, loader):
    """BASELINE config 3: a 1000x1000 SharedMatrix with 8 clients making
    concurrent cell edits (and concurrent shape edits) converges."""
    import random

    rng = random.Random(33)
    c0 = loader.resolve("t", "grid")
    m0 = c0.runtime.create_data_store("default").create_channel(
        "grid", "shared-matrix")
    m0.insert_rows(0, 1000)
    m0.insert_cols(0, 1000)
    clients = [c0] + [loader.resolve("t", "grid") for _ in range(7)]
    mats = [c.runtime.get_data_store("default").get_channel("grid")
            for c in clients]

    server._auto_drain = False  # force real concurrency
    server.drain()
    for round_ in range(5):
        for i, m in enumerate(mats):
            for _ in range(5):
                r, c = rng.randrange(m.row_count), rng.randrange(m.col_count)
                m.set_cell(r, c, f"c{i}r{round_}")
        if round_ == 2:
            mats[3].insert_rows(500, 2)  # concurrent shape change
        server.drain()
    server._auto_drain = True
    server.drain()

    assert mats[0].row_count == 1002 and mats[0].col_count == 1000
    ref = mats[0].to_lists()
    for m in mats[1:]:
        assert m.row_count == 1002 and m.col_count == 1000
        assert m.to_lists() == ref
    # some edits really landed
    assert sum(1 for row in ref for v in row if v is not None) >= 100
