"""Native C++ components: op log + chunk store, and the durable service.

Skipped wholesale when no g++ toolchain is present (the pure-Python
in-memory paths cover the same contracts).
"""

import hashlib

import pytest

from fluidframework_tpu.native import native_available

pytestmark = pytest.mark.skipif(
    not native_available(), reason="no native toolchain")


@pytest.fixture
def oplog(tmp_path):
    from fluidframework_tpu.native import NativeOpLog

    log = NativeOpLog(str(tmp_path / "log"))
    yield log
    log.close()


def test_oplog_append_read_roundtrip(oplog):
    assert oplog.append("t1", b"hello") == 0
    assert oplog.append("t1", b"") == 1
    assert oplog.append("t1", b"x" * 10_000) == 2
    assert oplog.append("t2", b"other") == 0
    assert oplog.length("t1") == 3
    assert oplog.read("t1", 0) == b"hello"
    assert oplog.read("t1", 1) == b""
    assert oplog.read("t1", 2) == b"x" * 10_000
    assert oplog.read("t2", 0) == b"other"
    with pytest.raises(IndexError):
        oplog.read("t1", 3)


def test_oplog_survives_reopen(tmp_path):
    from fluidframework_tpu.native import NativeOpLog

    path = str(tmp_path / "log")
    log = NativeOpLog(path)
    for i in range(50):
        log.append("ops", f"record-{i}".encode())
    log.sync()
    log.close()

    log2 = NativeOpLog(path)
    assert log2.length("ops") == 50
    assert log2.read("ops", 17) == b"record-17"
    assert log2.append("ops", b"after-restart") == 50
    log2.close()


def test_oplog_truncates_torn_record_durably(tmp_path):
    from fluidframework_tpu.native import NativeOpLog

    path = tmp_path / "log"
    log = NativeOpLog(str(path))
    log.append("t", b"AAAA")
    log.append("t", b"BBBB")
    log.sync()
    log.close()
    # simulate a crash mid-append: index entry present, data truncated
    with open(path / "t.idx", "ab") as f:
        f.write((4 + 4 + 4).to_bytes(8, "little"))  # record 2 start offset
    with open(path / "t.data", "ab") as f:
        f.write((4).to_bytes(4, "little") + b"CC")  # torn: 2 of 4 bytes

    log1 = NativeOpLog(str(path))
    assert log1.length("t") == 2  # torn record dropped
    assert log1.append("t", b"CCCC") == 2
    log1.sync()
    log1.close()

    # SECOND restart: the truncation must have been durable, or the stale
    # index entry resurrects and shifts every ordinal
    log2 = NativeOpLog(str(path))
    assert log2.length("t") == 3
    assert log2.read("t", 2) == b"CCCC"
    log2.close()


def test_durable_log_escapes_colliding_user_payloads(tmp_path):
    from fluidframework_tpu.service.durable_log import DurableLog

    log = DurableLog(str(tmp_path / "log"))
    tricky = {"contents": {"_msg": {"user": "data"}, "_esc": 1, "n": [1, {"_msg": 2}]}}
    log.append("t", tricky)
    assert log.read("t", 0) == tricky
    log.close()


def test_chunkstore_put_get_dedup(tmp_path):
    from fluidframework_tpu.native import NativeChunkStore

    store = NativeChunkStore(str(tmp_path / "cas"))
    data = b"the quick brown fox"
    h = store.put(data)
    assert h == hashlib.sha256(data).hexdigest()  # interoperable addressing
    assert store.get(h) == data
    assert store.has(h)
    assert store.put(data) == h  # dedup: same address
    assert not store.has("0" * 64)
    with pytest.raises(KeyError):
        store.get("0" * 64)
    big = bytes(range(256)) * 1000
    hb = store.put(big)
    assert store.get(hb) == big
    store.close()


def test_chunkstore_rejects_traversal_hashes(tmp_path):
    from fluidframework_tpu.native import NativeChunkStore

    store = NativeChunkStore(str(tmp_path / "cas"))
    with pytest.raises(KeyError):
        store.get("../" * 21 + "x")
    assert not store.has("../../etc/passwd")
    store.close()


def test_message_serialization_roundtrip():
    from fluidframework_tpu.protocol.messages import (
        DocumentMessage, MessageType, SequencedDocumentMessage, TraceHop)
    from fluidframework_tpu.protocol.serialization import (
        decode_message, encode_message)
    from fluidframework_tpu.service.deli import RawMessage

    seq = SequencedDocumentMessage(
        client_id="c1", sequence_number=7, minimum_sequence_number=3,
        client_sequence_number=2, reference_sequence_number=5,
        type=MessageType.OPERATION, contents={"kind": "chanop", "x": [1, 2]},
        traces=[TraceHop(service="deli", action="sequence", timestamp=1.5)])
    assert decode_message(encode_message(seq)) == seq

    raw = RawMessage(
        tenant_id="t", document_id="d", client_id="c1",
        operation=DocumentMessage(
            client_sequence_number=1, reference_sequence_number=0,
            type=MessageType.OPERATION, contents={"op": "set"}),
        timestamp=2.0)
    assert decode_message(encode_message(raw)) == raw


def test_durable_service_survives_process_restart(tmp_path):
    from fluidframework_tpu.driver import LocalDocumentServiceFactory
    from fluidframework_tpu.loader import Loader
    from fluidframework_tpu.service import LocalServer
    from fluidframework_tpu.service.durable_log import DurableLog

    path = str(tmp_path / "service-log")
    server = LocalServer(log=DurableLog(path))
    loader = Loader(LocalDocumentServiceFactory(server))
    c1 = loader.resolve("t", "doc")
    s1 = c1.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    s1.insert_text(0, "durable")
    server.checkpoint_all()
    server.log.sync()
    seq_before = server._orderers["t/doc"].deli.sequence_number
    deltas_before = server.log.length("deltas/t/doc")
    server.log.close()
    del server

    # a NEW process: same log directory, fresh everything else. Deli and
    # scribe restore from the checkpoint record persisted IN the log;
    # scriptorium rebuilds its delta collection by replaying the durable
    # deltas topic; no raw op is re-sequenced (no duplicate deltas)
    server2 = LocalServer(log=DurableLog(path))
    loader2 = Loader(LocalDocumentServiceFactory(server2))
    c2 = loader2.resolve("t", "doc")
    s2 = c2.runtime.get_data_store("default").get_channel("text")
    assert s2.get_text() == "durable"
    orderer = server2._orderers["t/doc"]
    assert orderer.deli.sequence_number > seq_before  # c2's join came after
    # replay did not duplicate any pre-restart delta
    joins_etc_after = server2.log.length("deltas/t/doc") - deltas_before
    assert joins_etc_after == 1  # exactly c2's join
    # and the doc is live again
    s2.insert_text(0, "still ")
    assert s2.get_text() == "still durable"


def test_oplog_truncates_torn_partial_index_entry(tmp_path):
    """A torn trailing PARTIAL index entry (not a multiple of 8 bytes) must
    be cut on recovery even when all complete entries validate — else the
    next append writes a misaligned index entry and later restarts corrupt
    every subsequent ordinal (ADVICE r1, oplog.cpp)."""
    from fluidframework_tpu.native import NativeOpLog

    path = tmp_path / "log"
    log = NativeOpLog(str(path))
    log.append("t", b"AAAA")
    log.append("t", b"BBBB")
    log.sync()
    log.close()
    # crash persisted 3 bytes of a new index entry but none of its data:
    # the 2 complete entries still match the data extent exactly
    with open(path / "t.idx", "ab") as f:
        f.write(b"\x10\x00\x00")

    log1 = NativeOpLog(str(path))
    assert log1.length("t") == 2
    assert log1.append("t", b"CCCC") == 2
    log1.sync()
    log1.close()

    log2 = NativeOpLog(str(path))
    assert log2.length("t") == 3
    assert log2.read("t", 0) == b"AAAA"
    assert log2.read("t", 1) == b"BBBB"
    assert log2.read("t", 2) == b"CCCC"
    log2.close()


def test_durable_restart_with_truncated_retention(tmp_path):
    """Retention + durable restart: the raw-log replay after a restart
    re-ticketed sequenced records, and scriptorium must NOT resurrect
    the prefix it truncated behind an acked summary."""
    from fluidframework_tpu.config import Config
    from fluidframework_tpu.driver import LocalDocumentServiceFactory
    from fluidframework_tpu.loader import Loader
    from fluidframework_tpu.runtime.summarizer import SummaryManager
    from fluidframework_tpu.service import LocalServer
    from fluidframework_tpu.service.durable_log import DurableLog

    path = str(tmp_path / "svc-log")
    blobs = str(tmp_path / "blobs")
    cfg = Config().with_overrides(log_retention_ops=3)
    # durable EVERYTHING: log (native oplog), blobs (native chunk
    # store), version records (versions topic in the log)
    server = LocalServer(log=DurableLog(path), config=cfg,
                         storage_dir=blobs)
    loader = Loader(LocalDocumentServiceFactory(server))
    c1 = loader.resolve("t", "doc")
    sm = SummaryManager(c1, max_ops=10**9)
    s1 = c1.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    for i in range(20):
        s1.insert_text(0, f"{i % 10}")
    sm.summarize_now()
    orderer = server._get_orderer("t", "doc")
    base = orderer.scriptorium.retained_base("t", "doc")
    assert base > 0
    server.checkpoint_all()
    server.log.sync()
    server.log.close()
    del server

    server2 = LocalServer(log=DurableLog(path), config=cfg,
                          storage_dir=blobs)
    loader2 = Loader(LocalDocumentServiceFactory(server2))
    c2 = loader2.resolve("t", "doc")  # boots from summary + retained tail
    s2 = c2.runtime.get_data_store("default").get_channel("text")
    assert s2.get_text() == s1.get_text()
    # the truncation SURVIVED the restart: the deltas-topic replay
    # rebuilt the store, and the checkpointed base re-truncated it
    o2 = server2._get_orderer("t", "doc")
    assert o2.scriptorium.retained_base("t", "doc") == base
    first_kept = min(
        (m.sequence_number
         for m in o2.scriptorium.get_deltas("t", "doc", base, 10**9)),
        default=None)
    assert first_kept is None or first_kept > base
    s2.insert_text(0, "alive ")
    assert s2.get_text().startswith("alive ")

def test_oplog_fd_cap_bounds_open_files(tmp_path):
    """The handle LRU keeps concurrently open FILE*s under the cap while
    topic metadata stays resident: evicted topics reopen transparently on
    the next touch, and sync() still covers records appended before an
    eviction (the evicted_unsynced fsync pass)."""
    from fluidframework_tpu.native import NativeOpLog

    path = str(tmp_path / "log")
    log = NativeOpLog(path)
    log.fd_cap(20)
    for i in range(100):
        log.append(f"topic-{i}", f"first-{i}".encode())
    assert 0 < log.open_files() <= 20
    # touch every topic again: cold handles reopen, hot ones evict
    for i in range(100):
        log.append(f"topic-{i}", f"second-{i}".encode())
    assert log.open_files() <= 20
    log.sync()  # must fsync evicted-while-unsynced topics too
    for i in range(0, 100, 7):
        assert log.read(f"topic-{i}", 0) == f"first-{i}".encode()
        assert log.read(f"topic-{i}", 1) == f"second-{i}".encode()
    assert log.open_files() <= 20
    log.close()

    # everything survived the churn durably
    log2 = NativeOpLog(path)
    for i in range(100):
        assert log2.length(f"topic-{i}") == 2
        assert log2.read(f"topic-{i}", 1) == f"second-{i}".encode()
    log2.close()


def test_oplog_fd_cap_bounds_segment_streams(tmp_path):
    """Segment streams ride the same fd budget as record topics; eviction
    must not lose resident block metadata or the ability to keep
    appending to a stream whose tail segment was closed."""
    from fluidframework_tpu.native import NativeOpLog

    path = str(tmp_path / "log")
    log = NativeOpLog(path)
    log.fd_cap(16)
    for i in range(40):
        log.seg_append(f"stream-{i}", 1, 2, f"blk-a-{i}".encode(), 0)
    assert log.open_files() <= 16
    for i in range(40):
        log.seg_append(f"stream-{i}", 3, 4, f"blk-b-{i}".encode(), 0)
    log.sync()
    for i in range(0, 40, 5):
        assert log.seg_count(f"stream-{i}") == 2
        assert log.seg_read(f"stream-{i}", 0) == f"blk-a-{i}".encode()
        assert log.seg_read(f"stream-{i}", 1) == f"blk-b-{i}".encode()
    assert log.open_files() <= 16
    log.close()

    log2 = NativeOpLog(path)
    for i in range(40):
        assert log2.seg_count(f"stream-{i}") == 2
        assert log2.seg_read(f"stream-{i}", 1) == f"blk-b-{i}".encode()
    log2.close()
