"""Deterministic merge-tree semantics tests.

Each test pins one concurrency rule (mirrors the reference's directed specs:
client.applyMsg.spec.ts, mergeTree.markRangeRemoved.spec.ts — SURVEY.md §4).
"""

import random

import pytest

from fluidframework_tpu.mergetree import MergeTreeClient
from tests.mergetree_fixtures import FarmClient, FarmServer, assert_converged


def make_farm(n, seed=0):
    rng = random.Random(seed)
    clients = [FarmClient(f"c{i}") for i in range(n)]
    server = FarmServer(clients, rng)
    return clients, server


def test_basic_insert_remove_local():
    c = MergeTreeClient("a")
    c.insert_text_local(0, "hello world")
    assert c.get_text() == "hello world"
    c.remove_range_local(5, 11)
    assert c.get_text() == "hello"
    c.insert_text_local(5, "!")
    assert c.get_text() == "hello!"


def test_sequential_edits_converge():
    (a, b), server = make_farm(2)
    a.insert(0, "abc")
    server.sequence_all()
    b.insert(3, "def")
    server.sequence_all()
    assert a.text() == b.text() == "abcdef"


def test_concurrent_inserts_same_position():
    (a, b), server = make_farm(2)
    a.insert(0, "base")
    server.sequence_all()
    # both insert at position 0 concurrently
    a.insert(0, "AA")
    b.insert(0, "BB")
    server.sequence_all()
    assert_converged([a, b], "concurrent same-pos insert")
    # both fragments present, base intact
    assert set(a.text()[:-4].replace("AA", "").replace("BB", "")) == set()
    assert a.text().endswith("base")
    assert "AA" in a.text() and "BB" in a.text()


def test_own_pending_then_remote_insert():
    (a, b), server = make_farm(2)
    a.insert(0, "base")
    server.sequence_all()
    # a types two chunks locally (unacked), b inserts concurrently at 0
    a.insert(0, "1")
    a.insert(1, "2")  # after its own pending "1"
    b.insert(0, "X")
    server.sequence_all()
    assert_converged([a, b], "pending-vs-remote")
    assert "12" in a.text()  # a's own ordering preserved


def test_insert_into_concurrently_removed_range():
    (a, b), server = make_farm(2)
    a.insert(0, "abcdef")
    server.sequence_all()
    # b inserts inside [1, 5) while a removes it: insert must survive
    b.insert(3, "XY")
    a.remove(1, 5)
    server.sequence_all()
    assert_converged([a, b], "insert into removed range")
    assert "XY" in a.text()
    assert a.text() == "aXYf"


def test_overlapping_concurrent_removes():
    (a, b), server = make_farm(2)
    a.insert(0, "abcdef")
    server.sequence_all()
    a.remove(1, 4)  # bcd
    b.remove(2, 5)  # cde
    server.sequence_all()
    assert_converged([a, b], "overlapping removes")
    assert a.text() == "af"


def test_remove_then_concurrent_annotate():
    (a, b), server = make_farm(2)
    a.insert(0, "abcdef")
    server.sequence_all()
    a.remove(0, 3)
    b.annotate(0, 6, {"bold": True})
    server.sequence_all()
    assert_converged([a, b], "remove vs annotate")
    assert a.text() == "def"


def test_annotate_lww_by_seq():
    (a, b), server = make_farm(2)
    a.insert(0, "xyz")
    server.sequence_all()
    a.annotate(0, 3, {"color": "red"})
    b.annotate(0, 3, {"color": "blue"})
    server.sequence_all()
    assert_converged([a, b], "annotate LWW")
    # whichever sequenced later wins — both replicas agree on the winner
    colors = {seg.props.get("color") for seg in a.client.tree.segments}
    assert len(colors) == 1


def test_annotate_delete_key():
    (a, b), server = make_farm(2)
    a.insert(0, "xyz")
    a.annotate(0, 3, {"k": 1})
    server.sequence_all()
    b.annotate(0, 3, {"k": None})
    server.sequence_all()
    assert_converged([a, b], "annotate delete")
    assert all("k" not in seg.props for seg in a.client.tree.segments)


def test_marker_insert():
    (a, b), server = make_farm(2)
    a.insert(0, "para1")
    a.submit(a.client.insert_marker_local(5, {"refType": 1}, {"type": "pg"}))
    server.sequence_all()
    assert a.client.get_length() == 6
    assert_converged([a, b], "marker")


def test_zamboni_compacts_and_preserves_text():
    (a, b), server = make_farm(2)
    for i in range(10):
        a.insert(a.client.get_length(), f"w{i}")
        server.sequence_all()
        b.insert(0, "z")
        server.sequence_all()
    a.remove(0, 5)
    server.sequence_all()
    # noops advance refSeq → msn rises → zamboni merges/drops
    a.insert(a.client.get_length(), ".")
    b.insert(0, "-")
    server.sequence_all()
    assert_converged([a, b], "zamboni")
    # removed-below-msn segments must be gone from both replicas
    assert all(
        seg.rem_seq is None or seg.rem_seq > a.client.tree.min_seq
        for seg in a.client.tree.segments
    )
    # compaction merged acked runs: fewer segments than ops issued
    assert len(a.client.tree.segments) < 24


def test_snapshot_roundtrip_and_catchup():
    (a, b), server = make_farm(2)
    a.insert(0, "hello ")
    b.insert(0, "say: ")
    server.sequence_all()
    a.annotate(0, 4, {"em": 1})
    server.sequence_all()
    snap = a.client.snapshot()
    c = MergeTreeClient.load("c_new", snap)
    assert c.get_text() == a.text()
    # catch-up: new client applies subsequent sequenced ops correctly
    b.insert(b.client.get_length(), "world")
    raw = b.outbound[-1]
    server.sequence_all()
    from fluidframework_tpu.protocol import MessageType, SequencedDocumentMessage

    c.apply_msg(
        SequencedDocumentMessage(
            client_id="c1",
            sequence_number=server.seq,
            minimum_sequence_number=0,
            client_sequence_number=raw["clientSeq"],
            reference_sequence_number=raw["refSeq"],
            type=MessageType.OPERATION,
            contents=raw["contents"],
        )
    )
    assert c.get_text() == a.text()


def test_snapshot_refuses_pending():
    c = MergeTreeClient("a")
    c.insert_text_local(0, "x")
    with pytest.raises(RuntimeError):
        c.snapshot()


def test_local_reference_slides_on_remove():
    (a, b), server = make_farm(2)
    a.insert(0, "abcdef")
    server.sequence_all()
    ref = a.client.create_reference(3)  # points at 'd'
    assert a.client.reference_position(ref) == 3
    b.remove(2, 5)  # removes cde including ref's segment
    server.sequence_all()
    # ref slid to a surviving segment; position is within the doc
    pos = a.client.reference_position(ref)
    assert 0 <= pos <= a.client.get_length()


def test_three_way_concurrent_edits():
    (a, b, c), server = make_farm(3)
    a.insert(0, "The quick brown fox")
    server.sequence_all()
    a.insert(19, " jumps")
    b.remove(4, 10)  # "quick "
    c.annotate(10, 15, {"style": "i"})
    server.sequence_all()
    assert_converged([a, b, c], "three-way")
