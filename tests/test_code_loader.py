"""Code loader: the quorum-agreed "code" proposal selects the runtime
factory every replica boots (ref: container.ts:1241 loadRuntimeFactory,
web-code-loader, "code" quorum proposals).
"""

import pytest

from fluidframework_tpu.driver import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.loader.code_loader import CodeLoader
from fluidframework_tpu.runtime.container_runtime import ContainerRuntime
from fluidframework_tpu.runtime.summarizer import SummaryManager
from fluidframework_tpu.service import LocalServer


class RuntimeV1(ContainerRuntime):
    code_version = "v1"


class RuntimeV2(ContainerRuntime):
    code_version = "v2"


@pytest.fixture
def server():
    return LocalServer()


def make_loader(server):
    code = CodeLoader()
    code.register("app/v1", RuntimeV1)
    code.register("app/v2", RuntimeV2)
    return Loader(LocalDocumentServiceFactory(server), code_loader=code)


def commit_proposals(container):
    """Quorum proposals commit when the msn passes them (unanimous
    silence); a couple of noops advance the single client's refSeq."""
    from fluidframework_tpu.protocol.messages import MessageType

    container.delta_manager.submit(MessageType.NOOP, None)
    container.delta_manager.submit(MessageType.NOOP, None)


def test_agreed_code_selects_runtime_on_boot(server):
    loader = make_loader(server)
    c1 = loader.resolve("t", "doc")
    c1.propose_code({"package": "app/v2", "config": {}})
    commit_proposals(c1)
    assert c1.quorum.get("code")["package"] == "app/v2"
    ds = c1.runtime.create_data_store("default")
    ds.create_channel("text", "shared-string").insert_text(0, "hi")
    SummaryManager(c1, max_ops=10**9).summarize_now()

    # a fresh replica boots from the summary whose quorum carries the
    # agreed code: it instantiates the v2 runtime
    c2 = loader.resolve("t", "doc")
    assert type(c2.runtime) is RuntimeV2
    assert c2.runtime.get_data_store("default") \
        .get_channel("text").get_text() == "hi"


def test_unregistered_package_fails_boot(server):
    loader = make_loader(server)
    c1 = loader.resolve("t", "doc")
    c1.propose_code({"package": "app/v3-not-installed"})
    commit_proposals(c1)
    c1.runtime.create_data_store("default")
    SummaryManager(c1, max_ops=10**9).summarize_now()
    with pytest.raises(KeyError, match="v3-not-installed"):
        loader.resolve("t", "doc")


def test_without_proposal_default_factory_boots(server):
    loader = make_loader(server)
    c1 = loader.resolve("t", "doc")
    assert type(c1.runtime) is ContainerRuntime  # the stock default
