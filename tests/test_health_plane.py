"""Live health plane: canary probes, the streaming doctor, and the
fleet health gate.

Three contracts pinned here:

* **Offline/live equivalence** — one incident fixture fed through BOTH
  consumers of ``tools/doctor_rules.py`` (the offline bundle doctor and
  the in-process HealthEngine) yields the identical anomaly set. The
  rules are shared verbatim, so the live verdict and the post-incident
  verdict can never drift.
* **Canary isolation** — ``__canary__`` probe traffic walks the REAL
  doors but never lands in placement heat, tenant token buckets, or the
  SLO hop windows: probing can never trigger rebalancing or shedding.
* **The state machine and the gate** — ok→degraded→critical streaks,
  hard signals, the flight-dump evidence chain, and the probe-backed
  ``Fleet.wait_healthy`` go/no-go primitive.
"""

from __future__ import annotations

import json
import os
import time
from types import SimpleNamespace

from fluidframework_tpu.obs.health import (
    STATE_CRITICAL,
    STATE_DEGRADED,
    STATE_OK,
    HealthEngine,
)
from fluidframework_tpu.obs.journal import (
    arm_journal,
    get_journal,
    read_journal,
    reset_journal,
)
from fluidframework_tpu.obs.metrics import (
    MetricsRegistry,
    get_registry,
    reset_registry,
)
from fluidframework_tpu.obs.probe import CANARY_TENANT

# --------------------------------------------------------------- fixture


def _entry(seq, ts, kind, core="core0", epoch=1, **labels):
    return {"id": f"{core}:{seq}", "seq": seq, "ts": ts, "core": core,
            "epoch": epoch, "kind": kind, "cause": None,
            "labels": labels or {}}


def _incident_journal():
    """Storm + cross-host epoch regression + wedged fence + failed
    migration, in one core's tail."""
    entries = [_entry(i + 1, 100.0 + i, "rebalance.suppressed")
               for i in range(10)]
    entries += [
        _entry(11, 120.0, "epoch.bump", epoch=5, part="0",
               change="claim"),
        _entry(12, 121.0, "epoch.bump", core="core2", epoch=3,
               part="0", change="claim"),  # later ts, LOWER epoch
        _entry(13, 150.0, "migration.fence", part="7", final_seq=9),
        _entry(14, 155.0, "migration.fail", part="9",
               error="target vanished"),
        _entry(15, 170.0, "operator.command", command="noop"),
    ]
    return entries


def _incident_bundle(tmp_path):
    """A bundle directory with one reachable core and a dead host
    group, dirty across every rule family the doctor knows."""
    bundle = tmp_path / "bundle"
    c0 = bundle / "cores" / "core0"
    c0.mkdir(parents=True)
    for owner in ("core2", "core3"):
        (bundle / "cores" / owner).mkdir()
    manifest = {"cores": {
        "core0": {"addr": "127.0.0.1:7000", "journal_armed": True},
        "core2": {"addr": "10.0.0.2:7000",
                  "error": "connection refused"},
        "core3": {"addr": "10.0.0.2:7001", "error": "timed out"},
    }}
    (bundle / "manifest.json").write_text(json.dumps(manifest))
    (bundle / "lint.json").write_text(json.dumps({
        "clean": False,
        "violations": [{"pass": "layers", "message": "bad import",
                        "path": "x.py", "line": 3}]}))
    placement = {
        "parts": {"0": {"owner": "ghost", "addr": "10.9.9.9:1",
                        "epoch": 5}},
        "cores": {
            "core0": {"addr": "127.0.0.1:7000", "state": "active",
                      "host": "h0"},
            "core2": {"addr": "10.0.0.2:7000", "state": "active",
                      "host": "h1"},
            "core3": {"addr": "10.0.0.2:7001", "state": "active",
                      "host": "h1"},
        }}
    (bundle / "placement.json").write_text(json.dumps(placement))
    scrape = ("fluid_obs_trace_unknown_hops 2\n"
              "fluid_placement_table_stale_rejections 3\n")
    (c0 / "scrape.prom").write_text(scrape)
    journal = _incident_journal()
    (c0 / "journal.jsonl").write_text(
        "\n".join(json.dumps(e) for e in journal) + "\n")
    boot = {"parts": [{"docs_booted": 1, "docs_pending": 4}],
            "executor": {"parked": 2, "tokens": 3.0},
            "counters": {"boot.part.full_replay": 1}}
    (c0 / "boot.json").write_text(json.dumps(boot))
    slo = {"slos": [{"slo": "interactive", "state": "burning",
                     "p99_ms": 80.0, "budget_ms": 50.0, "burn": 4,
                     "burn_ticks": 5}]}
    (c0 / "slo.json").write_text(json.dumps(slo))
    return bundle, {"manifest": manifest, "placement": placement,
                    "scrape": scrape, "journal": journal,
                    "boot": boot, "slo": slo}


# ------------------------------------------------ offline/live equivalence


def test_offline_live_equivalence(tmp_path):
    """The same incident through tools/doctor.py (bundle) and the
    HealthEngine (live sources) → the identical anomaly multiset and
    SLO burn rows. This is the whole point of doctor_rules.py: one
    rule body, two evaluation times."""
    from tools.doctor import diagnose

    bundle, art = _incident_bundle(tmp_path)
    report = diagnose(str(bundle))

    eng = HealthEngine(
        core="core0",
        scrape_fn=lambda: art["scrape"],
        journal_fn=lambda: list(art["journal"]),
        placement_fn=lambda: art["placement"],
        cores_fn=lambda: dict(art["manifest"]["cores"]),
        slo_fn=lambda: art["slo"],
        boot_fn=lambda: art["boot"],
        lint_fn=lambda: {"clean": False,
                         "violations": [{"pass": "layers",
                                         "message": "bad import",
                                         "path": "x.py", "line": 3}]},
        self_row_fn=lambda: art["manifest"]["cores"]["core0"],
        registry=MetricsRegistry(),
        recorder=SimpleNamespace(dump=lambda *a, **k: "dump"))
    eng.evaluate()

    assert sorted(eng.anomalies()) == sorted(report["anomalies"])
    assert report["anomalies"]  # the fixture is dirty, not vacuous
    assert len(report["anomalies"]) == 13
    # SLO burn stays out of anomalies in BOTH consumers, same rows
    assert eng.slo_burn == report["slo_burn"]
    assert eng.slo_burn[0]["core"] == "core0"
    # the dead host group is a hard signal: critical on the first tick
    assert eng.verdict() == "critical"
    assert eng.status()["components"]["placement"]["state"] == "critical"


def test_equivalence_on_healthy_fixture(tmp_path):
    """A quiet bundle: doctor says healthy, engine says ok — no rule
    fires in one consumer but not the other."""
    from tools.doctor import diagnose

    bundle = tmp_path / "bundle"
    c0 = bundle / "cores" / "core0"
    c0.mkdir(parents=True)
    (bundle / "manifest.json").write_text(json.dumps({"cores": {
        "core0": {"addr": "127.0.0.1:7000", "journal_armed": True}}}))
    journal = [_entry(1, 100.0, "lease.claim", part="0")]
    (c0 / "journal.jsonl").write_text(
        "\n".join(json.dumps(e) for e in journal) + "\n")
    (c0 / "scrape.prom").write_text("fluid_net_frames_total 5\n")
    report = diagnose(str(bundle))
    assert report["anomalies"] == []

    eng = HealthEngine(
        core="core0",
        scrape_fn=lambda: "fluid_net_frames_total 5\n",
        journal_fn=lambda: list(journal),
        self_row_fn=lambda: {"journal_armed": True},
        registry=MetricsRegistry())
    eng.evaluate()
    assert eng.anomalies() == []
    assert eng.verdict() == "ok"


# --------------------------------------------------------- state machine


def _probe_status(failures, error="boom"):
    return {"doors": {"connect": {
        "ok": failures == 0, "consec_failures": failures,
        "probes": 10, "last_ms": 1.0,
        "last_error": None if failures == 0 else error}}}


def test_engine_streak_escalation_and_recovery(tmp_path):
    """ok → degraded on the first anomalous tick, critical after
    ``critical_ticks`` consecutive, back to ok on recovery — each
    transition journaled, the critical one linked to a flight dump."""
    path = str(tmp_path / "journal" / "c0.jsonl")
    arm_journal(path, core="c0")
    try:
        dumps = []

        def dump(reason, **fields):
            dumps.append((reason, fields))
            return f"/flight/{len(dumps)}.jsonl"

        state = {"failures": 0}
        eng = HealthEngine(
            core="c0", probe_fn=lambda: _probe_status(state["failures"]),
            registry=MetricsRegistry(),
            recorder=SimpleNamespace(dump=dump),
            critical_ticks=3, probe_fail_critical=99)
        eng.evaluate()
        assert eng.verdict() == "ok"

        state["failures"] = 1
        eng.evaluate()
        assert eng.status()["components"]["probe"]["state"] == "degraded"
        state["failures"] = 2
        eng.evaluate()
        assert eng.verdict() == "degraded"  # streak 2 < 3
        state["failures"] = 3
        eng.evaluate()
        assert eng.verdict() == "critical"
        assert dumps and dumps[0][1]["component"] == "probe"

        state["failures"] = 0
        eng.evaluate()
        assert eng.verdict() == "ok"

        entries = read_journal(path)
        trans = [e for e in entries if e["kind"] == "health.state"]
        assert [(e["labels"]["prev"], e["labels"]["state"])
                for e in trans] == [("ok", "degraded"),
                                    ("degraded", "critical"),
                                    ("critical", "ok")]
        # the critical transition carries its evidence: cause is the
        # flight.dump entry journaled right before it
        crit = trans[1]
        dump_entries = [e for e in entries if e["kind"] == "flight.dump"]
        assert len(dump_entries) == 1
        assert crit["cause"] == dump_entries[0]["id"]
        assert dump_entries[0]["labels"]["reason"] == "health_critical"
    finally:
        reset_journal()


def test_engine_hard_probe_signal_skips_streak():
    """A canary door past ``probe_fail_critical`` consecutive failures
    is critical IMMEDIATELY — a dead front door does not get to ride
    out the streak."""
    eng = HealthEngine(
        core="c0", probe_fn=lambda: _probe_status(3),
        registry=MetricsRegistry(),
        recorder=SimpleNamespace(dump=lambda *a, **k: "d"),
        critical_ticks=100, probe_fail_critical=3)
    eng.evaluate()
    assert eng.verdict() == "critical"
    reasons = eng.status()["components"]["probe"]["reasons"]
    assert any("canary probe connect failing (3 consecutive)" in r
               for r in reasons)
    assert STATE_OK < STATE_DEGRADED < STATE_CRITICAL


def test_engine_unreachable_peer_rows_are_hard():
    """The prober's peer-reachability rows feed the placement rules:
    a whole host group of dead peers is the doctor's unreachable-host
    rule, evaluated live, and it is a hard critical."""
    placement = {"parts": {}, "cores": {
        "c0": {"addr": "127.0.0.1:1", "state": "active", "host": "h0"},
        "c1": {"addr": "10.0.0.2:1", "state": "active", "host": "h1"},
        "c2": {"addr": "10.0.0.2:2", "state": "active", "host": "h1"},
    }}
    rows = {"c1": {"addr": "10.0.0.2:1", "error": "refused"},
            "c2": {"addr": "10.0.0.2:2", "error": "timeout"}}
    eng = HealthEngine(
        core="c0", placement_fn=lambda: placement,
        cores_fn=lambda: rows, registry=MetricsRegistry(),
        recorder=SimpleNamespace(dump=lambda *a, **k: "d"),
        critical_ticks=100)
    eng.evaluate()
    assert eng.verdict() == "critical"
    assert any("host group h1" in r for r in eng.anomalies())


# ------------------------------------------------------- canary isolation


def test_admission_never_charges_canary():
    """The canary prober submits through the real admission gate but
    never consumes a token nor gets shed — even with a zero-rate
    bucket configured for it and the shed signal active."""
    from fluidframework_tpu.service.admission import AdmissionController

    adm = AdmissionController(lambda t: (0.001, 1.0),
                              registry=MetricsRegistry())
    adm.engine = SimpleNamespace(shed_signal="violated")
    conn = SimpleNamespace(tenant_id=CANARY_TENANT)
    for cseq in (1, 2, 3):
        assert adm.check(conn, 100, cseq, now=0.0) == 0.0
    assert CANARY_TENANT not in adm._buckets
    # a real tenant on the same controller IS shed (the gate works)
    real = SimpleNamespace(tenant_id="acme")
    assert adm.check(real, 100, 1, now=0.0) == 0.0  # burst admits
    assert adm.check(real, 100, 2, now=0.0) > 0.0


def test_stamp_abatch_skips_canary_hops(monkeypatch):
    """The egress hop observe — the SLO engine's read source — skips
    canary boxcars: probe latency may never burn a tenant SLO."""
    import fluidframework_tpu.service.front_end as fe
    from fluidframework_tpu.utils.telemetry import HOP_ADMIT, HOP_SUBMIT

    monkeypatch.setattr(fe.binwire, "stamp_cols_ops",
                        lambda *a, **k: b"")
    reset_registry()
    try:
        reg = get_registry()

        def batch(topic):
            box = SimpleNamespace(
                wire_cols=b"\x00", client_id="c",
                hops=[(HOP_SUBMIT, 1.0), (HOP_ADMIT, 1.002)])
            return SimpleNamespace(boxcar=box, base_seq=1, msns=None,
                                   timestamp=0.0), topic

        fe._stamp_abatch(*batch(f"{CANARY_TENANT}/__probe__0"))
        assert reg.window_sum("obs.hop.window_ms") == 0.0
        fe._stamp_abatch(*batch("acme/doc"))
        assert reg.window_sum("obs.hop.window_ms",
                              tenant="acme") > 0.0
        assert reg.window_sum("obs.hop.window_ms",
                              tenant=CANARY_TENANT) == 0.0
    finally:
        reset_registry()


# ------------------------------------------------- the fleet health gate


def test_fleet_wait_healthy_probe_backed_and_isolated(tmp_path):
    """End to end on an in-process fleet with the health plane armed:
    ``wait_healthy`` returns only after canaries have walked every
    door, the fleet ``admin_health`` verdict aggregates to ok, and the
    canary's synthetic traffic left ZERO trace in placement heat, hop
    windows, tenant buckets, or anywhere else in the scrape."""
    from fluidframework_tpu.service.placement_plane import admin_rpc
    from fluidframework_tpu.service.rebalancer import HEAT_OPS
    from fluidframework_tpu.service.topology import Fleet, default_spec

    reset_registry()
    spec = default_spec(str(tmp_path / "fleet"), n_cores=2,
                        n_partitions=4, lease_ttl=2.0,
                        health={"probe_tick_s": 0.2, "tick_s": 0.2})
    fl = Fleet(spec).start()
    try:
        fl.wait_claimed()
        verdicts = fl.wait_healthy(timeout=30.0)
        assert sorted(verdicts) == ["core0", "core1"]
        for h in verdicts.values():
            assert h["verdict"] == "ok"
            doors = h["probes"]["doors"]
            # every session door probed ok; two cores → route too
            for door in ("connect", "submit", "history", "route"):
                assert doors[door]["probes"] > 0
                assert doors[door]["ok"], doors[door]

        reply = admin_rpc(*fl.core_addr(0),
                          {"t": "admin_health", "fleet": 1},
                          timeout=15.0)
        fleet_h = reply["health"]
        assert fleet_h["fleet"] is True
        assert fleet_h["verdict"] == "ok"
        assert len(fleet_h["cores"]) == 2

        # ---- isolation: the probes ran, yet the canary is invisible
        reg = get_registry()
        assert reg.window_sum(HEAT_OPS) == 0.0  # no rebalancer input
        assert reg.window_sum("obs.hop.window_ms",
                              tenant=CANARY_TENANT) == 0.0
        assert CANARY_TENANT not in reg.scrape()
        for front in fl.fronts.values():
            adm = front.admission
            assert adm is None or CANARY_TENANT not in adm._buckets
        # but the probe's OWN metrics did land (it measures, after all)
        assert reg.window_sum("health.probe.ms", door="connect") > 0.0
    finally:
        fl.stop()
        reset_registry()


def test_wait_healthy_times_out_on_unarmed_fleet(tmp_path):
    """A fleet without ``spec.health`` answers ``unknown`` — the gate
    must refuse to pass it (fail closed), not vacuously succeed."""
    import pytest

    from fluidframework_tpu.service.topology import Fleet, default_spec

    spec = default_spec(str(tmp_path / "fleet"), n_cores=1,
                        n_partitions=2, lease_ttl=2.0)
    fl = Fleet(spec).start()
    try:
        fl.wait_claimed()
        with pytest.raises(TimeoutError) as ei:
            fl.wait_healthy(timeout=1.5)
        assert "unknown" in str(ei.value)
    finally:
        fl.stop()
