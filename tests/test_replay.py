"""Snapshot-regression corpus: committed recorded documents replay
byte-identically through the real client stack AND converge on the TPU
applier; any semantic drift in the CRDT fails here.

Ref: packages/test/snapshots/src/replayMultipleFiles.ts:33 (Compare
mode), packages/tools/replay-tool.
"""

import json
import os

import jax.numpy as jnp
import pytest

import fluidframework_tpu.service.tpu_applier as tpu_applier_mod
from fluidframework_tpu.driver.file import (
    FileDocumentService,
    ReadOnlyDocumentError,
)
from fluidframework_tpu.replay import (
    ReplayController,
    replay_and_compare,
    replay_through_applier,
)
from fluidframework_tpu.service.tpu_applier import TpuDocumentApplier

CORPUS = os.path.join(os.path.dirname(__file__), "corpus", "corpus")
SCENARIOS = sorted(os.listdir(CORPUS))


def load_expect(name):
    with open(os.path.join(CORPUS, name, "expect.json")) as f:
        return json.load(f)


@pytest.mark.parametrize("name", SCENARIOS)
def test_corpus_replays_byte_identical(name):
    problems = replay_and_compare(
        os.path.join(CORPUS, name), load_expect(name))
    assert problems == []


@pytest.mark.parametrize("name", SCENARIOS)
def test_corpus_device_replay_matches(name):
    """The applier (scribe-replay role) must produce the same text the
    live replicas converged on when the corpus was recorded."""
    text = replay_through_applier(os.path.join(CORPUS, name))
    assert text == load_expect(name)["final_text"]


def test_corpus_catches_kernel_change(monkeypatch):
    """An intentionally-broken kernel must FAIL the corpus comparison —
    this is the regression tripwire working."""
    real = tpu_applier_mod.apply_ops_batch

    def skewed(state, wave):
        # shift every insert one position right: a subtle semantic change
        pos = wave[..., 1]
        is_ins = wave[..., 0] == 1
        wave = wave.at[..., 1].set(jnp.where(is_ins & (pos > 0), pos - 1, pos))
        return real(state, wave)

    monkeypatch.setattr(tpu_applier_mod, "apply_ops_batch", skewed)
    # unique geometry → fresh jit trace picks up the patched kernel
    applier = TpuDocumentApplier(max_docs=3, max_slots=640,
                                 ops_per_dispatch=13)
    name = "text-conflict"
    text = replay_through_applier(os.path.join(CORPUS, name), applier)
    assert text != load_expect(name)["final_text"]


def test_file_driver_boots_from_snapshot_plus_tail():
    """text-basic carries a mid-stream acked summary: the file driver
    boots the container from it and the tail replays on top."""
    doc_dir = os.path.join(CORPUS, "text-basic")
    assert os.path.exists(os.path.join(doc_dir, "snapshot.json"))
    svc = FileDocumentService.from_dir(doc_dir)
    ctl = ReplayController(svc)
    assert ctl.container.existing  # booted from the snapshot
    assert ctl.container.delta_manager.last_processed_seq > 0
    result = ctl.run()
    assert result["final_text"] == load_expect("text-basic")["final_text"]


def test_file_driver_documents_are_read_only():
    svc = FileDocumentService.from_dir(os.path.join(CORPUS, "text-basic"))
    with pytest.raises(ReadOnlyDocumentError):
        svc.connect_to_delta_stream()
    with pytest.raises(ReadOnlyDocumentError):
        svc.connect_to_storage().upload_summary({}, None)
