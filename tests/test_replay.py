"""Snapshot-regression corpus: committed recorded documents replay
byte-identically through the real client stack AND converge on the TPU
applier; any semantic drift in the CRDT fails here.

Ref: packages/test/snapshots/src/replayMultipleFiles.ts:33 (Compare
mode), packages/tools/replay-tool.
"""

import json
import os

import jax.numpy as jnp
import pytest

import fluidframework_tpu.service.tpu_applier as tpu_applier_mod
from fluidframework_tpu.driver.file import (
    FileDocumentService,
    ReadOnlyDocumentError,
)
from fluidframework_tpu.replay import (
    ReplayController,
    replay_and_compare,
    replay_through_applier,
)
from fluidframework_tpu.service.tpu_applier import TpuDocumentApplier

CORPUS = os.path.join(os.path.dirname(__file__), "corpus", "corpus")
SCENARIOS = sorted(os.listdir(CORPUS))


def load_expect(name):
    with open(os.path.join(CORPUS, name, "expect.json")) as f:
        return json.load(f)


@pytest.mark.parametrize("name", SCENARIOS)
def test_corpus_replays_byte_identical(name):
    problems = replay_and_compare(
        os.path.join(CORPUS, name), load_expect(name))
    assert problems == []


@pytest.mark.parametrize("name", SCENARIOS)
def test_corpus_device_replay_matches(name):
    """The applier (scribe-replay role) must produce the same text the
    live replicas converged on when the corpus was recorded."""
    text = replay_through_applier(os.path.join(CORPUS, name))
    assert text == load_expect(name)["final_text"]


def test_corpus_catches_kernel_change(monkeypatch):
    """An intentionally-broken kernel must FAIL the corpus comparison —
    this is the regression tripwire working."""
    real = tpu_applier_mod.apply_ops_batch

    def skewed(state, wave):
        # shift every insert one position right: a subtle semantic change
        pos = wave[..., 1]
        is_ins = wave[..., 0] == 1
        wave = wave.at[..., 1].set(jnp.where(is_ins & (pos > 0), pos - 1, pos))
        return real(state, wave)

    monkeypatch.setattr(tpu_applier_mod, "apply_ops_batch", skewed)
    # unique geometry → fresh jit trace picks up the patched kernel
    applier = TpuDocumentApplier(max_docs=3, max_slots=640,
                                 ops_per_dispatch=13)
    name = "text-conflict"
    text = replay_through_applier(os.path.join(CORPUS, name), applier)
    assert text != load_expect(name)["final_text"]


def test_file_driver_boots_from_snapshot_plus_tail():
    """text-basic carries a mid-stream acked summary: the file driver
    boots the container from it and the tail replays on top."""
    doc_dir = os.path.join(CORPUS, "text-basic")
    assert os.path.exists(os.path.join(doc_dir, "snapshot.json"))
    svc = FileDocumentService.from_dir(doc_dir)
    ctl = ReplayController(svc)
    assert ctl.container.existing  # booted from the snapshot
    assert ctl.container.delta_manager.last_processed_seq > 0
    result = ctl.run()
    assert result["final_text"] == load_expect("text-basic")["final_text"]


def test_file_driver_documents_are_read_only():
    svc = FileDocumentService.from_dir(os.path.join(CORPUS, "text-basic"))
    with pytest.raises(ReadOnlyDocumentError):
        svc.connect_to_delta_stream()
    with pytest.raises(ReadOnlyDocumentError):
        svc.connect_to_storage().upload_summary({}, None)


def test_fetch_live_doc_then_replay_offline(tmp_path):
    """The fetch-tool role (packages/tools/fetch-tool): pull a LIVE
    networked doc into the file-driver layout, then replay it OFFLINE
    through the real client stack and converge to the live text. The
    doc is deliberately aged past an acked summary with AGGRESSIVE log
    retention (margin 0), so the service refuses from-zero delta reads
    (LogTruncatedError) — fetch must reconstruct from the snapshot plus
    the tail above its sequence number, the long-lived-production-doc
    case the tool exists for."""
    import os as _os
    import subprocess
    import sys
    import time

    from fluidframework_tpu.driver.network import (
        NetworkDocumentServiceFactory,
    )
    from fluidframework_tpu.loader import Loader
    from fluidframework_tpu.replay.fetch import fetch_document
    from fluidframework_tpu.runtime.summarizer import SummaryManager

    env = dict(_os.environ, FLUID_TPU_LOG_RETENTION_OPS="0")
    core = subprocess.Popen(
        [sys.executable, "-m", "fluidframework_tpu.service.front_end",
         "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd="/root/repo", env=env)
    try:
        line = core.stdout.readline().strip()
        assert line.startswith("LISTENING"), line
        port = int(line.rsplit(":", 1)[1])
        loader = Loader(NetworkDocumentServiceFactory("127.0.0.1", port))
        c = loader.resolve("t", "fetchdoc")
        sm = SummaryManager(c, max_ops=3)
        s = c.runtime.create_data_store("default").create_channel(
            "text", "shared-string")
        s.insert_text(0, "offline me")
        s.remove_text(0, 4)
        t0 = time.time()
        while sm.summaries_acked == 0 and time.time() - t0 < 30:
            time.sleep(0.02)
        assert sm.summaries_acked >= 1  # retention has truncated below it
        s.insert_text(0, "replay ")  # tail ops above the summary
        t0 = time.time()
        while c.runtime.pending.count and time.time() - t0 < 15:
            time.sleep(0.02)
        live_text = s.get_text()

        # from-zero delta reads are refused now — fetch must cope
        from fluidframework_tpu.driver.network import _Transport
        t = _Transport("127.0.0.1", port, timeout=10.0)
        try:
            import pytest as _pytest
            with _pytest.raises(RuntimeError, match="truncated"):
                t.request({"t": "get_deltas", "tenant": "t",
                           "doc": "fetchdoc", "from": 0, "to": 10**9})
        finally:
            t.close()

        doc_dir = fetch_document("127.0.0.1", port, "t", "fetchdoc",
                                 str(tmp_path))
        assert os.path.exists(os.path.join(doc_dir, "messages.json"))
    finally:
        core.terminate()
        core.wait(timeout=10)

    # the service is GONE; the fetched artifact replays standalone
    svc = FileDocumentService.from_dir(doc_dir)
    ctl = ReplayController(svc)
    assert ctl.container.existing  # booted from the fetched snapshot
    result = ctl.run()
    assert result["final_text"] == live_text == "replay ine me"
