"""Fixture: the bottom layer imports no siblings — clean."""

SENTINEL = 1
