"""Fixture: protocol importing utils is within the layer DAG — clean."""

from fluidframework_tpu.utils import helper  # noqa: F401  (legal)

WIDTH = helper.SENTINEL
