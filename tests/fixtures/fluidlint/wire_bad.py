"""Fixture: wire/width violations the wire pass must flag.

Never imported — parsed by AST only.
"""

import struct

import numpy as np

# not explicitly big-endian: native order varies by platform
HEADER = struct.Struct("HHi")

# native-size code 'l' changes width across platforms
TRAILER = struct.Struct(">Hl")


def apply_delta(wave16, base):
    # arithmetic on an int16 wave without an explicit cast: silent
    # promotion — the packed-wave width bug
    seq = wave16 + base
    return seq


def scale_packed(n):
    w = np.zeros(n, np.int16)
    w *= 4  # in-place arithmetic on an int16 array
    return w
