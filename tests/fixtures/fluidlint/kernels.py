"""Fixture kernels for the jaxpr pass — loaded by file path, never
imported as part of the tree (the hygiene/layer passes skip fixtures/).

``gatherful_kernel`` is the canonical TPU slow path: a computed-index
read per row, which vmap lowers to ``lax.gather``. ``clean_kernel``
computes the same values via a one-hot masked sum (the idiom
ops/apply.py uses). The int16 pair mirrors the packed-wave unpack in
service/tpu_applier.py with and without the explicit width cast.
"""

import jax
import jax.numpy as jnp


def gatherful_kernel(a, idx):
    # a[i, idx[i]] per row: batches to a gather primitive under vmap
    return jax.vmap(lambda row, j: row[j])(a, idx)


def clean_kernel(a, idx):
    # same result, gather-free: one-hot mask + masked sum
    cols = jnp.arange(a.shape[-1])[None, :]
    mask = cols == idx[:, None]
    return jnp.sum(jnp.where(mask, a, 0), axis=-1)


def int16_promoting_kernel(wave16, bases):
    # the delta is scaled while still int16 — the multiply runs at
    # int16 width and can overflow before the (implicit) widening
    return bases[:, :1] + wave16 * 2


def int16_clean_kernel(wave16, bases):
    return bases[:, :1] + wave16.astype(jnp.int32) * 2
