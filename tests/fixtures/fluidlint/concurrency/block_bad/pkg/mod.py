"""Seeded BLOCKING-ON-LOOP: a coroutine body sleeps synchronously and
dials a @blocking helper; a call_soon callback blocks too."""

import time

from .aff import blocking


@blocking("socket dial + round trip")
def dial(addr):
    return addr


async def poll_loop():
    time.sleep(0.1)  # SEEDED VIOLATION: sync sleep in a coroutine


async def fan_out():
    return dial("peer:1")  # SEEDED VIOLATION: @blocking on the loop


def sender(sock):
    sock.sendall(b"x")  # blocker, but unseeded: no loop context here


async def arm(loop):
    loop.call_soon(flush_now)


def flush_now(sock):
    sock.sendall(b"y")  # SEEDED VIOLATION: call_soon callback blocks
