"""Clean twin: the blocking fan-out runs behind run_in_executor; the
executor context is allowed to block."""

from .aff import blocking


@blocking("socket dial + round trip")
def dial(addr):
    return addr


def fleet_work():
    return [dial("peer:1"), dial("peer:2")]


async def fan_out(loop):
    return await loop.run_in_executor(None, fleet_work)
