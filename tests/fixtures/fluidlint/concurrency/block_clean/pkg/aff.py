"""Fixture-local no-op markers (the checker matches decorator NAMES,
so these twins keep the fixture importable without the real package)."""


def loop_only(loop_name="core"):
    def mark(fn):
        return fn
    return mark


def ticker_thread(ticker_name):
    def mark(fn):
        return fn
    return mark


def any_thread(fn):
    return fn


def holds_lock(lock_name):
    def mark(fn):
        return fn
    return mark


def blocking(why):
    def mark(fn):
        return fn
    return mark
