"""Clean twin: the ticker crosses to the loop through the sanctioned
call_soon_threadsafe seam, so no direct cross-affinity edge exists."""

from .aff import loop_only, ticker_thread


@loop_only("core")
def mutate_table():
    return {}


@ticker_thread("rebalancer")
def tick(loop):
    loop.call_soon_threadsafe(mutate_table)
