"""PR 11's donation-on-CPU bug, reconstructed: with the platform guard
commented out, dispatch synchronizes on every wave — the serialization
the overlap pipeline existed to avoid. The checker must see it."""

from .aff import loop_only


def apply_kernel(state, wave):
    return state


@loop_only("core")
def dispatch(state, wave):
    out = apply_kernel(state, wave)
    # if platform != "cpu":  # the guard the bug was missing
    out.block_until_ready()  # RECONSTRUCTED BUG: device sync on loop
    return out
