"""PR 11's stage-buffer rotation bug, reconstructed: the refill fence
keyed to input readiness instead of the CONSUMING execution — here
reduced to its race shape: the staging slot is written by the loop's
ingest and the worker's recycle with no fence at all."""

import threading


class Applier:
    def __init__(self):
        self._stage = None
        self._worker = None

    def start(self):
        self._worker = threading.Thread(target=self.recycle,
                                        name="applier")
        self._worker.start()

    async def ingest(self, ops):
        self._stage = list(ops)  # RECONSTRUCTED BUG: no rotation fence

    def recycle(self):
        self._stage = None  # worker-side refill, same slot, no fence
