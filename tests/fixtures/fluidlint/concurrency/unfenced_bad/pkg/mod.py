"""Seeded UNFENCED-SHARED-STATE: one attribute written from the worker
thread and from a coroutine with no common lock."""

import threading


class Pump:
    def __init__(self):
        self.value = 0
        self._worker = None

    def start(self):
        self._worker = threading.Thread(target=self.run, name="pump")
        self._worker.start()

    def run(self):
        self.value = 1  # thread write, no fence

    async def ingest(self, v):
        self.value = v  # SEEDED VIOLATION: loop write, no common fence
