"""Seeded LOCK-ORDER inversions: a journal-holding path acquires the
epoch-table flock (rank 0 after rank 3), and an applier-holding path
calls into an epoch-table holder."""

from .aff import holds_lock


def _flock(path):
    return open(path)


@holds_lock("journal_lock")
def flush_entry(path):
    with _flock(path):  # SEEDED VIOLATION: rank-0 lock after rank-3
        return 1


@holds_lock("epoch_table_flock")
def record_claim(rec):
    return rec


@holds_lock("applier_lock")
def drain_and_record():
    return record_claim({})  # SEEDED VIOLATION: callee takes rank 0
