"""Seeded CROSS-AFFINITY: the ticker calls a loop-affine mutator
directly instead of going through a loopback seam."""

from .aff import loop_only, ticker_thread


@loop_only("core")
def mutate_table(k):
    return {"k": k}


@ticker_thread("rebalancer")
def tick():
    return mutate_table(3)  # SEEDED VIOLATION: ticker -> @loop_only
