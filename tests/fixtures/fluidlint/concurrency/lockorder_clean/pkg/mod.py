"""Clean twin: acquisitions follow the global order, outermost first."""

from .aff import holds_lock


def _flock(path):
    return open(path)


def claim_then_drain(path):
    with _flock(path):  # rank 0 first...
        return drain()


@holds_lock("applier_lock")
def drain():  # ...then rank 2: ordered
    return 1
