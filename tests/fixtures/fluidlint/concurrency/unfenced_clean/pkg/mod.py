"""Clean twin: every cross-context write holds the same instance lock."""

import threading


class Pump:
    def __init__(self):
        self.value = 0
        self._lock = threading.Lock()
        self._worker = None

    def start(self):
        self._worker = threading.Thread(target=self.run, name="pump")
        self._worker.start()

    def run(self):
        with self._lock:
            self.value = 1

    async def ingest(self, v):
        with self._lock:
            self.value = v
