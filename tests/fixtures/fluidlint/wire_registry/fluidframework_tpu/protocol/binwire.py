"""Seeded frame-registry fixture: a reused wire id and a frame with no
codec-manifest entry (tools/fluidlint/registries.py FT_CODECS)."""

FT_SUBMIT = 1
FT_OPS = 1  # SEEDED VIOLATION: id 1 reused
FT_BOGUS = 9  # SEEDED VIOLATION: no (encoder, decoder) manifest entry
