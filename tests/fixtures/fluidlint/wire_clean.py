"""Fixture: wire/width discipline done right — the wire pass must come
back clean on this file.
"""

import struct

import numpy as np

HEADER = struct.Struct(">HHi")


def apply_delta(wave16, base):
    # explicit widening before math: the sanctioned pattern
    wide = wave16.astype(np.int32)
    return wide + base
