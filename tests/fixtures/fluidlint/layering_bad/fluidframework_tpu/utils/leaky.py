"""Fixture: a deliberate layering violation — the bottom layer (utils)
reaching UP into protocol. fluidlint's layer pass must flag this."""

from fluidframework_tpu.protocol import frame  # noqa: F401  (violation)
