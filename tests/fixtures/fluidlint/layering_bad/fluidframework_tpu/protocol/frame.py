"""Fixture: a legal import — protocol may use utils."""

from fluidframework_tpu.utils import leaky  # noqa: F401  (legal)
