"""Loader parity: detached create -> attach, readonly modes, read-scope
connections (ref: container.ts:510 attach flow, deltaManager.ts:274
readonly, tokens.ts scopes).
"""

import pytest

from fluidframework_tpu.driver import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.service import LocalServer
from fluidframework_tpu.service.tenants import (
    SCOPE_READ,
    TenantManager,
    sign_token,
)


@pytest.fixture
def server():
    return LocalServer()


@pytest.fixture
def loader(server):
    return Loader(LocalDocumentServiceFactory(server))


def test_detached_container_builds_offline_then_attaches(server, loader):
    detached = loader.create_detached("t", "newdoc")
    assert detached.detached and not detached.connected
    ds = detached.runtime.create_data_store("default")
    text = ds.create_channel("text", "shared-string")
    text.insert_text(0, "built offline")
    text.annotate_range(0, 5, {"bold": True})
    kv = ds.create_channel("kv", "shared-map")
    kv.set("made", "detached")
    # nothing reached the service yet
    assert server.get_deltas("t", "newdoc", 0, 10**9) == []

    detached.attach()
    assert detached.connected and not detached.detached
    assert detached.runtime.pending.count == 0  # initial state acked

    c2 = loader.resolve("t", "newdoc")
    ds2 = c2.runtime.get_data_store("default")
    assert ds2.get_channel("text").get_text() == "built offline"
    assert ds2.get_channel("kv").get("made") == "detached"
    # and the attached replica stays live
    ds2.get_channel("text").insert_text(0, ">")
    assert text.get_text() == ">built offline"


def test_attach_on_non_detached_container_refused(loader):
    c = loader.resolve("t", "doc")
    with pytest.raises(RuntimeError, match="not detached"):
        c.attach()


def test_force_readonly_blocks_local_edits(server, loader):
    c = loader.resolve("t", "doc")
    s = c.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    s.insert_text(0, "editable")
    c.force_readonly()
    assert c.readonly
    # the submission is refused and the now-divergent replica closes
    # (apps gate editing UI on c.readonly — same contract as the
    # reference's readonly assert, which kills the container)
    with pytest.raises(PermissionError, match="readonly"):
        s.insert_text(0, "nope")
    assert c.closed
    assert c.runtime.pending.count == 0  # nothing recorded as pending
    # the service never saw the refused edit: a fresh replica has the
    # pre-violation content only
    c2 = loader.resolve("t", "doc")
    assert (c2.runtime.get_data_store("default").get_channel("text")
            .get_text() == "editable")


def test_readonly_replica_keeps_receiving_remote_ops(server, loader):
    c = loader.resolve("t", "doc")
    s = c.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    s.insert_text(0, "editable")
    c.force_readonly()
    c2 = loader.resolve("t", "doc")
    c2.runtime.get_data_store("default").get_channel("text") \
        .insert_text(0, "remote ")
    assert s.get_text() == "remote editable"  # reads stay live
    c.force_readonly(False)
    s.insert_text(0, "again ")
    assert s.get_text() == "again remote editable"


def test_read_connection_stays_out_of_quorum_and_msn():
    """A read connection must not pin the collaboration window: it never
    joins the quorum, so the msn advances without it (ref: read
    connections live in the audience only)."""
    tm = TenantManager()
    tm.register("acme", "s3cret")
    server = LocalServer(tenants=tm)
    w = server.connect("acme", "doc",
                       token=sign_token("acme", "doc", "s3cret"))
    r = server.connect(
        "acme", "doc",
        token=sign_token("acme", "doc", "s3cret", scopes=(SCOPE_READ,)))
    assert r.mode == "read"
    deli = server._get_orderer("acme", "doc").deli
    assert r.client_id not in deli.clients  # not a quorum member

    from fluidframework_tpu.protocol.messages import (
        DocumentMessage,
        MessageType,
    )

    seen = []
    r.on_ops = lambda batch: seen.extend(batch)
    for i in range(1, 6):
        w.submit([DocumentMessage(
            client_sequence_number=i, reference_sequence_number=i,
            type=MessageType.OPERATION, contents={"i": i})])
    # the msn tracks the WRITER alone — the silent reader doesn't pin it
    assert deli._min_ref_seq() >= 5
    assert len([m for m in seen if m.type.value == "op"]) == 5  # reads live
    r.disconnect()  # no leave op needed; nothing joined


def test_read_scope_connection_watches_but_cannot_write():
    tm = TenantManager()
    tm.register("acme", "s3cret")
    server = LocalServer(tenants=tm)
    writer = server.connect(
        "acme", "doc", token=sign_token("acme", "doc", "s3cret"))
    reader = server.connect(
        "acme", "doc",
        token=sign_token("acme", "doc", "s3cret", scopes=(SCOPE_READ,)))
    seen, nacks = [], []
    reader.on_ops = lambda batch: seen.extend(batch)
    reader.on_nack = lambda n: nacks.append(n)

    from fluidframework_tpu.protocol.messages import (
        DocumentMessage,
        MessageType,
    )

    writer.submit([DocumentMessage(
        client_sequence_number=1, reference_sequence_number=0,
        type=MessageType.OPERATION, contents={"x": 1})])
    assert any(m.client_id == writer.client_id for m in seen)  # read works
    reader.submit([DocumentMessage(
        client_sequence_number=1, reference_sequence_number=0,
        type=MessageType.OPERATION, contents={"x": 2})])
    assert nacks and nacks[0].type.value == "InvalidScopeError"
    # the nacked op was never sequenced
    assert all(m.client_id != reader.client_id or m.type.value != "op"
               for m in server.get_deltas("acme", "doc", 0, 10**9))


def test_watch_only_client_heartbeats_and_msn_advances(server, loader):
    """A watcher that never edits must not pin the msn: after enough
    remote ops it sends a refSeq-advancing NOOP (deltaManager.ts:583
    noop heuristics), letting the collaboration window move."""
    editor = loader.resolve("t", "doc")
    watcher = loader.resolve("t", "doc")
    watcher.delta_manager.noop_frequency = 10
    s = editor.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    for i in range(30):
        s.insert_text(0, "x")
    deli = server._get_orderer("t", "doc").deli
    watcher_state = deli.clients[watcher.client_id]
    # the watcher's refSeq tracked the stream via heartbeats
    assert watcher_state.reference_sequence_number > 0
    lag = deli.sequence_number - deli._min_ref_seq()
    assert lag <= 2 * watcher.delta_manager.noop_frequency


def test_no_client_marker_when_doc_goes_quiet(server, loader):
    c1 = loader.resolve("t", "doc")
    seen = []
    conn = server.connect("t", "watchdoc")  # raw connection to observe
    conn.on_ops = lambda batch: seen.extend(batch)
    c2 = loader.resolve("t", "watchdoc")
    c2.close()
    conn.disconnect()  # last client leaves → NO_CLIENT marker
    types = [m.type.value for m in seen]
    assert "noClient" not in types  # c2's leave: conn still present
    # check the sequenced log directly for the marker after the LAST leave
    log = server.get_deltas("t", "watchdoc", 0, 10**9)
    assert [m.type.value for m in log][-1] == "noClient"
