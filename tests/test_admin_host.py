"""Admin surface + dev-host runner + the round-5 example apps.

Ref: server/admin + riddler tenantManager (management surface),
webpack-fluid-loader multiResolver.ts:75 (the dev host),
examples/data-objects/{todo,canvas} (the apps).
"""

from __future__ import annotations

import subprocess
import sys
import time

import pytest

from fluidframework_tpu.driver.network import NetworkDocumentServiceFactory
from fluidframework_tpu.loader import Loader


def _spawn(args):
    proc = subprocess.Popen(
        [sys.executable, "-m"] + args,
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd="/root/repo")
    line = proc.stdout.readline().strip()
    assert line.startswith("LISTENING"), line
    return proc, int(line.rsplit(":", 1)[1])


def _admin(port, *argv):
    from fluidframework_tpu import admin

    return admin.main(["--port", str(port), *argv])


def test_admin_status_docs_and_tenant_crud(capsys):
    core, port = _spawn(["fluidframework_tpu.service.front_end",
                         "--port", "0", "--admin-secret", "s3s4m3"])
    try:
        loader = Loader(NetworkDocumentServiceFactory("127.0.0.1", port))
        c = loader.resolve("t", "admindoc")
        s = c.runtime.create_data_store("default").create_channel(
            "text", "shared-string")
        s.insert_text(0, "hello")
        t0 = time.time()
        while c.runtime.pending.count > 0 and time.time() - t0 < 10:
            time.sleep(0.02)

        args = ("--admin-secret", "s3s4m3")
        assert _admin(port, *args, "status", "t", "admindoc") == 0
        out = capsys.readouterr().out
        import json

        status = json.loads(out)
        assert status["seq"] >= 2  # join + at least the insert
        assert status["clients"] and \
            status["clients"][0]["clientId"] == c.client_id
        assert status["msn"] <= status["seq"]

        assert _admin(port, *args, "docs") == 0
        assert "t/admindoc" in capsys.readouterr().out

        # a wrong secret is refused
        with pytest.raises(RuntimeError):
            _admin(port, "--admin-secret", "wrong", "docs")

        # tenant CRUD round-trip
        assert _admin(port, *args, "tenant-add", "acme", "shh") == 0
        capsys.readouterr()
        assert _admin(port, *args, "tenants") == 0
        assert "acme" in capsys.readouterr().out
        # tenancy is now enforcing: an unsigned connect is refused
        from fluidframework_tpu.service.tenants import AuthError, sign_token

        with pytest.raises(RuntimeError):
            loader.resolve("acme", "secured")
        signed = Loader(NetworkDocumentServiceFactory(
            "127.0.0.1", port,
            token_provider=lambda t, d: sign_token(t, d, "shh")))
        c2 = signed.resolve("acme", "secured")
        assert c2.connected
        assert _admin(port, *args, "tenant-rm", "acme") == 0
        assert _admin(port, *args, "tenant-rm", "acme") == 1
    finally:
        core.terminate()
        core.wait(timeout=10)


def test_admin_requires_secret_on_secured_deployment():
    core, port = _spawn(["fluidframework_tpu.service.front_end",
                         "--port", "0", "--tenant", "acme:shh"])
    try:
        with pytest.raises(RuntimeError):
            _admin(port, "docs")
    finally:
        core.terminate()
        core.wait(timeout=10)


def test_mutating_admin_calls_refused_without_secret(capsys):
    """On a secret-less deployment with NO tenants registered, reads
    stay open but tenant CRUD is refused: otherwise ANY client could
    register the first tenant, flip tenancy to enforcing, and lock
    every other client out (open bootstrap)."""
    core, port = _spawn(["fluidframework_tpu.service.front_end",
                         "--port", "0"])
    try:
        # read-only admin calls still work without a secret
        assert _admin(port, "docs") == 0
        capsys.readouterr()
        with pytest.raises(RuntimeError):
            _admin(port, "tenant-add", "acme", "shh")
        with pytest.raises(RuntimeError):
            _admin(port, "tenant-rm", "acme")
        # the refusal really kept tenancy open: unsigned connects work
        loader = Loader(NetworkDocumentServiceFactory("127.0.0.1", port))
        c = loader.resolve("t", "stillopen")
        assert c.client_id
    finally:
        core.terminate()
        core.wait(timeout=10)


@pytest.mark.parametrize("app", ["todo", "canvas", "sudoku", "album"])
def test_example_demo_converges(app):
    out = subprocess.run(
        [sys.executable, "-m", f"examples.{app}"],
        capture_output=True, text=True, timeout=240, cwd="/root/repo")
    assert out.returncode == 0, out.stdout + out.stderr
    assert "CONVERGED" in out.stdout


def test_dev_host_runs_app_on_gateway_topology():
    out = subprocess.run(
        [sys.executable, "-m", "fluidframework_tpu.host", "todo",
         "-t", "gateway"],
        capture_output=True, text=True, timeout=240, cwd="/root/repo")
    assert out.returncode == 0, out.stdout + out.stderr
    assert "CONVERGED" in out.stdout


def test_dev_host_runs_app_on_sharded_topology():
    out = subprocess.run(
        [sys.executable, "-m", "fluidframework_tpu.host", "canvas",
         "-t", "sharded"],
        capture_output=True, text=True, timeout=240, cwd="/root/repo")
    assert out.returncode == 0, out.stdout + out.stderr
    assert "CONVERGED" in out.stdout


def test_admin_monitor_ticks_live_status(capsys):
    """The service-monitor role: `admin monitor` prints ping RTT + one
    line per live doc with its seq/msn/client-count/applier lag."""
    core, port = _spawn(["fluidframework_tpu.service.front_end",
                         "--port", "0"])
    try:
        loader = Loader(NetworkDocumentServiceFactory("127.0.0.1", port))
        c = loader.resolve("t", "mondoc")
        s = c.runtime.create_data_store("default").create_channel(
            "text", "shared-string")
        s.insert_text(0, "watch me")
        t0 = time.time()
        while c.runtime.pending.count > 0 and time.time() - t0 < 10:
            time.sleep(0.02)

        assert _admin(port, "monitor", "--interval", "0.2",
                      "--count", "2") == 0
        out = capsys.readouterr().out
        assert out.count("tick ") == 2
        assert "t/mondoc: seq " in out
        assert "clients 1" in out
        assert "applier_lag -" in out  # no applier stage attached
    finally:
        core.terminate()
        core.wait(timeout=10)
