"""TpuDocumentApplier: the batched device replica must match the scalar
client replicas for every doc — the kernel-vs-full-stack convergence
check (the TPU analog of PartialSequenceLengths verification + the
scribe-replay BASELINE config 5).
"""

import numpy as np
import pytest

from fluidframework_tpu.driver import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.service import LocalServer
from fluidframework_tpu.service.tpu_applier import TpuDocumentApplier, channel_stream


@pytest.fixture
def server():
    return LocalServer()


@pytest.fixture
def loader(server):
    return Loader(LocalDocumentServiceFactory(server))


def feed_applier(applier, server, tenant, doc):
    for msg in channel_stream(server, tenant, doc, "default", "text"):
        applier.ingest(tenant, doc, msg, msg.contents)
    applier.finalize()  # flush + overflow fence (escalations observed)


def test_applier_matches_client_replicas(server, loader):
    c1 = loader.resolve("t", "doc")
    c2 = loader.resolve("t", "doc")
    s1 = c1.runtime.create_data_store("default").create_channel("text", "shared-string")
    s2 = c2.runtime.get_data_store("default").get_channel("text")
    s1.insert_text(0, "hello world")
    s2.insert_text(5, ", tpu")
    s1.remove_text(0, 5)
    s2.insert_text(s2.get_text().__len__(), "!")
    assert s1.get_text() == s2.get_text()

    applier = TpuDocumentApplier(max_docs=8, max_slots=64, ops_per_dispatch=4)
    feed_applier(applier, server, "t", "doc")
    assert applier.get_text("t", "doc") == s1.get_text()
    assert applier.host_escalations == 0


def test_applier_many_docs_fuzz(server, loader):
    rng = np.random.default_rng(11)
    docs = [f"doc{i}" for i in range(6)]
    strings = {}
    for d in docs:
        c = loader.resolve("t", d)
        strings[d] = c.runtime.create_data_store("default").create_channel(
            "text", "shared-string")
    for _ in range(120):
        d = docs[rng.integers(0, len(docs))]
        s = strings[d]
        n = len(s.get_text())
        if n > 4 and rng.random() < 0.35:
            a = int(rng.integers(0, n - 1))
            b = int(rng.integers(a + 1, n + 1))
            s.remove_text(a, b)
        else:
            pos = int(rng.integers(0, n + 1))
            s.insert_text(pos, f"[{rng.integers(0, 100)}]")

    applier = TpuDocumentApplier(max_docs=16, max_slots=512, ops_per_dispatch=8)
    for d in docs:
        feed_applier(applier, server, "t", d)
    for d in docs:
        assert applier.get_text("t", d) == strings[d].get_text(), d
    assert applier.host_escalations == 0
    assert applier.dispatches > 0


def test_applier_annotate_stays_on_device(server, loader):
    """Annotate is a first-class device op (round-1 VERDICT #3a): no host
    escalation, and the per-slot LWW prop table matches the client replica."""
    c1 = loader.resolve("t", "doc")
    s1 = c1.runtime.create_data_store("default").create_channel("text", "shared-string")
    s1.insert_text(0, "styled text")
    s1.annotate_range(0, 6, {"bold": True})
    s1.annotate_range(3, 8, {"size": 12})
    s1.annotate_range(0, 2, {"bold": None})  # delete
    s1.insert_text(4, "x")

    applier = TpuDocumentApplier(max_docs=4, max_slots=32, ops_per_dispatch=4)
    feed_applier(applier, server, "t", "doc")
    assert applier.host_escalations == 0
    assert applier.get_text("t", "doc") == s1.get_text()
    replica = c1.runtime.get_data_store("default").get_channel("text").client
    for pos in range(len(s1.get_text())):
        assert applier.get_properties_at("t", "doc", pos) == \
            replica.get_properties_at(pos), pos


def test_applier_zamboni_bounds_slots_under_churn(server, loader):
    """With deli's msn riding every staged op, device zamboni keeps the
    slot count bounded while two clients churn (round-1 VERDICT #3b)."""
    import numpy as np

    rng = np.random.default_rng(5)
    c1 = loader.resolve("t", "doc")
    c2 = loader.resolve("t", "doc")
    s1 = c1.runtime.create_data_store("default").create_channel("text", "shared-string")
    s2 = c2.runtime.get_data_store("default").get_channel("text")

    applier = TpuDocumentApplier(max_docs=4, max_slots=64, ops_per_dispatch=8)
    seen = 0
    max_count = 0
    for i in range(150):
        s = s1 if i % 2 == 0 else s2
        n = len(s.get_text())
        if n > 6 and rng.random() < 0.5:
            a = int(rng.integers(0, n - 3))
            s.remove_text(a, a + 3)
        else:
            s.insert_text(int(rng.integers(0, n + 1)), "ab")
        # feed the applier incrementally (live tail, not one big replay)
        msgs = list(channel_stream(server, "t", "doc", "default", "text"))
        for m in msgs[seen:]:
            applier.ingest("t", "doc", m, m.contents)
        seen = len(msgs)
        if i % 10 == 9:
            applier.flush()
            max_count = max(max_count, applier.slot_count("t", "doc"))
    applier.flush()
    assert applier.host_escalations == 0
    assert applier.get_text("t", "doc") == s1.get_text() == s2.get_text()
    # 150 ops with ~50% removes would need ≳150 slots without zamboni
    assert max(max_count, applier.slot_count("t", "doc")) < 60


def test_applier_escalates_capacity_overflow(server, loader):
    c1 = loader.resolve("t", "doc")
    s1 = c1.runtime.create_data_store("default").create_channel("text", "shared-string")
    for i in range(30):  # far beyond 8 slots after splits
        s1.insert_text(len(s1.get_text()) // 2, f"seg{i}")

    applier = TpuDocumentApplier(max_docs=4, max_slots=8, ops_per_dispatch=4)
    applier.set_replay_source(
        lambda t, d: list(channel_stream(server, t, d, "default", "text")))
    feed_applier(applier, server, "t", "doc")
    assert applier.host_escalations == 1
    assert applier.get_text("t", "doc") == s1.get_text()


def test_applier_on_virtual_mesh(server, loader):
    from fluidframework_tpu.parallel.mesh import make_mesh

    docs = [f"doc{i}" for i in range(4)]
    strings = {}
    for d in docs:
        c = loader.resolve("t", d)
        s = c.runtime.create_data_store("default").create_channel(
            "text", "shared-string")
        s.insert_text(0, f"content of {d}")
        strings[d] = s

    mesh = make_mesh(8, seg_shards=1)
    applier = TpuDocumentApplier(max_docs=8, max_slots=64,
                                 ops_per_dispatch=4, mesh=mesh)
    # mesh mode routes docs through the REAL placement table: one shard
    # per 'docs'-axis device, global row = shard * slots_per_shard + slot
    assert applier.placement.n_shards == 8
    for d in docs:
        feed_applier(applier, server, "t", d)
    shards = {applier.placement.lookup("t", d)[0] for d in docs}
    assert len(shards) > 1, "docs all hashed to one shard"
    for d in docs:
        assert applier.get_text("t", d) == strings[d].get_text()


def test_interval_only_batch_does_not_crash_dispatch():
    """A doc whose batch stages NOTHING on the device (interval metadata
    ops stage zero tuples) must not break the vectorized wave build when
    another doc has real ops in the same flush."""
    from fluidframework_tpu.protocol.messages import (
        MessageType,
        SequencedDocumentMessage,
    )

    applier = TpuDocumentApplier(max_docs=4, max_slots=16, ops_per_dispatch=4)
    applier.set_replay_source(lambda t, d: [])

    def msg(seq):
        return SequencedDocumentMessage(
            client_id="c1", sequence_number=seq, minimum_sequence_number=0,
            client_sequence_number=seq, reference_sequence_number=seq - 1,
            type=MessageType.OPERATION)

    applier.ingest("t", "iv-doc", msg(1), {"type": "interval", "op": "add"})
    applier.ingest("t", "txt-doc", msg(1), {"type": 0, "pos": 0, "text": "hi"})
    applier.flush()
    applier.finalize()
    assert applier.host_escalations == 0
    assert applier.get_text("t", "txt-doc") == "hi"


def test_applier_checkpoint_warm_restart(tmp_path, server, loader):
    """Device-farm checkpointing: save a fenced applier, load it in a
    'new process', and continue ingesting live ops with no replay."""
    from fluidframework_tpu.service.tpu_applier import (
        load_applier_checkpoint,
        save_applier_checkpoint,
    )

    docs = ["a", "b"]
    strings = {}
    applier = TpuDocumentApplier(max_docs=4, max_slots=64,
                                 ops_per_dispatch=4)
    applier.set_replay_source(lambda t, d: [])
    for d in docs:
        c = loader.resolve("t", d)
        s = c.runtime.create_data_store("default").create_channel(
            "text", "shared-string")
        s.insert_text(0, f"checkpointed {d} ")
        s.annotate_range(0, 3, {"bold": True})
        strings[d] = s
        feed_applier(applier, server, "t", d)

    path = str(tmp_path / "farm")
    save_applier_checkpoint(applier, path)

    revived = load_applier_checkpoint(path)
    revived.set_replay_source(lambda t, d: [])
    for d in docs:
        assert revived.get_text("t", d) == strings[d].get_text()
        assert revived.get_properties_at("t", d, 0).get("bold") is True

    # the revived farm keeps ingesting the live stream where it left off
    seen = {d: server.get_deltas("t", d, 0, 10**9)[-1].sequence_number
            for d in docs}
    for d in docs:
        strings[d].insert_text(0, ">> ")
        for m in channel_stream(server, "t", d, "default", "text"):
            if m.sequence_number > seen[d]:
                revived.ingest("t", d, m, m.contents)
    revived.finalize()
    assert revived.host_escalations == 0
    for d in docs:
        assert revived.get_text("t", d) == strings[d].get_text()
