"""Test configuration: force JAX onto CPU with 8 virtual devices so
multi-chip sharding paths are exercised without TPU hardware.

The axon TPU plugin (sitecustomize) pins ``jax_platforms="axon,cpu"`` at
interpreter start, so setting ``JAX_PLATFORMS`` in the environment here is
too late — the config must be updated through jax after import (safe as
long as no backend has been initialized, which holds at conftest time)."""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running test, excluded from the tier-1 `-m 'not slow'` "
        "run (multi-seed soaks, network stress)")
    config.addinivalue_line(
        "markers",
        "chaos: exercises the fault-injection plane "
        "(fluidframework_tpu/chaos); `-m chaos` selects the chaos suite")
