"""Op batching: FlushMode, orderSequentially atomicity, DeltaScheduler
time slicing (ref: containerRuntime.ts:1207-1271, deltaScheduler.ts:25,
end-to-end batching.spec.ts).
"""

import pytest

from fluidframework_tpu.driver import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.runtime.container_runtime import FlushMode
from fluidframework_tpu.service import LocalServer


@pytest.fixture
def server():
    return LocalServer()


@pytest.fixture
def loader(server):
    return Loader(LocalDocumentServiceFactory(server))


def test_turn_based_flush_coalesces_into_one_boxcar(server, loader):
    c1 = loader.resolve("t", "doc")
    s = c1.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    s.insert_text(0, "base")
    deli = server._get_orderer("t", "doc").deli
    boxcars_before = deli.boxcars_fast + deli.boxcars_fallback

    c1.runtime.set_flush_mode(FlushMode.TURN_BASED)
    s.insert_text(0, "a")
    s.insert_text(0, "b")
    s.insert_text(0, "c")
    # nothing sent yet: the service saw no new boxcars
    assert deli.boxcars_fast + deli.boxcars_fallback == boxcars_before
    assert s.get_text() == "cbabase"  # optimistic local state is live
    c1.runtime.flush()
    assert deli.boxcars_fast + deli.boxcars_fallback == boxcars_before + 1
    assert c1.runtime.pending.count == 0  # all acked
    c1.runtime.set_flush_mode(FlushMode.IMMEDIATE)

    # a second client sees the converged result
    c2 = loader.resolve("t", "doc")
    assert (c2.runtime.get_data_store("default").get_channel("text")
            .get_text() == "cbabase")


def test_batch_is_sequenced_contiguously(server, loader):
    """A flushed batch must not interleave with a concurrent client's
    ops in the total order (the boxcar/ScheduleManager guarantee)."""
    server._auto_drain = False
    c1 = loader.resolve("t", "doc")
    c2 = loader.resolve("t", "doc")
    server.drain()
    s1 = c1.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    server.drain()
    s2 = c2.runtime.get_data_store("default").get_channel("text")

    c1.runtime.set_flush_mode(FlushMode.TURN_BASED)
    s1.insert_text(0, "aaa")
    s1.insert_text(0, "bbb")
    c1.runtime.flush()      # queued as one boxcar
    s2.insert_text(0, "Z")  # concurrent single op
    server.drain()

    log = server.get_deltas("t", "doc", 0, 10**9)
    c1_id = c1.client_id
    batch_seqs = [m.sequence_number for m in log
                  if m.client_id == c1_id and m.type.value == "op"
                  and isinstance(m.contents, dict)
                  and m.contents.get("kind") == "chanop"
                  and "attach" not in m.contents["contents"]]
    # the two batched ops are adjacent in the total order
    assert batch_seqs[-1] == batch_seqs[-2] + 1
    # and batch metadata marks the boundaries
    marked = [m.metadata for m in log if m.sequence_number in batch_seqs[-2:]]
    assert marked == [{"batch": True}, {"batch": False}]
    assert s1.get_text() == s2.get_text()


def test_order_sequentially_batches_and_flushes(server, loader):
    c1 = loader.resolve("t", "doc")
    s = c1.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    deli = server._get_orderer("t", "doc").deli
    before = deli.boxcars_fast + deli.boxcars_fallback
    with c1.runtime.order_sequentially():
        s.insert_text(0, "x")
        s.insert_text(1, "y")
        s.insert_text(2, "z")
    assert deli.boxcars_fast + deli.boxcars_fallback == before + 1
    assert s.get_text() == "xyz"
    assert c1.runtime.pending.count == 0


def test_order_sequentially_exception_closes_container(loader):
    c1 = loader.resolve("t", "doc")
    s = c1.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    with pytest.raises(ValueError):
        with c1.runtime.order_sequentially():
            s.insert_text(0, "doomed")
            raise ValueError("app error mid-transaction")
    assert c1.closed


def test_delta_scheduler_yields_during_long_drain(server, loader):
    from fluidframework_tpu.loader.container import Container

    c1 = loader.resolve("t", "doc")
    s = c1.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    for i in range(40):
        s.insert_text(0, "x")
    # a late joiner catches up through delta storage; the scheduler hook
    # fires between slices of the backlog drain (DeltaScheduler role)
    svc = LocalDocumentServiceFactory(server).create_document_service(
        "t", "doc")
    late = Container(svc)
    yields = []
    late.delta_manager.inbound_slice = 10
    late.delta_manager.inbound_yield = lambda seq: yields.append(seq)
    late.load()
    assert len(yields) >= 3  # 40+ ops drained in >=4 slices
    assert (late.runtime.get_data_store("default").get_channel("text")
            .get_text() == s.get_text())


def test_per_client_pause_controls_interleaving(server, loader):
    """The OpProcessingController role client-side: freeze ONE replica,
    let the world move, then step its delivery deterministically."""
    c1 = loader.resolve("t", "doc")
    c2 = loader.resolve("t", "doc")
    s1 = c1.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    s1.insert_text(0, "base")
    s2 = c2.runtime.get_data_store("default").get_channel("text")

    c2.delta_manager.pause_inbound()
    s1.insert_text(4, "-one")
    s1.insert_text(8, "-two")
    assert s2.get_text() == "base"  # frozen replica saw nothing

    # c2 edits concurrently against its STALE view
    s2.insert_text(0, ">")
    assert s2.get_text() == ">base"

    # step exactly one buffered message; note its own ack may be among
    # the buffered traffic, so step until the first remote op lands
    stepped = c2.delta_manager.step_inbound(1)
    assert stepped == 1 and s2.get_text() != s1.get_text()

    c2.delta_manager.resume_inbound()
    assert s1.get_text() == s2.get_text() == ">base-one-two"


def test_legacy_pre_intervals_snapshot_still_loads(loader):
    """Cross-version compat: the pre-intervals SharedString snapshot
    layout (a bare merge-tree dict) must still boot (ref: compat.spec
    old-format tolerance)."""
    from fluidframework_tpu.dds.registry import load_channel

    c = loader.resolve("t", "doc")
    s = c.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    s.insert_text(0, "old format")
    legacy = s.snapshot()["mergetree"]  # the pre-intervals layout
    revived = load_channel("shared-string", "text2", legacy)
    assert revived.get_text() == "old format"
