"""Protocol core tests: quorum membership, unanimous-silence proposals,
ProtocolOpHandler snapshot round-trip.

Mirrors the reference's protocol-base unit tests (quorum join/leave/propose
semantics, SURVEY.md §2.7).
"""

from fluidframework_tpu.protocol import (
    MessageType,
    ProtocolOpHandler,
    Quorum,
    SequencedDocumentMessage,
)


def seqmsg(seq, msn, mtype, contents=None, client_id="A", ref_seq=0, client_seq=0):
    return SequencedDocumentMessage(
        client_id=client_id,
        sequence_number=seq,
        minimum_sequence_number=msn,
        client_sequence_number=client_seq,
        reference_sequence_number=ref_seq,
        type=mtype,
        contents=contents,
    )


def test_join_leave_membership():
    h = ProtocolOpHandler()
    h.process_message(seqmsg(1, 0, MessageType.CLIENT_JOIN, {"clientId": "A", "userId": "u1"}))
    h.process_message(seqmsg(2, 0, MessageType.CLIENT_JOIN, {"clientId": "B", "userId": "u2"}))
    assert set(h.quorum.members) == {"A", "B"}
    assert h.quorum.members["A"].sequence_number == 1
    h.process_message(seqmsg(3, 1, MessageType.CLIENT_LEAVE, "A"))
    assert set(h.quorum.members) == {"B"}
    assert h.sequence_number == 3
    assert h.minimum_sequence_number == 1


def test_proposal_accepts_when_msn_passes():
    h = ProtocolOpHandler()
    h.process_message(seqmsg(1, 0, MessageType.CLIENT_JOIN, {"clientId": "A"}))
    h.process_message(seqmsg(2, 0, MessageType.PROPOSE, {"key": "code", "value": "v2"}))
    assert not h.quorum.has("code")  # still pending: msn hasn't passed seq 2
    h.process_message(seqmsg(3, 2, MessageType.NOOP))
    assert h.quorum.get("code") == "v2"


def test_proposal_rejected_blocks_commit():
    h = ProtocolOpHandler()
    h.process_message(seqmsg(1, 0, MessageType.CLIENT_JOIN, {"clientId": "A"}))
    h.process_message(seqmsg(2, 0, MessageType.CLIENT_JOIN, {"clientId": "B"}))
    h.process_message(seqmsg(3, 0, MessageType.PROPOSE, {"key": "k", "value": 1}, client_id="A"))
    h.process_message(seqmsg(4, 0, MessageType.REJECT, 3, client_id="B"))
    h.process_message(seqmsg(5, 4, MessageType.NOOP))
    assert not h.quorum.has("k")
    assert 3 not in h.quorum.proposals  # settled (rejected), not pending


def test_duplicate_messages_ignored():
    h = ProtocolOpHandler()
    m = seqmsg(1, 0, MessageType.CLIENT_JOIN, {"clientId": "A"})
    h.process_message(m)
    h.process_message(m)  # replay below head: no-op
    assert len(h.quorum.members) == 1


def test_snapshot_roundtrip():
    h = ProtocolOpHandler()
    h.process_message(seqmsg(1, 0, MessageType.CLIENT_JOIN, {"clientId": "A", "userId": "u"}))
    h.process_message(seqmsg(2, 0, MessageType.PROPOSE, {"key": "code", "value": "v1"}))
    h.process_message(seqmsg(3, 2, MessageType.NOOP))
    h.process_message(seqmsg(4, 2, MessageType.PROPOSE, {"key": "pending", "value": 9}))

    snap = h.snapshot()
    h2 = ProtocolOpHandler.load(snap)
    assert h2.sequence_number == 4
    assert h2.minimum_sequence_number == 2
    assert h2.quorum.get("code") == "v1"
    assert 4 in h2.quorum.proposals  # pending proposal survives
    # pending proposal still commits after restore
    h2.process_message(seqmsg(5, 4, MessageType.NOOP))
    assert h2.quorum.get("pending") == 9


def test_snapshot_preserves_rejections():
    h = ProtocolOpHandler()
    h.process_message(seqmsg(1, 0, MessageType.CLIENT_JOIN, {"clientId": "A"}))
    h.process_message(seqmsg(2, 0, MessageType.CLIENT_JOIN, {"clientId": "B"}))
    h.process_message(seqmsg(3, 0, MessageType.PROPOSE, {"key": "k", "value": 1}, client_id="A"))
    h.process_message(seqmsg(4, 0, MessageType.REJECT, 3, client_id="B"))
    # restore mid-flight: the rejection must survive or replicas diverge
    h2 = ProtocolOpHandler.load(h.snapshot())
    h2.process_message(seqmsg(5, 4, MessageType.NOOP))
    assert not h2.quorum.has("k")


def test_sequence_gap_raises():
    import pytest
    from fluidframework_tpu.protocol.quorum import ProtocolError

    h = ProtocolOpHandler()
    h.process_message(seqmsg(1, 0, MessageType.CLIENT_JOIN, {"clientId": "A"}))
    with pytest.raises(ProtocolError):
        h.process_message(seqmsg(5, 0, MessageType.NOOP))


def test_malformed_reject_ignored():
    h = ProtocolOpHandler()
    h.process_message(seqmsg(1, 0, MessageType.CLIENT_JOIN, {"clientId": "A"}))
    h.process_message(seqmsg(2, 0, MessageType.REJECT, None))
    h.process_message(seqmsg(3, 0, MessageType.REJECT, {"bogus": True}))
    assert h.sequence_number == 3


def test_proposal_events_fire():
    q = Quorum()
    approved = []
    q.on("approveProposal", lambda p: approved.append((p.key, p.value)))
    q.add_proposal("k", "v", seq=5, local=True)
    q.update_minimum_sequence_number(5, 6)
    assert approved == [("k", "v")]
