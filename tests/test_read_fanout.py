"""Read-scale fan-out (ISSUE 12): relay-tree gateway tiers, read-only
fast sessions, and the coalesced presence lane.

Three planes under test:

- the :class:`~fluidframework_tpu.service.presence.PresenceLane` — LWW
  coalescing per (doc, client, type), flush-tick batching, and the
  ordering contract against sequenced ops;
- ``readonly`` sessions — no join op, no quorum membership, submit
  refused at the driver, ``session.readonly.connects`` counted;
- the relay tree — a gateway whose upstream is another gateway
  (``--upstream-gateway``), including the mid-tier-kill resubscribe
  with the exact-once substring audit.
"""

from __future__ import annotations

import socket
import subprocess
import sys
import time

import pytest

from fluidframework_tpu.driver import NetworkDocumentServiceFactory
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.obs import tier_counters
from fluidframework_tpu.protocol import binwire
from fluidframework_tpu.protocol.messages import MessageType, Signal
from fluidframework_tpu.service import LocalServer, NetworkFrontEnd
from fluidframework_tpu.service.presence import PresenceLane


def wait_for(pred, timeout=15.0, interval=0.005):
    t0 = time.time()
    while time.time() - t0 < timeout:
        try:
            if pred():
                return True
        except (KeyError, IndexError):
            pass
        time.sleep(interval)
    return False


# ------------------------------------------------------------ presence lane

def _lane():
    return PresenceLane(tier_counters("presence_test"))


def test_presence_lww_coalesces_per_client_and_type():
    lane = _lane()
    got = []
    lane.subscribe("t/d", got.append)
    for i in range(10):
        lane.publish("t/d", Signal(client_id="c1", type="cursor",
                                   content={"i": i}))
    lane.publish("t/d", Signal(client_id="c2", type="cursor",
                               content={"i": 99}))
    lane.publish("t/d", Signal(client_id="c1", type="select",
                               content={"s": 1}))
    lane.flush()
    assert len(got) == 1  # one batch per subscriber per flush
    sigs = {(s.client_id, s.type): s.content for s in got[0].signals}
    # the 10 cursor moves from c1 collapsed to the LAST one
    assert sigs == {("c1", "cursor"): {"i": 9},
                    ("c2", "cursor"): {"i": 99},
                    ("c1", "select"): {"s": 1}}
    snap = lane.counters.snapshot()
    assert snap["presence.lane.coalesced"] == 9
    assert snap["presence.lane.signals"] == 12


def test_presence_flush_batches_and_unwatched_topics_evaporate():
    lane = _lane()
    got_a, got_b = [], []
    sub_a, sub_b = got_a.append, got_b.append
    lane.subscribe("t/a", sub_a)
    lane.subscribe("t/a", sub_b)
    lane.publish("t/a", Signal(client_id="x", type="s", content=1))
    lane.publish("t/nobody", Signal(client_id="x", type="s", content=2))
    delivered = lane.flush()
    assert delivered == 2  # both t/a subscribers, nobody for t/nobody
    # the two subscribers share ONE batch object: encodings are shared
    assert got_a[0] is got_b[0]
    # nothing pending: flush is a no-op, not an empty broadcast
    assert lane.flush() == 0
    lane.unsubscribe("t/a", sub_a)
    assert lane.watching("t/a")  # sub_b still there
    lane.unsubscribe("t/a", sub_b)
    assert not lane.watching("t/a")


def test_presence_batch_encodes_once_per_wire_form():
    lane = _lane()
    batches = []
    lane.subscribe("t/d", batches.append)
    lane.publish("t/d", Signal(client_id="c", type="s", content={"k": 1}))
    lane.flush()
    pb = batches[0]
    assert pb.presence_frame() is pb.presence_frame()
    assert pb.fpresence_frame() is pb.fpresence_frame()
    assert pb.signal_dicts() is pb.signal_dicts()


def test_binwire_presence_roundtrip_and_topic_splice():
    sigs = [Signal(client_id="c1", type="cursor", content={"x": 3}),
            Signal(client_id=None, type="system", content=[1, "two"])]
    body = binwire.encode_presence(sigs)
    out = binwire.decode_presence(body)
    assert [(s.client_id, s.type, s.content) for s in out] \
        == [(s.client_id, s.type, s.content) for s in sigs]
    # the backbone form strips to the EXACT client form by byte splice
    fbody = binwire.encode_presence(sigs, topic="t/d")
    topic, stripped = binwire.fpresence_strip_topic(fbody)
    assert topic == "t/d"
    assert stripped == body
    out2 = binwire.decode_presence(fbody)  # decodable with topic too
    assert [s.content for s in out2] == [s.content for s in sigs]


# --------------------------------------------------------- readonly sessions

def test_readonly_connect_orders_no_join():
    server = LocalServer()
    w = server.connect("t", "d", None)
    seen = []
    w.on_op = seen.append
    r = server.connect("t", "d", None, readonly=True)
    assert r.mode == "readonly"
    w2 = server.connect("t", "d", None)  # control: a writer DOES join
    assert wait_for(lambda: any(
        m.type == "join" and w2.client_id in str(m.contents)
        for m in seen))
    # the readonly client's id never entered the op stream
    assert not any(r.client_id in str(m.contents) for m in seen)


def test_readonly_network_session_reads_but_cannot_write():
    fe = NetworkFrontEnd(LocalServer()).start_background()
    try:
        writer = Loader(NetworkDocumentServiceFactory(
            "127.0.0.1", fe.port)).resolve("t", "rdoc")
        s1 = writer.runtime.create_data_store("default").create_channel(
            "text", "shared-string")
        s1.insert_text(0, "read scale")
        reader = Loader(NetworkDocumentServiceFactory(
            "127.0.0.1", fe.port, readonly=True)).resolve("t", "rdoc")
        assert wait_for(lambda: reader.runtime.get_data_store("default")
                        .get_channel("text").get_text() == "read scale")
        assert fe.counters.snapshot()["session.readonly.connects"] == 1
        # a reader costs the quorum nothing: no join was ordered for it
        assert reader.delta_manager.connection.mode == "readonly"
        with pytest.raises(PermissionError):
            reader.delta_manager.submit(MessageType.OPERATION, {"x": 1})
    finally:
        fe.stop()


def test_readonly_live_tail_and_presence_publish():
    """A reader keeps tailing live edits AND may publish presence
    (viewers broadcast cursors without quorum membership)."""
    fe = NetworkFrontEnd(LocalServer()).start_background()
    try:
        writer = Loader(NetworkDocumentServiceFactory(
            "127.0.0.1", fe.port)).resolve("t", "taildoc")
        s1 = writer.runtime.create_data_store("default").create_channel(
            "text", "shared-string")
        s1.insert_text(0, "a")
        reader = Loader(NetworkDocumentServiceFactory(
            "127.0.0.1", fe.port, readonly=True)).resolve("t", "taildoc")
        got = []
        writer.on_signal = lambda sig: got.append(sig)
        s1.insert_text(1, "b")  # live edit AFTER the reader booted
        assert wait_for(lambda: reader.runtime.get_data_store("default")
                        .get_channel("text").get_text() == "ab")
        reader.submit_signal({"cursor": 7}, type="cursor")
        assert wait_for(lambda: any(
            s.content == {"cursor": 7} and s.type == "cursor"
            for s in got))
    finally:
        fe.stop()


# ---------------------------------------------------- presence over the wire

def test_signal_burst_coalesces_server_side():
    fe = NetworkFrontEnd(LocalServer()).start_background()
    try:
        loader = Loader(NetworkDocumentServiceFactory("127.0.0.1", fe.port))
        c1 = loader.resolve("t", "sigdoc")
        c2 = loader.resolve("t", "sigdoc")
        got = []
        c2.on_signal = lambda sig: got.append(sig)
        for i in range(50):
            c1.submit_signal({"i": i}, type="cursor")
        # the LAST write always lands (LWW), and the burst coalesced:
        # far fewer deliveries than publishes
        assert wait_for(lambda: any(
            s.content == {"i": 49} for s in got if s.type == "cursor"))
        snap = fe.counters.snapshot()
        assert snap["presence.lane.coalesced"] > 0
        assert len([s for s in got if s.type == "cursor"]) < 50
        assert snap["presence.lane.flushes"] >= 1
    finally:
        fe.stop()


def test_presence_never_overtakes_sequenced_ops():
    fe = NetworkFrontEnd(LocalServer()).start_background()
    try:
        loader = Loader(NetworkDocumentServiceFactory("127.0.0.1", fe.port))
        c1 = loader.resolve("t", "orderdoc")
        c2 = loader.resolve("t", "orderdoc")
        s1 = c1.runtime.create_data_store("default").create_channel(
            "text", "shared-string")
        s2_text_at_signal = []
        c2.on_signal = lambda sig, c2=c2: s2_text_at_signal.append(
            c2.runtime.get_data_store("default")
            .get_channel("text").get_text()) if sig.type == "mark" else None
        for i in range(20):
            s1.insert_text(len(s1.get_text()), f"{i % 10}")
        c1.submit_signal({"done": True}, type="mark")
        assert wait_for(lambda: len(s2_text_at_signal) >= 1)
        # the signal was submitted after 20 inserts; when it arrives,
        # every one of those ops has already been applied at c2
        assert s2_text_at_signal[0] == "01234567890123456789"
    finally:
        fe.stop()


# -------------------------------------------------------------- relay tree

def _spawn(args):
    proc = subprocess.Popen(
        [sys.executable, "-m"] + args,
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd="/root/repo")
    line = proc.stdout.readline().strip()
    assert line.startswith("LISTENING"), line
    return proc, int(line.rsplit(":", 1)[1])


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture(scope="module")
def tree():
    """core ← mid gateway ← leaf gateway, all separate OS processes.

    The mid tier runs the asyncio relay (it SERVES the backbone
    protocol to the leaf); the leaf dials it with --upstream-gateway."""
    core, core_port = _spawn(
        ["fluidframework_tpu.service.front_end", "--port", "0"])
    mid, p_mid = _spawn(["fluidframework_tpu.service.gateway",
                         "--core-port", str(core_port), "--python"])
    leaf, p_leaf = _spawn(["fluidframework_tpu.service.gateway",
                           "--upstream-gateway", f"127.0.0.1:{p_mid}"])
    try:
        yield core_port, p_mid, p_leaf
    finally:
        for proc in (leaf, mid, core):
            proc.terminate()
            proc.wait(timeout=10)


def test_relay_tree_converges_both_ways(tree):
    core_port, _, p_leaf = tree
    c1 = Loader(NetworkDocumentServiceFactory(
        "127.0.0.1", core_port)).resolve("t", "treedoc")
    c2 = Loader(NetworkDocumentServiceFactory(
        "127.0.0.1", p_leaf)).resolve("t", "treedoc")
    s1 = c1.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    s1.insert_text(0, "root")
    assert wait_for(lambda: c2.runtime.get_data_store("default")
                    .get_channel("text").get_text() == "root")
    s2 = c2.runtime.get_data_store("default").get_channel("text")
    s2.insert_text(4, " leaf")  # write path climbs two tiers
    assert wait_for(lambda: s1.get_text() == "root leaf"
                    and s2.get_text() == "root leaf")


def test_signals_traverse_the_tree(tree):
    core_port, _, p_leaf = tree
    c1 = Loader(NetworkDocumentServiceFactory(
        "127.0.0.1", core_port)).resolve("t", "treesig")
    c2 = Loader(NetworkDocumentServiceFactory(
        "127.0.0.1", p_leaf)).resolve("t", "treesig")
    got_down, got_up = [], []
    c2.on_signal = lambda sig: got_down.append(sig.content)
    c1.on_signal = lambda sig: got_up.append(sig.content)
    c1.submit_signal({"from": "root"})
    c2.submit_signal({"from": "leaf"})
    assert wait_for(lambda: {"from": "root"} in got_down)
    assert wait_for(lambda: {"from": "leaf"} in got_up)


def test_readonly_reader_through_the_tree(tree):
    core_port, _, p_leaf = tree
    c1 = Loader(NetworkDocumentServiceFactory(
        "127.0.0.1", core_port)).resolve("t", "treero")
    s1 = c1.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    s1.insert_text(0, "fan out")
    reader = Loader(NetworkDocumentServiceFactory(
        "127.0.0.1", p_leaf, readonly=True)).resolve("t", "treero")
    assert wait_for(lambda: reader.runtime.get_data_store("default")
                    .get_channel("text").get_text() == "fan out")
    assert reader.delta_manager.connection.mode == "readonly"
    s1.insert_text(len(s1.get_text()), " live")  # reader keeps tailing
    assert wait_for(lambda: reader.runtime.get_data_store("default")
                    .get_channel("text").get_text() == "fan out live")


@pytest.mark.slow
def test_midtier_gateway_kill_exact_once_delivery():
    """Kill the MID tier under live traffic; every marker written before,
    during, and after the outage must appear at the leaf's reader
    exactly once (the net_smoke audit: ``text.count(marker) != 1``)."""
    n_ops = 60
    core, core_port = _spawn(
        ["fluidframework_tpu.service.front_end", "--port", "0"])
    p_mid = _free_port()
    mid, _ = _spawn(["fluidframework_tpu.service.gateway",
                     "--core-port", str(core_port),
                     "--port", str(p_mid), "--python"])
    leaf, p_leaf = _spawn(["fluidframework_tpu.service.gateway",
                           "--upstream-gateway", f"127.0.0.1:{p_mid}"])
    try:
        writer = Loader(NetworkDocumentServiceFactory(
            "127.0.0.1", core_port)).resolve("t", "killdoc")
        s1 = writer.runtime.create_data_store("default").create_channel(
            "text", "shared-string")
        reader = Loader(NetworkDocumentServiceFactory(
            "127.0.0.1", p_leaf), auto_reconnect=True).resolve(
            "t", "killdoc")

        def rtext():
            return (reader.runtime.get_data_store("default")
                    .get_channel("text").get_text())

        def write(i):
            s1.insert_text(len(s1.get_text()), f"m{i:03d} ")

        for i in range(20):
            write(i)
        assert wait_for(lambda: rtext().count("m019 ") == 1)
        mid.kill()  # crash, not graceful shutdown
        mid.wait(timeout=10)
        for i in range(20, 40):
            write(i)  # written while the reader's tier is dark
        mid2, _ = _spawn(["fluidframework_tpu.service.gateway",
                          "--core-port", str(core_port),
                          "--port", str(p_mid), "--python"])
        try:
            for i in range(40, n_ops):
                write(i)
            # resubscribe + driver catch-up repair the gap: exactly-once
            assert wait_for(
                lambda: rtext().count(f"m{n_ops - 1:03d} ") == 1,
                timeout=30.0)
            text = rtext()
            lost = [i for i in range(n_ops)
                    if text.count(f"m{i:03d} ") != 1]
            assert not lost, f"lost-or-duplicated markers: {lost}"
        finally:
            mid2.terminate()
            mid2.wait(timeout=10)
    finally:
        for proc in (leaf, core):
            proc.terminate()
            proc.wait(timeout=10)
