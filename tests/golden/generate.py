"""Generate the cross-round format-freeze fixtures (VERDICT r3 item 6).

Run ONCE (from the repo root) at the round-4 format freeze:

    python -m tests.golden.generate

The committed outputs pin the round-3/4 on-disk and on-wire formats:

- ``wire_frames.json``   exact byte encodings of the framed JSON protocol
- ``messages.json``      encode_message bytes for every message shape
- ``svclog/`` + ``blobs/``  a durable service log + chunk store from a
  scripted session (ops, summary, checkpoints, retention metadata)
- ``applier_ckpt.*``     a TPU-applier device-farm checkpoint
- ``expected.json``      the semantic state the fixtures must reproduce

``test_compat.py`` loads these with CURRENT code and asserts both
byte-exact round-trips (wire/messages) and semantic restores (log,
blobs, checkpoint). If a future round changes a format, it must either
keep loading these files or ship an explicit migration + regenerate.
"""

import json
import os
import shutil

HERE = os.path.dirname(os.path.abspath(__file__))


def wire_frames() -> None:
    from fluidframework_tpu.service.front_end import _encode_frame

    frames = [
        {"t": "connect", "tenant": "acme", "doc": "d1", "rid": 1,
         "token": None, "details": {"mode": "write"}},
        {"t": "connected", "rid": 1, "clientId": "c-1", "seq": 7,
         "mode": "write", "maxMessageSize": 16384},
        {"t": "submit", "ops": [{"clientSequenceNumber": 1,
                                 "referenceSequenceNumber": 7,
                                 "type": 0,
                                 "contents": {"kind": "chanop",
                                              "address": "default",
                                              "contents": {
                                                  "address": "text",
                                                  "contents": {
                                                      "type": 0, "pos": 0,
                                                      "text": "hi"}}}}]},
        {"t": "ops", "msgs": [{"sequenceNumber": 8,
                               "minimumSequenceNumber": 7,
                               "clientSequenceNumber": 1,
                               "referenceSequenceNumber": 7,
                               "clientId": "c-1", "type": 0,
                               "contents": None, "timestamp": 0.0}]},
        {"t": "signal", "signal": {"clientId": "c-1",
                                   "content": {"ping": 1}}},
        {"t": "nack", "nack": {"code": 413, "message": "too large"}},
        {"t": "get_deltas", "tenant": "acme", "doc": "d1",
         "from": 0, "to": 100, "rid": 2},
        {"t": "error", "rid": 3, "message": "nope"},
    ]
    out = [{"frame": f, "hex": _encode_frame(f).hex()} for f in frames]
    with open(os.path.join(HERE, "wire_frames.json"), "w") as fh:
        json.dump(out, fh, indent=1)


def messages() -> None:
    from fluidframework_tpu.protocol.messages import (
        DocumentMessage, MessageType, Nack, NackErrorType,
        SequencedDocumentMessage,
    )
    from fluidframework_tpu.service.deli import RawMessage
    from fluidframework_tpu.protocol.serialization import encode_message

    shapes = {
        "sequenced_op": SequencedDocumentMessage(
            sequence_number=42, minimum_sequence_number=40,
            client_sequence_number=3, reference_sequence_number=41,
            client_id="client-a", type=MessageType.OPERATION,
            contents={"kind": "chanop", "address": "default",
                      "contents": {"address": "text",
                                   "contents": {"type": 1, "start": 0,
                                                "end": 2}}},
            timestamp=123.5),
        "join": SequencedDocumentMessage(
            sequence_number=1, minimum_sequence_number=0,
            client_sequence_number=-1, reference_sequence_number=-1,
            client_id=None, type=MessageType.CLIENT_JOIN,
            contents={"clientId": "client-a", "detail": {"mode": "write"},
                      "canEvict": True},
            timestamp=1.0),
        "raw": RawMessage(
            tenant_id="acme", document_id="d1", client_id="client-a",
            operation=DocumentMessage(
                client_sequence_number=1, reference_sequence_number=0,
                type=MessageType.OPERATION, contents={"x": 1}),
            timestamp=2.0),
        "nack": Nack(
            operation=DocumentMessage(
                client_sequence_number=9, reference_sequence_number=8,
                type=MessageType.OPERATION, contents=None),
            sequence_number=-1, code=429,
            type=NackErrorType.THROTTLING, message="rate"),
    }
    out = {k: encode_message(v).decode() for k, v in shapes.items()}
    with open(os.path.join(HERE, "messages.json"), "w") as fh:
        json.dump(out, fh, indent=1)


def service_log() -> dict:
    from fluidframework_tpu.driver import LocalDocumentServiceFactory
    from fluidframework_tpu.loader import Loader
    from fluidframework_tpu.runtime.summarizer import SummaryManager
    from fluidframework_tpu.service import LocalServer
    from fluidframework_tpu.service.durable_log import DurableLog

    logdir = os.path.join(HERE, "svclog")
    blobdir = os.path.join(HERE, "blobs")
    for d in (logdir, blobdir):
        shutil.rmtree(d, ignore_errors=True)

    clock = [1000.0]
    server = LocalServer(log=DurableLog(logdir), storage_dir=blobdir,
                         clock=lambda: clock[0])
    loader = Loader(LocalDocumentServiceFactory(server))
    c1 = loader.resolve("t", "doc")
    c2 = loader.resolve("t", "doc")
    s1 = c1.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    s1.insert_text(0, "golden ")
    s2 = c2.runtime.get_data_store("default").get_channel("text")
    s2.insert_text(7, "fixture")
    s1.annotate_range(0, 6, {"bold": True})
    s1.remove_text(0, 1)  # exercise remove + zamboni paths
    sm = SummaryManager(c1, max_ops=10**9)
    sm.summarize_now()
    s2.insert_text(0, "post-summary ")  # tail beyond the summary
    assert s1.get_text() == s2.get_text()
    server.checkpoint_all()
    server.log.sync()
    expected = {
        "text": s1.get_text(),
        "seq": server._orderers["t/doc"].deli.sequence_number,
        "summary_head": server._orderers["t/doc"].scribe.last_summary_head,
        "bold_at_0_after_boot": False,  # 'g' was removed; 'o' is pos 0
    }
    server.log.close()
    return expected


def applier_checkpoint() -> dict:
    from fluidframework_tpu.mergetree.client import MergeTreeClient
    from fluidframework_tpu.protocol.messages import (
        MessageType, SequencedDocumentMessage,
    )
    from fluidframework_tpu.service.tpu_applier import (
        TpuDocumentApplier, save_applier_checkpoint,
    )

    applier = TpuDocumentApplier(max_docs=4, max_slots=64,
                                 ops_per_dispatch=8)
    applier.set_replay_source(lambda t, d: [])
    oracle = MergeTreeClient("oracle")
    ops = [
        (0, {"type": 0, "pos": 0, "text": "device "}),
        (0, {"type": 0, "pos": 7, "text": "state"}),
        (1, {"type": 1, "start": 0, "end": 3}),
        (0, {"type": 2, "start": 0, "end": 4, "props": {"em": True}}),
    ]
    for i, (kind, op) in enumerate(ops):
        msg = SequencedDocumentMessage(
            sequence_number=i + 1, minimum_sequence_number=i,
            client_sequence_number=i + 1, reference_sequence_number=i,
            client_id="gen", type=MessageType.OPERATION,
            contents=op, timestamp=float(i))
        applier.ingest("t", "ckdoc", msg, op)
        oracle.apply_msg(msg, local=False)
    applier.finalize()
    save_applier_checkpoint(applier, os.path.join(HERE, "applier_ckpt"))
    return {"ckpt_text": oracle.get_text(),
            "ckpt_applied_seq": len(ops)}


def main() -> None:
    wire_frames()
    messages()
    expected = service_log()
    expected.update(applier_checkpoint())
    with open(os.path.join(HERE, "expected.json"), "w") as fh:
        json.dump(expected, fh, indent=1)
    print("golden fixtures written:", expected)


if __name__ == "__main__":
    main()
