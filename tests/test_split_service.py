"""Per-stage multi-process service composition (VERDICT r3 item 2).

Ref: the reference runs alfred/deli/scribe/… as independent processes
connected only by the Kafka log (routerlicious/src/*/www.ts,
kafka-service/runner.ts:13, docker-compose.yml). Here: the CORE process
(front_end --log-dir) owns sockets + deli + scriptorium + broadcaster
and is the durable log's single writer; the SCRIBE and APPLIER stages
run as separate OS processes tailing that log read-only
(service/stage_runner.py) and answering on their own backchannel logs.

The recovery property under test: kill -9 a stage mid-stream and
restart it over the same state dir — it resumes from its checkpoint,
replays idempotently, and the pipeline completes.
"""

from __future__ import annotations

import contextlib
import os
import signal
import subprocess
import sys
import time

import pytest

from fluidframework_tpu.driver.network import NetworkDocumentServiceFactory
from fluidframework_tpu.loader import Loader


def wait_for(cond, timeout=30.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(0.02)
    return False


def _spawn(args, ready_line):
    # stderr goes to a per-process temp file so a failing composition can
    # dump every tier's diagnostics (VERDICT r4 weak #7: the harness used
    # to DEVNULL it, leaving composition failures evidence-free)
    import tempfile
    errf = tempfile.NamedTemporaryFile(
        mode="w+", prefix=f"{args[0].rsplit('.', 1)[-1]}-", suffix=".err",
        delete=False)
    proc = subprocess.Popen(
        [sys.executable, "-m"] + args,
        stdout=subprocess.PIPE, stderr=errf, text=True,
        cwd="/root/repo")
    proc._stderr_path = errf.name
    line = proc.stdout.readline().strip()
    assert line.startswith(ready_line), line
    return proc, line


def _dump_stderr(procs) -> None:
    """Print every spawned process's captured stderr (on test failure)."""
    for p in (procs.values() if isinstance(procs, dict) else procs):
        path = getattr(p, "_stderr_path", None)
        if path and os.path.exists(path):
            with open(path) as f:
                text = f.read().strip()
            if text:
                print(f"--- stderr [{' '.join(p.args[2:4])}] ---\n{text}")


def _cleanup(procs) -> None:
    for p in (procs.values() if isinstance(procs, dict) else procs):
        if p.poll() is None:
            p.terminate()
            p.wait(timeout=10)
        path = getattr(p, "_stderr_path", None)
        if path and os.path.exists(path):
            os.unlink(path)


def _spawn_stage(stage, log_dir, state_dir):
    proc, _ = _spawn(
        ["fluidframework_tpu.service.stage_runner", "--stage", stage,
         "--log-dir", str(log_dir), "--state-dir", str(state_dir)],
        "READY")
    return proc


@contextlib.contextmanager
def split_deployment(tmp_path, stages=("scribe", "applier")):
    log_dir = tmp_path / "log"
    storage_dir = tmp_path / "blobs"
    state_dirs = {s: tmp_path / f"{s}-state" for s in stages}
    procs = {}
    # spawn INSIDE the try: a tier that dies before its ready line must
    # still dump stderr and not leak the already-started processes
    try:
        for s in stages:
            procs[s] = _spawn_stage(s, log_dir, state_dirs[s])
        core_args = ["fluidframework_tpu.service.front_end", "--port", "0",
                     "--log-dir", str(log_dir),
                     "--storage-dir", str(storage_dir)]
        if "scribe" in stages:
            core_args.append("--external-scribe")
        for s in stages:
            core_args += ["--consume-backchannel", str(state_dirs[s])]
        core, line = _spawn(core_args, "LISTENING")
        procs["core"] = core
        port = int(line.rsplit(":", 1)[1])
        yield port, procs, state_dirs, log_dir
    except BaseException:
        _dump_stderr(procs)
        raise
    finally:
        _cleanup(procs)


def _applied_seq(state_dir, tenant, doc):
    """Newest applied-seq status the applier stage reported."""
    from fluidframework_tpu.service.durable_log import DurableLog
    from fluidframework_tpu.service.stage_runner import BACKCHANNEL_TOPIC

    try:
        log = DurableLog(str(state_dir), readonly=True)
    except OSError:
        return 0
    try:
        n = log.refresh_topic(BACKCHANNEL_TOPIC)
        best = 0
        for i in range(n):
            rec = log.read(BACKCHANNEL_TOPIC, i)
            if rec.get("kind") == "applied" and rec["tenant"] == tenant \
                    and rec["doc"] == doc:
                best = max(best, rec["applied_seq"])
        return best
    finally:
        log.close()


def test_summary_flow_through_external_scribe(tmp_path):
    """Client summary validated + acked by the scribe PROCESS: upload →
    SUMMARIZE sequenced by the core's deli → scribe stage validates
    against the announced upload → ack ordered back through the
    backchannel → a fresh client boots from the committed version."""
    from fluidframework_tpu.runtime.summarizer import SummaryManager

    with split_deployment(tmp_path, stages=("scribe",)) as (port, _, _, _):
        loader = Loader(NetworkDocumentServiceFactory("127.0.0.1", port))
        c1 = loader.resolve("t", "doc")
        sm = SummaryManager(c1, max_ops=3)
        s = c1.runtime.create_data_store("default").create_channel(
            "text", "shared-string")
        s.insert_text(0, "abcdef")
        s.remove_text(0, 2)
        assert wait_for(lambda: sm.summaries_acked >= 1)
        c2 = loader.resolve("t", "doc")
        assert c2._base_snapshot is not None
        assert wait_for(lambda: c2.runtime.get_data_store("default")
                        .get_channel("text").get_text() == "cdef")


def test_scribe_stage_killed_and_restarted_mid_stream(tmp_path):
    """kill -9 the scribe process while a summary is in flight: no ack
    while it is down; a restart over the same state dir replays from its
    checkpoint and the ack lands."""
    from fluidframework_tpu.runtime.summarizer import SummaryManager

    with split_deployment(tmp_path, stages=("scribe",)) as (
            port, procs, state_dirs, log_dir):
        loader = Loader(NetworkDocumentServiceFactory("127.0.0.1", port))
        c1 = loader.resolve("t", "doc")
        sm = SummaryManager(c1, max_ops=3)
        s = c1.runtime.create_data_store("default").create_channel(
            "text", "shared-string")
        s.insert_text(0, "first")
        assert wait_for(lambda: sm.summaries_acked >= 1)

        os.kill(procs["scribe"].pid, signal.SIGKILL)
        procs["scribe"].wait(timeout=10)

        # summary submitted while the validator is DEAD
        for i in range(4):
            s.insert_text(0, f"{i}")
        time.sleep(1.0)
        assert sm.summaries_acked == 1  # nothing is acking

        procs["scribe"] = _spawn_stage("scribe", log_dir,
                                       state_dirs["scribe"])
        assert wait_for(lambda: sm.summaries_acked >= 2)


def test_applier_stage_catches_up_and_survives_kill(tmp_path):
    """The TPU applier as its own process: consumes the deltas log,
    reports applied seqs on its backchannel, and after kill -9 +
    restart resumes from its device-farm checkpoint (warm restart, no
    full replay) to catch back up to the stream tail."""
    with split_deployment(tmp_path, stages=("scribe", "applier")) as (
            port, procs, state_dirs, log_dir):
        loader = Loader(NetworkDocumentServiceFactory("127.0.0.1", port))
        c1 = loader.resolve("t", "doc")
        s = c1.runtime.create_data_store("default").create_channel(
            "text", "shared-string")
        for i in range(20):
            s.insert_text(0, "ab")
        tail = c1.delta_manager.last_processed_seq

        def caught_up(target):
            return _applied_seq(state_dirs["applier"], "t", "doc") >= target
        assert wait_for(lambda: caught_up(tail), timeout=120)

        os.kill(procs["applier"].pid, signal.SIGKILL)
        procs["applier"].wait(timeout=10)
        for i in range(10):
            s.insert_text(0, "cd")
        tail2 = c1.delta_manager.last_processed_seq
        assert tail2 > tail

        procs["applier"] = _spawn_stage("applier", log_dir,
                                        state_dirs["applier"])
        assert wait_for(lambda: caught_up(tail2), timeout=120)


def test_doc_partitioned_appliers_and_rebalance(tmp_path):
    """Two applier PROCESSES split the doc space by the stable doc hash;
    a redeploy with swapped assignments MOVES every doc to the other
    process, which catches up to the stream tail (VERDICT r3 item 2:
    rebalance between processes)."""
    from fluidframework_tpu.service.stage_runner import doc_partition

    def spawn_applier(log_dir, state_dir, part):
        proc, _ = _spawn(
            ["fluidframework_tpu.service.stage_runner", "--stage",
             "applier", "--log-dir", str(log_dir),
             "--state-dir", str(state_dir), "--partition", part],
            "READY")
        return proc

    log_dir = tmp_path / "log"
    states = [tmp_path / "a0", tmp_path / "a1"]
    appliers = [spawn_applier(log_dir, states[0], "0/2"),
                spawn_applier(log_dir, states[1], "1/2")]
    core, line = _spawn(
        ["fluidframework_tpu.service.front_end", "--port", "0",
         "--log-dir", str(log_dir),
         "--storage-dir", str(tmp_path / "blobs")], "LISTENING")
    port = int(line.rsplit(":", 1)[1])
    try:
        loader = Loader(NetworkDocumentServiceFactory("127.0.0.1", port))
        docs = [f"pdoc{i}" for i in range(4)]
        strings, tails = {}, {}
        for d in docs:
            c = loader.resolve("t", d)
            s = c.runtime.create_data_store("default").create_channel(
                "text", "shared-string")
            for _ in range(6):
                s.insert_text(0, "ab")
            strings[d] = (c, s)
            tails[d] = c.delta_manager.last_processed_seq
        owner = {d: doc_partition("t", d, 2) for d in docs}
        assert set(owner.values()) == {0, 1}  # both partitions in play

        # each doc is applied ONLY by its owner
        for d in docs:
            k = owner[d]
            assert wait_for(
                lambda d=d, k=k: _applied_seq(states[k], "t", d)
                >= tails[d], timeout=150)  # applier JAX boot + first
            # compile run ~50 s ALONE on this host; full-suite CPU
            # contention stretches it past the old 60 s window (flake)
            assert _applied_seq(states[1 - k], "t", d) == 0

        # REBALANCE: redeploy with swapped assignments; keep editing
        for p in appliers:
            p.terminate()
            p.wait(timeout=10)
        for d in docs:
            c, s = strings[d]
            s.insert_text(0, "z")
            tails[d] = c.delta_manager.last_processed_seq
        appliers = [spawn_applier(log_dir, states[0], "1/2"),
                    spawn_applier(log_dir, states[1], "0/2")]
        for d in docs:
            new_state = states[0] if owner[d] == 1 else states[1]
            assert wait_for(
                lambda d=d, st=new_state: _applied_seq(st, "t", d)
                >= tails[d], timeout=90)
    except BaseException:
        _dump_stderr(appliers + [core])
        raise
    finally:
        _cleanup(appliers + [core])


def _set_ctl(state_dir, mode: str, steps: int) -> None:
    import json as _json

    os.makedirs(state_dir, exist_ok=True)
    tmp = str(state_dir) + ".ctltmp"
    with open(tmp, "w") as f:
        _json.dump({"mode": mode, "steps": steps}, f)
    os.replace(tmp, os.path.join(state_dir, "ctl.json"))


def test_cross_process_deterministic_stepping(tmp_path):
    """Drive the scribe PROCESS one record at a time (VERDICT r4 #9 —
    opProcessingController.ts:16 across the process boundary): with the
    stage paused the summary is never acked even though the core is
    live; stepping releases exactly one log record per step, and the
    ack appears at one specific step boundary (the SUMMARIZE record's),
    never before."""
    from fluidframework_tpu.runtime.summarizer import SummaryManager
    from fluidframework_tpu.service.durable_log import DurableLog

    # pause the stage BEFORE it starts: the whole stream is stepped
    scribe_state = tmp_path / "scribe-state"
    _set_ctl(scribe_state, "pause", 0)

    with split_deployment(tmp_path, stages=("scribe",)) as (
            port, _, state_dirs, log_dir):
        loader = Loader(NetworkDocumentServiceFactory("127.0.0.1", port))
        c1 = loader.resolve("t", "doc")
        sm = SummaryManager(c1, max_ops=3)
        s = c1.runtime.create_data_store("default").create_channel(
            "text", "shared-string")
        s.insert_text(0, "abcdef")
        s.remove_text(0, 2)
        # the summarize op is in flight...
        assert wait_for(lambda: sm._pending_handle is not None)
        # ...but the paused validator never acks it
        time.sleep(2.0)
        assert sm.summaries_acked == 0

        # step the stage record by record; the ack must land at exactly
        # one boundary and stay monotonic. Per step, wait on the stage's
        # own observables — its post-step checkpoint (cp topic) and
        # backchannel emissions — instead of sleeping a fixed window
        # (a blind 5 s x ~7 pre-ack steps was ~35 s of pure sleep).
        state_view = DurableLog(str(scribe_state), readonly=True)
        try:
            last_cp = state_view.refresh_topic("cp/t/doc")
            last_bc = state_view.refresh_topic("backchannel")
            acked_at = None
            for step in range(1, 200):
                _set_ctl(scribe_state, "pause", step)
                t0 = time.time()
                while time.time() - t0 < 10.0 and sm.summaries_acked == 0:
                    cp = state_view.refresh_topic("cp/t/doc")
                    if cp > last_cp:
                        last_cp = cp
                        break  # stage consumed this step's budget
                    time.sleep(0.02)
                bc = state_view.refresh_topic("backchannel")
                if bc > last_bc:
                    # the stage emitted (ack/version) this step: give the
                    # core's backchannel poll the window to relay it
                    last_bc = bc
                    t1 = time.time()
                    while time.time() - t1 < 10.0 \
                            and sm.summaries_acked == 0:
                        time.sleep(0.02)
                if sm.summaries_acked >= 1:
                    acked_at = step
                    break
        finally:
            state_view.close()
        assert acked_at is not None, "stepping never released the ack"
        # the stream up to the summarize spans several records (joins,
        # the two edits, the upload announcement, the summarize): the
        # ack cannot have been released by the first step
        shared = DurableLog(str(log_dir), readonly=True)
        try:
            n_deltas = shared.refresh_topic("deltas/t/doc")
        finally:
            shared.close()
        assert acked_at > 1
        assert n_deltas >= acked_at - 1  # steps consumed real records


def test_full_production_composition(tmp_path):
    """EVERY tier at once, each its own OS process: storage server
    (commit/ref DAG), ordering core over the durable log with an
    external scribe, a scribe stage, two partitioned applier stages,
    and a gateway terminating the client socket. A client edits through
    the gateway, its summary is validated by the scribe PROCESS, the
    ref advances in the storage PROCESS, a fresh client boots from it,
    and an applier stage reports the doc applied to the stream tail."""
    from fluidframework_tpu.runtime.summarizer import SummaryManager
    from fluidframework_tpu.service.stage_runner import doc_partition
    from fluidframework_tpu.service.storage_client import (
        RemoteStorage,
        StorageConnection,
    )

    log_dir = tmp_path / "log"
    sstate = tmp_path / "scribe"
    astates = [tmp_path / "ap0", tmp_path / "ap1"]
    procs = []
    try:
        store, line = _spawn(
            ["fluidframework_tpu.service.storage_server",
             "--dir", str(tmp_path / "store")], "LISTENING")
        procs.append(store)
        sport = int(line.rsplit(":", 1)[1])
        procs.append(_spawn_stage("scribe", log_dir, sstate))
        for i, st in enumerate(astates):
            p, _ = _spawn(
                ["fluidframework_tpu.service.stage_runner", "--stage",
                 "applier", "--log-dir", str(log_dir),
                 "--state-dir", str(st), "--partition", f"{i}/2"],
                "READY")
            procs.append(p)
        core, line = _spawn(
            ["fluidframework_tpu.service.front_end", "--port", "0",
             "--log-dir", str(log_dir),
             "--storage-server", str(sport), "--external-scribe",
             "--consume-backchannel", str(sstate),
             "--consume-backchannel", str(astates[0]),
             "--consume-backchannel", str(astates[1])], "LISTENING")
        procs.append(core)
        port = int(line.rsplit(":", 1)[1])
        gw, line = _spawn(["fluidframework_tpu.service.gateway",
                           "--core-port", str(port)], "LISTENING")
        procs.append(gw)
        gport = int(line.rsplit(":", 1)[1])

        loader = Loader(NetworkDocumentServiceFactory("127.0.0.1", gport))
        c1 = loader.resolve("t", "doc")
        # max_ops must be reachable: the scenario produces exactly 4
        # OPERATION messages (2 channel attaches + 2 inserts) — at
        # max_ops=6 the heuristic would never fire and the ack assert
        # starves without any tier being at fault (the round-4 failure)
        sm = SummaryManager(c1, max_ops=4)
        s = c1.runtime.create_data_store("default").create_channel(
            "text", "shared-string")
        for w in ("full ", "stack "):
            s.insert_text(0, w)
        assert wait_for(lambda: sm.summaries_acked >= 1, timeout=60)

        st = RemoteStorage(StorageConnection("127.0.0.1", sport),
                           "t", "doc")
        assert st.get_ref() is not None      # scribe→core→storage ref
        c2 = loader.resolve("t", "doc")      # boots from the ref
        assert c2._base_snapshot is not None
        assert wait_for(lambda: c2.runtime.get_data_store("default")
                        .get_channel("text").get_text()
                        == "stack full ")
        owner = doc_partition("t", "doc", 2)
        tail = c1.delta_manager.last_processed_seq
        assert wait_for(
            lambda: _applied_seq(astates[owner], "t", "doc") >= tail,
            timeout=90)
    except BaseException:
        _dump_stderr(procs)
        raise
    finally:
        _cleanup(procs)
