"""Fleet cold start: lazy O(snapshot+tail) rehydration, boot-storm
admission, and the topology spec that makes a restart one object.

The contract under test (service/rehydrate.py + local_orderer.py):

* A core restart builds NO doc pipelines at claim time; a doc's first
  route boots it from the latest acked summary + the durable-log tail
  (deli from its checkpoint offset, scriptorium from the first block
  covering the retention base, scribe from its own durable offset) —
  and the rehydrated doc is byte-identical to a whole-log replay.
* ``boot.part.lazy`` / ``boot.part.full_replay`` counters prove which
  path ran: a checkpointed + summarized doc must NEVER whole-log
  replay.
* The rehydration executor parks first-routes beyond its token budget
  on the shed-retry lane (``BootPending`` → driver retry), then serves
  them — warm docs never queue behind a boot storm.
* ``TopologySpec`` round-trips through JSON, and a Fleet started from
  it claims exactly the partitions the spec declares.
"""

from __future__ import annotations

import os
import random
import shutil
import time

import pytest

from fluidframework_tpu.driver.local import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.service.durable_log import DurableLog
from fluidframework_tpu.service.local_server import LocalServer
from fluidframework_tpu.service.rehydrate import (
    BootPending,
    RehydrationExecutor,
    boot_counters,
)
from fluidframework_tpu.service.service_summarizer import (
    HostReplicaSource,
    ServiceSummarizer,
)


def wait_for(cond, timeout=30.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(0.02)
    return False


def _summarize(server, tenant, doc):
    svc = ServiceSummarizer(server, HostReplicaSource(server))
    version = svc.summarize_doc(tenant, doc)
    assert version is not None
    return version


def _seeded_edits(s, rng, n):
    for i in range(n):
        text = s.get_text()
        if text and rng.random() < 0.3:
            at = rng.randrange(len(text))
            s.remove_text(at, min(len(text), at + rng.randint(1, 4)))
        else:
            at = rng.randrange(len(text) + 1)
            s.insert_text(at, f"w{i}-{rng.randint(0, 999)} ")


def _counters_delta(before):
    after = boot_counters().snapshot()
    return {k: after.get(k, 0) - before.get(k, 0)
            for k in set(after) | set(before)}


def _build_corpus(tmp_path, seed, docs=("a", "b"), head=30, tail=12):
    """A durable-log + storage corpus: seeded edits, a summary +
    checkpoint mid-stream, MORE edits after (the tail a lazy boot must
    replay), then the server abandoned without close — a crash."""
    log_dir = str(tmp_path / "log")
    store_dir = str(tmp_path / "store")
    server = LocalServer(log=DurableLog(log_dir), storage_dir=store_dir)
    loader = Loader(LocalDocumentServiceFactory(server))
    rng = random.Random(seed)
    texts = {}
    for doc in docs:
        c = loader.resolve("t", doc)
        s = c.runtime.create_data_store("default").create_channel(
            "text", "shared-string")
        _seeded_edits(s, rng, head)
        _summarize(server, "t", doc)
        _seeded_edits(s, rng, tail)  # the tail past the summary
        texts[doc] = s.get_text()
        assert texts[doc]
    server.checkpoint_all()
    server.log.flush()
    # abandoned, not closed: the on-disk state is a SIGKILL's aftermath
    return log_dir, store_dir, texts


def _boot_text(log_dir, store_dir, doc, lazy):
    server = LocalServer(log=DurableLog(log_dir), storage_dir=store_dir)
    server.lazy_boot = lazy
    loader = Loader(LocalDocumentServiceFactory(server))
    c = loader.resolve("t", doc)
    ok = wait_for(
        lambda: "default" in c.runtime.data_stores
        and "text" in c.runtime.get_data_store("default").channels)
    assert ok, f"doc {doc} never materialized after boot"
    text = c.runtime.get_data_store("default").get_channel(
        "text").get_text()
    mode = server._orderers[f"t/{doc}"].boot_mode
    return text, mode


# =====================================================================
# lazy rehydration == whole-log replay, to the byte
# =====================================================================

@pytest.mark.parametrize("seed", [0, 7, 42])
def test_lazy_boot_equals_full_replay(tmp_path, seed):
    log_dir, store_dir, texts = _build_corpus(tmp_path, seed)
    lazy_dir = str(tmp_path / "lazy")
    full_dir = str(tmp_path / "full")
    shutil.copytree(log_dir, lazy_dir)
    shutil.copytree(log_dir, full_dir)

    before = boot_counters().snapshot()
    for doc, want in texts.items():
        lazy_text, lazy_mode = _boot_text(lazy_dir, store_dir, doc,
                                          lazy=True)
        full_text, full_mode = _boot_text(full_dir, store_dir, doc,
                                          lazy=False)
        assert lazy_mode == "lazy"
        assert full_mode is None  # the untouched warm path
        assert lazy_text == want
        assert full_text == want
    delta = _counters_delta(before)
    assert delta.get("boot.part.lazy", 0) == len(texts)
    # the contract the storm bench asserts fleet-wide: a checkpointed +
    # summarized doc NEVER whole-log replays
    assert delta.get("boot.part.full_replay", 0) == 0


def test_unsummarized_doc_full_replays_and_converges(tmp_path):
    """No checkpoint/summary → the safety fallback: identical to the
    old boot (offset 0), counted as boot.part.full_replay."""
    log_dir = str(tmp_path / "log")
    server = LocalServer(log=DurableLog(log_dir))
    loader = Loader(LocalDocumentServiceFactory(server))
    c = loader.resolve("t", "raw")
    s = c.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    for i in range(10):
        s.insert_text(0, f"x{i} ")
    want = s.get_text()
    server.log.flush()

    before = boot_counters().snapshot()
    text, mode = _boot_text(log_dir, str(tmp_path / "store"), "raw",
                            lazy=True)
    assert mode == "full_replay"
    assert text == want
    delta = _counters_delta(before)
    assert delta.get("boot.part.full_replay", 0) == 1
    assert delta.get("boot.part.lazy", 0) == 0


def test_fresh_doc_counts_fresh(tmp_path):
    server = LocalServer(log=DurableLog(str(tmp_path / "log")))
    server.lazy_boot = True
    before = boot_counters().snapshot()
    loader = Loader(LocalDocumentServiceFactory(server))
    c = loader.resolve("t", "newdoc")
    c.runtime.create_data_store("default")
    delta = _counters_delta(before)
    assert delta.get("boot.part.fresh", 0) == 1
    assert delta.get("boot.part.full_replay", 0) == 0


# =====================================================================
# boot-storm admission: park beyond the budget, then serve
# =====================================================================

def test_executor_parks_beyond_burst_then_serves():
    now = [0.0]
    ex = RehydrationExecutor(boots_per_s=10.0, burst=2,
                             clock=lambda: now[0])
    ex.admit("t", "d0")
    ex.admit("t", "d1")
    with pytest.raises(BootPending) as ei:
        ex.admit("t", "d2")
    assert ei.value.retry_after_ms > 0
    assert ex.parked == 1 and ex.booted == 2
    # the bucket refills with time: the parked boot's retry is served
    now[0] += 0.2
    ex.admit("t", "d2")
    assert ex.booted == 3
    st = ex.status()
    assert st["booted"] == 3 and st["parked"] == 1


def test_storm_parks_then_serves_in_connect_path(tmp_path):
    """Through LocalServer.connect: warm docs bypass admission, cold
    boots beyond the budget park with a retry hint."""
    server = LocalServer(log=DurableLog(str(tmp_path / "log")))
    server.lazy_boot = True
    now = [0.0]
    server.rehydrator = RehydrationExecutor(boots_per_s=10.0, burst=1,
                                            clock=lambda: now[0])
    before = boot_counters().snapshot()
    loader = Loader(LocalDocumentServiceFactory(server))
    loader.resolve("t", "cold0")
    with pytest.raises(BootPending):
        loader.resolve("t", "cold1")
    # a WARM doc is untouched by the storm gate — no token needed
    loader.resolve("t", "cold0")
    assert _counters_delta(before).get("boot.part.parked", 0) == 1
    now[0] += 0.2
    loader.resolve("t", "cold1")  # parked boot now serves
    assert server.rehydrator.booted == 2


def test_boot_pending_retries_transparently_over_network(tmp_path):
    """The full lane: BootPending → error frame code=boot_pending →
    driver parks on the jittered retry lane → connect succeeds."""
    from fluidframework_tpu.driver.network import (
        NetworkDocumentServiceFactory,
    )
    from fluidframework_tpu.service.front_end import NetworkFrontEnd

    from fluidframework_tpu.obs import tier_snapshot

    server = LocalServer(log=DurableLog(str(tmp_path / "log")))
    server.lazy_boot = True
    fe = NetworkFrontEnd(server).start_background()
    fe.enable_boot_admission(boots_per_s=5.0, burst=1)
    before = tier_snapshot("driver").get("boot.parked.retries", 0)
    try:
        loader = Loader(NetworkDocumentServiceFactory(
            "127.0.0.1", fe.port))
        c0 = loader.resolve("t", "na")
        c1 = loader.resolve("t", "nb")  # parked at least once, retried
        s = c1.runtime.create_data_store("default").create_channel(
            "text", "shared-string")
        s.insert_text(0, "storm survivor")
        assert wait_for(lambda: c1.runtime.pending.count == 0)
        retries = tier_snapshot("driver").get("boot.parked.retries", 0)
        assert retries - before >= 1
        assert c0.connected and c1.connected
    finally:
        fe.stop()


# =====================================================================
# partition checkpoint isolation (one bad orderer ≠ zero checkpoints)
# =====================================================================

def test_partition_checkpoint_isolates_failures():
    from fluidframework_tpu.service.core import InMemoryDb
    from fluidframework_tpu.service.broadcaster import PubSub
    from fluidframework_tpu.service.local_log import LocalLog
    from fluidframework_tpu.service.partitions import Partition

    part = Partition(0, LocalLog(), InMemoryDb(), PubSub())
    o_bad = part.orderer("t", "bad")
    o_good = part.orderer("t", "good")
    calls = []
    o_good_cp = o_good.checkpoint
    o_good.checkpoint = lambda: (calls.append("good"), o_good_cp())[1]

    def boom():
        raise RuntimeError("disk full")
    o_bad.checkpoint = boom

    with pytest.raises(RuntimeError, match="disk full"):
        part.checkpoint()
    assert calls == ["good"]  # the healthy doc still checkpointed

    # graceful close: same isolation, and EVERY orderer still closes
    closed = []
    for key, o in part.orderers.items():
        o_close = o.close

        def close(key=key, o_close=o_close):
            closed.append(key)
            o_close()
        o.close = close
    with pytest.raises(RuntimeError, match="disk full"):
        part.close(graceful=True)
    assert sorted(closed) == ["t/bad", "t/good"]
    assert not part.orderers


# =====================================================================
# topology spec: round-trip, and the fleet it declares
# =====================================================================

def test_topology_spec_round_trips(tmp_path):
    from fluidframework_tpu.service.topology import (
        GatewaySpec,
        TopologySpec,
        default_spec,
    )

    spec = default_spec(str(tmp_path / "fleet"), n_cores=3,
                        n_partitions=8, lease_ttl=2.5,
                        summarize_every=50, boot_rate=77.0,
                        boot_burst=9)
    spec.gateways = [GatewaySpec(name="gw0"),
                     GatewaySpec(name="gw1", upstream=0)]
    path = str(tmp_path / "topology.json")
    spec.save(path)
    loaded = TopologySpec.load(path)
    assert loaded == spec
    assert loaded.to_dict() == spec.to_dict()
    # partitions are fully covered, disjointly, by the core prefers
    claimed = [k for c in loaded.cores for k in c.prefer]
    assert sorted(claimed) == list(range(8))


def test_fleet_from_spec_claims_declared_partitions(tmp_path):
    from fluidframework_tpu.service.placement_plane import EpochTable
    from fluidframework_tpu.service.topology import Fleet, default_spec

    spec = default_spec(str(tmp_path / "fleet"), n_cores=2,
                        n_partitions=4, lease_ttl=1.0)
    before = boot_counters().snapshot()
    fl = Fleet(spec).start()
    try:
        fl.wait_claimed()
        table = EpochTable.for_shard_dir(spec.shard_dir).read()
        # spec → running fleet → spec: the table's claim map IS the
        # spec's prefer map, per core address
        addr_of_core = {i: f"{spec.host}:{fl.core_ports[i]}"
                        for i in fl.core_ports}
        for i, core in enumerate(spec.cores):
            for k in core.prefer:
                assert table["parts"][str(k)]["addr"] == addr_of_core[i]
        delta = _counters_delta(before)
        assert delta.get("topology.fleet.starts", 0) == 1
        assert delta.get("topology.core.spawns", 0) == 2
    finally:
        fl.stop()
