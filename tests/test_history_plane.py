"""Doc history plane specs (PR 17): commit/ref codec + torn-tail
recovery, near-free fork, point-in-time replay, CRDT-mediated
integrate, chunk GC ref-counting across the commit graph, and the
crash-mid-fork adopt-or-discard contract — locally and over sockets.
"""

from __future__ import annotations

import os
import random
import tempfile
import time

import pytest

from fluidframework_tpu.chaos.hooks import armed
from fluidframework_tpu.chaos.plane import FaultPlane, SimulatedCrash
from fluidframework_tpu.driver import (
    LocalDocumentServiceFactory,
    NetworkDocumentServiceFactory,
)
from fluidframework_tpu.driver.file import (
    FileDocumentService,
    record_document,
)
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.loader.container import Container
from fluidframework_tpu.obs import tier_snapshot
from fluidframework_tpu.protocol import refgraph
from fluidframework_tpu.service import LocalServer, NetworkFrontEnd
from fluidframework_tpu.service.history_plane import (
    MAIN_REF,
    HistoryPlane,
    fork_pin_ref,
)
from fluidframework_tpu.service.service_summarizer import (
    HostReplicaSource,
    ServiceSummarizer,
)

SEEDS = (0, 7, 42)


@pytest.fixture
def server():
    return LocalServer()


@pytest.fixture
def loader(server):
    return Loader(LocalDocumentServiceFactory(server))


def summarize(server, tenant, doc):
    return ServiceSummarizer(
        server, HostReplicaSource(server)).summarize_doc(tenant, doc)


def head_seq(server, tenant, doc):
    return server._get_orderer(tenant, doc).deli.sequence_number


def get_text(container):
    return container.runtime.get_data_store(
        "default").get_channel("text").get_text()


# ================================================================ codec


def _sample_commit(i=0):
    return {"id": f"c{i:04x}", "version": f"v{i}", "base_seq": 10 * i,
            "parents": [f"c{i - 1:04x}"] if i else [],
            "chunk_ids": [f"chunk{i}", f"chunk{i + 1}"],
            "ts": 1700000000.0 + i,
            "extra": {"fork_of": {"tenant": "t", "doc": "d", "seq": i}}
            if i % 3 == 0 else {}}


def test_codec_roundtrip_all_kinds():
    payloads = [refgraph.encode_commit(_sample_commit(i)) for i in range(4)]
    payloads.append(refgraph.encode_ref(MAIN_REF, "c0002", ts=5.0))
    payloads.append(refgraph.encode_ref("fork/t/d2", None))
    payloads.append(refgraph.encode_discard("c0003"))
    buf = b"".join(refgraph.frame_record(p) for p in payloads)
    records, clean = refgraph.scan_records(buf)
    assert clean == len(buf)
    assert [r["t"] for r in records] == ["commit"] * 4 + ["ref", "ref",
                                                          "discard"]
    for i in range(4):
        want = _sample_commit(i)
        got = {k: records[i][k] for k in want}
        assert got == want
    assert records[4] == {"t": "ref", "name": MAIN_REF, "commit": "c0002",
                          "ts": 5.0}
    assert records[5]["commit"] is None  # empty id = ref delete
    commits, refs, discarded = refgraph.replay_records(records)
    assert set(commits) == {f"c{i:04x}" for i in range(4)}
    assert refs == {MAIN_REF: "c0002"}
    assert discarded == {"c0003"}


@pytest.mark.parametrize("seed", SEEDS)
def test_codec_torn_tail_fuzz(seed):
    """A tear at ANY byte offset decodes to a clean record prefix —
    never an exception, never a corrupt record — and RefLog heals the
    tear on its next append."""
    rng = random.Random(seed)
    payloads = [refgraph.encode_commit(_sample_commit(i)) for i in range(6)]
    payloads.append(refgraph.encode_ref(MAIN_REF, "c0005", ts=1.0))
    frames = [refgraph.frame_record(p) for p in payloads]
    buf = b"".join(frames)
    ends = [0]
    for f in frames:
        ends.append(ends[-1] + len(f))

    cuts = {rng.randrange(len(buf) + 1) for _ in range(200)}
    cuts.update(ends)  # every clean boundary too
    for cut in sorted(cuts):
        records, clean = refgraph.scan_records(buf[:cut])
        # clean prefix = the greatest whole-record boundary <= cut
        want_n = max(i for i, e in enumerate(ends) if e <= cut)
        assert len(records) == want_n, f"cut at {cut}"
        assert clean == ends[want_n]
        for i, rec in enumerate(records[:6]):
            assert rec["id"] == f"c{i:04x}"

    # flipping a byte inside a payload kills that record AND the tail
    # (CRC gate) but never the records before it
    pos = len(frames[0]) + 12
    flipped = bytearray(buf)
    flipped[pos] ^= 0xFF
    records, clean = refgraph.scan_records(bytes(flipped))
    assert len(records) == 1 and clean == ends[1]

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "doc.hist")
        log = refgraph.RefLog(path)
        log.append(*payloads)
        tear = ends[3] + rng.randrange(1, len(frames[3]))
        log.truncate_at(tear)
        assert len(log.load()) == 3
        log.append(refgraph.encode_discard("c0001"))
        records = log.load()  # healed: clean prefix + the new record
        assert [r["t"] for r in records] == ["commit"] * 3 + ["discard"]


# ==================================================== fork equivalence


def _drive_doc(server, loader, doc, seed, rounds=36):
    """Deterministic editing session; returns (channel, {seq: text})."""
    rng = random.Random(seed)
    c = loader.resolve("t", doc)
    s = c.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    s.insert_text(0, "base text. ")
    oracle = {}
    for r in range(rounds):
        length = len(s.get_text())
        roll = rng.random()
        if roll < 0.6 or length < 5:
            s.insert_text(rng.randrange(length + 1), f"w{r} ")
        elif roll < 0.85:
            a = rng.randrange(length - 2)
            s.remove_text(a, min(length, a + 1 + rng.randrange(3)))
        else:
            a = rng.randrange(length - 2)
            s.annotate_range(a, min(length, a + 2), {"k": r % 4})
        oracle[head_seq(server, "t", doc)] = s.get_text()
        if r == rounds // 3:
            summarize(server, "t", doc)
    return s, oracle


@pytest.mark.parametrize("seed", SEEDS)
def test_fork_boot_equals_whole_log_replay(server, loader, seed):
    """The O(snapshot) fork boot must agree byte-for-byte with a legacy
    whole-log replay of the parent advanced to the same seq."""
    doc = f"doc{seed}"
    s, oracle = _drive_doc(server, loader, doc, seed)
    probed = sorted(q for q in oracle
                    if q > 2 * len(oracle) // 3)  # past the summary
    fork_seq = probed[len(probed) // 2]

    res = server.history.fork("t", doc, at_seq=fork_seq,
                              new_doc=f"{doc}-fork")
    assert res["base_seq"] <= fork_seq <= res["fork_seq"]
    assert res["shared_chunks"] > 0  # content-addressed: zero new bytes
    fork_text = get_text(loader.resolve("t", f"{doc}-fork"))
    assert fork_text == oracle[fork_seq]

    with tempfile.TemporaryDirectory() as d:
        doc_dir = record_document(server, "t", doc, d)
        os.remove(os.path.join(doc_dir, "snapshot.json"))
        whole = Container(FileDocumentService.from_dir(doc_dir)).load(
            connect=False)
        whole.delta_manager.advance_to(fork_seq)
        assert get_text(whole) == fork_text


# ========================================================= time travel


def test_time_travel_reads(server, loader):
    doc = "tt"
    s, oracle = _drive_doc(server, loader, doc, seed=1)
    summarize(server, "t", doc)
    mid = sorted(oracle)[len(oracle) // 2]
    tail = max(oracle)

    at = server.history.replay_read("t", doc, mid)
    assert at["base_seq"] <= mid
    assert at["commit"]["version"] == at["version"]["id"]

    for q in (mid, tail):
        hc = loader.resolve_at("t", doc, q)
        assert get_text(hc) == oracle[q]
        assert hc.readonly and not hc.connected
    hc = loader.resolve_at("t", doc, mid)
    with pytest.raises(PermissionError, match="readonly"):
        hc.runtime.get_data_store("default").get_channel(
            "text").insert_text(0, "nope")
    svc = LocalDocumentServiceFactory(server).create_document_service(
        "t", doc)
    with pytest.raises(RuntimeError, match="offline"):
        svc.history().replay_service(mid).connect_to_delta_stream()

    # newest-first log, refs/main at the newest commit
    log = server.history.log("t", doc)
    assert len(log) >= 2
    assert [c["base_seq"] for c in log] == sorted(
        (c["base_seq"] for c in log), reverse=True)
    assert server.history.refs("t", doc)[MAIN_REF] == log[0]["id"]


def test_history_reads_survive_retention_trim(server, loader):
    """History reads are explicitly historical: a range below the
    retention base falls back to the durable-log scan instead of
    refusing with log_truncated."""
    doc = "trim"
    s, oracle = _drive_doc(server, loader, doc, seed=3)
    version = summarize(server, "t", doc)
    assert version
    trim_at = head_seq(server, "t", doc)
    orderer = server._get_orderer("t", doc)
    dropped = orderer.scriptorium.truncate_below("t", doc, trim_at)
    assert dropped > 0
    before = tier_snapshot("service").get("history.replay.log_scans", 0)
    early = sorted(oracle)[3]
    msgs = server.history.read_deltas("t", doc, 0, early + 1)
    assert msgs and msgs[-1].sequence_number == early
    assert tier_snapshot("service").get(
        "history.replay.log_scans", 0) > before


# =========================================================== integrate


@pytest.mark.parametrize("seed", SEEDS)
def test_integrate_equivalence_with_concurrent_writers(server, loader,
                                                       seed):
    """Integrate rides the ordinary total order, so every parent
    replica — live clients AND a from-scratch boot — converges to one
    text that carries both the fork's and the concurrent writers'
    edits."""
    rng = random.Random(seed)
    doc = f"int{seed}"
    s, _ = _drive_doc(server, loader, doc, seed, rounds=20)
    summarize(server, "t", doc)
    res = server.history.fork("t", doc, new_doc=f"{doc}-fork")

    fc = loader.resolve("t", f"{doc}-fork")
    ft = fc.runtime.get_data_store("default").get_channel("text")
    writer = loader.resolve("t", doc)
    wt = writer.runtime.get_data_store("default").get_channel("text")
    for i in range(8):  # interleaved fork + parent edits
        ft.insert_text(rng.randrange(len(ft.get_text()) + 1), f"F{i} ")
        wt.insert_text(rng.randrange(len(wt.get_text()) + 1), f"P{i} ")

    out = server.history.integrate("t", f"{doc}-fork")
    assert out["parent"] == doc and out["ops"] == 8

    texts = {s.get_text(), wt.get_text(),
             get_text(loader.resolve("t", doc))}
    assert len(texts) == 1, "parent replicas diverged after integrate"
    # later random-position inserts may land INSIDE earlier tokens, so
    # count the marker characters (unique to fork/parent edits) instead
    # of asserting intact substrings
    merged = texts.pop()
    assert merged.count("F") == 8 and merged.count("P") == 8
    assert get_text(fc) == ft.get_text()  # fork untouched by integrate

    with pytest.raises(ValueError, match="not a fork"):
        server.history.integrate("t", doc)


# ================================================================== GC


def test_gc_pins_fork_chunks_and_sweeps_dead_ones(server, loader):
    """Both sides of the ref-count: trimming the parent's history never
    unlinks chunks a live fork pin can still boot from, while commits no
    ref reaches are swept."""
    doc = "gcdoc"
    c = loader.resolve("t", doc)
    s = c.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    s.insert_text(0, "gen one ")
    summarize(server, "t", doc)
    gen1 = server.history.log("t", doc)[0]
    server.history.fork("t", doc, new_doc=f"{doc}-fork")

    # rewrite everything so generation 2 shares no chunks with gen 1
    s.remove_text(0, len(s.get_text()))
    s.insert_text(0, "generation two content, fully rewritten ")
    summarize(server, "t", doc)
    gen2 = server.history.log("t", doc)[0]
    dead_if_unpinned = set(gen1["chunk_ids"]) - set(gen2["chunk_ids"])
    assert dead_if_unpinned, "generations unexpectedly share all chunks"

    # pinned side: the fork's pin holds gen1 alive through a GC
    out = server.history.gc_chunks("t")
    assert out["deleted"] == 0
    assert set(gen1["chunk_ids"]) <= server.history.pinned_chunks("t", doc)
    for cid in gen1["chunk_ids"]:
        assert server.blob_store.get(cid) is not None
    fork_boot = loader.resolve("t", f"{doc}-fork")
    assert "gen one" in get_text(fork_boot)

    # unpinned side: drop the pin (as an integrated-and-released fork
    # would) and the same sweep reclaims gen1's now-unreachable chunks
    pstore = server.history._store("t", doc)
    pin = fork_pin_ref("t", f"{doc}-fork")
    server.history._append(pstore, refgraph.encode_ref(pin, None))
    server.history._set_ref(pstore, pin, None)
    out = server.history.gc_chunks("t", documents=[doc])
    assert out["deleted"] >= len(dead_if_unpinned)
    for cid in dead_if_unpinned:
        with pytest.raises(KeyError):
            server.blob_store.get(cid)
    for cid in gen2["chunk_ids"]:  # refs/main still pins gen2
        assert server.blob_store.get(cid) is not None


# ==================================================== crash mid-fork


def test_crash_mid_fork_adopt_or_discard(server, loader):
    """Tear a fork at both windows; a rebuilt plane (the restart) must
    leave the graph consistent: unseeded commit discarded, seeded
    commit adopted, never a dangling ref either way."""
    doc = "crashy"
    _drive_doc(server, loader, doc, seed=5, rounds=12)
    summarize(server, "t", doc)
    plane = FaultPlane(0)
    plane.rule("history.fork", "crash", at=1,
               when=lambda ctx: ctx.get("stage") == "commit")
    plane.rule("history.fork", "crash", at=1,
               when=lambda ctx: ctx.get("stage") == "seeded")
    with armed(plane, server=server):
        with pytest.raises(SimulatedCrash):
            server.history.fork("t", doc, new_doc="f-torn")
        reboot1 = HistoryPlane(server)
        fstore = reboot1._store("t", "f-torn")
        pstore = reboot1._store("t", doc)
        assert fstore.commits and not fstore.refs  # discarded, not adopted
        assert set(fstore.commits) <= fstore.discarded
        assert fork_pin_ref("t", "f-torn") not in pstore.refs
        assert reboot1.log("t", "f-torn") == []  # discard filters the log

        with pytest.raises(SimulatedCrash):
            server.history.fork("t", doc, new_doc="f-seeded")
        reboot2 = HistoryPlane(server)
        fstore = reboot2._store("t", "f-seeded")
        pstore = reboot2._store("t", doc)
        assert MAIN_REF in fstore.refs  # adopted: refs restored
        assert fstore.refs[MAIN_REF] in fstore.commits
        assert fork_pin_ref("t", "f-seeded") in pstore.refs
    # both planes still alive here: the registry tracks their counters
    assert reboot1.counters.snapshot().get("history.ref.recovered") == 1
    assert reboot2.counters.snapshot().get("history.ref.recovered") == 1
    # the adopted fork is a real doc: it boots and reads
    assert get_text(loader.resolve("t", "f-seeded"))


# ============================================================= sockets


def wait_for(pred, timeout=10.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if pred():
            return True
        time.sleep(0.02)
    return False


def test_history_over_sockets():
    """The whole surface through the front end's history doors: log
    (binary FT_HISTORY frames), time-travel resolve_at, fork,
    integrate — and the service counters account for all of it."""
    fe = NetworkFrontEnd(LocalServer()).start_background()
    try:
        factory = NetworkDocumentServiceFactory("127.0.0.1", fe.port)
        loader = Loader(factory)
        c = loader.resolve("t", "doc")
        text = c.runtime.create_data_store("default").create_channel(
            "text", "shared-string")
        for i in range(25):
            text.insert_text(0, f"x{i} ")
        assert wait_for(lambda: text.get_text().startswith("x24 "))
        svc = factory.create_document_service("t", "doc")
        svc._rpc_transport().request(
            {"t": "admin_summarize", "tenant": "t", "doc": "doc"})
        mid_text = text.get_text()
        mid_seq = svc._rpc_transport().request(
            {"t": "admin_status", "tenant": "t",
             "doc": "doc"})["status"]["seq"]
        for i in range(6):
            text.insert_text(0, f"y{i} ")
        assert wait_for(lambda: text.get_text().startswith("y5 "))
        tail_text = text.get_text()

        h = svc.history()
        log = h.log()
        assert log and h.refs()[MAIN_REF] == log[0]["id"]
        assert h.at(mid_seq)["base_seq"] <= mid_seq
        assert get_text(loader.resolve_at("t", "doc", mid_seq)) == mid_text

        res = h.fork(new_doc="doc2")
        assert res["shared_chunks"] > 0
        c2 = loader.resolve("t", "doc2")
        t2 = c2.runtime.get_data_store("default").get_channel("text")
        assert wait_for(lambda: t2.get_text() == tail_text)
        t2.insert_text(0, "FORK ")
        assert wait_for(lambda: t2.get_text().startswith("FORK "))
        out = factory.create_document_service("t", "doc2") \
            .history().integrate()
        assert out["ops"] == 1
        assert wait_for(lambda: text.get_text().startswith("FORK "))

        # the socket-created fork pins its chunks server-side: supersede
        # the parent's generation, sweep, and the fork must still boot
        # cold from the blobs the pin kept alive
        svc._rpc_transport().request(
            {"t": "admin_summarize", "tenant": "t", "doc": "doc"})
        pinned = fe.server.history.pinned_chunks("t", "doc2")
        assert pinned
        fe.server.history.gc_chunks("t")
        assert all(fe.server.blob_store.has(cid) for cid in pinned)
        cold = Loader(NetworkDocumentServiceFactory(
            "127.0.0.1", fe.port)).resolve("t", "doc2")
        assert get_text(cold).startswith("FORK ")

        snap = tier_snapshot("service")
        assert snap.get("history.fork.boots", 0) >= 1
        assert snap.get("history.replay.reads", 0) >= 1
        assert snap.get("history.integrate.ops", 0) >= 1
        assert snap.get("history.commit.records", 0) >= 1
    finally:
        fe.stop()


def test_replay_tool_history_first_vs_legacy(server, loader):
    """The unified replay tool: live docs with a committed version boot
    history-first; file-driver docs without one replay the whole log and
    count under history.replay.legacy — and the two agree."""
    doc = "rp"
    s, _ = _drive_doc(server, loader, doc, seed=9, rounds=18)
    summarize(server, "t", doc)
    s.insert_text(0, "tail ")
    from fluidframework_tpu.replay.tool import ReplayController

    svc = LocalDocumentServiceFactory(server).create_document_service(
        "t", doc)
    hist = ReplayController(svc)
    assert hist.history is not None
    assert hist.container.delta_manager.last_processed_seq > 0  # O(snap)
    got = hist.run(10)

    with tempfile.TemporaryDirectory() as d:
        doc_dir = record_document(server, "t", doc, d)
        os.remove(os.path.join(doc_dir, "snapshot.json"))
        before = tier_snapshot("driver").get("history.replay.legacy", 0)
        legacy = ReplayController(FileDocumentService.from_dir(doc_dir))
        assert legacy.history is None
        got2 = legacy.run(10)
        assert tier_snapshot("driver").get(
            "history.replay.legacy", 0) == before + 1
    assert got["final_text"] == got2["final_text"] == s.get_text()
    common = set(got["snapshots"]) & set(got2["snapshots"])
    assert common
    for q in common:
        assert got["snapshots"][q] == got2["snapshots"][q]
