"""Concurrency contract checker specs: every violation class must fire
on a seeded fixture (self-tests that MUST fail), stay silent on the
clean twin, and the real tree must gate at ZERO unwaivered findings.

The two hard-way bugs this repo actually shipped and fixed — PR 11's
donation-on-CPU ``block_until_ready`` serialization and the
stage-buffer rotation without a consuming-execution fence — are
reconstructed as fixture copies, so the checker provably would have
caught them (ROADMAP "concurrency contracts").

Ref: RacerD's annotate-and-propagate design; Clang -Wthread-safety
REQUIRES()/EXCLUDES() capability analysis.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

from tools.fluidlint.concurrency_check import check_concurrency
from tools.fluidlint.concurrency_waivers import WAIVERS
from tools.fluidlint.registries import LOCK_ORDER, LOCK_RANK

HERE = os.path.dirname(os.path.abspath(__file__))
FIX = os.path.join(HERE, "fixtures", "fluidlint", "concurrency")
REPO = os.path.abspath(os.path.join(HERE, ".."))


def _check(case, waivers=(), waived_out=None):
    """Run ONLY the concurrency pass over one fixture package."""
    return check_concurrency(repo_root=os.path.join(FIX, case),
                             roots=("pkg",), waivers=waivers,
                             waived_out=waived_out)


def _messages(case, **kw):
    return [v.message for v in _check(case, **kw)]


def _run_cli(*argv, cwd=REPO):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, "-m", "tools.fluidlint", *argv],
        cwd=cwd, env=env, capture_output=True, text=True)


# ------------------------------------------------- seeded self-tests
# Each bad fixture is a MUST-FAIL self-test: if the checker ever stops
# seeing these, the pass is broken, not the tree clean.


def test_cross_affinity_detected():
    msgs = _messages("cross_bad")
    assert len(msgs) == 1
    (m,) = msgs
    assert m.startswith("CROSS-AFFINITY:")
    assert "mod.mutate_table" in m and "@loop_only('core')" in m
    # the witness chain names the seed AND the offending caller
    assert "ticker:rebalancer" in m and "mod.tick" in m


def test_cross_affinity_clean_twin_via_seam():
    # same shape, but the ticker crosses through call_soon_threadsafe:
    # the sanctioned seam TRANSFERS context instead of propagating it
    assert _messages("cross_clean") == []


def test_blocking_on_loop_detected():
    msgs = _messages("block_bad")
    assert len(msgs) == 3, msgs
    joined = "\n".join(msgs)
    # a coroutine body that sleeps synchronously
    assert "time.sleep() in mod.poll_loop" in joined
    # a coroutine that dials a @blocking helper (edge check, not entry)
    assert "mod.fan_out calls @blocking mod.dial" in joined
    # a call_soon callback runs ON the loop — its sendall counts
    assert ".sendall() in mod.flush_now" in joined
    assert "call_soon callback in mod.arm" in joined
    # ...but the unseeded helper with no loop context stays silent
    assert "mod.sender" not in joined


def test_blocking_clean_twin_via_executor():
    # the same @blocking dial behind run_in_executor: the handed-off
    # thunk runs in 'executor' context, where blocking is the point
    assert _messages("block_clean") == []


def test_unfenced_shared_state_detected():
    msgs = _messages("unfenced_bad")
    assert len(msgs) == 1
    (m,) = msgs
    assert m.startswith("UNFENCED-SHARED-STATE:")
    assert "Pump.value" in m
    # both writer contexts are named — that's the triage handle
    assert "loop" in m and "thread:pump" in m


def test_unfenced_clean_twin_common_lock():
    # both writers hold self._lock: the common fence clears the group
    assert _messages("unfenced_clean") == []


def test_lock_order_inversions_detected():
    msgs = _messages("lockorder_bad")
    assert len(msgs) == 2, msgs
    joined = "\n".join(msgs)
    # lexical inversion: @holds_lock('journal_lock') body takes the
    # epoch-table flock (rank 0 after rank 3) — the seeded flock case
    assert ("mod.flush_entry acquires 'epoch_table_flock' while "
            "holding 'journal_lock'") in joined
    # call-edge inversion: applier holder calls an epoch-table holder
    assert ("mod.drain_and_record acquires 'epoch_table_flock' while "
            "holding 'applier_lock'") in joined
    # the message teaches the global order
    assert " -> ".join(LOCK_ORDER) in joined


def test_lock_order_clean_twin_ordered():
    assert _messages("lockorder_clean") == []


# -------------------------------------- hard-way bug reconstructions


def test_hardway_donation_on_cpu_bug_is_caught():
    """PR 11's donation bug: with the platform guard gone, dispatch
    block_until_ready()s every wave ON the loop — the checker flags it
    as BLOCKING-ON-LOOP in loop:core context."""
    msgs = [m for m in _messages("hardway")
            if m.startswith("BLOCKING-ON-LOOP:")]
    assert len(msgs) == 1
    (m,) = msgs
    assert ".block_until_ready() in donation.dispatch" in m
    assert "loop:core" in m


def test_hardway_rotation_fence_bug_is_caught():
    """The stage-buffer rotation bug: the staging slot refilled by the
    worker while the loop's ingest writes it, no fence keyed to the
    consuming execution — flagged as UNFENCED-SHARED-STATE."""
    msgs = [m for m in _messages("hardway")
            if m.startswith("UNFENCED-SHARED-STATE:")]
    assert len(msgs) == 1
    (m,) = msgs
    assert "Applier._stage" in m
    assert "ingest (loop)" in m and "recycle (thread:applier)" in m


# -------------------------------------------------- waiver machinery


def test_waiver_suppresses_and_is_reported():
    waiver = ("CROSS-AFFINITY", "mod.tick", "mod.mutate_table",
              "fixture: prove the waiver plumbing")
    waived = []
    assert _check("cross_bad", waivers=(waiver,),
                  waived_out=waived) == []
    assert len(waived) == 1
    # the printed entry carries the justification, not just the match
    assert "prove the waiver plumbing" in waived[0]


def test_stale_waiver_is_itself_a_violation():
    waiver = ("BLOCKING-ON-LOOP", "mod.no_such_function", "",
              "this excuse matches nothing")
    msgs = _messages("cross_clean", waivers=(waiver,))
    assert len(msgs) == 1
    assert "stale waiver" in msgs[0]
    assert "mod.no_such_function" in msgs[0]


def test_lock_rank_matches_order():
    assert tuple(sorted(LOCK_RANK, key=LOCK_RANK.get)) == LOCK_ORDER


# --------------------------------------------------- real-tree gates


def test_real_tree_gates_at_zero_unwaivered():
    """THE tentpole gate: the shipped tree has zero unwaivered
    concurrency findings (and zero stale waivers — stale entries show
    up as violations, so this asserts the waiver table is live too)."""
    waived = []
    violations = check_concurrency(repo_root=REPO, waived_out=waived)
    assert violations == [], "\n".join(v.message for v in violations)
    # every crossing the tree does make is sanctioned WITH an argument
    assert len(waived) >= len(WAIVERS)
    for _rule, _qual, _detail, why in WAIVERS:
        assert any(why in w for w in waived), why


def test_real_tree_without_waivers_shows_the_sanctioned_findings():
    """The waiver table is not decorative: stripped of it, the tree's
    sanctioned crossings surface (the by-design loopback RPC block and
    the in-proc actuation fallback among them)."""
    msgs = [v.message
            for v in check_concurrency(repo_root=REPO, waivers=())]
    assert msgs, "waivers waive nothing — table is dead weight"
    joined = "\n".join(msgs)
    assert "MigrationEngine._rpc_adopt" in joined
    assert "Rebalancer.tick" in joined


# ------------------------------------------------------ CLI surfaces


def test_cli_concurrency_pass_clean_and_prints_waivers():
    r = _run_cli("--pass", "concurrency")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "fluidlint: clean [concurrency]" in r.stdout
    # the text verdict shows WHAT was waived, never just "clean"
    assert "waived concurrency finding(s):" in r.stdout
    assert "loopback" in r.stdout  # a justification made it to stdout


def test_cli_fix_order_prints_lock_table():
    r = _run_cli("--fix-order")
    assert r.returncode == 0
    for i, name in enumerate(LOCK_ORDER):
        assert f"{i}. {name}" in r.stdout
    assert "outermost first" in r.stdout


def test_cli_json_report_shape():
    r = _run_cli("--pass", "concurrency", "--json")
    assert r.returncode == 0, r.stdout + r.stderr
    report = json.loads(r.stdout)
    assert report["clean"] is True
    assert report["passes"] == ["concurrency"]
    assert report["violations"] == []
    assert len(report["waived"]) >= len(WAIVERS)


def test_doctor_folds_lint_report_into_triage(tmp_path):
    """The debug-bundle seam: doctor reads the capturing build's
    ``lint.json`` (written by ``admin bundle`` via ``fluidlint
    --json``) and surfaces a dirty tree as an anomaly — deploying past
    the gate is an incident signal of its own."""
    from tools.doctor import diagnose

    bundle = tmp_path / "bundle"
    bundle.mkdir()
    (bundle / "lint.json").write_text(json.dumps({
        "clean": False, "passes": ["concurrency"],
        "violations": [{"pass": "concurrency", "path": "x.py",
                        "line": 3, "message": "BLOCKING-ON-LOOP: ...",
                        "suggestion": ""}],
        "waived": []}))
    report = diagnose(str(bundle))
    assert report["lint"]["clean"] is False
    assert any("lint [concurrency]" in a and "BLOCKING-ON-LOOP" in a
               for a in report["anomalies"])

    # a clean report raises no anomaly; a bundle without lint.json
    # (captured off-repo) reads as "not captured", never as an error
    (bundle / "lint.json").write_text(json.dumps(
        {"clean": True, "passes": [], "violations": [], "waived": []}))
    report = diagnose(str(bundle))
    assert report["lint"]["clean"] and report["anomalies"] == []
    (bundle / "lint.json").unlink()
    assert diagnose(str(bundle))["lint"] is None


def test_exit_one_contract_on_violations():
    # the ci.sh strict-gate contract: findings mean a nonzero verdict.
    # Drive main() in-process against a seeded fixture (the CLI scans
    # the real package roots, so the fixture rides in via the checker).
    violations = _check("cross_bad")
    assert violations and all(v.pass_name == "concurrency"
                              for v in violations)
    # and the Violation fields the JSON report serializes are populated
    (v,) = violations
    assert v.path.endswith("mod.py") and v.line > 0 and v.suggestion
