"""Pallas VMEM-resident apply vs the XLA scan kernel: field-for-field
parity on fuzzed op streams (and through the existing kernel-vs-oracle
suite, parity with the scalar merge-tree).

Runs in interpreter mode on the CPU test mesh; the TPU path compiles the
real Mosaic kernel (exercised by bench/driver runs).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fluidframework_tpu.ops.apply import apply_ops_batch
from fluidframework_tpu.ops.doc_state import DocState
from fluidframework_tpu.ops.opgen import generate_batch_ops
from fluidframework_tpu.ops.pallas_apply import pallas_apply_ops_batch

FIELDS = ("length", "text_start", "flags", "ins_seq", "ins_client",
          "rem_seq", "rem_client_a", "rem_client_b", "prop_key",
          "prop_val", "count", "overflow")


def _run_pair(seed, D=16, S=64, K=24, **gen):
    rng = np.random.default_rng(seed)
    state = jax.vmap(lambda _: DocState.empty(S))(jnp.arange(D))
    ops = jnp.asarray(generate_batch_ops(rng, D, K, **gen))
    ref = apply_ops_batch(state, ops)
    got = pallas_apply_ops_batch(state, ops, interpret=True)
    return ref, got


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_pallas_matches_xla_scan(seed):
    ref, got = _run_pair(seed, remove_fraction=0.3, annotate_fraction=0.15,
                         max_insert=6)
    for f in FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(got, f)), np.asarray(getattr(ref, f)), f)


def test_pallas_matches_on_annotate_heavy_stream():
    ref, got = _run_pair(9, remove_fraction=0.15, annotate_fraction=0.5,
                         max_insert=4)
    for f in FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(got, f)), np.asarray(getattr(ref, f)), f)


def test_pallas_flags_overflow_identically():
    # tiny slot budget: splits overflow some docs; the flag must match
    ref, got = _run_pair(4, D=8, S=16, K=32, remove_fraction=0.4,
                         annotate_fraction=0.1, max_insert=8)
    assert np.asarray(ref.overflow).any()  # the stream really overflows
    np.testing.assert_array_equal(
        np.asarray(got.overflow), np.asarray(ref.overflow))


def test_applier_with_pallas_dense_step_matches_live_clients():
    """The live TpuDocumentApplier with use_pallas rides the same
    sequenced stream as real clients and converges identically
    (interpret mode on the CPU test mesh)."""
    from fluidframework_tpu.driver import LocalDocumentServiceFactory
    from fluidframework_tpu.loader import Loader
    from fluidframework_tpu.service import LocalServer
    from fluidframework_tpu.service.tpu_applier import (
        TpuDocumentApplier,
        channel_stream,
    )

    server = LocalServer()
    loader = Loader(LocalDocumentServiceFactory(server))
    c1 = loader.resolve("t", "pdoc")
    c2 = loader.resolve("t", "pdoc")
    s1 = c1.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    s1.insert_text(0, "pallas in the loop")
    s2 = c2.runtime.get_data_store("default").get_channel("text")
    s2.insert_text(0, ">> ")
    s1.remove_text(3, 10)
    s1.annotate_range(0, 4, {"bold": True})
    assert s1.get_text() == s2.get_text()

    applier = TpuDocumentApplier(max_docs=8, max_slots=64,
                                 ops_per_dispatch=8, use_pallas=True,
                                 pallas_interpret=True)
    applier.set_replay_source(lambda t, d: [])
    for m in channel_stream(server, "t", "pdoc", "default", "text"):
        applier.ingest("t", "pdoc", m, m.contents)
    applier.finalize()
    assert applier.host_escalations == 0
    assert applier.get_text("t", "pdoc") == s1.get_text()


# ------------------------------------------- kernel / overlap matrix

SEEDS = (0, 7, 42)


def _fuzz_session(seed, doc):
    """Seeded two-client session through the real stack; returns the
    server and the converged oracle text."""
    from fluidframework_tpu.driver import LocalDocumentServiceFactory
    from fluidframework_tpu.loader import Loader
    from fluidframework_tpu.service import LocalServer

    server = LocalServer()
    loader = Loader(LocalDocumentServiceFactory(server))
    c1 = loader.resolve("t", doc)
    c2 = loader.resolve("t", doc)
    s1 = c1.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    s1.insert_text(0, "kernel matrix seed text")
    s2 = c2.runtime.get_data_store("default").get_channel("text")
    rng = np.random.default_rng(seed)
    for _ in range(48):
        s = (s1, s2)[int(rng.integers(0, 2))]
        n = len(s.get_text())
        r = rng.random()
        if n > 4 and r < 0.3:
            a = int(rng.integers(0, n - 1))
            s.remove_text(a, int(rng.integers(a + 1, min(n, a + 5) + 1)))
        elif n > 2 and r < 0.45:
            a = int(rng.integers(0, n - 1))
            s.annotate_range(a, a + 1, {"k": int(rng.integers(0, 4))})
        else:
            s.insert_text(int(rng.integers(0, n + 1)),
                          f"<{rng.integers(0, 99)}>")
    assert s1.get_text() == s2.get_text()
    return server, s1.get_text()


@pytest.fixture(scope="module")
def sessions():
    return {seed: _fuzz_session(seed, f"mx{seed}") for seed in SEEDS}


def _drive_applier(server, doc, **kw):
    from fluidframework_tpu.service.tpu_applier import (
        TpuDocumentApplier,
        channel_stream,
    )

    applier = TpuDocumentApplier(max_docs=16, max_slots=256,
                                 ops_per_dispatch=8, **kw)
    applier.set_replay_source(lambda t, d: [])
    for m in channel_stream(server, "t", doc, "default", "text"):
        applier.ingest("t", doc, m, m.contents)
    applier.finalize()
    assert applier.host_escalations == 0
    return applier


@pytest.mark.parametrize("kernel", ["auto", "xla", "pallas"])
@pytest.mark.parametrize("seed", SEEDS)
def test_applier_kernel_matrix_matches_oracle(sessions, kernel, seed):
    """applier.kernel=auto|pallas|xla all converge to the scalar oracle
    through the real client stack. ``auto`` resolves per backend; a
    forced ``pallas`` compiles the REAL Mosaic kernel, so off-TPU it is
    skipped LOUDLY — never silently green."""
    if kernel == "pallas" and jax.default_backend() != "tpu":
        pytest.skip(
            "applier.kernel=pallas forces the real Mosaic lowering, "
            f"which needs a TPU (backend={jax.default_backend()}); "
            "interpret-mode parity for the same kernel is covered by "
            "the tests above, and this forced lane runs on TPU CI")
    server, want = sessions[seed]
    applier = _drive_applier(server, f"mx{seed}", kernel=kernel)
    assert applier.get_text("t", f"mx{seed}") == want
    want_lane = ("pallas" if kernel == "pallas"
                 or (kernel == "auto" and jax.default_backend() == "tpu")
                 else "xla")
    assert applier.kernel_lane == want_lane


@pytest.mark.parametrize("shards", [0, 2, 4, 8])
@pytest.mark.parametrize("seed", SEEDS)
def test_overlap_on_off_equivalence(sessions, shards, seed):
    """The overlap-staged pipeline (wave N+1 stages on the host while
    wave N executes on device) must be a pure perf change: overlap on
    and off converge identically, locally and across 2/4/8-shard
    meshes, with strict wave order preserved through finalize."""
    server, want = sessions[seed]
    kw = {}
    if shards:
        from fluidframework_tpu.parallel.mesh import make_mesh

        kw["mesh"] = make_mesh(shards, seg_shards=1)
    doc = f"mx{seed}"
    on = _drive_applier(server, doc, overlap=True, **kw)
    off = _drive_applier(server, doc, overlap=False, **kw)
    assert on.get_text("t", doc) == off.get_text("t", doc) == want
    # both lanes really dispatched through the stage/execute split and
    # fed the per-lane stage accounting (the dense lane used to report
    # zero staging cost — the asymmetry this PR fixes)
    for applier in (on, off):
        assert applier.waves_staged == applier.dispatches > 0
        assert applier.stage_seconds > 0
        assert applier.stage_bytes > 0
