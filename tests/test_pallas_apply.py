"""Pallas VMEM-resident apply vs the XLA scan kernel: field-for-field
parity on fuzzed op streams (and through the existing kernel-vs-oracle
suite, parity with the scalar merge-tree).

Runs in interpreter mode on the CPU test mesh; the TPU path compiles the
real Mosaic kernel (exercised by bench/driver runs).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fluidframework_tpu.ops.apply import apply_ops_batch
from fluidframework_tpu.ops.doc_state import DocState
from fluidframework_tpu.ops.opgen import generate_batch_ops
from fluidframework_tpu.ops.pallas_apply import pallas_apply_ops_batch

FIELDS = ("length", "text_start", "flags", "ins_seq", "ins_client",
          "rem_seq", "rem_client_a", "rem_client_b", "prop_key",
          "prop_val", "count", "overflow")


def _run_pair(seed, D=16, S=64, K=24, **gen):
    rng = np.random.default_rng(seed)
    state = jax.vmap(lambda _: DocState.empty(S))(jnp.arange(D))
    ops = jnp.asarray(generate_batch_ops(rng, D, K, **gen))
    ref = apply_ops_batch(state, ops)
    got = pallas_apply_ops_batch(state, ops, interpret=True)
    return ref, got


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_pallas_matches_xla_scan(seed):
    ref, got = _run_pair(seed, remove_fraction=0.3, annotate_fraction=0.15,
                         max_insert=6)
    for f in FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(got, f)), np.asarray(getattr(ref, f)), f)


def test_pallas_matches_on_annotate_heavy_stream():
    ref, got = _run_pair(9, remove_fraction=0.15, annotate_fraction=0.5,
                         max_insert=4)
    for f in FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(got, f)), np.asarray(getattr(ref, f)), f)


def test_pallas_flags_overflow_identically():
    # tiny slot budget: splits overflow some docs; the flag must match
    ref, got = _run_pair(4, D=8, S=16, K=32, remove_fraction=0.4,
                         annotate_fraction=0.1, max_insert=8)
    assert np.asarray(ref.overflow).any()  # the stream really overflows
    np.testing.assert_array_equal(
        np.asarray(got.overflow), np.asarray(ref.overflow))


def test_applier_with_pallas_dense_step_matches_live_clients():
    """The live TpuDocumentApplier with use_pallas rides the same
    sequenced stream as real clients and converges identically
    (interpret mode on the CPU test mesh)."""
    from fluidframework_tpu.driver import LocalDocumentServiceFactory
    from fluidframework_tpu.loader import Loader
    from fluidframework_tpu.service import LocalServer
    from fluidframework_tpu.service.tpu_applier import (
        TpuDocumentApplier,
        channel_stream,
    )

    server = LocalServer()
    loader = Loader(LocalDocumentServiceFactory(server))
    c1 = loader.resolve("t", "pdoc")
    c2 = loader.resolve("t", "pdoc")
    s1 = c1.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    s1.insert_text(0, "pallas in the loop")
    s2 = c2.runtime.get_data_store("default").get_channel("text")
    s2.insert_text(0, ">> ")
    s1.remove_text(3, 10)
    s1.annotate_range(0, 4, {"bold": True})
    assert s1.get_text() == s2.get_text()

    applier = TpuDocumentApplier(max_docs=8, max_slots=64,
                                 ops_per_dispatch=8, use_pallas=True,
                                 pallas_interpret=True)
    applier.set_replay_source(lambda t, d: [])
    for m in channel_stream(server, "t", "pdoc", "default", "text"):
        applier.ingest("t", "pdoc", m, m.contents)
    applier.finalize()
    assert applier.host_escalations == 0
    assert applier.get_text("t", "pdoc") == s1.get_text()
