"""Pallas VMEM-resident apply vs the XLA scan kernel: field-for-field
parity on fuzzed op streams (and through the existing kernel-vs-oracle
suite, parity with the scalar merge-tree).

Runs in interpreter mode on the CPU test mesh; the TPU path compiles the
real Mosaic kernel (exercised by bench/driver runs).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fluidframework_tpu.ops.apply import apply_ops_batch
from fluidframework_tpu.ops.doc_state import DocState
from fluidframework_tpu.ops.opgen import generate_batch_ops
from fluidframework_tpu.ops.pallas_apply import pallas_apply_ops_batch

FIELDS = ("length", "text_start", "flags", "ins_seq", "ins_client",
          "rem_seq", "rem_client_a", "rem_client_b", "prop_key",
          "prop_val", "count", "overflow")


def _run_pair(seed, D=16, S=64, K=24, **gen):
    rng = np.random.default_rng(seed)
    state = jax.vmap(lambda _: DocState.empty(S))(jnp.arange(D))
    ops = jnp.asarray(generate_batch_ops(rng, D, K, **gen))
    ref = apply_ops_batch(state, ops)
    got = pallas_apply_ops_batch(state, ops, interpret=True)
    return ref, got


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_pallas_matches_xla_scan(seed):
    ref, got = _run_pair(seed, remove_fraction=0.3, annotate_fraction=0.15,
                         max_insert=6)
    for f in FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(got, f)), np.asarray(getattr(ref, f)), f)


def test_pallas_matches_on_annotate_heavy_stream():
    ref, got = _run_pair(9, remove_fraction=0.15, annotate_fraction=0.5,
                         max_insert=4)
    for f in FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(got, f)), np.asarray(getattr(ref, f)), f)


def test_pallas_flags_overflow_identically():
    # tiny slot budget: splits overflow some docs; the flag must match
    ref, got = _run_pair(4, D=8, S=16, K=32, remove_fraction=0.4,
                         annotate_fraction=0.1, max_insert=8)
    assert np.asarray(ref.overflow).any()  # the stream really overflows
    np.testing.assert_array_equal(
        np.asarray(got.overflow), np.asarray(ref.overflow))
