"""Columnar ingress: codec equivalence, fallback nacks, splice stamping.

The columnar fast path (protocol/binwire.py FT_COLS_*) must be an
optimization, not a semantic fork: ``encode_submit_columns`` /
``decode_submit_columns`` round-trip to exactly the DocumentMessages the
rec-frame codec carries; every bulk-admission miss (unjoined client,
clientSeq gap, oversize op) lands the identical nacks the scalar door
produces; and the broadcast frame contains the ingress column bytes
VERBATIM (the deli stamp is a splice, not a re-encode).
"""

from __future__ import annotations

import json
import random
import socket
import time

import pytest

from fluidframework_tpu.driver import NetworkDocumentServiceFactory
from fluidframework_tpu.protocol import binwire
from fluidframework_tpu.protocol.messages import (
    DocumentMessage,
    MessageType,
    TraceHop,
)
from fluidframework_tpu.service import LocalServer, NetworkFrontEnd
from fluidframework_tpu.service.core import QueuedMessage
from fluidframework_tpu.service.array_batch import ArrayBoxcar
from fluidframework_tpu.service.deli import DeliLambda, RawMessage


def wait_for(pred, timeout=10.0, interval=0.005):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if pred():
            return True
        time.sleep(interval)
    return bool(pred())


def _chanop(op):
    return {"kind": "chanop", "address": "default",
            "contents": {"address": "text", "contents": op}}


_POOL = ["a", "bc", "déf", "ghij", "héllo", "жopб", "x" * 40]


def _rand_cols_ops(rng: random.Random, n: int, cseq0: int = 1) -> list:
    """n random columnar-eligible ops on one channel."""
    ops = []
    rseq = rng.randrange(100)
    for i in range(n):
        r = rng.random()
        if r < 0.5:
            op = {"type": 0, "pos": rng.randrange(10_000),
                  "text": rng.choice(_POOL)}
        elif r < 0.8:
            a = rng.randrange(10_000)
            op = {"type": 1, "start": a, "end": a + 1 + rng.randrange(40)}
        else:
            a = rng.randrange(10_000)
            op = {"type": 2, "start": a, "end": a + 2,
                  "props": {"k": rng.randrange(4), "s": rng.choice(_POOL)}}
        rseq += rng.randrange(3)
        ops.append(DocumentMessage(
            client_sequence_number=cseq0 + i,
            reference_sequence_number=rseq,
            type=MessageType.OPERATION, contents=_chanop(op)))
    return ops


def test_cols_roundtrip_equivalence_fuzz():
    """decode(encode_submit_columns(ops)) materializes exactly the ops the
    rec-frame codec round-trips — field-for-field."""
    rng = random.Random(21)
    for trial in range(50):
        ops = _rand_cols_ops(rng, rng.randrange(1, 40))
        body = binwire.encode_submit_columns(ops)
        assert body is not None
        assert binwire.is_binary(body)
        sid, sc = binwire.decode_submit_columns(body)
        assert sid is None
        assert binwire.cols_to_ops(sc) == ops
        # the rec-frame door carries the same messages
        _, rec = binwire.decode_submit(binwire.encode_submit(ops))
        assert rec == ops


def test_cols_fsubmit_relay_equivalence():
    """The gateway's 6-byte prepend relay equals direct sid encoding and
    survives decode — same contract as the rec-frame family."""
    rng = random.Random(22)
    ops = _rand_cols_ops(rng, 12)
    plain = binwire.encode_submit_columns(ops)
    direct = binwire.encode_submit_columns(ops, sid=777)
    assert binwire.submit_to_fsubmit(plain, 777) == direct
    sid, sc = binwire.decode_submit_columns(direct)
    assert sid == 777
    assert binwire.cols_to_ops(sc) == ops


def test_non_columnable_shapes_return_none():
    """Every ineligible shape falls back (None) instead of mis-encoding."""
    ok = _rand_cols_ops(random.Random(23), 3)
    assert binwire.encode_submit_columns(ok) is not None

    def variant(mutate):
        ops = _rand_cols_ops(random.Random(23), 3)
        mutate(ops)
        return binwire.encode_submit_columns(ops)

    assert variant(lambda o: setattr(o[1], "metadata", {"batch": True})) \
        is None
    assert variant(lambda o: o[1].traces.append(
        TraceHop(service="client", action="submit", timestamp=1.0))) is None
    assert variant(lambda o: setattr(o[1], "type", MessageType.NOOP)) is None
    assert variant(lambda o: setattr(o[1], "contents",
                                     {"kind": "attach", "blob": "x"})) is None
    # second channel in the boxcar → not a single-channel column frame
    assert variant(lambda o: o[1].contents["contents"].__setitem__(
        "address", "other")) is None
    # marker insert (extra key) and out-of-range int
    assert variant(lambda o: o[1].contents["contents"]["contents"].update(
        {"type": 0, "pos": 1, "text": "t", "marker": True})) is None
    assert variant(lambda o: o[1].contents["contents"].__setitem__(
        "contents", {"type": 0, "pos": 2**31, "text": "t"})) is None


def test_stamp_is_verbatim_splice_and_decodes():
    """stamp_cols_ops must contain the ingress column bytes unmodified,
    and the stamped frame must decode/scan to the sequenced stream."""
    rng = random.Random(24)
    ops = _rand_cols_ops(rng, 9)
    body = binwire.encode_submit_columns(ops)
    _, sc = binwire.decode_submit_columns(body)
    msns = list(range(92, 92 + 9))
    stamped = binwire.stamp_cols_ops(sc.cols, "client-7", 100, msns, 1234.5)
    assert sc.cols in stamped  # the splice invariant
    topic, out = binwire.decode_cols_ops(stamped)
    assert topic is None
    assert [m.contents for m in out] == [m.contents for m in ops]
    assert [m.sequence_number for m in out] == list(range(100, 109))
    assert [m.minimum_sequence_number for m in out] == msns
    assert all(m.client_id == "client-7" and m.timestamp == 1234.5
               and m.type is MessageType.OPERATION for m in out)
    # scan_ops agrees without materializing, and yields the stamp
    # timestamp as every record's deli time
    for m, (cid, seq, cseq, deli_ts, delta) in zip(
            out, binwire.scan_ops(stamped)):
        assert (cid, seq, cseq, deli_ts) == (
            "client-7", m.sequence_number, m.client_sequence_number, 1234.5)
        op = m.contents["contents"]["contents"]
        if op["type"] == 0:
            assert delta == len(op["text"])
        elif op["type"] == 1:
            assert delta == op["start"] - op["end"]
        else:
            assert delta == 0
    # fops twin strips back to the identical ops frame
    fops = binwire.stamp_cols_ops(sc.cols, "client-7", 100, msns, 1234.5,
                                  topic="op/t/doc")
    t, stripped = binwire.fops_strip_topic(fops)
    assert t == "op/t/doc" and stripped == stamped


class _Capture:
    def __init__(self):
        self.sequenced = []
        self.nacks = []

    def send(self, msg):
        self.sequenced.append(msg)

    def send_batch(self, batch):
        if isinstance(batch, list):
            self.sequenced.extend(batch)
        else:
            self.sequenced.extend(batch.messages())

    def nack(self, client_id, nack):
        self.nacks.append((client_id, nack))


def _cols_boxcar(ops) -> ArrayBoxcar:
    """An ArrayBoxcar exactly as the columnar ingress door builds it."""
    _, sc = binwire.decode_submit_columns(binwire.encode_submit_columns(ops))
    return ArrayBoxcar(
        tenant_id="t", document_id="d", client_id="",
        ds_id=sc.ds_id, channel_id=sc.channel_id, kind=sc.kind,
        a=sc.a, b=sc.b, cseq=sc.cseq, rseq=sc.rseq,
        text=sc.text, text_off=sc.text_off, props=sc.props,
        wire_cols=sc.cols)


def test_bulk_admission_misses_nack_like_scalar():
    """Unjoined client and clientSeq gap through the columnar-built
    ArrayBoxcar produce the identical sequenced stream + nacks the
    scalar lane produces for the same ops."""
    rng = random.Random(25)
    join = RawMessage("t", "d", None, DocumentMessage(
        -1, -1, MessageType.CLIENT_JOIN, {"clientId": "a"}), 1000.0)
    good = _rand_cols_ops(rng, 4, cseq0=1)
    gap = _rand_cols_ops(rng, 3, cseq0=9)       # expected 5, got 9
    ghost = _rand_cols_ops(rng, 2, cseq0=1)     # never joined

    def feed(cap, columnar: bool):
        deli = DeliLambda("t", "d", send_sequenced=cap.send,
                          send_nack=cap.nack, clock=lambda: 1000.0,
                          send_sequenced_batch=cap.send_batch)
        records = [join]
        for cid, ops in (("a", good), ("a", gap), ("ghost", ghost)):
            if columnar:
                box = _cols_boxcar(ops)
                box.client_id = cid
                box.timestamp = 1001.0
                records.append(box)
            else:
                records.extend(RawMessage("t", "d", cid, op, 1001.0)
                               for op in ops)
        for off, rec in enumerate(records):
            deli.handler(QueuedMessage(off + 1, "raw", 0, rec))
        return deli

    cap_c, cap_s = _Capture(), _Capture()
    deli = feed(cap_c, columnar=True)
    feed(cap_s, columnar=False)
    assert deli.boxcars_fast == 1        # the good boxcar rode the lane
    assert deli.boxcars_fallback == 2    # gap + ghost fell back
    key = lambda m: (m.client_id, m.sequence_number,
                     m.minimum_sequence_number, m.client_sequence_number,
                     m.reference_sequence_number, m.type, repr(m.contents))
    assert [key(m) for m in cap_c.sequenced] \
        == [key(m) for m in cap_s.sequenced]
    assert [(c, n.code, n.type, n.message) for c, n in cap_c.nacks] \
        == [(c, n.code, n.type, n.message) for c, n in cap_s.nacks]
    assert cap_c.nacks  # the misses really nacked


@pytest.fixture
def front_end():
    fe = NetworkFrontEnd(LocalServer()).start_background()
    yield fe
    fe.stop()


def test_oversize_nack_identical_through_either_door(front_end, monkeypatch):
    """An over-limit op in a columnar frame nacks exactly like the same
    op through the rec-frame door (shared _filter_oversized)."""
    factory = NetworkDocumentServiceFactory("127.0.0.1", front_end.port)
    big = DocumentMessage(
        client_sequence_number=1, reference_sequence_number=0,
        type=MessageType.OPERATION,
        contents=_chanop({"type": 0, "pos": 0, "text": "x" * 20_000}))

    def drive(doc):
        conn = factory.create_document_service(
            "t", doc).connect_to_delta_stream()
        nacks = []
        conn.on_nack = nacks.append
        conn.submit([big])
        assert wait_for(lambda: nacks)
        conn.close()
        return nacks[0]

    n_cols = drive("doc-cols")
    srv = front_end.counters.snapshot()
    assert srv.get("net.ingress.fallback", 0) >= 1  # failed the fast bound
    monkeypatch.setattr(binwire, "encode_submit_columns",
                        lambda ops, sid=None: None)
    n_rec = drive("doc-rec")
    assert (n_cols.code, n_cols.type, n_cols.message) \
        == (n_rec.code, n_rec.type, n_rec.message)
    assert n_cols.code == 413
    assert n_cols.operation.client_sequence_number \
        == n_rec.operation.client_sequence_number == 1


def _frame(obj: dict) -> bytes:
    body = json.dumps(obj, separators=(",", ":")).encode()
    return len(body).to_bytes(4, "big") + body


def test_stamped_splice_reaches_subscribers_through_fanout(front_end):
    """A columnar submit's column bytes appear VERBATIM inside the
    binwire broadcast every subscriber receives, and the second
    subscriber is served from the encode-once cache."""
    ops = _rand_cols_ops(random.Random(26), 8)
    body = binwire.encode_submit_columns(ops)
    _, sc = binwire.decode_submit_columns(body)

    def connect(doc):
        s = socket.create_connection(("127.0.0.1", front_end.port),
                                     timeout=10)
        s.sendall(_frame({"t": "connect", "tenant": "t", "doc": doc,
                          "rid": 1, "bin": 1}))
        return s

    s1, s2 = connect("doc"), connect("doc")
    bufs = {s1: b"", s2: b""}

    def read_frame(s):
        while True:
            buf = bufs[s]
            if len(buf) >= 4:
                n = int.from_bytes(buf[:4], "big")
                if len(buf) >= 4 + n:
                    bufs[s] = buf[4 + n:]
                    return buf[4:4 + n]
            chunk = s.recv(65536)
            if not chunk:
                raise ConnectionError("closed")
            bufs[s] += chunk

    for s in (s1, s2):  # drain the connect reply (JSON)
        while binwire.is_binary(read_frame(s)):
            pass
    s1.sendall(binwire.frame(body))

    def next_cols(s):
        while True:
            f = read_frame(s)
            if binwire.is_binary(f) and f[1] in (binwire.FT_COLS_OPS,
                                                 binwire.FT_COLS_FOPS):
                return f

    b1, b2 = next_cols(s1), next_cols(s2)
    assert b1 == b2                 # encode-once: both got the same bytes
    assert sc.cols in b1            # the submit's columns, unmodified
    _, msgs = binwire.decode_ops(b1)
    assert [m.contents for m in msgs] == [m.contents for m in ops]
    assert [m.client_sequence_number for m in msgs] \
        == [m.client_sequence_number for m in ops]
    assert [m.sequence_number for m in msgs] \
        == list(range(msgs[0].sequence_number,
                      msgs[0].sequence_number + len(ops)))
    # the broadcast bytes can reach the sockets before the server loop
    # executes its post-batch counter increments — poll, don't snapshot
    snap = front_end.counters.snapshot
    assert wait_for(lambda: snap().get("net.ingress.columnar", 0) >= 1)
    assert wait_for(lambda: snap().get("net.fanout.cache_hits", 0) >= 1)
    s1.close()
    s2.close()
