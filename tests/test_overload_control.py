"""Overload control loop: windowed series, SLO engine, admission gate,
and the driver's transparent shed-retry lane end to end.

Ref: server/routerlicious throttling middleware (Alfred's per-tenant
throttler) is the analog; our admission decision lives in
service/admission.py and the closed loop is ours (ARCHITECTURE.md
"Overload control").
"""

import time

import pytest

from fluidframework_tpu.obs.metrics import (
    MetricsRegistry,
    WindowedSeries,
    _Series,
    parse_prometheus,
)
from fluidframework_tpu.obs.slo import (
    STATE_OK,
    STATE_VIOLATED,
    STATE_WARN,
    SloEngine,
    SloSpec,
    parse_slo_spec,
)
from fluidframework_tpu.service.admission import (
    RETRY_AFTER_MAX_MS,
    RETRY_AFTER_MIN_MS,
    AdmissionController,
    TokenBucket,
    retry_after_ms,
)


def wait_for(pred, timeout: float = 20.0) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.005)
    return bool(pred())


# ------------------------------------------------------ windowed series


def test_windowed_series_rotation_and_expiry():
    """A bucket's epoch going stale resets it in place (lazy rotation);
    reads merge only buckets still inside the window."""
    ws = WindowedSeries(window_s=10.0, buckets=10)  # 1s buckets
    ws.observe(1.0, now=100.0)
    ws.observe(2.0, now=100.5)  # same bucket
    ws.observe(3.0, now=105.0)
    count, merged = ws.stats(now=105.0)
    assert count == 3 and sorted(merged) == [1.0, 2.0, 3.0]
    # 11s later the epoch-100 bucket is outside the window
    count, merged = ws.stats(now=111.0)
    assert count == 1 and merged == [3.0]
    # writing into the recycled slot resets it rather than accumulating
    ws.observe(9.0, now=110.0)  # epoch 110 -> slot 0, was epoch 100
    count, merged = ws.stats(now=110.9)
    assert count == 2 and sorted(merged) == [3.0, 9.0]
    # a narrower read window trims to the trailing seconds
    count, merged = ws.stats(now=110.9, window_s=1.0)
    assert count == 1 and merged == [9.0]


def test_windowed_series_reservoir_keeps_true_count():
    ws = WindowedSeries(window_s=10.0, buckets=10, max_per_bucket=16)
    for i in range(1000):
        ws.observe(float(i), now=200.0)
    count, merged = ws.stats(now=200.0)
    assert count == 1000 and len(merged) == 16
    # reservoir samples the whole stream, not just the first 16
    assert max(merged) > 15.0


def test_windowed_quantile_empty_is_zero():
    ws = WindowedSeries()
    assert ws.quantile(0.99, now=5.0) == 0.0


def test_window_stats_label_subset_merges_tenants():
    """A pair-only filter merges every tenant's series of that pair —
    the SLO engine's untenanted specs read the whole pair."""
    reg = MetricsRegistry()
    reg.observe_windowed("obs.hop.window_ms", 5.0, now=50.0,
                         pair="submit_to_admit", tenant="a")
    reg.observe_windowed("obs.hop.window_ms", 7.0, now=50.0,
                         pair="submit_to_admit", tenant="b")
    reg.observe_windowed("obs.hop.window_ms", 9.0, now=50.0,
                         pair="admit_to_deli")
    count, q = reg.window_stats("obs.hop.window_ms", now=50.0,
                                pair="submit_to_admit")
    assert count == 2 and q[0.99] == 7.0
    count, q = reg.window_stats("obs.hop.window_ms", now=50.0,
                                pair="submit_to_admit", tenant="a")
    assert count == 1 and q[0.99] == 5.0
    # windowed series render into the scrape as summary families
    series = parse_prometheus(reg.scrape())
    assert (("pair", "submit_to_admit"), ("tenant", "a")) in \
        series["fluid_obs_hop_window_ms_count"]


def test_series_reservoir_admits_late_samples():
    """Past the sample cap the reservoir keeps replacing — lifetime
    quantiles represent the whole stream, not the first 4096 values."""
    s = _Series()
    for _ in range(4096):
        s.add(0.0)
    for _ in range(4096):
        s.add(1.0)
    assert s.count == 8192 and len(s.samples) == 4096
    late = sum(1 for v in s.samples if v == 1.0)
    # uniform reservoir: expect ~half; anything >0 proves replacement,
    # the wide band keeps the (seeded, deterministic) check honest
    assert 1000 < late < 3000


# -------------------------------------------------- prometheus escaping


def test_prometheus_hostile_label_roundtrip():
    reg = MetricsRegistry()
    evil = 'ten"ant\\with\nnewline'
    reg.inc("net.admission.shed", 4, tenant=evil, reason="rate")
    text = reg.scrape()
    assert "\n\n" not in text  # the raw newline did not split the line
    series = parse_prometheus(text)
    key = (("reason", "rate"), ("tenant", evil))
    assert series["fluid_net_admission_shed"][key] == 4


# ------------------------------------------------------------ slo specs


def test_parse_slo_spec_forms():
    s = parse_slo_spec("ingest=submit_to_admit:25:5:3")
    assert (s.name, s.pair, s.p99_budget_ms, s.window_s, s.burn_ticks) \
        == ("ingest", "submit_to_admit", 25.0, 5.0, 3)
    assert s.tenant is None
    t = parse_slo_spec("vip=submit_to_admit@acme:10")
    assert t.tenant == "acme" and t.window_s == 10.0 and t.burn_ticks == 2
    for bad in ("noequals", "a=pair", "a=pair:NaNish:x"):
        with pytest.raises(ValueError):
            parse_slo_spec(bad)


def test_slo_state_machine_frozen_clock(tmp_path):
    """ok -> warn on the first over-budget tick, violated after
    burn_ticks consecutive, back to ok on recovery; the violations
    counter and flight dump fire only on the ok->violated transition."""
    from fluidframework_tpu.obs import FlightRecorder

    reg = MetricsRegistry()
    rec = FlightRecorder(dump_dir=str(tmp_path))
    spec = SloSpec(name="ingest", pair="submit_to_admit",
                   p99_budget_ms=10.0, window_s=10.0, burn_ticks=2,
                   min_count=2)
    eng = SloEngine([spec], registry=reg, recorder=rec)

    def tick(now):
        eng.evaluate(now=now)
        return eng._state["ingest"]

    # under min_count: one hot sample is noise
    reg.observe_windowed("obs.hop.window_ms", 500.0, now=100.0,
                         pair="submit_to_admit")
    assert tick(100.0) == STATE_OK and not eng.shed_signal
    # sustained burn: warn, then violated
    reg.observe_windowed("obs.hop.window_ms", 400.0, now=100.2,
                         pair="submit_to_admit")
    assert tick(100.5) == STATE_WARN and not eng.shed_signal
    assert tick(101.0) == STATE_VIOLATED
    assert eng.shed_signal and "submit_to_admit" in eng.violated_pairs
    # staying violated does not re-count or re-dump
    assert tick(101.5) == STATE_VIOLATED
    series = parse_prometheus(reg.scrape())
    assert series["fluid_obs_slo_violations"][(("slo", "ingest"),)] == 1
    assert series["fluid_obs_slo_state"][(("slo", "ingest"),)] == 2
    assert rec.last_dump is not None
    # recovery: the window drains 11s later
    assert tick(112.0) == STATE_OK
    assert not eng.shed_signal and not eng.violated_pairs
    row = eng.status()[0]
    assert row["state"] == "ok" and row["burn"] == 0


# --------------------------------------------------------- token bucket


def test_token_bucket_deterministic_refill():
    b = TokenBucket(rate=10.0, burst=20.0)
    assert b.take(20, now=0.0) == 0.0          # full burst affordable
    assert b.take(5, now=0.0) == 0.5           # 5 short at 10/s
    assert b.tokens == 0.0                     # failed take leaves tokens
    assert b.take(5, now=0.5) == 0.0           # refilled exactly 5
    assert b.take(1, now=0.5) == pytest.approx(0.1)
    b2 = TokenBucket(rate=10.0, burst=20.0)
    assert b2.take(20, now=1000.0) == 0.0      # start time irrelevant
    assert b2.take(3, now=1000.2) == pytest.approx(0.1)


def test_token_bucket_oversize_admits_when_full():
    """A boxcar larger than burst admits once the bucket is FULL, going
    negative (refill pays the debt) — refusing it outright would
    livelock the driver's coalesced shed-backlog resubmit forever."""
    b = TokenBucket(rate=100.0, burst=50.0)
    assert b.take(500, now=0.0) == 0.0
    assert b.tokens == -450.0
    # in debt: even one token is refused until the refill catches up
    assert b.take(1, now=1.0) > 0.0            # tokens = -350
    assert b.take(1, now=5.0) == 0.0           # refilled to burst cap
    # partially full is NOT full: the oversize rule needs tokens==burst
    b3 = TokenBucket(rate=100.0, burst=50.0)
    b3.take(20, now=0.0)
    wait = b3.take(500, now=0.0)
    assert wait == pytest.approx(470 / 100.0)


def test_retry_after_clamp():
    assert retry_after_ms(0.001) == RETRY_AFTER_MIN_MS
    assert retry_after_ms(0.4) == 400
    assert retry_after_ms(99.0) == RETRY_AFTER_MAX_MS


# ------------------------------------------------- admission controller


class _FakeConn:
    def __init__(self, tenant):
        self.tenant_id = tenant


class _FakeEngine:
    def __init__(self):
        self.shed_signal = False


def test_admission_soft_admit_vs_shed():
    """Depletion alone only soft-admits (accounting, not refusal);
    depletion DURING an SLO burn sheds with a bounded retry-after."""
    reg = MetricsRegistry()
    eng = _FakeEngine()
    adm = AdmissionController(lambda t: (100.0, 10.0), registry=reg)
    adm.engine = eng
    conn = _FakeConn("acme")
    assert adm.check(conn, 10, 1, now=0.0) == 0.0
    # depleted + healthy SLOs: admitted anyway, overage accounted
    assert adm.check(conn, 10, 11, now=0.0) == 0.0
    series = parse_prometheus(reg.scrape())
    assert series["fluid_net_admission_delayed"][(("tenant", "acme"),)] \
        == 10
    # depleted + burning: the whole boxcar sheds
    eng.shed_signal = True
    wait = adm.check(conn, 10, 21, now=0.0)
    assert wait > 0.0
    series = parse_prometheus(reg.scrape())
    key = (("reason", "rate"), ("tenant", "acme"))
    assert series["fluid_net_admission_shed"][key] == 10
    # master switch off (bench control arm): back to soft-admit
    adm.shedding = False
    conn2 = _FakeConn("acme")
    assert adm.check(conn2, 999, 1, now=100.0) == 0.0


def test_admission_unlimited_tenant_never_gated():
    adm = AdmissionController(lambda t: None, registry=MetricsRegistry())
    adm.engine = _FakeEngine()
    adm.engine.shed_signal = True
    assert adm.check(_FakeConn("free"), 10 ** 6, 1, now=0.0) == 0.0


def test_admission_ordering_watermark():
    """Once cseq N shed, later cseqs shed too (reason=ordering) until
    the client rewinds to N — admitting them would gap clientSeq at
    deli."""
    reg = MetricsRegistry()
    eng = _FakeEngine()
    eng.shed_signal = True
    adm = AdmissionController(lambda t: (100.0, 10.0), registry=reg)
    adm.engine = eng
    conn = _FakeConn("acme")
    assert adm.check(conn, 10, 1, now=0.0) == 0.0   # burst spent
    assert adm.check(conn, 5, 11, now=0.0) > 0.0    # shed; resume=11
    assert conn._shed_resume == 11
    # ops behind the watermark shed regardless of bucket state
    assert adm.check(conn, 5, 16, now=50.0) > 0.0
    series = parse_prometheus(reg.scrape())
    key = (("reason", "ordering"), ("tenant", "acme"))
    assert series["fluid_net_admission_shed"][key] == 5
    # the rewind (resubmit from cseq 11) clears the watermark and,
    # with the bucket refilled, admits
    assert adm.check(conn, 10, 11, now=50.0) == 0.0
    assert conn._shed_resume is None


# --------------------------------------------- shed/backoff end to end


@pytest.mark.parametrize("lane", ["columnar", "rec"])
def test_shed_retry_contract_end_to_end(lane):
    """A rated tenant overruns its bucket during an armed SLO burn: the
    server sheds with retry_after_ms, the driver transparently backs
    off and resubmits, and EVERY op is eventually acked with its
    payload intact — no app-visible nack, on both wire lanes."""
    from fluidframework_tpu.driver.network import (
        NetworkDocumentServiceFactory,
    )
    from fluidframework_tpu.protocol.messages import (
        DocumentMessage,
        MessageType,
        TraceHop,
    )
    from fluidframework_tpu.service.front_end import NetworkFrontEnd
    from fluidframework_tpu.service.local_server import LocalServer
    from fluidframework_tpu.service.tenants import TenantManager

    tm = TenantManager()
    tm.set_rate("t", 50.0, burst=50.0)
    front = NetworkFrontEnd(LocalServer(tenants=tm)).start_background()
    engine = SloEngine([SloSpec(
        name="trigger", pair="submit_to_admit", p99_budget_ms=0.0,
        burn_ticks=1, min_count=1)])
    front.attach_slo(engine)
    factory = NetworkDocumentServiceFactory("127.0.0.1", front.port)
    try:
        conn = factory.create_document_service(
            "t", f"shed-{lane}").connect_to_delta_stream()
        conn.trace_sample_n = 1
        acked = {}
        hard = []
        conn.on_op = lambda m: (
            m.client_id == conn.client_id
            and acked.__setitem__(m.client_sequence_number, m))
        conn.on_nack = lambda m: hard.append(m)

        def op(cseq):
            if lane == "columnar":
                contents = {"kind": "chanop", "address": "default",
                            "contents": {"address": "text",
                                         "contents": {"type": 0, "pos": 0,
                                                      "text": "x"}}}
                traces = []
            else:
                contents = {"free": "form", "cseq": cseq}
                # rec lane: the client stamp rides a TraceHop record
                traces = [TraceHop("client", "submit", time.time())]
            return DocumentMessage(
                client_sequence_number=cseq,
                reference_sequence_number=0,
                type=MessageType.OPERATION, contents=contents,
                traces=traces)

        # prime inside the budget, then arm the hair-trigger SLO
        conn.submit([op(c) for c in (1, 2)])
        assert wait_for(lambda: len(acked) == 2)
        engine.evaluate()
        assert engine.shed_signal
        # overrun: burst is long spent, so this boxcar sheds
        conn.submit([op(c) for c in range(3, 103)])
        snap = factory.counters.snapshot
        assert wait_for(
            lambda: snap().get("driver.submit.shed_retries", 0) > 0)
        # ...and the retry lane converges without clearing the burn
        # (bucket refill + full-bucket oversize admission)
        assert wait_for(lambda: len(acked) == 102, timeout=30.0)
        assert not hard, f"hard nack leaked: {hard[0]}"
        if lane == "rec":
            assert acked[50].contents == {"free": "form", "cseq": 50}
        else:
            assert acked[50].contents["contents"]["address"] == "text"
        from fluidframework_tpu.obs import get_registry

        shed = parse_prometheus(get_registry().scrape()).get(
            "fluid_net_admission_shed", {})
        assert sum(shed.values()) > 0
        conn.close()
    finally:
        engine.stop()
        front.stop()
