"""Tenancy/auth at the front door (riddler role) + the unified config
registry (SURVEY §5.6).

Ref: routerlicious/src/riddler/tenantManager.ts,
protocol-definitions/src/tokens.ts (ITokenClaims JWT),
server config.json nconf layering.
"""

import subprocess
import sys
import time

import pytest

from fluidframework_tpu.config import Config
from fluidframework_tpu.service import LocalServer
from fluidframework_tpu.service.tenants import (
    AuthError,
    SCOPE_READ,
    TenantManager,
    sign_token,
)


# ------------------------------------------------------------------ tokens

def test_valid_token_accepted_and_claims_returned():
    tm = TenantManager()
    tm.register("acme", "s3cret")
    token = sign_token("acme", "doc1", "s3cret", user={"id": "u7"})
    claims = tm.validate(token, "acme", "doc1")
    assert claims["user"]["id"] == "u7"


@pytest.mark.parametrize("case", [
    "wrong_secret", "wrong_tenant", "wrong_doc", "expired", "missing",
    "malformed", "scope", "unknown_tenant",
])
def test_invalid_tokens_rejected(case):
    tm = TenantManager()
    tm.register("acme", "s3cret")
    token = {
        "wrong_secret": lambda: sign_token("acme", "doc1", "WRONG"),
        "wrong_tenant": lambda: sign_token("evil", "doc1", "s3cret"),
        "wrong_doc": lambda: sign_token("acme", "other", "s3cret"),
        "expired": lambda: sign_token("acme", "doc1", "s3cret",
                                      lifetime_s=-10),
        "missing": lambda: None,
        "malformed": lambda: "not.a.token",
        "scope": lambda: sign_token("acme", "doc1", "s3cret",
                                    scopes=(SCOPE_READ,)),
        "unknown_tenant": lambda: sign_token("nobody", "doc1", "x"),
    }[case]()
    tenant = "nobody" if case == "unknown_tenant" else "acme"
    with pytest.raises(AuthError):
        tm.validate(token, tenant, "doc1")


def test_empty_registry_is_open_dev_mode():
    tm = TenantManager()
    assert tm.validate(None, "any", "doc")["scopes"]


def test_server_connect_enforces_tokens():
    tm = TenantManager()
    tm.register("acme", "s3cret")
    server = LocalServer(tenants=tm)
    with pytest.raises(AuthError):
        server.connect("acme", "doc")
    conn = server.connect("acme", "doc",
                          token=sign_token("acme", "doc", "s3cret"))
    assert conn.client_id


def test_invalid_token_rejected_over_the_wire():
    """Cross-process: a front end started with --tenant refuses a bad
    token at connect and admits a signed one."""
    from fluidframework_tpu.driver.network import (
        NetworkDocumentServiceFactory,
    )

    proc = subprocess.Popen(
        [sys.executable, "-m", "fluidframework_tpu.service.front_end",
         "--port", "0", "--tenant", "acme:s3cret"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd="/root/repo")
    try:
        line = proc.stdout.readline().strip()
        port = int(line.rsplit(":", 1)[1])

        no_token = NetworkDocumentServiceFactory("127.0.0.1", port)
        svc = no_token.create_document_service("acme", "doc")
        with pytest.raises(RuntimeError, match="token"):
            svc.connect_to_delta_stream()

        good = NetworkDocumentServiceFactory(
            "127.0.0.1", port,
            token_provider=lambda t, d: sign_token(t, d, "s3cret"))
        conn = good.create_document_service(
            "acme", "doc").connect_to_delta_stream()
        assert conn.client_id
        conn.close()
    finally:
        proc.terminate()
        proc.wait(timeout=10)


# ------------------------------------------------------------------ config

def test_config_layering_defaults_overrides_env(monkeypatch):
    base = Config()
    assert base.max_message_size == 16 * 1024
    c = base.with_overrides(max_message_size=1024)
    assert c.max_message_size == 1024 and base.max_message_size == 16 * 1024
    monkeypatch.setenv("FLUID_TPU_CLIENT_TIMEOUT_S", "42.5")
    env = Config.from_env(c)
    assert env.client_timeout_s == 42.5
    assert env.max_message_size == 1024  # explicit layer survives env
    with pytest.raises(KeyError):
        base.with_overrides(nonsense=1)


def test_config_threads_into_service_limits():
    cfg = Config().with_overrides(client_timeout_s=7.0)
    now = [0.0]
    server = LocalServer(clock=lambda: now[0], config=cfg)
    conn = server.connect("t", "doc")
    orderer = server._get_orderer("t", "doc")
    assert orderer.deli._client_timeout == 7.0
    now[0] = 8.0
    server.expire_idle_clients()
    assert conn.client_id not in orderer.deli.clients


def test_config_sets_front_end_message_cap():
    from fluidframework_tpu.service.front_end import NetworkFrontEnd

    cfg = Config().with_overrides(max_message_size=2048)
    fe = NetworkFrontEnd(server=LocalServer(config=cfg))
    assert fe.max_message_size == 2048


def test_storage_and_backfill_rpcs_require_tokens_too():
    """Tenancy covers the REST-role endpoints: a tokenless connection
    must not read a secured doc's op stream or write its storage."""
    from fluidframework_tpu.driver.network import (
        NetworkDocumentServiceFactory,
    )

    proc = subprocess.Popen(
        [sys.executable, "-m", "fluidframework_tpu.service.front_end",
         "--port", "0", "--tenant", "acme:s3cret"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd="/root/repo")
    try:
        line = proc.stdout.readline().strip()
        port = int(line.rsplit(":", 1)[1])

        bare = NetworkDocumentServiceFactory("127.0.0.1", port) \
            .create_document_service("acme", "doc")
        with pytest.raises(RuntimeError, match="token"):
            bare.connect_to_delta_storage().get_deltas(0, 100)
        with pytest.raises(RuntimeError, match="token"):
            bare.connect_to_storage().write_blob(b"sneak")

        def tokens(t, d):
            return sign_token(t, d, "s3cret")

        read_only = NetworkDocumentServiceFactory(
            "127.0.0.1", port,
            token_provider=lambda t, d: sign_token(
                t, d, "s3cret", scopes=(SCOPE_READ,))) \
            .create_document_service("acme", "doc")
        assert read_only.connect_to_delta_storage().get_deltas(0, 10) == []
        with pytest.raises(RuntimeError, match="scope"):
            read_only.connect_to_storage().write_blob(b"nope")

        full = NetworkDocumentServiceFactory(
            "127.0.0.1", port, token_provider=tokens) \
            .create_document_service("acme", "doc")
        blob_id = full.connect_to_storage().write_blob(b"legit")
        assert full.connect_to_storage().read_blob(blob_id) == b"legit"
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def test_config_bool_env_parses_spellings(monkeypatch):
    """ADVICE r3: FLUID_TPU_APPLIER_USE_PALLAS=0 must DISABLE, not
    silently enable via bool('0') is True."""
    for raw, want in [("0", False), ("false", False), ("no", False),
                      ("off", False), ("1", True), ("true", True),
                      ("YES", True), ("On", True)]:
        monkeypatch.setenv("FLUID_TPU_APPLIER_USE_PALLAS", raw)
        assert Config.from_env().applier_use_pallas is want, raw
    monkeypatch.setenv("FLUID_TPU_APPLIER_USE_PALLAS", "maybe")
    with pytest.raises(ValueError):
        Config.from_env()


def test_config_empty_env_value_keeps_default(monkeypatch):
    monkeypatch.setenv("FLUID_TPU_APPLIER_USE_PALLAS", "")
    # the default is None (defer to applier_kernel); empty env keeps it
    assert Config.from_env().applier_use_pallas is None
    monkeypatch.setenv("FLUID_TPU_CLIENT_TIMEOUT_S", "")
    assert Config.from_env().client_timeout_s == Config().client_timeout_s
