"""Framework layer: DataObject lifecycle, undo-redo, interceptions,
value sequences.
"""

import pytest

from fluidframework_tpu.driver import LocalDocumentServiceFactory
from fluidframework_tpu.framework import (
    DataObject,
    DataObjectFactory,
    UndoRedoStackManager,
    intercepted_map,
    intercepted_string,
)
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.service import LocalServer


@pytest.fixture
def server():
    return LocalServer()


@pytest.fixture
def loader(server):
    return Loader(LocalDocumentServiceFactory(server))


# ------------------------------------------------------------ data object

class Notes(DataObject):
    def initializing_first_time(self):
        self.create_channel("text", "shared-string")
        self.root.set("title", "untitled")
        self.calls = "first"

    def initializing_from_existing(self):
        self.calls = "existing"


def test_data_object_lifecycle(loader):
    factory = DataObjectFactory("notes", Notes)
    c1 = loader.resolve("t", "doc")
    n1 = factory.create_or_load(c1)
    assert n1.calls == "first"
    assert n1.root.get("title") == "untitled"
    n1.get_channel("text").insert_text(0, "hello")

    c2 = loader.resolve("t", "doc")
    n2 = factory.create_or_load(c2)
    assert n2.calls == "existing"
    assert n2.root.get("title") == "untitled"
    assert n2.get_channel("text").get_text() == "hello"


# --------------------------------------------------------------- undo-redo

def undo_setup(loader):
    c1 = loader.resolve("t", "doc")
    c2 = loader.resolve("t", "doc")
    ds = c1.runtime.create_data_store("default")
    s1 = ds.create_channel("text", "shared-string")
    m1 = ds.create_channel("kv", "shared-map")
    mgr = UndoRedoStackManager()
    mgr.attach_string(s1)
    mgr.attach_map(m1)
    ds2 = c2.runtime.get_data_store("default")
    return mgr, s1, m1, ds2.get_channel("text"), ds2.get_channel("kv")


def test_undo_redo_string(loader):
    mgr, s1, m1, s2, m2 = undo_setup(loader)
    s1.insert_text(0, "hello")
    mgr.close_current_operation()
    s1.insert_text(5, " world")
    mgr.close_current_operation()
    assert mgr.undo()
    assert s1.get_text() == s2.get_text() == "hello"
    assert mgr.undo()
    assert s1.get_text() == s2.get_text() == ""
    assert mgr.redo()
    assert s1.get_text() == s2.get_text() == "hello"
    assert mgr.redo()
    assert s1.get_text() == s2.get_text() == "hello world"


def test_undo_remove_restores_text(loader):
    mgr, s1, m1, s2, m2 = undo_setup(loader)
    s1.insert_text(0, "abcdef")
    mgr.close_current_operation()
    s1.remove_text(2, 4)
    mgr.close_current_operation()
    assert s1.get_text() == "abef"
    mgr.undo()
    assert s1.get_text() == s2.get_text() == "abcdef"


def test_undo_slides_past_remote_edits(loader):
    mgr, s1, m1, s2, m2 = undo_setup(loader)
    s1.insert_text(0, "base ")
    mgr.close_current_operation()
    s1.insert_text(5, "LOCAL")
    mgr.close_current_operation()
    s2.insert_text(0, "remote ")  # shifts everything right
    mgr.undo()  # must remove LOCAL, not whatever now sits at 5..10
    assert s1.get_text() == s2.get_text() == "remote base "


def test_undo_map_and_redo_clear_on_new_edit(loader):
    mgr, s1, m1, s2, m2 = undo_setup(loader)
    m1.set("k", 1)
    mgr.close_current_operation()
    m1.set("k", 2)
    mgr.close_current_operation()
    mgr.undo()
    assert m1.get("k") == m2.get("k") == 1
    assert mgr.can_redo
    m1.set("k", 9)  # fresh edit invalidates the redo future
    assert not mgr.can_redo
    mgr.undo()
    assert m1.get("k") == 1
    mgr.undo()
    assert not m1.has("k") and not m2.has("k")


# ------------------------------------------------------------ interceptions

def test_interceptions_stamp_attribution(loader):
    c1 = loader.resolve("t", "doc")
    ds = c1.runtime.create_data_store("default")
    s = ds.create_channel("text", "shared-string")
    m = ds.create_channel("kv", "shared-map")
    user = {"user": "alice"}
    si = intercepted_string(s, lambda props: dict(props or {}, **user))
    mi = intercepted_map(m, lambda k, v: {"value": v, **user})
    si.insert_text(0, "hi")
    mi.set("k", 42)
    # reads pass through to the underlying DDS
    assert si.get_text() == "hi"
    assert mi.get("k") == {"value": 42, "user": "alice"}
    seg = s.client.tree.segments[0]
    assert seg.props == {"user": "alice"}


# ---------------------------------------------------------- value sequences

def test_number_and_object_sequences(loader):
    c1 = loader.resolve("t", "doc")
    c2 = loader.resolve("t", "doc")
    ds = c1.runtime.create_data_store("default")
    nums = ds.create_channel("nums", "shared-number-sequence")
    objs = ds.create_channel("objs", "shared-object-sequence")
    nums.insert_range(0, [1, 2, 3])
    nums.insert_range(1, [10])
    nums.remove_range(0, 1)
    objs.insert_range(0, [{"a": 1}, {"b": 2}])
    ds2 = c2.runtime.get_data_store("default")
    assert ds2.get_channel("nums").get_items() == [10, 2, 3]
    assert nums.get_items() == [10, 2, 3]
    assert nums.get_item(1) == 2
    assert ds2.get_channel("objs").get_items() == [{"a": 1}, {"b": 2}]


def test_matrix_undo_redo(loader):
    """Matrix undo: cell LWW reverts and inserted rows/cols retract
    (VectorUndoProvider scope: removals are not undoable)."""
    from fluidframework_tpu.framework.undo_redo import UndoRedoStackManager

    c1 = loader.resolve("t", "doc")
    c2 = loader.resolve("t", "doc")
    m = c1.runtime.create_data_store("default").create_channel(
        "grid", "shared-matrix")
    m.insert_rows(0, 2)
    m.insert_cols(0, 2)
    m.set_cell(0, 0, "keep")

    mgr = UndoRedoStackManager()
    mgr.attach_matrix(m)

    m.set_cell(0, 0, "edited")
    mgr.close_current_operation()
    m.insert_rows(2, 1)
    m.set_cell(2, 1, "new-row-cell")
    mgr.close_current_operation()

    m2 = c2.runtime.get_data_store("default").get_channel("grid")
    assert m2.row_count == 3 and m2.get_cell(2, 1) == "new-row-cell"

    assert mgr.undo()  # retract the row insert (incl. its cell edit)
    assert m.row_count == 2 and m2.row_count == 2
    assert mgr.undo()  # revert the cell edit
    assert m.get_cell(0, 0) == "keep" and m2.get_cell(0, 0) == "keep"
    assert mgr.redo()
    assert m.get_cell(0, 0) == "edited" and m2.get_cell(0, 0) == "edited"
