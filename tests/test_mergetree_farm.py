"""Randomized convergence farms — the merge-tree's safety net.

Mirrors the reference's client.conflictFarm.spec.ts and
client.reconnectFarm.spec.ts (SURVEY.md §4): N clients × rounds of random
concurrent ops, sequenced in random interleavings (per-client FIFO
preserved), asserting every client converges to identical rich text. The
reconnect farm additionally drops unsequenced ops and resubmits
regenerated (rebased) ops mid-stream.

Seeds are fixed: any failure is reproducible and prints a per-client
segment dump (assert_converged).
"""

from __future__ import annotations

import random

import pytest

from tests.mergetree_fixtures import (
    FarmClient,
    FarmServer,
    assert_converged,
    random_op,
)


@pytest.mark.parametrize("n_clients,rounds,ops_per_round,seed", [
    (2, 30, 2, 1),
    (2, 30, 2, 2),
    (3, 25, 2, 3),
    (3, 25, 3, 4),
    (5, 15, 2, 5),
    (5, 20, 3, 6),
    (8, 10, 2, 7),
])
def test_conflict_farm(n_clients, rounds, ops_per_round, seed):
    rng = random.Random(seed)
    clients = [FarmClient(f"c{i}") for i in range(n_clients)]
    server = FarmServer(clients, rng)
    for rnd in range(rounds):
        # all clients generate ops concurrently (unsequenced)
        for fc in clients:
            for _ in range(ops_per_round):
                random_op(fc, rng)
        # sequence a random PREFIX, generate more ops mid-stream, then drain
        # — exercises ops created against partially-delivered state
        partial = rng.randint(0, server.pending_count())
        for _ in range(partial):
            server.sequence_one()
        for fc in clients:
            if rng.random() < 0.3:
                random_op(fc, rng)
        server.sequence_all()
        assert_converged(clients, f"seed={seed} round={rnd}")


@pytest.mark.parametrize("seed", range(8))
def test_conflict_farm_inserts_removes_only(seed):
    """Denser pure insert/remove pressure (the kernel hot path)."""
    rng = random.Random(1000 + seed)
    clients = [FarmClient(f"c{i}") for i in range(4)]
    server = FarmServer(clients, rng)
    for rnd in range(20):
        for fc in clients:
            for _ in range(3):
                random_op(fc, rng, allow_annotate=False)
        server.sequence_all()
        assert_converged(clients, f"ir-seed={seed} round={rnd}")


@pytest.mark.parametrize("seed", range(6))
def test_reconnect_farm(seed):
    """Random ops + random disconnects: unsequenced ops are dropped at the
    server and the client resubmits regenerated ops against current state."""
    rng = random.Random(2000 + seed)
    clients = [FarmClient(f"c{i}") for i in range(3)]
    server = FarmServer(clients, rng)
    for rnd in range(20):
        for fc in clients:
            for _ in range(2):
                random_op(fc, rng)
        # sequence a random prefix
        for _ in range(rng.randint(0, server.pending_count())):
            server.sequence_one()
        # one client "reconnects": drop its queued ops, rebase, resubmit
        victim = rng.choice(clients)
        victim.outbound.clear()
        for op in victim.client.regenerate_pending_ops():
            victim.client_seq += 1
            from fluidframework_tpu.mergetree import op_to_wire

            victim.outbound.append(
                {
                    "clientSeq": victim.client_seq,
                    "refSeq": victim.client.tree.current_seq,
                    "contents": op_to_wire(op),
                }
            )
        server.sequence_all()
        assert_converged(clients, f"rc-seed={seed} round={rnd}")


@pytest.mark.parametrize("seed", range(6))
def test_reconnect_storm_farm(seed):
    """Double reconnects with sequencing in between — regression for the
    fragment-ordering bugs (segment groups + pending-op renumbering)."""
    rng = random.Random(3000 + seed)
    clients = [FarmClient(f"c{i}") for i in range(3)]
    server = FarmServer(clients, rng)

    def reconnect(fc):
        fc.outbound.clear()
        for op in fc.client.regenerate_pending_ops():
            fc.client_seq += 1
            from fluidframework_tpu.mergetree import op_to_wire

            fc.outbound.append(
                {
                    "clientSeq": fc.client_seq,
                    "refSeq": fc.client.tree.current_seq,
                    "contents": op_to_wire(op),
                }
            )

    for rnd in range(15):
        for fc in clients:
            for _ in range(rng.randint(1, 4)):
                random_op(fc, rng)
        for _ in range(rng.randint(0, server.pending_count())):
            server.sequence_one()
        for _ in range(rng.randint(0, 2)):
            reconnect(rng.choice(clients))
            for _ in range(rng.randint(0, server.pending_count())):
                server.sequence_one()
        server.sequence_all()
        assert_converged(clients, f"storm-seed={seed} round={rnd}")


def test_long_document_growth():
    """A single long-running doc: growth + windowed compaction stay sane."""
    rng = random.Random(42)
    clients = [FarmClient(f"c{i}") for i in range(3)]
    server = FarmServer(clients, rng)
    for rnd in range(150):
        for fc in clients:
            random_op(fc, rng)
        server.sequence_all()
    assert_converged(clients, "long-doc")
    text_len = clients[0].client.get_length()
    seg_count = len(clients[0].client.tree.segments)
    # zamboni keeps metadata roughly proportional to text, not to op count
    assert seg_count < max(200, text_len)
