"""Partition runtime: doc->partition routing, rebalance with checkpoint
handoff, crash recovery from the durable checkpoint + raw-log replay
(ref: lambdas-driver kafka-service/partitionManager.ts:22,93,
partition.ts:24).
"""

from fluidframework_tpu.protocol.messages import DocumentMessage, MessageType
from fluidframework_tpu.service.broadcaster import BroadcasterLambda, PubSub
from fluidframework_tpu.service.core import InMemoryDb
from fluidframework_tpu.service.deli import RawMessage
from fluidframework_tpu.service.local_log import LocalLog
from fluidframework_tpu.service.partitions import (
    PartitionManager,
    partition_of,
)

N_PARTS = 8
DOCS = [f"doc{i}" for i in range(10)]


def mk_manager():
    log, db, pubsub = LocalLog(), InMemoryDb(), PubSub()
    pm = PartitionManager(N_PARTS, log, db, pubsub)
    return pm, log, db, pubsub


def join(pm, log, doc, client_id):
    pm.order(RawMessage(
        tenant_id="t", document_id=doc, client_id=None,
        operation=DocumentMessage(
            client_sequence_number=-1, reference_sequence_number=-1,
            type=MessageType.CLIENT_JOIN, contents={"clientId": client_id}),
        timestamp=1.0))
    log.drain()


def submit(pm, log, doc, client_id, cseq, ref):
    pm.order(RawMessage(
        tenant_id="t", document_id=doc, client_id=client_id,
        operation=DocumentMessage(
            client_sequence_number=cseq, reference_sequence_number=ref,
            type=MessageType.OPERATION, contents={"n": cseq}),
        timestamp=1.0))
    log.drain()


def collect(pubsub, doc, into):
    pubsub.subscribe(BroadcasterLambda.topic("t", doc),
                     lambda batch: into.extend(batch))


def test_routing_is_stable_and_spread():
    pids = {partition_of("t", d, N_PARTS) for d in DOCS}
    assert len(pids) > 2  # docs spread over partitions
    assert all(partition_of("t", d, N_PARTS)
               == partition_of("t", d, N_PARTS) for d in DOCS)


def test_rebalance_preserves_sequencing():
    pm, log, db, pubsub = mk_manager()
    pm.add_host("hostA")
    seen = {d: [] for d in DOCS}
    for d in DOCS:
        collect(pubsub, d, seen[d])
        join(pm, log, d, "c1")
        submit(pm, log, d, "c1", 1, 0)

    # a second host joins: half the partitions move (checkpoint + close
    # on A, lazy resume on B)
    pm.add_host("hostB")
    assert set(pm.assignment.values()) == {"hostA", "hostB"}
    for d in DOCS:
        submit(pm, log, d, "c1", 2, 1)
        submit(pm, log, d, "c1", 3, 1)

    for d in DOCS:
        seqs = [m.sequence_number for m in seen[d]]
        # join + 3 ops, dense, no duplicates, no gaps — across the move
        assert seqs == [1, 2, 3, 4], (d, seqs)


def test_crash_recovery_resumes_from_checkpoint():
    pm, log, db, pubsub = mk_manager()
    pm.add_host("hostA")
    pm.add_host("hostB")
    doc = DOCS[0]
    owner = pm.assignment[partition_of("t", doc, N_PARTS)]
    seen = []
    collect(pubsub, doc, seen)
    join(pm, log, doc, "c1")
    submit(pm, log, doc, "c1", 1, 0)
    pm.checkpoint_all()
    submit(pm, log, doc, "c1", 2, 1)  # after the checkpoint

    # the owner CRASHES: no graceful checkpoint; survivors take over and
    # replay the raw log past the stored checkpoint
    pm.remove_host(owner, crashed=True)
    assert pm.assignment[partition_of("t", doc, N_PARTS)] != owner
    submit(pm, log, doc, "c1", 3, 2)

    # crash recovery is AT-LEAST-ONCE at the broadcast layer: the op
    # ticketed after the last checkpoint is re-broadcast by the new host
    # (clients dedupe by seq — DeltaManager drops seq <= last processed).
    # What must hold: re-ticketed records are BYTE-IDENTICAL (same seq,
    # same contents — deterministic replay), and sequencing continues
    # densely with no gaps.
    by_seq = {}
    for m in seen:
        if m.sequence_number in by_seq:
            prev = by_seq[m.sequence_number]
            assert (prev.contents, prev.client_id, prev.type) == \
                (m.contents, m.client_id, m.type)
        by_seq[m.sequence_number] = m
    assert sorted(by_seq) == [1, 2, 3, 4]  # join + 3 ops, no gaps
    assert [by_seq[s].contents.get("n") for s in (2, 3, 4)] == [1, 2, 3]


def test_single_host_gets_everything_and_release_is_graceful():
    pm, log, db, pubsub = mk_manager()
    host = pm.add_host("solo")
    for d in DOCS:
        join(pm, log, d, "c1")
    assert set(pm.assignment.values()) == {"solo"}
    assert sum(len(p.orderers) for p in host.partitions.values()) == len(DOCS)
    pm.remove_host("solo")
    assert not pm.assignment
