"""Headline benchmark: the service path end-to-end, plus the raw kernel.

Three measurements in ONE JSON line (round-1 VERDICT #2: an end-to-end
number, not a dispatch microbenchmark):

- ``value`` (headline): sequenced ops/sec through the FULL in-process
  service path at 1024 docs × 2 clients — boxcar submission, deli's
  vectorized ticket fast lane, scriptorium persistence, scribe protocol
  replica, broadcast fan-out to every connected client, AND the async
  TpuDocumentApplier consuming the stream as packed device waves
  (BASELINE config 4 analog; north star 50k ops/s).
- ``kernel_ops_per_sec``: the batched device kernel alone at scale
  (10k-doc scribe-replay role, BASELINE config 5), timed against a real
  host readback — NOT block_until_ready, which the axon tunnel treats as
  a no-op and which inflated the round-1 number.
- ``net_p99_ack_ms`` / ``net_p50_ack_ms``: op-ack latency through real
  TCP sockets (submit → own op broadcast back), north star p99 < 50 ms.

vs_north_star_50k is the headline value against the 50k north star
(BASELINE.json — the reference repo publishes no numbers of its own);
vs_scalar_deli_x is the same value against the single-process scalar
``_ticket`` lane, the per-op reference the array/columnar path amortizes.
"""

from __future__ import annotations

import json
import time

import numpy as np

NORTH_STAR_OPS_PER_SEC = 50_000.0


def bench_kernel() -> tuple:
    """Batched device apply+zamboni at 8k docs, honest readback timing.

    Returns (pallas_ops_per_sec, xla_ops_per_sec): the Pallas
    VMEM-resident kernel (ops/pallas_apply.py) is the headline; the XLA
    scan rides along as the comparison baseline."""
    import jax
    import jax.numpy as jnp

    from fluidframework_tpu.ops.apply import (
        apply_ops_batch,
        compact_batch,
        wave_min_seq,
    )
    from fluidframework_tpu.ops.doc_state import DocState
    from fluidframework_tpu.ops.opgen import generate_batch_ops
    from fluidframework_tpu.ops.pallas_apply import pallas_apply_ops_batch

    # K=64 halves the per-dispatch fixed overhead per op vs K=32 (the
    # scan step cost is dominated by dispatch, not depth); S=256 leaves
    # zero docs overflowing on this stream — checked below, because an
    # overflowed doc silently skips work and would inflate the number
    D, S, K, NB = 8192, 256, 64, 2
    rng = np.random.default_rng(42)

    state0 = jax.vmap(lambda _: DocState.empty(S))(jnp.arange(D))
    stream = generate_batch_ops(
        rng, D, K * NB, remove_fraction=0.4, annotate_fraction=0.1, max_insert=8)
    batches = [jnp.asarray(stream[:, i * K : (i + 1) * K]) for i in range(NB)]

    results = []
    for apply_fn in (pallas_apply_ops_batch, apply_ops_batch):
        @jax.jit
        def step(state, ops, apply_fn=apply_fn):
            state = apply_fn(state, ops)
            return compact_batch(state, wave_min_seq(ops))

        # compile + warm up, with a real transfer as the sync point
        s = step(state0, batches[0])
        assert int(np.asarray(s.count).min()) > 0

        t0 = time.perf_counter()
        cur = state0
        for ops in batches:
            cur = step(cur, ops)
        counts = np.asarray(cur.count)  # host readback = the honest fence
        dt = time.perf_counter() - t0
        assert counts.min() > 0, "streams failed to apply"
        assert not np.asarray(cur.overflow).any(), "overflowed docs skip work"
        results.append(D * K * NB / dt)
    return results[0], results[1]


def bench_scalar_deli() -> float:
    """The scalar ``_ticket`` lane in isolation: one process, one doc,
    per-op RawMessages through deli.handler — no boxcars, no arrays.

    This is the per-op-object reference the boxcar/array/columnar path
    amortizes; ``vs_scalar_deli_x`` publishes how much of the headline
    comes from batching vs from the kernel. Median of 3 trials."""
    from fluidframework_tpu.protocol.messages import (
        DocumentMessage,
        MessageType,
    )
    from fluidframework_tpu.service.core import QueuedMessage
    from fluidframework_tpu.service.deli import DeliLambda, RawMessage

    def chanop(i: int) -> dict:
        return {"kind": "chanop", "address": "default",
                "contents": {"address": "text",
                             "contents": {"type": 0, "pos": i,
                                          "text": "abcdefgh"}}}

    n = 100_000
    rates = []
    for trial in range(3):
        deli = DeliLambda("bench", "scalar",
                          send_sequenced=lambda m: None,
                          send_nack=lambda c, nk: None,
                          clock=lambda: 1000.0)
        deli.handler(QueuedMessage(1, "raw", 0, RawMessage(
            "bench", "scalar", None,
            DocumentMessage(-1, -1, MessageType.CLIENT_JOIN,
                            {"clientId": "c1"}), 1000.0)))
        records = [
            QueuedMessage(i + 2, "raw", 0, RawMessage(
                "bench", "scalar", "c1",
                DocumentMessage(i + 1, 0, MessageType.OPERATION,
                                chanop(i)), 1000.0))
            for i in range(n)
        ]
        handler = deli.handler
        t0 = time.perf_counter()
        for rec in records:
            handler(rec)
        dt = time.perf_counter() - t0
        assert deli.sequence_number == n + 1  # join + every op ticketed
        rates.append(n / dt)
    return sorted(rates)[1]


def bench_service() -> dict:
    """Full in-process pipeline with the TPU applier riding the stream.

    BASELINE config 4 scale: 1024 docs × 2 clients, each client's 32-op
    submissions riding the raw log as one boxcar (deli's vectorized fast
    lane), the async TpuDocumentApplier consuming the broadcast as a
    packed-wave device pipeline stage. Median of 3 trials: the shared
    bench host has bursty CPU contention."""
    import gc

    from fluidframework_tpu.service.load_gen import run_inproc
    from fluidframework_tpu.service.tpu_applier import TpuDocumentApplier

    # compile warm-up on a THROWAWAY applier: reusing it would leave
    # warm-up doc state in the placement slots the measured docs hash to
    # (same names, fresh server, seqs restarting at 1)
    warm = TpuDocumentApplier(max_docs=1024, max_slots=256,
                              ops_per_dispatch=32)
    run_inproc(n_docs=8, clients_per_doc=2, ops_per_client=8,
               applier=warm, seed=99, batch_size=8)
    warm.close()
    # GC posture for the measured trials: the op path allocates acyclic
    # graphs only, and collector walks over the live scriptorium logs
    # were the dominant mid-trial latency source — disable the cycle
    # collector outright (service processes run the same posture) and
    # sweep between trials.
    def trial(seed: int, array_lane: bool) -> dict:
        gc.collect()      # contention can depress 2 trials in a row
        gc.freeze()
        gc.disable()
        applier = TpuDocumentApplier(
            max_docs=1024, max_slots=256, ops_per_dispatch=32,
            async_dispatch=True, min_wave_ops=32768)
        stats = run_inproc(n_docs=1024, clients_per_doc=2, ops_per_client=48,
                           applier=applier, flush_every=4096, seed=seed,
                           batch_size=24, array_lane=array_lane)
        applier.close()
        gc.enable()
        gc.unfreeze()
        assert stats.applier_escalations == 0
        assert stats.ops_acked == stats.ops_submitted
        assert stats.applier_ops == stats.ops_submitted
        return stats.summary()

    # headline: the ARRAY LANE (the deli-tpu marshal, SURVEY §7 —
    # boxcars ride the pipeline as int arrays; deli tickets with numpy;
    # the applier bulk-loads device chunks; no per-op objects anywhere
    # on the hot path). The dict lane rides along for comparison — the
    # same pipeline fed per-op message objects.
    trials = [trial(1 + t, True) for t in range(5)]
    trials.sort(key=lambda s: s["ops_per_sec"])
    headline = trials[len(trials) // 2]
    dict_lane = sorted(trial(20 + t, False)["ops_per_sec"]
                       for t in range(3))[1]
    headline["ops_per_sec_dict_lane"] = dict_lane

    # the same pipeline over the DURABLE C++ op log (the split-service
    # core's posture: every raw/delta record encoded + written to disk)
    import tempfile

    from fluidframework_tpu.service.durable_log import DurableLog

    def durable_trial(seed: int) -> float:
        gc.collect()
        gc.freeze()
        gc.disable()
        applier = TpuDocumentApplier(
            max_docs=1024, max_slots=256, ops_per_dispatch=32,
            async_dispatch=True, min_wave_ops=32768)
        stats = run_inproc(n_docs=1024, clients_per_doc=2,
                           ops_per_client=48, applier=applier,
                           flush_every=4096, seed=seed, batch_size=24,
                           array_lane=True,
                           log=DurableLog(tempfile.mkdtemp()))
        applier.close()
        gc.enable()
        gc.unfreeze()
        assert stats.ops_acked == stats.ops_submitted
        return stats.ops_per_sec
    headline["ops_per_sec_durable_log"] = round(
        sorted(durable_trial(40 + t) for t in range(3))[1], 1)

    # the north star names 10k-doc scale: prove the number holds at 8192
    # concurrent docs (393k ops through the full path, same assertions)
    warm8k = TpuDocumentApplier(max_docs=8192, max_slots=256,
                                ops_per_dispatch=32)
    run_inproc(n_docs=8, clients_per_doc=2, ops_per_client=8,
               applier=warm8k, seed=99, batch_size=8)
    warm8k.close()
    big = []
    for t in range(5):  # median of 5, same protocol as the headline
        gc.collect()
        gc.freeze()
        gc.disable()
        applier = TpuDocumentApplier(
            max_docs=8192, max_slots=256, ops_per_dispatch=32,
            async_dispatch=True, min_wave_ops=196608)
        stats = run_inproc(n_docs=8192, clients_per_doc=2,
                           ops_per_client=24, applier=applier,
                           flush_every=32768, seed=5 + t, batch_size=24,
                           array_lane=True)
        applier.close()
        gc.enable()
        gc.unfreeze()
        assert stats.applier_escalations == 0
        assert stats.ops_acked == stats.ops_submitted
        assert stats.applier_ops == stats.ops_submitted
        big.append(stats.ops_per_sec)
    big.sort()
    headline["ops_per_sec_8k_docs"] = round(big[2], 1)
    # run-to-run spread at the scale config (task: keep < 15%)
    headline["ops_per_sec_8k_docs_spread"] = round(
        (big[-1] - big[0]) / big[2], 3)
    return headline


def bench_segment_storage() -> dict:
    """Columnar segment store vs the scalar record lane over the SAME
    ~100k-op deltas stream: recovery-replay seconds per GB of log, and
    seq-range backfill throughput.

    The segmented lane persists each 32-op boxcar as one packed column
    block; recovery decode is a frombuffer per block and backfill is a
    binary search plus raw byte-range copies (``backfill_decodes`` is
    counter-verified ZERO — no block is decoded server-side). The
    legacy lane is the pre-segment record format (``segmented=False``),
    replayed through the same DurableLog API."""
    import os
    import shutil
    import tempfile

    from fluidframework_tpu.service.array_batch import (
        ArrayBoxcar,
        SequencedArrayBatch,
    )
    from fluidframework_tpu.service.durable_log import DurableLog

    N_RECORDS, OPS = 3125, 32  # 100k ops in knee-geometry boxcars
    topic = "deltas/t/bench-doc"

    def record(base_seq: int) -> dict:
        text = "abcdefgh" * (OPS // 4)
        box = ArrayBoxcar(
            tenant_id="t", document_id="bench-doc", client_id="c1",
            ds_id="default", channel_id="text",
            kind=np.zeros(OPS, np.int8),
            a=np.arange(OPS, dtype=np.int32),
            b=np.zeros(OPS, np.int32),
            cseq=np.arange(base_seq, base_seq + OPS, dtype=np.int32),
            rseq=np.full(OPS, base_seq - 1, np.int32),
            text=text,
            text_off=np.arange(0, 2 * OPS + 2, 2, dtype=np.int32),
            props=None, timestamp=float(base_seq))
        return {"tenant_id": "t", "document_id": "bench-doc",
                "abatch": SequencedArrayBatch(
                    boxcar=box, base_seq=base_seq,
                    msns=np.arange(base_seq, base_seq + OPS,
                                   dtype=np.int64),
                    timestamp=float(base_seq))}

    def stream_bytes(d: str) -> int:
        return sum(os.path.getsize(os.path.join(d, f))
                   for f in os.listdir(d))

    out: dict = {}
    total_ops = N_RECORDS * OPS
    for segmented, tag in ((True, ""), (False, "_legacy")):
        d = tempfile.mkdtemp(prefix="bench-seglog-")
        try:
            log = DurableLog(d, segmented=segmented)
            seq = 1
            for _ in range(N_RECORDS):
                log.append(topic, record(seq))
                seq += OPS
            log.sync()
            log.close()
            nbytes = stream_bytes(d)

            # recovery replay: a fresh process decodes the whole stream
            log = DurableLog(d, segmented=segmented)
            log._read_cache.clear()
            t0 = time.perf_counter()
            n = log.length(topic)
            replayed = 0
            for i in range(n):
                replayed += log.read(topic, i)["abatch"].n
            recovery_s = time.perf_counter() - t0
            assert replayed == total_ops

            # backfill: the full seq range through the columnar door
            # (raw byte ranges) or, on the legacy lane, the record
            # replay a scalar backfill performs
            before = log.counters.snapshot()
            t0 = time.perf_counter()
            res = log.delta_blocks(topic, 0, total_ops + 1)
            if res is not None:
                payloads, legacy_msgs = res
                served = len(payloads)
            else:
                served = 0
                log._read_cache.clear()
                for i in range(n):
                    served += len(
                        log.read(topic, i)["abatch"].messages())
            backfill_s = time.perf_counter() - t0
            after = log.counters.snapshot()
            if segmented:
                assert served == N_RECORDS
                out["backfill_decodes"] = (
                    after.get("storage.segment.decodes", 0)
                    - before.get("storage.segment.decodes", 0))
                assert out["backfill_decodes"] == 0
            log.close()

            out[f"durable_log_recovery_s_per_gb{tag}"] = round(
                recovery_s / (nbytes / 1e9), 3)
            out[f"backfill_ops_per_sec{tag}"] = round(
                total_ops / backfill_s, 1)
            out[f"durable_log_bytes_per_op{tag}"] = round(
                nbytes / total_ops, 2)
        finally:
            shutil.rmtree(d, ignore_errors=True)
    return out


REPO = __import__("os").path.dirname(__import__("os").path.abspath(__file__))


def _lean_cmd(mod: str, *args: str) -> list:
    """Service/worker process command line WITHOUT the site hook.

    The bench host's sitecustomize imports the full JAX stack into every
    Python process (~2s of CPU); neither the socket front end nor the
    load workers need it, and on a small-core host that startup tax was
    charged against the measured trial. ``-S`` skips the hook; numpy's
    site-packages dir rides PYTHONPATH (set in _spawn)."""
    import sys

    return [sys.executable, "-S", "-m", mod, *args]


def _lean_env() -> dict:
    import os

    import numpy

    sp = os.path.dirname(os.path.dirname(numpy.__file__))
    env = dict(os.environ, PYTHONPATH=f"{REPO}:{sp}")
    env.pop("JAX_PLATFORMS", None)
    return env


def _spawn_listening(mod: str, *args: str):
    import subprocess

    proc = subprocess.Popen(
        _lean_cmd(mod, *args), stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True, cwd=REPO, env=_lean_env())
    line = proc.stdout.readline().strip()
    assert line.startswith("LISTENING"), line
    return proc, int(line.rsplit(":", 1)[1])


def _proc_cpu_s(pid: int) -> float:
    """utime+stime of a process from /proc/<pid>/stat, in seconds —
    the per-core-lane CPU attribution for the sharded rows (on a 1-CPU
    host the lanes time-slice, and this is the published proof each
    subprocess did real sequencing work rather than idling)."""
    import os

    try:
        with open(f"/proc/{pid}/stat") as f:
            s = f.read()
        fields = s[s.rindex(")") + 2:].split()
        ticks = int(fields[11]) + int(fields[12])  # utime + stime
        return ticks / os.sysconf("SC_CLK_TCK")
    except (OSError, ValueError):
        return 0.0


def _query_counters(port: int) -> dict:
    """The front end's socket-tier batching counters (admin_counters
    RPC) — published so a run that never engaged ingress coalescing /
    flush eliding / fan-out caching is visible in the report."""
    import socket

    try:
        with socket.create_connection(("127.0.0.1", port), timeout=5) as s:
            body = json.dumps({"t": "admin_counters", "rid": 1}).encode()
            s.sendall(len(body).to_bytes(4, "big") + body)

            def read_exactly(n):
                buf = b""
                while len(buf) < n:
                    chunk = s.recv(n - len(buf))
                    if not chunk:
                        raise ConnectionError("closed")
                    buf += chunk
                return buf

            while True:
                n = int.from_bytes(read_exactly(4), "big")
                frame = json.loads(read_exactly(n).decode())
                if frame.get("rid") == 1:
                    return frame.get("counters", {})
    except (OSError, ValueError):
        return {}


def _query_hop_breakdown(port: int) -> dict:
    """Per-hop-pair observation counts from the core's labeled metrics
    registry (admin_metrics_scrape RPC, Prometheus text): the published
    proof that every tier stamped — a refactor that silently drops a
    TraceHop stamp shows up here as a missing pair, not as a latency
    mystery two rounds later."""
    import socket

    from fluidframework_tpu.obs import parse_prometheus

    try:
        with socket.create_connection(("127.0.0.1", port), timeout=5) as s:
            body = json.dumps(
                {"t": "admin_metrics_scrape", "rid": 1}).encode()
            s.sendall(len(body).to_bytes(4, "big") + body)

            def read_exactly(n):
                buf = b""
                while len(buf) < n:
                    chunk = s.recv(n - len(buf))
                    if not chunk:
                        raise ConnectionError("closed")
                    buf += chunk
                return buf

            while True:
                n = int.from_bytes(read_exactly(4), "big")
                frame = json.loads(read_exactly(n).decode())
                if frame.get("rid") == 1:
                    series = parse_prometheus(frame.get("scrape", ""))
                    return {
                        dict(k).get("pair"): v
                        for k, v in series.get(
                            "fluid_obs_hop_ms_count", {}).items()}
    except (OSError, ValueError):
        return {}


def _query_probe_p99(port: int) -> dict:
    """door → p99 ms of the canary's blackbox probes, scraped from the
    armed core's windowed registry after the knee-rate run: what each
    real door (connect/submit/history/route) cost END TO END while the
    core served tenant load."""
    from fluidframework_tpu.obs import parse_prometheus

    try:
        frame = _admin_rpc(port, {"t": "admin_metrics_scrape"},
                           timeout=10.0)
    except (OSError, ValueError, RuntimeError):
        return {}
    series = parse_prometheus(frame.get("scrape", ""))
    out = {}
    for key, v in series.get("fluid_health_probe_ms", {}).items():
        labels = dict(key)
        if labels.get("quantile") in ("0.99", 0.99):
            out[labels.get("door", "?")] = round(float(v), 3)
    return out


def bench_network() -> dict:
    """Socket load against a front-end PROCESS: at-load op-ack latency.

    Orchestrator + asyncio runner processes (ref: service-load-test
    nodeStressTest.ts — workers must not share a GIL with the server or
    each other). Clients submit boxcars of 32 ops (the outbound
    DeltaQueue flush, same batching the in-proc headline uses) over the
    binary wire; the sweep raises the boxcar rate until ack p99 crosses
    the 50 ms north star and reports the highest sustainable load.

    Three measurements:
    - knee sweep at 256 docs × 2 clients (512 connections, direct);
    - the same geometry through 2 gateway processes (scale-out tier —
      on a single-core bench host the extra hop costs CPU from the same
      budget, so direct usually wins here; the gateway number is the
      honest cross-check, not the headline);
    - BASELINE config-4 geometry: 1000 docs × 10 clients = 10,000 live
      sockets at a reduced per-client rate.
    """
    import os
    import subprocess
    import tempfile
    import time as _time

    def run_workers(ports: list, nworkers: int, docs: int, cpd: int,
                    rate: float, batch: int, rounds: int, prefix: str,
                    start_margin: float = 6.0, timeout: float = 300.0,
                    extra: tuple = ()) -> dict:
        start_at = _time.time() + start_margin
        workers = [
            subprocess.Popen(
                _lean_cmd("fluidframework_tpu.service.load_async",
                          "--port", str(ports[w % len(ports)]),
                          "--docs", str(docs),
                          "--clients-per-doc", str(cpd),
                          "--rounds", str(rounds), "--batch", str(batch),
                          "--rate", str(rate), "--seed", str(w),
                          "--start-at", str(start_at),
                          "--doc-prefix", f"{prefix}w{w}d", *extra),
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                text=True, cwd=REPO, env=_lean_env())
            for w in range(nworkers)
        ]
        lats, ops, acked, secs, errors = [], 0, 0, 0.0, []
        late = 0.0
        hops: dict = {}
        for w in workers:
            out, _ = w.communicate(timeout=timeout)
            r = json.loads(out)
            lats.extend(r["lat_ms"])
            ops += r["ops"]
            acked += r["acked"]
            secs = max(secs, r["seconds"])
            late = max(late, r.get("late_s", 0.0))
            errors.extend(r.get("errors", []))
            for k, v in r["hops"].items():
                hops.setdefault(k, []).extend(v)
        assert acked == ops, (acked, ops, errors[:3])

        def pct(vals, p):
            vals = sorted(vals)
            return round(vals[int(p * (len(vals) - 1))], 3) if vals else 0.0

        return {
            "rate_hz": rate,
            "ops_per_sec": round(ops / secs, 1) if secs else 0.0,
            "p50_ack_ms": pct(lats, 0.50),
            "p99_ack_ms": pct(lats, 0.99),
            # a worker that finished connecting AFTER the synchronized
            # start measured the join storm, not steady load: the trial
            # is tainted and the caller should retry with a wider margin
            "late_s": late,
            "hops": {name: {"p50_ms": pct(v, 0.50), "p99_ms": pct(v, 0.99)}
                     for name, v in hops.items()},
        }

    fe, port = _spawn_listening("fluidframework_tpu.service.front_end",
                                "--port", "0")
    gws = []
    try:
        # production topology: clients terminate at gateway processes,
        # each muxing its sessions over ONE core backbone socket — the
        # core then serves G sockets instead of hundreds, which measures
        # FASTER than direct termination even on one host (fan-out
        # encode/sends move off the ordering process's queueing point)
        for _ in range(4):
            gw, gw_port = _spawn_listening(
                "fluidframework_tpu.service.gateway",
                "--core-port", str(port))
            gws.append((gw, gw_port))
        gw_ports = [p for _, p in gws]
        knee_ports = gw_ports[:2]

        # warm-up: orderer creation, joins, first broadcasts (discarded)
        run_workers(knee_ports, 2, 8, 2, 2.0, 8, 4, "warm",
                    start_margin=3.0)

        # ---- knee sweep: 256 docs × 2 clients, boxcars of 32, through
        # 2 gateways. A failed rung is retried once: the bench host has
        # bursty co-tenant CPU (round-3 note), and one burst must not
        # stop the sweep at an artificially low knee. ----
        best = None
        for rate in (1.25, 1.5, 1.75, 2.0, 2.5):
            for attempt in ("", "b"):  # one retry per rung
                r = run_workers(knee_ports, 4, 64, 2, rate, 32,
                                max(8, int(8 * rate)), f"k{rate}{attempt}")
                if r["p99_ack_ms"] < 50.0:
                    break
            if r["p99_ack_ms"] < 50.0:
                best = r
            else:
                if best is None:
                    best = r  # even the lightest load misses: report it
                break
        # confirm the knee: median p99 of 5 runs (bursty co-tenant CPU
        # can depress two consecutive trials). If the confirm median
        # misses the target, step DOWN a rung and re-confirm, all the
        # way to 0.5 (8k ops/s): the published knee is the highest rate
        # whose own confirmation median holds p99 < 50 ms — never a
        # rate that only hit the target in a lucky sweep run (VERDICT
        # r4 #2: the knee must be honest even if it is small).
        knee_rate = best["rate_hz"]
        while True:
            confirms = sorted(
                (run_workers(knee_ports, 4, 64, 2, knee_rate, 32,
                             max(8, int(8 * knee_rate)), f"c{knee_rate}{t}")
                 for t in range(5)),
                key=lambda r: r["p99_ack_ms"])
            best = confirms[2]
            if best["p99_ack_ms"] < 50.0 or knee_rate <= 0.5:
                break
            knee_rate = round(knee_rate - 0.25, 2)

        # ---- the same geometry terminating directly at the core ----
        direct = run_workers([port], 4, 64, 2, knee_rate, 32,
                             max(8, int(8 * knee_rate)), "direct")

        # batching counters accumulated over everything the core served
        # so far (sweep + confirms + direct): proof the amortization
        # engaged under load, reported as net_batching
        batching = _query_counters(port)

        # relay-depth leg: a dedicated 2-level relay tree (leaf gateway
        # dialing a mid gateway via --upstream-gateway; the mid runs the
        # asyncio relay, which is the tier that SERVES the backbone
        # protocol downward — the native epoll relay the knee gateways
        # run does not stack). One short traced burst at the knee rate:
        # each tier appends its own HOP_RELAY stamp, so the core's
        # registry gains the relay_to_relay pair (the per-tier relay
        # cost the flat gateway geometry can never witness)
        mid, mid_port = _spawn_listening(
            "fluidframework_tpu.service.gateway",
            "--core-port", str(port), "--python")
        leaf, leaf_port = _spawn_listening(
            "fluidframework_tpu.service.gateway",
            "--upstream-gateway", f"127.0.0.1:{mid_port}")
        try:
            run_workers([leaf_port], 2, 8, 2, knee_rate, 32,
                        max(8, int(8 * knee_rate)), "rlyrly",
                        extra=("--trace-sample-n", "4"))
        finally:
            leaf.terminate()
            mid.terminate()
            leaf.wait(timeout=10)
            mid.wait(timeout=10)

        # per-hop-pair counts from the core's metrics registry over the
        # same window: the knee runs went through gateways with 1-in-16
        # trace sampling armed, so all four server-visible legs (submit→
        # relay→admit→deli→fanout) must have counted — plus
        # relay_to_relay from the stacked-leaf burst above
        hop_breakdown = _query_hop_breakdown(port)

        # device-dispatch leg: a split-service core (subprocess applier
        # stage tailing the log, backchannel consumed by the core) — the
        # applier's stage/execute wall stamps thread back over the
        # backchannel and fold into the core's registry as
        # stage_to_execute. Short burst, then poll until the fold lands
        # (the stage checkpoints once per second).
        split_dir = tempfile.mkdtemp(prefix="bench-split-")
        log_dir = os.path.join(split_dir, "log")
        state_dir = os.path.join(split_dir, "applier-state")
        applier = subprocess.Popen(
            _lean_cmd("fluidframework_tpu.service.stage_runner",
                      "--stage", "applier", "--log-dir", log_dir,
                      "--state-dir", state_dir),
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, cwd=REPO, env=_lean_env())
        assert applier.stdout.readline().strip() == "READY"
        sfe = None
        try:
            sfe, sfe_port = _spawn_listening(
                "fluidframework_tpu.service.front_end", "--port", "0",
                "--log-dir", log_dir,
                "--consume-backchannel", state_dir)
            run_workers([sfe_port], 2, 8, 2, knee_rate, 32,
                        max(8, int(8 * knee_rate)), "stgexe")
            deadline = _time.monotonic() + 20.0
            split_hops = _query_hop_breakdown(sfe_port)
            while ("stage_to_execute" not in split_hops
                   and _time.monotonic() < deadline):
                _time.sleep(0.25)
                split_hops = _query_hop_breakdown(sfe_port)
            for pair, n in split_hops.items():
                hop_breakdown[pair] = hop_breakdown.get(pair, 0) + n
        finally:
            applier.terminate()
            if sfe is not None:
                sfe.terminate()
                sfe.wait(timeout=10)
            try:
                applier.wait(timeout=10)
            except subprocess.TimeoutExpired:
                applier.kill()

        # armed/disarmed A/B at the knee rate: the sampling knob must
        # cost ~nothing when off AND ~nothing at 1-in-16 — two
        # back-to-back runs, same geometry, published side by side
        rounds = max(8, int(8 * knee_rate))
        trace_ab = {
            "armed_ops_per_sec": run_workers(
                knee_ports, 4, 64, 2, knee_rate, 32, rounds,
                "abarm")["ops_per_sec"],
            "disarmed_ops_per_sec": run_workers(
                knee_ports, 4, 64, 2, knee_rate, 32, rounds,
                "aboff", extra=("--trace-sample-n", "0"))["ops_per_sec"],
        }

        # audit-journal A/B at the knee rate: same geometry against two
        # fresh direct-terminated cores, one with --journal armed. The
        # journal only writes on control-plane EVENTS (never per op), so
        # armed steady-state throughput must match disarmed within
        # noise — the published proof the audit spine is free when idle
        # and ~free when armed
        journal_ab = {}
        for tag, fe_extra in (
                ("armed", ("--journal",
                           os.path.join(tempfile.mkdtemp(
                               prefix="bench-journal-"), "fe.jsonl"))),
                ("disarmed", ())):
            jfe, jport = _spawn_listening(
                "fluidframework_tpu.service.front_end", "--port", "0",
                *fe_extra)
            try:
                journal_ab[f"{tag}_ops_per_sec"] = run_workers(
                    [jport], 4, 64, 2, knee_rate, 32, rounds,
                    f"jab{tag}")["ops_per_sec"]
            finally:
                jfe.terminate()
                jfe.wait(timeout=10)

        # health-plane A/B at the knee rate: same geometry against two
        # fresh direct-terminated cores, one with --probe armed (canary
        # ticker + streaming health engine). The canary is one synthetic
        # session per tick on a reserved tenant that every admission
        # seam excludes, so armed steady-state throughput must match
        # disarmed within noise — the published proof that watching the
        # doors costs ~nothing. The armed core's registry is scraped
        # after the run for health.probe.ms p99 per door: the blackbox
        # door latencies AT LOAD, not on an idle core.
        health_ab = {}
        for tag, fe_extra in (
                ("armed", ("--probe", "--probe-tick", "0.5",
                           "--health-tick", "0.5")),
                ("disarmed", ())):
            hfe, hport = _spawn_listening(
                "fluidframework_tpu.service.front_end", "--port", "0",
                *fe_extra)
            try:
                health_ab[f"{tag}_ops_per_sec"] = run_workers(
                    [hport], 4, 64, 2, knee_rate, 32, rounds,
                    f"hab{tag}")["ops_per_sec"]
                if tag == "armed":
                    health_ab["probe_p99_ms"] = _query_probe_p99(hport)
            finally:
                hfe.terminate()
                hfe.wait(timeout=10)

        # ---- BASELINE config 4: 1000 docs × 10 clients, 4 gateways.
        # The 10× fan-out geometry has its own (lower) knee: step the
        # per-client rate down until the p99 target holds. If even the
        # lightest rate misses, the lightest run is reported and its
        # published p99 field is the saturation marker. ----
        cfg4 = None
        for rate in (0.15, 0.125, 0.1, 0.075, 0.05, 0.035):
            for attempt, margin in (("", 40.0), ("b", 110.0)):
                # one retry per rate, at a much wider start margin: a
                # co-tenant burst during the 10k-connection phase makes
                # workers START LATE (late_s > 0), and a late trial
                # measures the join storm riding into the load window —
                # the dominant cause of the multi-second cfg4 p99 tails
                cfg4 = run_workers(gw_ports, 4, 250, 10, rate, 8, 3,
                                   f"cfg4r{rate}{attempt}",
                                   start_margin=margin, timeout=420.0)
                if cfg4["p99_ack_ms"] < 50.0 and cfg4["late_s"] == 0:
                    break
            if cfg4["p99_ack_ms"] < 50.0 and cfg4["late_s"] == 0:
                break

        # ---- NORTH-STAR geometry: 10,000 DOCS (1 client each, 10k
        # sockets, 4 gateways). The north star names 10k docs; cfg4's
        # 1k-docs × 10-clients row exercises fan-out, this row
        # exercises doc-table scale (10× the orderers, no fan-out
        # amplification). Same taint/retry machinery as cfg4: a late
        # worker (late_s > 0) measured the join storm, so each rate
        # retries once at a wider start margin before stepping down. ----
        n10k = None
        for rate in (0.15, 0.125, 0.1, 0.075, 0.05, 0.035):
            for attempt, margin in (("", 40.0), ("b", 110.0)):
                n10k = run_workers(gw_ports, 4, 2500, 1, rate, 8, 3,
                                   f"t10k{rate}{attempt}",
                                   start_margin=margin, timeout=420.0)
                if n10k["p99_ack_ms"] < 50.0 and n10k["late_s"] == 0:
                    break
            if n10k["p99_ack_ms"] < 50.0 and n10k["late_s"] == 0:
                break
        # the single-core tier is torn down — and WAITED on — before the
        # sharded run: 4 gateways dropping 10k sockets spend seconds in
        # teardown, and that CPU must not bleed into the sharded trial
        for gw, _ in gws:
            gw.terminate()
        for gw, _ in gws:
            try:
                gw.wait(timeout=10)
            except subprocess.TimeoutExpired:
                gw.kill()
        gws = []
        fe.terminate()
        fe.wait(timeout=10)
        fe = None

        sharded = bench_sharded(best["rate_hz"], run_workers)
        sharded4 = bench_sharded(best["rate_hz"], run_workers, n_cores=4)
        blip = bench_migration_blip()
        return {
            "knee": best,
            "direct": direct,
            "cfg4": cfg4,
            "net_10k_docs": n10k,
            "sharded": sharded,
            "sharded_4core": sharded4,
            "migration_blip": blip,
            "batching": batching,
            "hop_breakdown": hop_breakdown,
            "trace_ab": trace_ab,
            "journal_ab": journal_ab,
            "health_ab": health_ab,
        }
    finally:
        for gw, _ in gws:
            gw.terminate()
        if fe is not None:
            fe.terminate()
            fe.wait(timeout=10)


def bench_overload_sweep(knee: dict) -> dict:
    """Closed-loop overload control at 0.5×–4× the measured knee.

    Every rung runs TWO tenants against a fresh front end with the
    admission gate armed (``--tenant-rate`` + ``--slo`` on the
    ``submit_to_admit`` leg — the queueing-visible hop: admit→deli is
    one event-loop iteration, but frames waiting to be READ show up
    between the client's submit stamp and the admit stamp):

    - ``bulk``: token bucket capped at 0.9× the knee throughput,
      offered the swept multiple of the knee load — the shed candidate;
    - ``steady``: no configured rate (structurally unsheddable), a
      fixed ~5%-of-knee trickle on every rung — what an innocent
      co-tenant feels while the neighbor floods.

    The 4× rung repeats with ``--no-shed`` (buckets still account, the
    SLO still trips, nothing sheds) as the collapse control, and a
    caps-free pair at 1× (armed vs plain front) prices the windowed
    registry + SLO ticker themselves. Workers resubmit shed ops after
    the server's jittered ``retry_after_ms`` (load_async shed lane), so
    ``acked_frac`` < 1 on a rung means the backlog outlived the
    worker's ack-wait budget — the honest saturation marker."""
    import subprocess
    import time as _time

    knee_rate = knee.get("rate_hz") or 0.0
    knee_ops = knee.get("ops_per_sec") or 0.0
    knee_p99 = knee.get("p99_ack_ms") or 50.0
    if not (knee_rate and knee_ops):
        return {"skipped": "no knee measurement"}
    budget_ms = round(max(20.0, 1.5 * knee_p99), 1)
    cap = round(0.9 * knee_ops, 1)

    def pct(vals, p):
        vals = sorted(vals)
        return round(vals[int(p * (len(vals) - 1))], 3) if vals else None

    def spawn_worker(port, w, tenant, docs, cpd, rate, batch, rounds,
                     prefix, start_at, timeout):
        return subprocess.Popen(
            _lean_cmd("fluidframework_tpu.service.load_async",
                      "--port", str(port), "--docs", str(docs),
                      "--clients-per-doc", str(cpd),
                      "--rounds", str(rounds), "--batch", str(batch),
                      "--rate", str(rate), "--seed", str(w),
                      "--start-at", str(start_at), "--tenant", tenant,
                      "--timeout", str(timeout),
                      "--doc-prefix", f"{prefix}w{w}d"),
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, cwd=REPO, env=_lean_env())

    def run_rung(mult, tag, shed=True, caps=True, slo=True):
        fe_args = ["--port", "0"]
        if slo:
            fe_args += ["--slo",
                        f"overload=submit_to_admit:{budget_ms}:5:2"]
        if caps:
            fe_args += ["--tenant-rate", f"bulk:{cap}:{cap}"]
        if not shed:
            fe_args.append("--no-shed")
        fe, port = _spawn_listening(
            "fluidframework_tpu.service.front_end", *fe_args)
        try:
            flood_rate = round(knee_rate * mult, 4)
            rounds = max(6, int(8 * flood_rate))
            start_at = _time.time() + 6.0
            floods = [
                spawn_worker(port, w, "bulk", 64, 2, flood_rate, 32,
                             rounds, f"ov{tag}", start_at, 60.0)
                for w in range(4)]
            steady = spawn_worker(port, 9, "steady", 16, 2, 2.0, 8, 16,
                                  f"ov{tag}s", start_at, 60.0)
            results = []
            for w in floods + [steady]:
                out, _ = w.communicate(timeout=300)
                results.append(json.loads(out))
            st = results[-1]
            fl = results[:-1]
            secs = max(r["seconds"] for r in results)
            acked = sum(r["acked"] for r in results)
            offered = sum(r["ops"] for r in results)
            return {
                "offered_x": mult,
                "offered_ops": offered,
                # goodput over the whole window INCLUDING the ack/drain
                # wait — the collapse signal the control rung exposes
                "ops_per_sec": round(acked / secs, 1) if secs else 0.0,
                "acked_frac": round(acked / offered, 4) if offered else None,
                "shed_nacks": sum(r.get("shed", 0) for r in results),
                "steady_p99_ack_ms": pct(st["lat_ms"], 0.99),
                "steady_acked_frac": (round(st["acked"] / st["ops"], 4)
                                      if st["ops"] else None),
                "bulk_p99_ack_ms": pct(
                    [v for r in fl for v in r["lat_ms"]], 0.99),
            }
        finally:
            fe.terminate()
            try:
                fe.wait(timeout=10)
            except subprocess.TimeoutExpired:
                fe.kill()

    rungs = [run_rung(m, f"s{m}") for m in (0.5, 1.0, 2.0, 4.0)]
    control = run_rung(4.0, "c", shed=False)
    ab_armed = run_rung(1.0, "aa", caps=False)
    ab_plain = run_rung(1.0, "ap", caps=False, slo=False)
    return {
        "budget_ms": budget_ms,
        "bulk_cap_ops_per_sec": cap,
        "rungs": rungs,
        "control_no_shed_4x": control,
        # the SLO/windowed-registry machinery alone (no caps, nothing
        # sheds): the two throughputs must sit within run-to-run noise
        "slo_ab": {"armed_ops_per_sec": ab_armed["ops_per_sec"],
                   "plain_ops_per_sec": ab_plain["ops_per_sec"]},
    }


def _admin_rpc(port: int, frame: dict, timeout: float = 30.0) -> dict:
    """One rid-matched admin RPC round trip against a front-end process.

    The timeout is the caller's to size: ``admin_summarize`` replies only
    after the server's host replica has ingested the whole log tail and
    committed the version, which on a 100k-op doc is tens of seconds."""
    import socket

    with socket.create_connection(("127.0.0.1", port), timeout=timeout) as s:
        body = json.dumps(dict(frame, rid=1)).encode()
        s.sendall(len(body).to_bytes(4, "big") + body)

        def read_exactly(n):
            buf = b""
            while len(buf) < n:
                chunk = s.recv(n - len(buf))
                if not chunk:
                    raise ConnectionError("closed")
                buf += chunk
            return buf

        while True:
            n = int.from_bytes(read_exactly(4), "big")
            reply = json.loads(read_exactly(n).decode())
            if reply.get("rid") != 1:
                continue
            if reply.get("t") == "error":
                raise RuntimeError(reply.get("message"))
            return reply


def bench_join_storm() -> dict:
    """Late-joiner catch-up on a long-lived doc: snapshot+Δ vs replay.

    ONE front-end process, ONE doc carrying ≥ 100k sequenced ops at the
    config-4 per-doc geometry (10 synthetic socket clients). Three
    measurements, ordered so each boot shape is forced honestly:

    - **whole-log replay**: cold Loader boots BEFORE any summary exists
      — every op replays through the client merge-tree (the
      pre-snapshot-plane catch-up cost, O(whole log));
    - **snapshot+Δ storm**: after ONE service summary (the
      ``admin_summarize`` door onto the summarizer), a storm of cold
      joiners — each with a fresh driver cache — boots while a trickle
      writer keeps the stream moving, so every time-to-interactive is
      a true MID-STREAM join: snapshot fetch + bounded Δ backfill;
    - **counter assertions** (in-bench, hard): the server frames the
      snapshot exactly ONCE for the whole storm (per-join re-encodes
      == 0), no joiner falls back to the legacy tree shim, and every
      joiner's backfill was snapshot-bounded. A storm that silently
      rode the JSON tree path would otherwise publish a plausible
      number that measures the wrong plane.
    """
    import subprocess
    import time as _time

    from fluidframework_tpu.driver.network import NetworkDocumentServiceFactory
    from fluidframework_tpu.loader.container import Loader
    from fluidframework_tpu.obs import tier_counters

    doc = "jstorm0"
    # ONE explicit Counters handed to every factory: tier_counters vends
    # a fresh instance per call, so per-boot deltas are only observable
    # through a shared instance
    drv = tier_counters("driver")

    def boot(label):
        """Cold boot: fresh factory (empty snapshot/chunk cache), timed
        resolve → the doc is interactive (caught up + channel readable)."""
        factory = NetworkDocumentServiceFactory("127.0.0.1", port,
                                                counters=drv)
        t0 = _time.perf_counter()
        c = Loader(factory).resolve("bench", doc)
        # interactive = the channel answers from converged state
        assert len(c.runtime.get_data_store("default")
                   .get_channel("text").get_text()) > 0, label
        dt = _time.perf_counter() - t0
        c.close()
        return dt

    def pct(vals, p):
        vals = sorted(vals)
        return round(vals[int(p * (len(vals) - 1))], 3) if vals else None

    fe, port = _spawn_listening(
        "fluidframework_tpu.service.front_end", "--port", "0")
    trickle = None
    try:
        # attach the doc with a real writer (raw synthetic clients never
        # send the attach op a booting runtime needs to route chanops)
        writer = Loader(NetworkDocumentServiceFactory(
            "127.0.0.1", port)).resolve("bench", doc)
        ss = writer.runtime.create_data_store("default").create_channel(
            "text", "shared-string")
        ss.insert_text(0, "join-storm seed ")
        deadline = _time.time() + 30
        while writer.runtime.pending.count and _time.time() < deadline:
            _time.sleep(0.01)
        assert writer.runtime.pending.count == 0, "writer never quiesced"
        writer.close()

        # the long-lived doc: 10 clients × 320 rounds × 32-op boxcars
        # = 102,400 ops on one stream (config-4 per-doc geometry)
        w = subprocess.Popen(
            _lean_cmd("fluidframework_tpu.service.load_async",
                      "--port", str(port), "--docs", "1",
                      "--clients-per-doc", "10", "--rounds", "320",
                      "--batch", "32", "--rate", "8", "--seed", "7",
                      "--doc-prefix", "jstorm", "--timeout", "300"),
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, cwd=REPO, env=_lean_env())
        res = json.loads(w.communicate(timeout=900)[0])
        doc_ops = res["acked"]
        assert doc_ops >= 100_000, f"doc too short: {doc_ops} acked"

        # A: whole-log replay (no summary committed yet, so the columnar
        # door reports "no version" and the boot replays from seq 0)
        pre = drv.snapshot()
        replay_s = [round(boot(f"replay{i}"), 3) for i in range(2)]
        d = drv.snapshot()
        assert d.get("boot.backfill.full", 0) \
            - pre.get("boot.backfill.full", 0) == 2, \
            "replay boots were not whole-log"

        # ONE service summary through the operator door
        version = _admin_rpc(
            port, {"t": "admin_summarize", "tenant": "bench", "doc": doc},
            timeout=600.0)["version"]

        # trickle writer: the stream keeps moving, so every storm boot
        # is a mid-stream join with a real post-snapshot Δ to backfill
        trickle = subprocess.Popen(
            _lean_cmd("fluidframework_tpu.service.load_async",
                      "--port", str(port), "--docs", "1",
                      "--clients-per-doc", "2", "--rounds", "400",
                      "--batch", "8", "--rate", "2", "--seed", "11",
                      "--doc-prefix", "jstorm", "--timeout", "60"),
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            cwd=REPO, env=_lean_env())
        _time.sleep(1.0)

        # B: the storm — cold snapshot+Δ boots
        joins = 8
        pre_srv = _query_counters(port)
        pre_drv = drv.snapshot()
        tti = [round(boot(f"join{i}"), 3) for i in range(joins)]
        post_srv = _query_counters(port)
        post_drv = drv.snapshot()

        def delta(post, pre, name):
            return post.get(name, 0) - pre.get(name, 0)

        encodes = delta(post_srv, pre_srv, "storage.snapshot.encodes")
        reencodes = encodes - 1  # first serve fills the framed cache
        assert reencodes == 0, \
            f"snapshot re-encoded during the storm ({encodes} encodes)"
        assert delta(post_drv, pre_drv, "boot.snapshot.fallback") == 0, \
            "a joiner fell back to the legacy tree shim"
        assert delta(post_drv, pre_drv, "boot.snapshot.used") == joins
        assert delta(post_drv, pre_drv, "boot.backfill.bounded") == joins, \
            "a joiner's backfill was not snapshot-bounded"

        speedup = round(pct(replay_s, 0.5) / pct(tti, 0.5), 1)
        assert speedup >= 10.0, \
            f"snapshot+Δ only {speedup}x faster than whole-log replay"
        return {
            "doc_ops": doc_ops,
            "joins": joins,
            "replay_boot_s": replay_s,
            "tti_p50_s": pct(tti, 0.5),
            "tti_p99_s": pct(tti, 0.99),
            "speedup_vs_replay_x": speedup,
            "reencodes_per_join": reencodes,
            "snapshot_version": version,
            "counters": {
                "storage.snapshot.encodes": encodes,
                "storage.snapshot.served": delta(
                    post_srv, pre_srv, "storage.snapshot.served"),
                "storage.snapshot.cache_hits": delta(
                    post_srv, pre_srv, "storage.snapshot.cache_hits"),
                "storage.snapshot.legacy_tree": delta(
                    post_srv, pre_srv, "storage.snapshot.legacy_tree"),
                "boot.chunks.fetched": delta(
                    post_drv, pre_drv, "boot.chunks.fetched"),
            },
        }
    finally:
        if trickle is not None:
            trickle.terminate()
        fe.terminate()
        try:
            fe.wait(timeout=10)
        except subprocess.TimeoutExpired:
            fe.kill()


def bench_net_read_storm() -> dict:
    """Read-scale fan-out through a 2-level relay tree: writer ack and
    core-tier egress vs read-only subscriber count.

    Topology: core ← gw1 (``--core-port``, Python backbone) ← gw2
    (``--upstream-gateway``), every subscriber parked on gw2, ONE writer
    attached directly to the core. Subscribers are raw binary sockets
    (``readonly=1`` connect, no Loader, no join/quorum) living in THIS
    process behind a selectors drain — the host fd budget, not client
    CPU, bounds the swarm, so the 10k target scales to the host and the
    row carries ``host_limited`` when capped. Three probe windows
    (0 readers → n/10 → n) price the claim three ways:

    - **writer ack p99** at full fan-out vs the zero-reader baseline:
      the relay tree must keep reader cost off the write path (asserted
      within 10% unless host_limited — on a 1-CPU host every tier
      time-slices the writer's core);
    - **core-tier bytes/op**: gw1's ``fanout.upstream.bytes`` delta per
      acked op, window-scoped so connect-burst replies don't pollute it
      — asserted ~flat across the 10× subscriber growth (the
      once-per-doc-per-link subscription is what makes it flat);
    - **zero re-encodes above the core**: ``fanout.relay.encodes`` == 0
      at BOTH gateway levels, always asserted — every hop splices
      cached backbone bytes, never re-serializes.

    Delivery is proven at the edge, not inferred: every subscriber
    socket must grow past its pre-window watermark before a window's
    counters are read (which also quiesces in-flight fan-out so the
    byte deltas are complete).
    """
    import os
    import resource
    import selectors
    import socket as _socket
    import time as _time

    from fluidframework_tpu.driver.network import NetworkDocumentServiceFactory
    from fluidframework_tpu.loader.container import Loader

    target = 10_000
    cpus = os.cpu_count() or 1
    soft = resource.getrlimit(resource.RLIMIT_NOFILE)[0]
    # fd budget: n reader sockets here + n accepted in gw2 (its own
    # limit), minus headroom for the writer/admin/spawn plumbing
    n = min(target, max(256, soft - 512), target if cpus >= 4 else 2_000)
    host_limited = (cpus < 4) or (n < target)
    doc = "rstorm0"

    def pct(vals, p):
        vals = sorted(vals)
        return round(vals[int(p * (len(vals) - 1))], 3)

    def gw_counters(port: int) -> dict:
        # same wire shape as _query_counters, different door: the
        # gateway answers THIS tier's fanout.* counters locally instead
        # of relaying to the core
        with _socket.create_connection(("127.0.0.1", port), timeout=10) as s:
            body = json.dumps({"t": "gateway_counters", "rid": 1}).encode()
            s.sendall(len(body).to_bytes(4, "big") + body)

            def read_exactly(k):
                buf = b""
                while len(buf) < k:
                    chunk = s.recv(k - len(buf))
                    if not chunk:
                        raise ConnectionError("closed")
                    buf += chunk
                return buf

            while True:
                m = int.from_bytes(read_exactly(4), "big")
                frame = json.loads(read_exactly(m).decode())
                if frame.get("rid") == 1:
                    return frame.get("counters", {})

    sel = selectors.DefaultSelector()
    socks: list = []
    rx: dict = {}
    core = gw1 = gw2 = writer = None

    def pump(cond, deadline_s: float) -> bool:
        """Drain subscriber sockets until cond() or deadline."""
        end = _time.monotonic() + deadline_s
        while not cond():
            if _time.monotonic() >= end:
                return False
            for key, _ in sel.select(0.2):
                s = key.fileobj
                try:
                    b = s.recv(1 << 16)
                except (BlockingIOError, InterruptedError):
                    continue
                except OSError:
                    b = b""
                if b:
                    rx[s] += len(b)
                else:
                    sel.unregister(s)  # EOF: rx stops growing, the
                    # delivery watermark check names the window
        return True

    def add_readers(count: int, gw2_port: int) -> None:
        base = len(socks)
        for i in range(count):
            s = _socket.create_connection(("127.0.0.1", gw2_port),
                                          timeout=30)
            s.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
            body = json.dumps({"t": "connect", "tenant": "bench",
                               "doc": doc, "bin": 1, "readonly": 1,
                               "rid": base + i}).encode()
            s.sendall(len(body).to_bytes(4, "big") + body)
            s.setblocking(False)
            rx[s] = 0
            sel.register(s, selectors.EVENT_READ)
            socks.append(s)
            # self-pacing: keep the un-replied connect burst under the
            # accept backlog by waiting for handshakes to catch up
            if len(socks) % 64 == 0:
                want = len(socks) - 64
                assert pump(
                    lambda: sum(1 for t in socks if rx[t] > 0) >= want,
                    120.0), "reader handshakes stalled mid-burst"
        assert pump(lambda: all(rx[t] > 0 for t in socks), 120.0), \
            "reader handshakes stalled"

    def probe(k: int) -> list:
        lats = []
        for i in range(k):
            t0 = _time.perf_counter()
            sstr.insert_text(0, "x")
            deadline = _time.monotonic() + 30.0
            while (writer.runtime.pending.count
                   and _time.monotonic() < deadline):
                _time.sleep(0.0005)
            assert writer.runtime.pending.count == 0, \
                f"read-storm probe op {i} never acked"
            lats.append((_time.perf_counter() - t0) * 1e3)
        return lats

    def window(k: int) -> list:
        marks = {s: rx[s] for s in socks}
        lats = probe(k)
        # edge delivery proof + fan-out quiesce before counters are read
        assert pump(lambda: all(rx[s] > marks[s] for s in socks), 60.0), \
            "a subscriber saw no broadcast bytes this window"
        return lats

    try:
        core, core_port = _spawn_listening(
            "fluidframework_tpu.service.front_end", "--port", "0")
        gw1, gw1_port = _spawn_listening(
            "fluidframework_tpu.service.gateway",
            "--core-port", str(core_port), "--python")
        gw2, gw2_port = _spawn_listening(
            "fluidframework_tpu.service.gateway",
            "--upstream-gateway", f"127.0.0.1:{gw1_port}")

        writer = Loader(NetworkDocumentServiceFactory(
            "127.0.0.1", core_port)).resolve("bench", doc)
        sstr = writer.runtime.create_data_store(
            "default").create_channel("text", "shared-string")
        sstr.insert_text(0, "read-storm seed ")
        deadline = _time.monotonic() + 30
        while writer.runtime.pending.count and _time.monotonic() < deadline:
            _time.sleep(0.01)
        assert writer.runtime.pending.count == 0, "writer never quiesced"

        k = 60
        baseline = probe(k)

        pre_core = _query_counters(core_port)
        add_readers(n // 10, gw2_port)
        g1_pre, g2_pre = gw_counters(gw1_port), gw_counters(gw2_port)
        lat_small = window(k)
        g1_mid = gw_counters(gw1_port)

        add_readers(n - n // 10, gw2_port)
        post_core = _query_counters(core_port)
        # every raw subscriber must have landed as a READONLY session at
        # the core (an error-reply handshake would count bytes too)
        ro = (post_core.get("session.readonly.connects", 0)
              - pre_core.get("session.readonly.connects", 0))
        assert ro == n, f"expected {n} readonly connects at core, got {ro}"
        g1_mid2 = gw_counters(gw1_port)  # re-mark: exclude connect burst
        lat_full = window(k)
        g1_post, g2_post = gw_counters(gw1_port), gw_counters(gw2_port)

        bpo_small = (g1_mid.get("fanout.upstream.bytes", 0)
                     - g1_pre.get("fanout.upstream.bytes", 0)) / k
        bpo_full = (g1_post.get("fanout.upstream.bytes", 0)
                    - g1_mid2.get("fanout.upstream.bytes", 0)) / k
        assert bpo_small > 0, "no core egress reached gw1 (dead relay?)"
        growth = bpo_full / bpo_small
        assert growth <= 1.5, \
            f"core bytes/op grew {growth:.2f}x over 10x subscribers"

        # zero re-encode invariant above the core — ALWAYS asserted
        for name, g in (("gw1", g1_post), ("gw2", g2_post)):
            enc = g.get("fanout.relay.encodes", 0)
            assert enc == 0, f"{name} re-encoded {enc} fan-out frames"
        splices = (g2_post.get("fanout.relay.splices", 0)
                   - g2_pre.get("fanout.relay.splices", 0))
        assert splices > 0, "relay splice path never engaged at gw2"

        ack_ratio = round(pct(lat_full, 0.99)
                          / max(pct(baseline, 0.99), 1e-9), 3)
        if not host_limited:
            assert ack_ratio <= 1.10, \
                f"writer ack p99 {ack_ratio}x baseline under full fan-out"
        return {
            "target_readers": target,
            "readers": n,
            "host_limited": host_limited,
            "tree_levels": 2,
            "baseline_p99_ack_ms": pct(baseline, 0.99),
            "p99_ack_ms_small": pct(lat_small, 0.99),
            "p99_ack_ms_full": pct(lat_full, 0.99),
            "ack_p99_vs_baseline_x": ack_ratio,
            "core_bytes_per_op_small": round(bpo_small, 1),
            "core_bytes_per_op_full": round(bpo_full, 1),
            "core_bytes_per_op_growth_x": round(growth, 3),
            "relay_encodes": 0,
            "relay_splices_gw2": splices,
            "readonly_connects": n,
        }
    finally:
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
        if writer is not None:
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass
        for p in (gw2, gw1, core):
            if p is not None:
                p.terminate()
        for p in (gw2, gw1, core):
            if p is not None:
                try:
                    p.wait(timeout=10)
                except Exception:  # noqa: BLE001
                    p.kill()


def bench_sharded(knee_rate: float, run_workers, n_cores: int = 2) -> dict:
    """The SHARDED ordering core at the knee geometry (VERDICT r4 #4):
    ``n_cores`` core PROCESSES over placement leases, gateways routing
    by doc partition. On a MULTI-core host this row is the sequencer
    scaling out (target ≥1.5× per added core vs the 1-core knee); this
    bench host has ONE CPU (nproc=1), where the core lanes can only
    time-slice it — such a row is published ``host_limited`` with
    per-lane CPU attribution (/proc/<pid>/stat utime+stime across the
    measured rung) as the proof the lanes are separate processes doing
    real sequencing work (mechanism correctness is
    tests/test_sharded_core.py + tests/test_placement_plane.py)."""
    import os
    import tempfile

    shard_dir = tempfile.mkdtemp(prefix="bench-shard-")
    host_limited = (os.cpu_count() or 1) < n_cores
    cores = []
    gws = []
    try:
        for prefer in range(n_cores):
            c, _ = _spawn_listening(
                "fluidframework_tpu.service.front_end", "--port", "0",
                "--shard-dir", shard_dir, "--shards", str(n_cores),
                "--prefer", str(prefer))
            cores.append(c)
        for _ in range(2):
            gw, gp = _spawn_listening(
                "fluidframework_tpu.service.gateway", "--shard-dir",
                shard_dir, "--shards", str(n_cores))
            gws.append((gw, gp))
        ports = [p for _, p in gws]
        run_workers(ports, 2, 8, 2, 2.0, 8, 4, "swarm", start_margin=3.0)
        last = None
        for mult in (1.5, 1.0, 0.75):
            rate = round(knee_rate * mult, 3)
            cpu0 = [_proc_cpu_s(c.pid) for c in cores]
            try:
                r = run_workers(ports, 4, 64, 2, rate, 32,
                                max(8, int(8 * rate)),
                                f"sh{n_cores}c{rate}")
            except AssertionError:
                # rung drowned outright (acks never completed before the
                # workers' wait budget): on a 1-CPU host time-sliced
                # cores saturate below the 1-core knee — step down
                last = {"rate_hz": rate, "ops_per_sec": 0.0,
                        "p50_ack_ms": None, "p99_ack_ms": None,
                        "late_s": None, "drowned": True,
                        "n_cores": n_cores, "host_limited": host_limited}
                continue
            r["n_cores"] = n_cores
            r["host_limited"] = host_limited
            r["core_cpu_s"] = [round(_proc_cpu_s(c.pid) - c0, 2)
                               for c, c0 in zip(cores, cpu0)]
            last = r
            if r["p99_ack_ms"] < 50.0:
                return r
        return last
    finally:
        for gw, _ in gws:
            gw.terminate()
        for c in cores:
            c.terminate()
            c.wait(timeout=10)


def bench_migration_blip() -> dict:
    """p99 ack of a steady probe stream across a FORCED live migration
    (``admin_migrate_doc`` on the doc's source core, 2 sharded core
    processes + a gateway): the writer rides the gateway with
    auto-reconnect, so the migration-window p99 prices the whole
    seal → redirect-bounce → epoch flip → reconnect + pending-replay
    path. Published next to a no-migration baseline of the SAME probe;
    zero loss is asserted (pending must drain), not assumed."""
    import os
    import tempfile
    import threading
    import time as _time

    from fluidframework_tpu.driver.network import (
        NetworkDocumentServiceFactory,
        _Transport,
    )
    from fluidframework_tpu.loader.container import Loader
    from fluidframework_tpu.service.stage_runner import doc_partition

    shard_dir = tempfile.mkdtemp(prefix="bench-blip-")
    cores, core_ports, gw = [], [], None
    writer = None
    try:
        for prefer in ("0", "1"):
            c, p = _spawn_listening(
                "fluidframework_tpu.service.front_end", "--port", "0",
                "--shard-dir", shard_dir, "--shards", "2",
                "--prefer", prefer, "--lease-ttl", "1.5")
            cores.append(c)
            core_ports.append(p)
        gw, gw_port = _spawn_listening(
            "fluidframework_tpu.service.gateway", "--shard-dir",
            shard_dir, "--shards", "2")
        writer = Loader(NetworkDocumentServiceFactory(
            "127.0.0.1", gw_port), auto_reconnect=True).resolve(
            "bench", "blipdoc")
        sstr = writer.runtime.create_data_store(
            "default").create_channel("text", "shared-string")

        def probe(n: int) -> list:
            lats = []
            for i in range(n):
                t0 = _time.perf_counter()
                sstr.insert_text(0, "x")
                deadline = _time.monotonic() + 30.0
                while (writer.runtime.pending.count
                       and _time.monotonic() < deadline):
                    _time.sleep(0.0005)
                assert writer.runtime.pending.count == 0, \
                    f"blip probe op {i} never acked (lost across the flip)"
                lats.append((_time.perf_counter() - t0) * 1e3)
            return lats

        def pct(vals, p):
            vals = sorted(vals)
            return round(vals[int(p * (len(vals) - 1))], 3)

        baseline = probe(150)

        k = doc_partition("bench", "blipdoc", 2)
        target = f"127.0.0.1:{core_ports[1 - k]}"

        def migrate():
            _time.sleep(0.15)  # land mid-probe
            t = _Transport("127.0.0.1", core_ports[k], timeout=30.0)
            try:
                t.request({"t": "admin_migrate_doc", "tenant": "bench",
                           "doc": "blipdoc", "target": target})
            finally:
                t.close()

        mig = threading.Thread(target=migrate)
        mig.start()
        try:
            window = probe(150)
        finally:
            mig.join()
        return {
            "baseline_p99_ms": pct(baseline, 0.99),
            "migration_p99_ms": pct(window, 0.99),
            "migration_max_ms": round(max(window), 3),
            "host_limited": (os.cpu_count() or 1) < 2,
        }
    finally:
        if writer is not None:
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass
        if gw is not None:
            gw.terminate()
        for c in cores:
            c.terminate()
        for c in cores:
            c.wait(timeout=10)


def bench_net_rebalance_storm() -> dict:
    """Armed-vs-disarmed A/B of the self-driving placement loop under a
    hotspot storm (service/rebalancer.py).

    Topology per arm: 4 partitions, 3 core processes — core 0 prefers
    ALL partitions (the pathological placement), cores 1/2 join cold —
    plus one gateway. Four writers ride the gateway with auto-reconnect,
    one per partition, one of them viral. The armed arm runs every core
    with ``--rebalance`` (0.25s tick, 2s dwell); the disarmed arm is the
    identical topology with the loop off. Every probe op must ack
    (pending drains to zero) in BOTH arms — op loss across an automatic
    migration would fail the run, not just skew a percentile.

    Published: head/tail windowed p99 of the viral writer per arm, the
    fleet ``placement.rebalance.*`` counter deltas (``admin_placement
    fleet=true``), end-of-run ownership spread, and per-core flap
    counts. The armed arm must actually migrate (fleet
    migrations_issued > 0), must not flap (0 re-moves inside dwell),
    and ends with every core owning partitions; the disarmed arm is
    the control that issued nothing."""
    import os
    import tempfile
    import time as _time

    from fluidframework_tpu.driver.network import (
        NetworkDocumentServiceFactory,
    )
    from fluidframework_tpu.loader.container import Loader
    from fluidframework_tpu.service.stage_runner import doc_partition

    n_shards = 4
    storm_s = 10.0

    def doc_for(k: int) -> str:
        i = 0
        while True:
            d = f"rb{i}"
            if doc_partition("bench", d, n_shards) == k:
                return d
            i += 1

    def pct(vals, p):
        vals = sorted(vals)
        return round(vals[int(p * (len(vals) - 1))], 3) if vals else None

    def run_arm(armed: bool) -> dict:
        shard_dir = tempfile.mkdtemp(prefix="bench-rbstorm-")
        cores, ports, gw = [], [], None
        writers = []
        try:
            extra = (("--rebalance", "--rebalance-tick", "0.25",
                      "--rebalance-dwell", "2.0", "--rebalance-budget",
                      "1") if armed else ())
            for i in range(3):
                prefer = ("--prefer", "0,1,2,3") if i == 0 else ()
                c, p = _spawn_listening(
                    "fluidframework_tpu.service.front_end", "--port", "0",
                    "--shard-dir", shard_dir, "--shards", str(n_shards),
                    "--lease-ttl", "1.5", *prefer, *extra)
                cores.append(c)
                ports.append(p)
            gw, gw_port = _spawn_listening(
                "fluidframework_tpu.service.gateway", "--shard-dir",
                shard_dir, "--shards", str(n_shards))
            chans = []
            for k in range(n_shards):
                w = Loader(NetworkDocumentServiceFactory(
                    "127.0.0.1", gw_port), auto_reconnect=True).resolve(
                    "bench", doc_for(k))
                writers.append(w)
                chans.append(w.runtime.create_data_store(
                    "default").create_channel("text", "shared-string"))

            def acked_insert(w, ch) -> float:
                t0 = _time.perf_counter()
                ch.insert_text(0, "x")
                deadline = _time.monotonic() + 30.0
                while (w.runtime.pending.count
                       and _time.monotonic() < deadline):
                    _time.sleep(0.0005)
                assert w.runtime.pending.count == 0, \
                    "storm op never acked (lost across a rebalance flip)"
                return (_time.perf_counter() - t0) * 1e3

            samples = []  # (t_since_start, ack_ms) of the viral writer
            t_start = _time.monotonic()
            while _time.monotonic() - t_start < storm_s:
                for _ in range(4):  # partition 0 is viral
                    ms = acked_insert(writers[0], chans[0])
                    samples.append((_time.monotonic() - t_start, ms))
                for w, ch in zip(writers[1:], chans[1:]):
                    acked_insert(w, ch)

            head = [ms for t, ms in samples if t <= 2.5]
            tail = [ms for t, ms in samples if t >= storm_s - 2.5]
            placement = _admin_rpc(
                ports[0], {"t": "admin_placement", "fleet": True}
            )["placement"]
            fleet = {k: v for k, v in placement["counters"].items()
                     if k.startswith("placement.rebalance.")}
            flaps, owning = 0, 0
            for p in ports:
                st = _admin_rpc(
                    p, {"t": "admin_rebalance_status"})["rebalance"]
                flaps += st.get("flaps", 0)
                own = _admin_rpc(
                    p, {"t": "admin_placement"})["placement"]["owned"]
                owning += 1 if own else 0
            return {
                "armed": armed,
                "hot_ops": len(samples),
                "head_p99_ms": pct(head, 0.99),
                "tail_p99_ms": pct(tail, 0.99),
                "tail_p50_ms": pct(tail, 0.50),
                "migrations_issued": fleet.get(
                    "placement.rebalance.migrations_issued", 0),
                "suppressed_hysteresis": fleet.get(
                    "placement.rebalance.suppressed_hysteresis", 0),
                "flaps": flaps,
                "cores_owning": owning,
            }
        finally:
            for w in writers:
                try:
                    w.close()
                except Exception:  # noqa: BLE001
                    pass
            if gw is not None:
                gw.terminate()
            for c in cores:
                c.terminate()
            for c in cores:
                c.wait(timeout=10)

    armed = run_arm(True)
    disarmed = run_arm(False)
    assert armed["migrations_issued"] > 0, \
        "armed storm issued no automatic migrations"
    assert armed["flaps"] == 0, \
        f"armed storm flapped ({armed['flaps']} re-moves inside dwell)"
    assert disarmed["migrations_issued"] == 0, \
        "disarmed control migrated — the A/B is not a control"
    return {
        "armed": armed,
        "disarmed": disarmed,
        # the loop's win: the viral writer's settled-window p99 once
        # the hotspot has been spread, vs the same window with the one
        # overloaded core still carrying everything. On a 1-CPU host
        # the three core lanes time-slice and the contrast compresses.
        "tail_p99_armed_vs_disarmed_ms": [
            armed["tail_p99_ms"], disarmed["tail_p99_ms"]],
        "host_limited": (os.cpu_count() or 1) < 4,
    }


def bench_net_fork_storm() -> dict:
    """Near-free fork at scale: 1k forks of a ≥100k-op doc.

    ONE in-process front end over a durable log + on-disk chunk store
    (the server must be reachable for byte accounting — the storm's
    storage cost is measured as real directory growth, not a counter's
    claim). The doc is driven to ≥100k sequenced ops at the config-4
    per-doc geometry, summarized ONCE, then forked 1000 times through
    the socket history door. Published and asserted:

    - **p50/p99 fork-boot ms**: wall time of each ``history fork`` RPC —
      the server seeds the fork's v0 (parent chunks re-referenced),
      adopts the post-base tail, and constructs the fork's pipeline
      before replying, so the RPC IS the boot;
    - **bytes-per-fork + dedupe ratio**: on-disk growth across the storm
      divided by forks, against the snapshot bytes each fork
      re-references — the near-zero-copy claim, asserted ≥ 10x;
    - **O(snapshot) client boots** (hard): a sample of forks cold-boots
      through fresh Loaders; ``boot.backfill.full`` must stay ZERO for
      the whole storm window (a fork that silently replays the parent's
      100k ops fails here, not in a latency mystery);
    - **integrate equivalence** (hard, seeds 0/7/42): fork + concurrent
      parent/fork writers + integrate, then the parent replayed TWO
      independent ways — history-first over sockets vs whole-log from a
      recorded file doc — must agree on every shared fingerprint seq
      and the final text.
    """
    import os
    import random
    import shutil
    import subprocess
    import tempfile
    import time as _time

    from fluidframework_tpu.driver.file import (
        FileDocumentService,
        record_document,
    )
    from fluidframework_tpu.driver.network import NetworkDocumentServiceFactory
    from fluidframework_tpu.loader.container import Loader
    from fluidframework_tpu.obs import tier_counters, tier_snapshot
    from fluidframework_tpu.replay.tool import ReplayController
    from fluidframework_tpu.service.durable_log import DurableLog
    from fluidframework_tpu.service.front_end import NetworkFrontEnd
    from fluidframework_tpu.service.local_server import LocalServer
    from fluidframework_tpu.service.service_summarizer import (
        HostReplicaSource,
        ServiceSummarizer,
    )

    doc = "fstorm0"
    n_forks = 1000
    boot_sample = 16

    def du(path):
        total = 0
        for dirpath, _, files in os.walk(path):
            for f in files:
                try:
                    total += os.path.getsize(os.path.join(dirpath, f))
                except OSError:
                    pass
        return total

    def pct(vals, p):
        vals = sorted(vals)
        return round(vals[int(p * (len(vals) - 1))], 3) if vals else None

    root = tempfile.mkdtemp(prefix="bench-fork-")
    server = LocalServer(log=DurableLog(os.path.join(root, "log")),
                         storage_dir=os.path.join(root, "store"))
    front = NetworkFrontEnd(server).start_background()
    port = front.port
    factory = NetworkDocumentServiceFactory("127.0.0.1", port)
    drv = tier_counters("driver")

    def quiesce(container, what):
        deadline = _time.time() + 60
        while container.runtime.pending.count and _time.time() < deadline:
            _time.sleep(0.01)
        assert container.runtime.pending.count == 0, \
            f"{what} never quiesced"

    try:
        # attach + drive the long-lived doc (10 clients × 320 × 32-op
        # boxcars = 102,400 ops — the join-storm geometry)
        writer = Loader(factory).resolve("bench", doc)
        ss = writer.runtime.create_data_store("default").create_channel(
            "text", "shared-string")
        ss.insert_text(0, "fork-storm seed ")
        quiesce(writer, "fork-storm writer")
        w = subprocess.Popen(
            _lean_cmd("fluidframework_tpu.service.load_async",
                      "--port", str(port), "--docs", "1",
                      "--clients-per-doc", "10", "--rounds", "320",
                      "--batch", "32", "--rate", "8", "--seed", "7",
                      "--doc-prefix", "fstorm", "--timeout", "300"),
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, cwd=REPO, env=_lean_env())
        doc_ops = json.loads(w.communicate(timeout=900)[0])["acked"]
        assert doc_ops >= 100_000, f"doc too short: {doc_ops} acked"
        writer.close()

        ServiceSummarizer(server, HostReplicaSource(server)).summarize_doc(
            "bench", doc)
        head = server.history.log("bench", doc)[0]
        shared_bytes = sum(len(server.blob_store.get(cid))
                           for cid in head["chunk_ids"])

        # the storm: 1k fork RPCs through the socket history door
        h = factory.create_document_service("bench", doc).history()
        pre_bytes = du(root)
        pre_svc = tier_snapshot("service")
        pre_drv = drv.snapshot()
        fork_ms = []
        for i in range(n_forks):
            t0 = _time.perf_counter()
            res = h.fork(new_doc=f"fstormf{i:04d}")
            fork_ms.append(round((_time.perf_counter() - t0) * 1e3, 2))
            assert res["shared_chunks"] > 0, f"fork {i} shared no chunks"
        post_bytes = du(root)
        post_svc = tier_snapshot("service")

        def delta(post, pre, name):
            return post.get(name, 0) - pre.get(name, 0)

        boots = delta(post_svc, pre_svc, "history.fork.boots")
        assert boots == n_forks, \
            f"server counted {boots} fork boots for {n_forks} forks"
        bytes_per_fork = round((post_bytes - pre_bytes) / n_forks, 1)
        dedupe_x = round(shared_bytes / max(bytes_per_fork, 1.0), 1)
        assert dedupe_x >= 10.0, \
            (f"forks are not near-free: {bytes_per_fork} B/fork written "
             f"vs {shared_bytes} B re-referenced ({dedupe_x}x)")

        # O(snapshot) sample boots: cold Loaders on a spread of forks
        want_text = None
        tti = []
        for i in range(0, n_forks, max(1, n_forks // boot_sample)):
            jf = NetworkDocumentServiceFactory("127.0.0.1", port,
                                               counters=drv)
            t0 = _time.perf_counter()
            c = Loader(jf).resolve("bench", f"fstormf{i:04d}")
            text = (c.runtime.get_data_store("default")
                    .get_channel("text").get_text())
            tti.append(round(_time.perf_counter() - t0, 3))
            if want_text is None:
                want_text = text
            assert text == want_text, f"fork {i} diverged from the storm"
            c.close()
        post_drv = drv.snapshot()
        full = delta(post_drv, pre_drv, "boot.backfill.full")
        assert full == 0, \
            f"{full} whole-log replay(s) inside the fork storm window"
        assert delta(post_drv, pre_drv, "boot.backfill.bounded") \
            == len(tti), "a fork boot was not snapshot-bounded"

        # integrate equivalence at three seeds: concurrent fork/parent
        # writers, integrate, then two INDEPENDENT replays of the parent
        # (history-first over sockets vs whole-log from a file record)
        # must agree on every shared fingerprint
        eq_fps = 0
        for seed in (0, 7, 42):
            dn, fn = f"eq{seed}", f"eq{seed}f"
            rng = random.Random(seed)
            pw = Loader(factory).resolve("bench", dn)
            ps = pw.runtime.create_data_store("default").create_channel(
                "text", "shared-string")
            for i in range(24):
                ps.insert_text(rng.randrange(len(ps.get_text()) + 1),
                               f"s{i} ")
            quiesce(pw, f"eq{seed} base writer")
            ServiceSummarizer(
                server, HostReplicaSource(server)).summarize_doc(
                "bench", dn)
            factory.create_document_service("bench", dn).history().fork(
                new_doc=fn)
            fw = Loader(factory).resolve("bench", fn)
            fs = fw.runtime.get_data_store("default").get_channel("text")
            for i in range(6):  # interleaved divergence on both sides
                fs.insert_text(rng.randrange(len(fs.get_text()) + 1),
                               f"F{i} ")
                ps.insert_text(rng.randrange(len(ps.get_text()) + 1),
                               f"P{i} ")
            quiesce(fw, f"eq{seed} fork writer")
            quiesce(pw, f"eq{seed} parent writer")
            out = factory.create_document_service(
                "bench", fn).history().integrate()
            assert out["ops"] == 6, f"seed {seed}: {out['ops']} ops"
            deadline = _time.time() + 60
            while ps.get_text().count("F") < 6 \
                    and _time.time() < deadline:
                _time.sleep(0.01)
            assert ps.get_text().count("F") == 6, \
                f"seed {seed}: integrated edits never landed"
            hist = ReplayController(factory.create_document_service(
                "bench", dn)).run(25)
            with tempfile.TemporaryDirectory() as d:
                doc_dir = record_document(server, "bench", dn, d)
                snap = os.path.join(doc_dir, "snapshot.json")
                if os.path.exists(snap):
                    os.remove(snap)
                legacy = ReplayController(
                    FileDocumentService.from_dir(doc_dir)).run(25)
            assert hist["final_text"] == legacy["final_text"] \
                == ps.get_text(), f"seed {seed}: final-text drift"
            common = set(hist["snapshots"]) & set(legacy["snapshots"])
            assert common, f"seed {seed}: no shared fingerprint seqs"
            for q in common:
                assert hist["snapshots"][q] == legacy["snapshots"][q], \
                    f"seed {seed}: fingerprint drift at seq {q}"
            eq_fps += len(common)
            fw.close()
            pw.close()

        return {
            "doc_ops": doc_ops,
            "forks": n_forks,
            "fork_p50_ms": pct(fork_ms, 0.5),
            "fork_p99_ms": pct(fork_ms, 0.99),
            "bytes_per_fork": bytes_per_fork,
            "snapshot_bytes_shared": shared_bytes,
            "dedupe_ratio_x": dedupe_x,
            "boot_sample_tti_p50_s": pct(tti, 0.5),
            "boot_sample_boots": len(tti),
            "boot_backfill_full_in_bench": full,
            "integrate_equivalence": {"seeds": [0, 7, 42], "ok": True,
                                      "fingerprints_compared": eq_fps},
            "counters": {
                "history.fork.boots": boots,
                "history.fork.tail_ops": delta(
                    post_svc, pre_svc, "history.fork.tail_ops"),
                "history.commit.records": post_svc.get(
                    "history.commit.records", 0),
            },
        }
    finally:
        front.stop()
        shutil.rmtree(root, ignore_errors=True)


def bench_net_cold_storm() -> dict:
    """Fleet cold-start storm: kill a 2-core subprocess fleet and
    restart it from its topology spec with 1k/4k/10k docs on disk.

    One ``TopologySpec`` (service/topology.py) IS the fleet: cores +
    storage tier + admission knobs, restarted with ``Fleet.restart()``
    — no per-core argv reconstruction. Docs are seeded incrementally
    (each axis point reuses the previous point's corpus), summarized
    through the service summarizer and checkpointed by the cores' own
    2 s ticker, so every boot in the restarted generation is the lazy
    O(snapshot + durable-log tail) path. Per axis point:

    - **cold-boot time**: kill -9 → restart from spec → first-route
      every doc (raw readonly connects, ``boot_pending`` replies
      retried after their ``retryAfterMs``) until the whole corpus
      serves — the client-driven boot storm, wall-clocked end to end;
    - **time-to-first-edit**: one sampled cold doc boots through a
      real Loader and acks one edit, timed from connect start — what a
      reconnecting user feels;
    - **warm-doc ack p99 during the storm** vs the same probe on a
      quiet fleet: the admission gate's whole point is that docs
      already booted keep their latency while thousands of cold boots
      queue behind the token bucket (asserted ≤ 1.5x unless
      host_limited — on a 1-CPU host the storm time-slices the probe);
    - **the lazy contract, in-bench (hard)**: ``admin_boot_status``
      summed over the restarted cores must show ZERO
      ``boot.part.full_replay`` — a missing summary or checkpoint
      fails the bench here, not in a latency mystery.
    """
    import os
    import shutil
    import socket as _socket
    import tempfile
    import threading
    import time as _time

    from fluidframework_tpu.driver.network import (
        NetworkDocumentServiceFactory,
        _Transport,
    )
    from fluidframework_tpu.loader.container import Loader
    from fluidframework_tpu.service.placement_plane import EpochTable
    from fluidframework_tpu.service.stage_runner import doc_partition
    from fluidframework_tpu.service.topology import Fleet, default_spec

    axis = [1000, 4000, 10000]
    n_parts = 8
    warm_docs = [f"warm{i}" for i in range(4)]
    host_limited = (os.cpu_count() or 1) < 4

    def pct(vals, p):
        vals = sorted(vals)
        return round(vals[int(p * (len(vals) - 1))], 3)

    def fr(obj):
        body = json.dumps(obj, separators=(",", ":")).encode()
        return len(body).to_bytes(4, "big") + body

    def read_frame(s, buf):
        while True:
            if len(buf[0]) >= 4:
                n = int.from_bytes(buf[0][:4], "big")
                if len(buf[0]) >= 4 + n:
                    body, buf[0] = buf[0][4:4 + n], buf[0][4 + n:]
                    return json.loads(body)
            chunk = s.recv(65536)
            if not chunk:
                raise ConnectionError("cold-storm socket closed")
            buf[0] += chunk

    def chanop(cseq, i):
        return {"clientSequenceNumber": cseq,
                "referenceSequenceNumber": 0, "type": "op",
                "contents": {"kind": "chanop", "address": "default",
                             "contents": {"address": "text",
                                          "contents": {"type": 0,
                                                       "pos": 0,
                                                       "text": f"s{i} "}}}}

    root = tempfile.mkdtemp(prefix="bench-cold-storm-")
    # lease_ttl: long enough that a core stalled by storm work on a
    # time-sliced host doesn't lose partitions mid-measurement (churn
    # is the chaos drill's subject, not this bench's); restart still
    # only waits one ttl for the killed generation's leases to stale
    spec = default_spec(os.path.join(root, "fleet"), n_cores=2,
                        n_partitions=n_parts, lease_ttl=6.0,
                        summarize_every=10 ** 6)
    fl = Fleet(spec, subprocess=True).start()
    fl.wait_claimed()
    table = EpochTable.for_shard_dir(spec.shard_dir)

    def port_for(doc):
        k = doc_partition("bench", doc, n_parts)
        rec = table.read()["parts"][str(k)]
        return int(rec["addr"].rsplit(":", 1)[1])

    def resolve_net(doc):
        """Loader boot at the doc's CURRENT owner; ownership can churn
        for a beat after wait_claimed (the chaos drill's reroute
        idiom), so re-read the table and retry on routing refusals."""
        deadline = _time.monotonic() + 30.0
        while True:
            try:
                return Loader(NetworkDocumentServiceFactory(
                    "127.0.0.1", port_for(doc))).resolve("bench", doc)
            except (RuntimeError, ConnectionError) as e:
                if ("not the owner" not in str(e)
                        or _time.monotonic() >= deadline):
                    raise
                _time.sleep(0.2)

    def seed(doc):
        deadline = _time.monotonic() + 30.0
        while True:
            s = _socket.create_connection(("127.0.0.1", port_for(doc)),
                                          timeout=30)
            buf = [b""]
            s.sendall(fr({"t": "connect", "tenant": "bench", "doc": doc,
                          "rid": 1, "bin": 0}))
            reply = read_frame(s, buf)
            while reply.get("rid") != 1:
                reply = read_frame(s, buf)
            if reply.get("t") == "error":
                # ownership can churn for a beat around a takeover:
                # re-read the table (port_for) and retry at the owner
                s.close()
                assert ("not the owner" in str(reply.get("message"))
                        and _time.monotonic() < deadline), \
                    f"seed refused: {reply}"
                _time.sleep(0.2)
                continue
            s.sendall(fr({"t": "submit",
                          "ops": [chanop(i + 1, i) for i in range(4)]}))
            s.close()
            return

    def route_cold(doc):
        """One first route: raw readonly connect, boot_pending replies
        retried after their advertised backoff. Returns retry count."""
        parked = 0
        while True:
            s = _socket.create_connection(("127.0.0.1", port_for(doc)),
                                          timeout=30)
            buf = [b""]
            s.sendall(fr({"t": "connect", "tenant": "bench", "doc": doc,
                          "rid": 1, "bin": 0, "readonly": 1}))
            reply = read_frame(s, buf)
            while reply.get("rid") != 1:
                reply = read_frame(s, buf)
            s.close()
            if reply.get("t") != "error":
                return parked
            if "not the owner" in str(reply.get("message", "")):
                _time.sleep(0.2)  # reroute: the loop re-reads the table
                continue
            assert reply.get("code") == "boot_pending", \
                f"cold route refused: {reply}"
            parked += 1
            _time.sleep((reply.get("retryAfterMs") or 50) / 1000)

    def summarize_all(docs):
        trans = {p: _Transport("127.0.0.1", p, timeout=30.0)
                 for p in fl.core_ports.values()}
        try:
            for doc in docs:
                deadline = _time.monotonic() + 30.0
                while True:
                    try:
                        trans[port_for(doc)].request_rid(
                            {"t": "admin_summarize", "tenant": "bench",
                             "doc": doc})
                        break
                    except RuntimeError as e:
                        if ("not the owner" not in str(e)
                                or _time.monotonic() >= deadline):
                            raise
                        _time.sleep(0.2)
        finally:
            for t in trans.values():
                t.close()

    def boot_totals():
        tot = {}
        for p in fl.core_ports.values():
            t = _Transport("127.0.0.1", p, timeout=30.0)
            try:
                _, rep = t.request_rid({"t": "admin_boot_status"})
            finally:
                t.close()
            for k, v in rep["boot"]["counters"].items():
                tot[k] = tot.get(k, 0) + v
        return tot

    def warm_probe(sstrs, lats, stop=None):
        """Round-robin timed edits on the warm docs until ``stop`` is
        set (or one pass when no stop event is given)."""
        while True:
            for c, sstr in sstrs:
                t0 = _time.perf_counter()
                sstr.insert_text(0, "w")
                deadline = _time.monotonic() + 60.0
                while (c.runtime.pending.count
                       and _time.monotonic() < deadline):
                    _time.sleep(0.0005)
                assert c.runtime.pending.count == 0, \
                    "warm probe op never acked during the storm"
                lats.append((_time.perf_counter() - t0) * 1e3)
                _time.sleep(0.002)
            if stop is None or stop.is_set():
                return

    rows = []
    seeded = 0
    try:
        for doc in warm_docs:
            seed(doc)
        for target in axis:
            for d in range(seeded, target):
                seed(f"cs{d}")
            seeded = target
            summarize_all([f"cs{d}" for d in range(target)] + warm_docs)
            _time.sleep(3.0)  # two checkpoint-ticker passes

            fl.restart()
            fl.wait_claimed()

            # warm docs boot first, then a quiet-fleet baseline probe
            warm = []
            for doc in warm_docs:
                c = resolve_net(doc)
                warm.append((c, c.runtime.get_data_store(
                    "default").get_channel("text")))
            baseline: list = []
            for _ in range(15):
                warm_probe(warm, baseline)

            # the storm: first-route every cold doc, wall-clocked;
            # doc 0 boots through a real Loader (time-to-first-edit)
            parked = [0]
            tti = [0.0]

            def storm(n=target):
                t0 = _time.perf_counter()
                c = resolve_net("cs0")
                ds = c.runtime.data_stores
                sstr = (c.runtime.get_data_store("default")
                        .get_channel("text")
                        if "default" in ds else
                        c.runtime.create_data_store(
                            "default").create_channel(
                                "text", "shared-string"))
                sstr.insert_text(0, "first ")
                while c.runtime.pending.count:
                    _time.sleep(0.0005)
                tti[0] = (_time.perf_counter() - t0) * 1e3
                c.close()
                for d in range(1, n):
                    parked[0] += route_cold(f"cs{d}")

            stop = threading.Event()
            storm_lats: list = []
            prober = threading.Thread(
                target=warm_probe, args=(warm, storm_lats, stop))
            t0 = _time.monotonic()
            prober.start()
            try:
                storm()
            finally:
                stop.set()
                prober.join()
            cold_boot_s = _time.monotonic() - t0

            tot = boot_totals()
            replays = tot.get("boot.part.full_replay", 0)
            assert replays == 0, \
                (f"{replays} doc(s) whole-log replayed at the {target} "
                 f"point — the O(snapshot+tail) contract broke: {tot}")
            lazy = tot.get("boot.part.lazy", 0)
            assert lazy >= target, \
                f"only {lazy} lazy boots for {target} docs: {tot}"
            ratio = round(pct(storm_lats, 0.99)
                          / max(pct(baseline, 0.99), 1e-9), 3)
            if not host_limited:
                assert ratio <= 1.5, \
                    (f"warm-doc ack p99 {ratio}x baseline during the "
                     f"{target}-doc storm (admission gate not holding)")
            for c, _sstr in warm:
                c.close()
            rows.append({
                "docs": target,
                "cold_boot_s": round(cold_boot_s, 2),
                "boots_per_s": round(target / cold_boot_s, 1),
                "time_to_first_edit_ms": round(tti[0], 1),
                "warm_p99_ack_ms_baseline": pct(baseline, 0.99),
                "warm_p99_ack_ms_storm": pct(storm_lats, 0.99),
                "warm_p99_vs_baseline_x": ratio,
                "parked_retries": parked[0],
                "boot_part_lazy": lazy,
                "boot_part_full_replay": 0,
            })
        return {
            "axis": rows,
            "cores": 2,
            "partitions": n_parts,
            "host_limited": host_limited,
            "admission": {"rate_per_s": spec.boot_rate,
                          "burst": spec.boot_burst},
        }
    finally:
        fl.stop()
        shutil.rmtree(root, ignore_errors=True)


def bench_net_multihost() -> dict:
    """Weak scaling across simulated host groups: 1 → 2 → 4 hosts, one
    core + one gateway each, per-host offered load held constant.

    Each fleet comes from one ``multihost_spec`` (service/topology.py):
    ``h0`` is the placement host (shard dir, storage tier, table door);
    every other group runs in a DISJOINT working dir with its cores on
    ``RemoteTableClient`` — the lease/epoch plane reached only over the
    ``admin_table_*`` door. Per axis point:

    - **ops/s, total and per host**: every host's gateway carries the
      same load mix — 4 docs owned by its OWN host (doc names mined so
      their partitions land in that host's pinned prefer set) plus, on
      multi-host points, 2 docs owned by the NEXT host. Weak-scaling
      efficiency = total(H) / (H × total(1)).
    - **same-host vs cross-host ack + hop p99**: the mined prefixes
      classify every worker as same- or cross-host at its entry
      gateway, so the ack split (and the per-hop-pair taxonomy split,
      trace tails sampled 1-in-16) is exact, not inferred.
    - **locality hit rate**: ``fanout.upstream.same_host /
      (same_host + cross_host)`` summed over the gateways' own counter
      scrape — the host-aware routing proof.
    - **disjointness, in-bench (hard)**: every remote-group process's
      ``/proc/<pid>/fd`` table is scanned — an fd open under the
      placement host's shard dir fails the bench (remote groups share
      sockets, never files), and remote working dirs must contain no
      ``placement/`` lease/table state at all.
    - **the remote-table boot path (hard)**: at the 2-host point the
      h1 group is kill -9'd (its own process group) and respawned from
      its spec copy; its checkpointed docs must re-serve with
      ``boot.part.full_replay == 0`` — lazy O(snapshot+tail) boots
      through the door, not through any shared file.
    """
    import os
    import shutil
    import socket as _socket
    import subprocess
    import tempfile
    import time as _time

    from fluidframework_tpu.driver.network import _Transport
    from fluidframework_tpu.service.stage_runner import doc_partition
    from fluidframework_tpu.service.topology import Fleet, multihost_spec

    axis = [1, 2, 4]
    parts_per_host = 4
    docs_same, docs_cross = 4, 2
    rate, batch, rounds = 2.0, 8, 24
    host_limited = (os.cpu_count() or 1) < 4

    def pct(vals, p):
        vals = sorted(vals)
        return round(vals[int(p * (len(vals) - 1))], 3) if vals else None

    def fr(obj):
        body = json.dumps(obj, separators=(",", ":")).encode()
        return len(body).to_bytes(4, "big") + body

    def read_frame(s, buf):
        while True:
            if len(buf[0]) >= 4:
                n = int.from_bytes(buf[0][:4], "big")
                if len(buf[0]) >= 4 + n:
                    body, buf[0] = buf[0][4:4 + n], buf[0][4 + n:]
                    return json.loads(body)
            chunk = s.recv(65536)
            if not chunk:
                raise ConnectionError("multihost socket closed")
            buf[0] += chunk

    def mine_prefix(tag, owner_parts, n_docs, n_parts):
        """A doc prefix whose first n_docs docs ALL partition into
        owner_parts — exact entry-gateway-vs-owner classification."""
        for t in range(200_000):
            p = f"{tag}x{t}d"
            if all(doc_partition("bench", f"{p}{d}", n_parts)
                   in owner_parts for d in range(n_docs)):
                return p
        raise AssertionError(f"no prefix mined for {tag}")

    def gw_counters(addr):
        s = _socket.create_connection(addr, timeout=10)
        buf = [b""]
        try:
            s.sendall(fr({"t": "gateway_counters", "rid": 1}))
            reply = read_frame(s, buf)
            while reply.get("rid") != 1:
                reply = read_frame(s, buf)
            return reply["counters"]
        finally:
            s.close()

    def run_point(root, n_hosts):
        n_parts = parts_per_host * n_hosts
        spec = multihost_spec(
            os.path.join(root, f"fleet{n_hosts}"), n_hosts, 1, n_parts,
            lease_ttl=6.0, summarize_every=10 ** 6)
        host_parts = {h: set(spec.cores[h].prefer)
                      for h in range(n_hosts)}
        fl = Fleet(spec, subprocess=True).start()
        try:
            fl.wait_claimed()

            # one load worker per (gateway, locality class)
            plans = []  # (host, cls, prefix, docs)
            for h in range(n_hosts):
                plans.append((h, "same", mine_prefix(
                    f"mh{n_hosts}s{h}", host_parts[h], docs_same,
                    n_parts), docs_same))
                if n_hosts > 1:
                    plans.append((h, "cross", mine_prefix(
                        f"mh{n_hosts}c{h}",
                        host_parts[(h + 1) % n_hosts], docs_cross,
                        n_parts), docs_cross))
            start_at = _time.time() + 6.0
            workers = []
            for w, (h, cls, prefix, docs) in enumerate(plans):
                gh, gp = fl.gateway_addr(h)
                workers.append((cls, subprocess.Popen(
                    _lean_cmd("fluidframework_tpu.service.load_async",
                              "--host", gh, "--port", str(gp),
                              "--docs", str(docs),
                              "--clients-per-doc", "1",
                              "--rounds", str(rounds),
                              "--batch", str(batch),
                              "--rate", str(rate), "--seed", str(w),
                              "--start-at", str(start_at),
                              "--doc-prefix", prefix),
                    stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                    text=True, cwd=REPO, env=_lean_env())))
            lats = {"same": [], "cross": []}
            hops = {"same": {}, "cross": {}}
            ops = acked = 0
            secs = 0.0
            for cls, w in workers:
                out, _ = w.communicate(timeout=300)
                r = json.loads(out)
                lats[cls].extend(r["lat_ms"])
                for k, v in r["hops"].items():
                    hops[cls].setdefault(k, []).extend(v)
                ops += r["ops"]
                acked += r["acked"]
                secs = max(secs, r["seconds"])
                assert not r["errors"], (n_hosts, cls, r["errors"][:3])
            assert acked == ops, (n_hosts, acked, ops)

            # locality hit rate from the gateways' OWN counters
            same = cross = 0
            for h in range(n_hosts):
                c = gw_counters(fl.gateway_addr(h))
                same += c.get("fanout.upstream.same_host", 0)
                cross += c.get("fanout.upstream.cross_host", 0)
            assert same > 0, "no same-host routes counted"
            if n_hosts > 1:
                assert cross > 0, "cross-host workers counted no " \
                                  "cross-host routes"

            # disjointness: remote groups share SOCKETS, never files —
            # no remote-group fd may be open under the placement dir,
            # and no placement/lease/table state may exist in a remote
            # working dir (the placement dir is effectively unreadable
            # to them: nothing ever opened it)
            canon = os.path.join(spec.shard_dir, "")
            leaked = []
            for hid, procs in fl.host_procs.items():
                if not spec.host_is_remote(hid):
                    continue
                for p in procs:
                    fd_dir = f"/proc/{p.pid}/fd"
                    for fd in os.listdir(fd_dir):
                        try:
                            tgt = os.readlink(os.path.join(fd_dir, fd))
                        except OSError:
                            continue
                        if tgt.startswith(canon):
                            leaked.append((hid, p.pid, tgt))
                entries = os.listdir(spec.host_dir(hid))
                assert "placement" not in entries, \
                    (f"host {hid} grew local placement state: "
                     f"{entries}")
            assert not leaked, \
                f"remote groups touched placement-host files: {leaked}"

            # the remote-table boot path: kill -9 the h1 group, respawn
            # it from its spec copy, and every checkpointed doc must
            # lazy-boot (zero whole-log replays) through the door
            replay = lazy = None
            if n_hosts == 2:
                h1_prefix = next(p for h, cls, p, _ in plans
                                 if h == 1 and cls == "same")
                t = _Transport("127.0.0.1", fl.core_ports[1],
                               timeout=30.0)
                try:
                    for d in range(docs_same):
                        t.request_rid({"t": "admin_summarize",
                                       "tenant": "bench",
                                       "doc": f"{h1_prefix}{d}"})
                finally:
                    t.close()
                _time.sleep(3.0)  # two checkpoint-ticker passes
                fl.kill_host("h1")
                fl.start_host("h1")
                fl.wait_claimed(parts=host_parts[1], timeout=60.0)
                for d in range(docs_same):
                    s = _socket.create_connection(
                        ("127.0.0.1", fl.core_ports[1]), timeout=30)
                    buf = [b""]
                    s.sendall(fr({"t": "connect", "tenant": "bench",
                                  "doc": f"{h1_prefix}{d}", "rid": 1,
                                  "bin": 0, "readonly": 1}))
                    reply = read_frame(s, buf)
                    while reply.get("rid") != 1:
                        reply = read_frame(s, buf)
                    s.close()
                    if (reply.get("t") == "error"
                            and reply.get("code") == "boot_pending"):
                        _time.sleep(
                            (reply.get("retryAfterMs") or 50) / 1000)
                t = _Transport("127.0.0.1", fl.core_ports[1],
                               timeout=30.0)
                try:
                    _, rep = t.request_rid({"t": "admin_boot_status"})
                finally:
                    t.close()
                tot = rep["boot"]["counters"]
                replay = tot.get("boot.part.full_replay", 0)
                lazy = tot.get("boot.part.lazy", 0)
                assert replay == 0, \
                    (f"{replay} whole-log replays through the "
                     f"remote-table boot path: {tot}")

            row = {
                "hosts": n_hosts,
                "partitions": n_parts,
                "ops_per_sec": round(ops / secs, 1) if secs else 0.0,
                "ops_per_sec_per_host":
                    round(ops / secs / n_hosts, 1) if secs else 0.0,
                "same_host_ack_p99_ms": pct(lats["same"], 0.99),
                "cross_host_ack_p99_ms": pct(lats["cross"], 0.99),
                "hop_p99_ms": {
                    cls: {name: pct(v, 0.99)
                          for name, v in hv.items()}
                    for cls, hv in hops.items() if hv},
                "locality": {
                    "same_host_routes": same,
                    "cross_host_routes": cross,
                    "hit_rate": round(same / max(same + cross, 1), 3)},
                "remote_fd_leaks": 0,
            }
            if replay is not None:
                row["host_restart"] = {
                    "boot_part_full_replay": replay,
                    "boot_part_lazy": lazy}
            return row
        finally:
            fl.stop()

    root = tempfile.mkdtemp(prefix="bench-multihost-")
    try:
        rows = [run_point(root, h) for h in axis]
        base = rows[0]["ops_per_sec"] or 1e-9
        for r in rows[1:]:
            r["weak_scaling_efficiency"] = round(
                r["ops_per_sec"] / (r["hosts"] * base), 3)
        return {"axis": rows, "cores_per_host": 1,
                "rate_hz_per_client": rate,
                "host_limited": host_limited}
    finally:
        shutil.rmtree(root, ignore_errors=True)


def bench_multichip() -> dict:
    """Per-device scaling of the doc-mesh lane (tools/bench_multichip):
    docs axis 1→2→4→8 on forced host devices, in a FRESH process — XLA
    parses the virtual-device flag once, at first backend init, so this
    process's already-initialized backend can't host the sweep. Writes
    the MULTICHIP_r06 artifact as a side effect."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.abspath(__file__))
    out = subprocess.run(
        [sys.executable, "-m", "tools.bench_multichip",
         "--out", os.path.join(repo, "MULTICHIP_r06.json")],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd=repo, timeout=600)
    if out.returncode:
        return {"ok": False, "rc": out.returncode}
    result = json.loads(out.stdout.strip().splitlines()[-1])
    return {
        "ok": result["ok"],
        "n_devices": result["n_devices"],
        "forced_host": result["forced_host"],
        "mesh_vs_local_1shard": result["mesh_vs_local_1shard"],
        "rungs": [
            {k: r[k] for k in ("docs_axis", "ops_per_sec",
                               "scaling_efficiency", "staging_ms_per_wave")}
            for r in result["rungs"]],
    }


def main() -> None:
    # network first: the latency measurement must not share the process
    # with a TPU tunnel already saturated by the kernel/service benches
    net = bench_network()
    overload = bench_overload_sweep(net["knee"])
    join_storm = bench_join_storm()
    read_storm = bench_net_read_storm()
    rebalance_storm = bench_net_rebalance_storm()
    fork_storm = bench_net_fork_storm()
    cold_storm = bench_net_cold_storm()
    multihost = bench_net_multihost()
    kernel_ops, kernel_xla_ops = bench_kernel()
    scalar_deli = bench_scalar_deli()
    service = bench_service()
    seg_storage = bench_segment_storage()
    multichip = bench_multichip()
    print(
        json.dumps(
            {
                "metric": "service_path_ops_per_sec",
                "value": service["ops_per_sec"],
                "unit": "ops/s",
                # against the 50k NORTH STAR (BASELINE.json: the
                # reference repo publishes no numbers of its own)
                "vs_north_star_50k": round(
                    service["ops_per_sec"] / NORTH_STAR_OPS_PER_SEC, 3),
                # the scalar _ticket lane (one process, per-op message
                # objects, no boxcars) and the headline's speedup over
                # it: what the boxcar/array/columnar batching buys
                "scalar_deli_ops_per_sec": round(scalar_deli, 1),
                "vs_scalar_deli_x": round(
                    service["ops_per_sec"] / scalar_deli, 2),
                # the same pipeline fed per-op message objects instead
                # of the array-lane boxcars (deli-tpu marshal)
                "ops_per_sec_dict_lane": service.get("ops_per_sec_dict_lane"),
                # and over the durable C++ op log (split-core posture)
                "ops_per_sec_durable_log": service.get(
                    "ops_per_sec_durable_log"),
                # columnar segment store vs the scalar record lane over
                # the same 100k-op deltas stream: recovery replay s/GB
                # and seq-range backfill throughput (backfill_decodes
                # is counter-verified zero — raw byte-range serving)
                **seg_storage,
                # ack latency AT the headline load (submit → own
                # broadcast, per boxcar): the north star's "p99 < 50 ms
                # at >= 50k ops/s" measured on one path simultaneously
                "p99_ack_ms_at_load": service["p99_ack_ms"],
                # Pallas VMEM-resident kernel; the XLA scan for comparison
                "kernel_ops_per_sec": round(kernel_ops, 1),
                "kernel_xla_ops_per_sec": round(kernel_xla_ops, 1),
                # the same full path at 8192 concurrent docs (scale proof)
                "ops_per_sec_8k_docs": service.get("ops_per_sec_8k_docs"),
                # at-load socket knee (256 docs × 2 clients, binary wire,
                # 32-op boxcars, 2-gateway production topology): the
                # highest rate whose median-of-5 confirmation holds
                # p99 < 50 ms (stepped down from the sweep if needed; at
                # the floor the published p99 marks a miss)
                "net_max_load_ops_per_sec": net["knee"]["ops_per_sec"],
                "net_p50_ack_ms": net["knee"]["p50_ack_ms"],
                "net_p99_ack_ms": net["knee"]["p99_ack_ms"],
                "net_docs": 256,
                "net_clients": 512,
                "net_hops": net["knee"].get("hops", {}),
                # same geometry terminating directly at the core — the
                # gateway tier must not lose to it
                "net_direct_ops_per_sec": net["direct"]["ops_per_sec"],
                "net_direct_p99_ack_ms": net["direct"]["p99_ack_ms"],
                # BASELINE config 4: 1000 docs × 10 clients (10k sockets)
                "net_ops_per_sec_1k_docs": net["cfg4"]["ops_per_sec"],
                "net_p50_ack_ms_1k_docs": net["cfg4"]["p50_ack_ms"],
                "net_p99_ack_ms_1k_docs": net["cfg4"]["p99_ack_ms"],
                # north-star geometry: 10,000 docs × 1 client (10k
                # sockets, doc-table scale without fan-out amplification)
                "net_10k_docs": net["net_10k_docs"],
                # 2-core SHARDED ordering core at the knee geometry
                # (VERDICT r4 #4: the sequencer scales out; target
                # >= 1.5x the 1-core knee)
                "net_sharded_2core_ops_per_sec":
                    net["sharded"]["ops_per_sec"],
                "net_sharded_2core_p99_ack_ms":
                    net["sharded"]["p99_ack_ms"],
                # 4-core lane ladder (placement control plane): on a
                # multi-CPU host the target is ≥1.5× per added core vs
                # the 1-core knee; on this 1-CPU host both sharded rows
                # carry host_limited=true plus per-lane CPU attribution
                # (/proc/<pid>/stat) proving the subprocess lanes worked
                "net_sharded_2core_cpu_s": net["sharded"].get("core_cpu_s"),
                "net_sharded_2core_host_limited":
                    net["sharded"].get("host_limited"),
                "net_sharded_4core_ops_per_sec":
                    net["sharded_4core"]["ops_per_sec"],
                "net_sharded_4core_p99_ack_ms":
                    net["sharded_4core"]["p99_ack_ms"],
                "net_sharded_4core_cpu_s":
                    net["sharded_4core"].get("core_cpu_s"),
                "net_sharded_4core_host_limited":
                    net["sharded_4core"].get("host_limited"),
                # p99/max ack of a steady probe across one forced live
                # migration vs the same probe undisturbed: the price of
                # a seal → flip → reconnect+replay window under traffic
                "migration_blip_ms": net["migration_blip"],
                # socket-tier batching counters from the core that served
                # the knee+direct runs: nonzero ingress coalescing and
                # flush eliding is the proof the amortization engaged
                "net_batching": {
                    k: v for k, v in net.get("batching", {}).items()
                    if k.startswith("net.")},
                # per-hop-pair observation counts scraped from the core's
                # metrics registry (admin_metrics_scrape) over the knee
                # window: every server-visible leg must have counted
                "net_hop_breakdown": net.get("hop_breakdown", {}),
                # trace sampling armed (1-in-16) vs disarmed at the knee
                # rate: the two throughputs must sit within run-to-run
                # noise of each other
                "net_trace_ab": net.get("trace_ab", {}),
                # live health plane armed (canary prober + streaming
                # engine) vs disarmed at the knee rate: the two
                # throughputs must sit within run-to-run noise, and the
                # armed run publishes the canary's per-door p99 at load
                "net_health_ab": net.get("health_ab", {}),
                # closed-loop overload control: offered load 0.5×–4× of
                # the knee against the armed admission gate (capped
                # "bulk" tenant sheds, uncapped "steady" tenant rides
                # through), plus the --no-shed collapse control and the
                # caps-free armed/plain overhead pair
                "net_overload_sweep": overload,
                # late-joiner catch-up on a ≥100k-op doc (config-4
                # per-doc geometry): p50/p99 time-to-interactive of a
                # cold-join storm through the columnar snapshot plane,
                # vs whole-log replay; encode-once counter-asserted
                # (per-join snapshot re-encodes == 0)
                "net_join_storm": join_storm,
                # read-scale fan-out: 10k-target read-only subscribers
                # (scaled to host, host_limited when capped) behind a
                # 2-level relay tree; writer ack p99 vs zero-reader
                # baseline, core-tier bytes/op across 10x subscriber
                # growth (~flat asserted), relay re-encodes
                # counter-asserted 0 above the core
                "net_read_storm": read_storm,
                # self-driving placement A/B: the same 3-core hotspot
                # storm with the rebalancer armed vs off. Armed must
                # migrate (fleet counters), never flap, lose nothing,
                # and end with every core owning partitions
                "net_rebalance_storm": rebalance_storm,
                # doc history plane at scale: 1k near-free forks of a
                # ≥100k-op doc through the socket history door — fork
                # RPC p50/p99, on-disk bytes-per-fork vs the snapshot
                # bytes each fork re-references (dedupe ≥10x asserted),
                # zero whole-log replays in-bench, and the integrated
                # parent fingerprint-equal across history-first and
                # whole-log replays at seeds 0/7/42
                "net_fork_storm": fork_storm,
                # fleet cold start from one topology spec: kill -9 a
                # 2-core subprocess fleet with 1k/4k/10k docs on disk,
                # restart from the spec, first-route the whole corpus.
                # Cold-boot time + time-to-first-edit per point, warm-
                # doc ack p99 during the storm vs quiet baseline, and
                # boot.part.full_replay == 0 asserted in-bench (every
                # boot is snapshot + durable tail, never whole log)
                "net_cold_storm": cold_storm,
                # weak scaling across simulated host groups (1→2→4):
                # per-host load constant, cores on RemoteTableClient
                # through the admin_table_* door, same- vs cross-host
                # ack/hop p99 split, gateway locality hit rate, /proc
                # fd-scanned file disjointness, and full_replay == 0
                # through the remote-table boot path after a host-group
                # kill -9 + respawn
                "net_multihost": multihost,
                # per-device scaling of the doc-mesh applier lane (docs
                # axis 1→2→4→8, forced host devices; full artifact in
                # MULTICHIP_r06.json). mesh_vs_local_1shard is the mesh
                # tax at one shard — the fast-lane claim needs it ≈ 1
                "multichip": multichip,
            }
        )
    )


if __name__ == "__main__":
    import sys as _sys

    if len(_sys.argv) > 1:
        # one lane by name (`python bench.py net_cold_storm`): any
        # argless bench_* runs standalone and prints its own row
        _fn = globals().get(f"bench_{_sys.argv[1]}")
        if not callable(_fn):
            _sys.exit(f"unknown bench lane: {_sys.argv[1]}")
        print(json.dumps({_sys.argv[1]: _fn()}, indent=2, default=str))
    else:
        main()
