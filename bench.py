"""Headline benchmark: batched merge-tree sequenced-op apply throughput.

Measures merge-tree ops/sec across a batch of concurrent documents on one
chip — the TPU analog of BASELINE.md config 4 (N SharedString docs of
concurrent edits). Prints ONE JSON line; vs_baseline is against the
north-star target of 50,000 ops/sec (BASELINE.json — the reference repo
publishes no numbers, so the north star is the bar).
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

NORTH_STAR_OPS_PER_SEC = 50_000.0


def main() -> None:
    from fluidframework_tpu.ops.apply import apply_ops_batch, compact_batch
    from fluidframework_tpu.ops.doc_state import DocState
    from fluidframework_tpu.ops.opgen import generate_batch_ops

    D, S, K, NB = 512, 512, 32, 4  # docs × slots × ops/dispatch × dispatches
    rng = np.random.default_rng(42)

    from fluidframework_tpu.ops.apply import wave_min_seq

    @jax.jit
    def step(state, ops):
        state = apply_ops_batch(state, ops)
        return compact_batch(state, wave_min_seq(ops))

    state = jax.vmap(lambda _: DocState.empty(S))(jnp.arange(D))
    # one continuous valid stream of K*NB ops per doc, split into NB dispatches
    stream = generate_batch_ops(
        rng, D, K * NB, remove_fraction=0.4, annotate_fraction=0.1, max_insert=8)
    batches = [jnp.asarray(stream[:, i * K : (i + 1) * K]) for i in range(NB)]

    # compile + warm up
    state = jax.block_until_ready(step(state, batches[0]))

    n_rounds = 8
    fresh = jax.vmap(lambda _: DocState.empty(S))(jnp.arange(D))
    finals = []  # keep every round's end state so no dispatch escapes the wait
    t0 = time.perf_counter()
    for _ in range(n_rounds):
        cur = fresh  # streams are generated against an empty doc
        for ops in batches:
            cur = step(cur, ops)
        finals.append(cur.count)
    jax.block_until_ready(finals)
    dt = time.perf_counter() - t0

    assert not bool(jnp.any(finals[-1] == 0)), "streams failed to apply"
    ops_per_sec = D * K * NB * n_rounds / dt
    print(
        json.dumps(
            {
                "metric": "merge_tree_ops_per_sec",
                "value": round(ops_per_sec, 1),
                "unit": "ops/s",
                "vs_baseline": round(ops_per_sec / NORTH_STAR_OPS_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
