#!/usr/bin/env python
"""Bundle triage: read an ``admin bundle`` directory and print what an
on-call operator needs first.

    python tools/doctor.py BUNDLE_DIR

The bundle (fluidframework_tpu/admin.py ``bundle --out DIR``) holds the
fleet's debug surface frozen at capture time: ``placement.json`` (epoch
table + membership), and per core under ``cores/<owner>/`` the metrics
scrape (``scrape.prom``), windowed history rings (``history.json``),
journal tail (``journal.jsonl``), SLO status (``slo.json``), rebalancer
status (``rebalance.json``) and any flight dumps that were readable at
capture (``flight/``); plus ``lint.json`` — the capturing build's
``fluidlint --json`` report — whenever the repo checkout was present.
The doctor joins these into a triage report:

1. fleet summary — cores, states, capture errors;
2. hop-pair latency table — the slowest legs of the pipeline by mean,
   from each core's scrape (where the tail latency actually lives:
   relay depth, shed parking, device dispatch);
3. SLO burn — specs not in ``ok``, with their windowed p99 vs budget;
4. recent migrations — each commit/fail with its CAUSAL CHAIN walked
   root-first through the merged fleet journal (operator command or
   rebalance plan → seal → fence → checkpoint → adopt → epoch bump);
5. anomalies — orphaned partitions (owner not in the membership),
   draining/drained cores still owning partitions, migration failures,
   rebalance suppression storms, version-skew hop drops
   (``obs.trace.unknown_hops``), disarmed journals, journal write
   errors, cold-start regressions from ``boot.json`` (a doc that paid
   a whole-log replay, or parked boots idling against a refilled
   admission bucket — the storm stalled), static-contract
   violations in the capturing build (a dirty ``lint.json`` in
   production is an incident signal of its own — someone deployed past
   the gate), and the multi-host trio: an UNREACHABLE HOST GROUP
   (every core a host id advertises failed capture — a machine down,
   not a core restarting), a CROSS-HOST EPOCH REGRESSION (a later
   ``epoch.bump`` with a lower epoch for the same partition — two
   cores wrote the table through different planes), and remote-table
   writes rejected by the door's fence
   (``placement.table.stale_rejections`` in a scrape — a zombie
   ex-owner kept writing after takeover).

Read-only; exit 0 with "healthy" when nothing needs attention, exit 1
when any anomaly or active SLO burn was found (so a CI gate can assert
a bundle is quiet — or assert it ISN'T after a forced incident).
"""

from __future__ import annotations

import json
import os
import re
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from fluidframework_tpu.obs.journal import (  # noqa: E402
    causal_chain,
    merge_entries,
)

#: scrape lines for the hop summaries: fluid_obs_hop_ms_count{...} N
_SCRAPE_RE = re.compile(
    r'^fluid_obs_hop_ms_(count|sum)\{([^}]*)\}\s+([0-9.eE+-]+)')
_LABEL_RE = re.compile(r'(\w+)="([^"]*)"')

#: consecutive rebalance.suppressed entries (no plan between) that
#: count as a storm — the loop wants to move but can't
STORM_THRESHOLD = 10


def _load_json(path):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _load_journal(path) -> list:
    out = []
    try:
        f = open(path)
    except OSError:
        return out
    with f:
        for line in f:
            try:
                e = json.loads(line)
            except ValueError:
                continue
            if isinstance(e, dict) and "kind" in e:
                out.append(e)
    return out


def _hop_table(scrape_text: str) -> dict:
    """pair → (count, sum_ms) from one core's Prometheus scrape."""
    acc: dict = {}
    for line in scrape_text.splitlines():
        m = _SCRAPE_RE.match(line)
        if m is None:
            continue
        stat, labels_s, val = m.group(1), m.group(2), float(m.group(3))
        labels = dict(_LABEL_RE.findall(labels_s))
        pair = labels.get("pair")
        if pair is None:
            continue
        count, total = acc.get(pair, (0.0, 0.0))
        if stat == "count":
            count += val
        else:
            total += val
        acc[pair] = (count, total)
    return acc


def _scrape_counter(scrape_text: str, name: str) -> float:
    total = 0.0
    pat = re.compile(r"^" + re.escape(name) + r'(?:\{[^}]*\})?\s+'
                     r"([0-9.eE+-]+)")
    for line in scrape_text.splitlines():
        m = pat.match(line)
        if m is not None:
            total += float(m.group(1))
    return total


def _fmt_entry(e: dict) -> str:
    labels = " ".join(f"{k}={v}" for k, v in
                      sorted((e.get("labels") or {}).items()))
    epoch = e.get("epoch")
    return (f"e{epoch if epoch is not None else '-'} "
            f"[{e.get('id')}] {e.get('kind')}  {labels}")


def diagnose(bundle_dir: str) -> dict:
    """Parse the bundle into a triage dict (the printable report's
    data source — tests and the net_smoke gate assert on this)."""
    report: dict = {"cores": {}, "hop_pairs": [], "slo_burn": [],
                    "migrations": [], "anomalies": [], "lint": None}
    anomalies = report["anomalies"]
    manifest = _load_json(os.path.join(bundle_dir, "manifest.json")) or {}
    # static-contract status of the build that captured the bundle
    # (admin bundle runs `fluidlint --json` when the repo is present):
    # a dirty tree in production is itself an incident signal
    lint = _load_json(os.path.join(bundle_dir, "lint.json"))
    report["lint"] = lint
    if lint is not None and not lint.get("clean", True):
        for v in lint.get("violations", []):
            anomalies.append(
                f"lint [{v.get('pass')}]: {v.get('message')} "
                f"({v.get('path')}:{v.get('line')})")
    placement = _load_json(os.path.join(bundle_dir, "placement.json"))
    cores_dir = os.path.join(bundle_dir, "cores")
    owners = (sorted(os.listdir(cores_dir))
              if os.path.isdir(cores_dir) else [])

    hop_acc: dict = {}
    per_core_journals = []
    for owner in owners:
        cdir = os.path.join(cores_dir, owner)
        row = dict(manifest.get("cores", {}).get(owner, {}))
        report["cores"][owner] = row
        if row.get("error"):
            anomalies.append(
                f"core {owner}: capture error ({row['error']}) — "
                "unreachable or mid-restart at bundle time")
        scrape_path = os.path.join(cdir, "scrape.prom")
        try:
            with open(scrape_path) as f:
                scrape = f.read()
        except OSError:
            scrape = ""
        for pair, (count, total) in _hop_table(scrape).items():
            c, t = hop_acc.get(pair, (0.0, 0.0))
            hop_acc[pair] = (c + count, t + total)
        unknown = _scrape_counter(scrape, "fluid_obs_trace_unknown_hops")
        if unknown:
            anomalies.append(
                f"core {owner}: {int(unknown)} hop stamp(s) outside "
                "this build's taxonomy (version-skewed client?) — "
                "the breakdown is missing legs")
        rejected = _scrape_counter(
            scrape, "fluid_placement_table_stale_rejections")
        if rejected:
            anomalies.append(
                f"core {owner}: {int(rejected)} remote-table write(s) "
                "rejected by the door's fence — a zombie ex-owner kept "
                "writing the epoch table after takeover (the fence held, "
                "but that core's lease view is stale: check its host "
                "group's clock and network)")
        journal = _load_journal(os.path.join(cdir, "journal.jsonl"))
        per_core_journals.append(journal)
        if row.get("journal_armed") is False and not journal:
            anomalies.append(
                f"core {owner}: journal disarmed — no audit trail "
                "from this core")
        err = sum(1 for e in journal if e.get("kind") == "core.recover")
        if err:
            row["recoveries"] = err
        slo = _load_json(os.path.join(cdir, "slo.json")) or {}
        for r in slo.get("slos", []):
            if r.get("state") != "ok":
                report["slo_burn"].append({"core": owner, **r})
        # cold-start surface: rehydration progress at capture time
        boot = _load_json(os.path.join(cdir, "boot.json"))
        if boot is not None:
            ex = boot.get("executor") or {}
            booted = sum(p.get("docs_booted", 0)
                         for p in boot.get("parts", []))
            pending = sum(p.get("docs_pending", 0)
                          for p in boot.get("parts", []))
            row["boot"] = {"booted": booted, "pending": pending,
                           "parked": ex.get("parked", 0)}
            replays = (boot.get("counters") or {}).get(
                "boot.part.full_replay", 0)
            if replays:
                anomalies.append(
                    f"core {owner}: {replays} doc boot(s) paid a "
                    "WHOLE-LOG replay — a summary or checkpoint is "
                    "missing, so the cold-start bound is gone for "
                    "those docs")
            if (pending and ex.get("parked", 0)
                    and ex.get("tokens", 0) >= 1):
                anomalies.append(
                    f"core {owner}: {pending} doc(s) still pending "
                    f"with {ex['parked']} boot(s) parked against a "
                    "refilled admission bucket — the storm stalled "
                    "(clients gave up retrying, or first routes never "
                    "arrived)")
        # suppression storm: longest run of rebalance.suppressed
        # without an actionable plan breaking it
        run = best = 0
        for e in journal:
            kind = e.get("kind", "")
            if kind == "rebalance.suppressed":
                run += 1
                best = max(best, run)
            elif kind == "rebalance.plan":
                run = 0
        if best >= STORM_THRESHOLD:
            anomalies.append(
                f"core {owner}: rebalance suppression storm ({best} "
                "consecutive suppressed ticks) — the loop wants to "
                "move but hysteresis/budget keeps refusing; check "
                "dwell/budget settings vs the heat imbalance")

    report["hop_pairs"] = sorted(
        ((pair, count, total / count if count else 0.0, total)
         for pair, (count, total) in hop_acc.items()),
        key=lambda r: -r[3])

    merged = merge_entries(per_core_journals)
    report["journal_merged"] = merged
    # cross-host epoch regression: replayed in WALL-CLOCK order, each
    # partition's epoch.bump sequence must only move forward — a later
    # bump with a lower epoch means two cores wrote the table through
    # different planes (a host group split-brained past the fence)
    last_bump: dict = {}
    for e in sorted((e for e in merged if e.get("kind") == "epoch.bump"),
                    key=lambda e: (e.get("ts", 0.0), e.get("epoch", 0))):
        part = (e.get("labels") or {}).get("part")
        epoch = e.get("epoch")
        if part is None or epoch is None:
            continue
        prev = last_bump.get(part)
        if prev is not None and epoch < prev[0]:
            anomalies.append(
                f"part {part}: epoch regressed e{epoch} on "
                f"{e.get('core')} after e{prev[0]} on {prev[1]} — two "
                "cores wrote the epoch table through different planes "
                "(a remote group bypassing the table door?)")
        if prev is None or epoch > prev[0]:
            last_bump[part] = (epoch, e.get("core"))
    for e in merged:
        if e.get("kind") in ("migration.commit", "migration.fail"):
            report["migrations"].append(
                {"entry": e, "chain": causal_chain(merged, e["id"])})
            if e["kind"] == "migration.fail":
                anomalies.append(
                    f"migration of part "
                    f"{(e.get('labels') or {}).get('part')} FAILED on "
                    f"{e.get('core')}: "
                    f"{(e.get('labels') or {}).get('error')}")
    report["migrations"] = report["migrations"][-5:]

    if placement is not None:
        member_states = {owner: row.get("state")
                         for owner, row in
                         (placement.get("cores") or {}).items()}
        owned_by: dict = {}
        for k, part in (placement.get("parts") or {}).items():
            owned_by.setdefault(part.get("owner"), []).append(k)
            if member_states and part.get("owner") not in member_states:
                anomalies.append(
                    f"part {k}: owner {part.get('owner')} is not in "
                    "the core membership — orphaned routing entry "
                    "(stale lease / dead core?)")
        for owner, state in member_states.items():
            if state in ("draining", "drained") and owned_by.get(owner):
                anomalies.append(
                    f"core {owner} is {state} but still owns parts "
                    f"{sorted(owned_by[owner])} — evacuation stuck?")
        # unreachable host group: every core a host id advertises in the
        # membership failed capture — that is a machine (or its network)
        # down, not a core restarting; triage the host first
        by_host: dict = {}
        for owner, row in (placement.get("cores") or {}).items():
            host = row.get("host")
            if host is not None:
                by_host.setdefault(host, []).append(owner)
        for host, members in sorted(by_host.items()):
            captured = [o for o in members if o in report["cores"]]
            if captured and all(report["cores"][o].get("error")
                                for o in captured):
                anomalies.append(
                    f"host group {host}: all {len(captured)} core(s) "
                    f"({', '.join(sorted(captured))}) unreachable at "
                    "capture — the whole host group is down or "
                    "partitioned from the entry core")
    return report


def print_report(report: dict) -> None:
    print("== fleet ==")
    for owner, row in sorted(report["cores"].items()):
        extra = ""
        if row.get("recoveries"):
            extra += f"  recoveries={row['recoveries']}"
        if row.get("boot"):
            b = row["boot"]
            extra += (f"  boot={b['booted']}/"
                      f"{b['booted'] + b['pending']}")
        if row.get("error"):
            extra += "  CAPTURE-ERROR"
        print(f"  core {owner} @ {row.get('addr', '?')}"
              f"  journal={'armed' if row.get('journal_armed') else 'off'}"
              f"{extra}")
    print("\n== slowest hop pairs (fleet, by total ms) ==")
    if not report["hop_pairs"]:
        print("  (no hop observations in any scrape)")
    for pair, count, mean, total in report["hop_pairs"][:8]:
        print(f"  {pair:<22} n={int(count):<8} mean {mean:8.3f} ms  "
              f"total {total:10.1f} ms")
    print("\n== SLO burn ==")
    if not report["slo_burn"]:
        print("  all specs ok")
    for r in report["slo_burn"]:
        print(f"  [{r['state'].upper()}] {r['slo']} on {r['core']}: "
              f"p99 {r['p99_ms']}ms / budget {r['budget_ms']}ms "
              f"(burn {r['burn']}/{r['burn_ticks']})")
    print("\n== recent migrations (causal chains, root first) ==")
    if not report["migrations"]:
        print("  none in the journal window")
    for m in report["migrations"]:
        print(f"  {_fmt_entry(m['entry'])}")
        for link in m["chain"]:
            print(f"    {_fmt_entry(link)}")
    print("\n== static contracts (capturing build) ==")
    lint = report.get("lint")
    if lint is None:
        print("  (no lint.json in bundle — captured without the repo)")
    elif lint.get("clean"):
        waived = lint.get("waived", [])
        print(f"  clean ({len(lint.get('passes', []))} passes"
              f", {len(waived)} waived concurrency finding(s))")
    else:
        print(f"  DIRTY: {len(lint.get('violations', []))} "
              "violation(s) — see anomalies")
    print("\n== anomalies ==")
    if not report["anomalies"]:
        print("  none — healthy")
    for a in report["anomalies"]:
        print(f"  ! {a}")


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1 or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 2
    bundle_dir = argv[0]
    if not os.path.isdir(bundle_dir):
        print(f"not a bundle directory: {bundle_dir}")
        return 2
    report = diagnose(bundle_dir)
    print_report(report)
    return 1 if report["anomalies"] or report["slo_burn"] else 0


if __name__ == "__main__":
    sys.exit(main())
