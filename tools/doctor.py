#!/usr/bin/env python
"""Bundle triage: read an ``admin bundle`` directory and print what an
on-call operator needs first.

    python tools/doctor.py BUNDLE_DIR

The bundle (fluidframework_tpu/admin.py ``bundle --out DIR``) holds the
fleet's debug surface frozen at capture time: ``placement.json`` (epoch
table + membership), and per core under ``cores/<owner>/`` the metrics
scrape (``scrape.prom``), windowed history rings (``history.json``),
journal tail (``journal.jsonl``), SLO status (``slo.json``), rebalancer
status (``rebalance.json``) and any flight dumps that were readable at
capture (``flight/``); plus ``lint.json`` — the capturing build's
``fluidlint --json`` report — whenever the repo checkout was present.
The doctor joins these into a triage report:

1. fleet summary — cores, states, capture errors;
2. hop-pair latency table — the slowest legs of the pipeline by mean,
   from each core's scrape (where the tail latency actually lives:
   relay depth, shed parking, device dispatch);
3. SLO burn — specs not in ``ok``, with their windowed p99 vs budget;
4. recent migrations — each commit/fail with its CAUSAL CHAIN walked
   root-first through the merged fleet journal (operator command or
   rebalance plan → seal → fence → checkpoint → adopt → epoch bump);
5. anomalies — orphaned partitions (owner not in the membership),
   draining/drained cores still owning partitions, migration failures,
   rebalance suppression storms, version-skew hop drops
   (``obs.trace.unknown_hops``), disarmed journals, journal write
   errors, cold-start regressions from ``boot.json`` (a doc that paid
   a whole-log replay, or parked boots idling against a refilled
   admission bucket — the storm stalled), static-contract
   violations in the capturing build (a dirty ``lint.json`` in
   production is an incident signal of its own — someone deployed past
   the gate), wedged migrations (a ``migration.fence`` with no
   commit/fail while the journal kept moving — the partition sealed
   with nobody coming to adopt it), and the multi-host trio: an UNREACHABLE HOST GROUP
   (every core a host id advertises failed capture — a machine down,
   not a core restarting), a CROSS-HOST EPOCH REGRESSION (a later
   ``epoch.bump`` with a lower epoch for the same partition — two
   cores wrote the table through different planes), and remote-table
   writes rejected by the door's fence
   (``placement.table.stale_rejections`` in a scrape — a zombie
   ex-owner kept writing after takeover).

Read-only; exit 0 with "healthy" when nothing needs attention, exit 1
when any anomaly or active SLO burn was found (so a CI gate can assert
a bundle is quiet — or assert it ISN'T after a forced incident).

The anomaly rules themselves live in ``tools/doctor_rules.py``, shared
verbatim with the in-process streaming doctor
(``fluidframework_tpu/obs/health.py``) — the live verdict and the
post-incident bundle verdict run the SAME code, never a re-derivation.
"""

from __future__ import annotations

import json
import os
import re
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from fluidframework_tpu.obs.journal import (  # noqa: E402
    causal_chain,
    merge_entries,
)
from tools import doctor_rules as rules  # noqa: E402
from tools.doctor_rules import (  # noqa: E402,F401  (re-exported names)
    STORM_THRESHOLD,
    scrape_counter as _scrape_counter,
)

#: scrape lines for the hop summaries: fluid_obs_hop_ms_count{...} N
_SCRAPE_RE = re.compile(
    r'^fluid_obs_hop_ms_(count|sum)\{([^}]*)\}\s+([0-9.eE+-]+)')
_LABEL_RE = re.compile(r'(\w+)="([^"]*)"')


def _load_json(path):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _load_journal(path) -> list:
    out = []
    try:
        f = open(path)
    except OSError:
        return out
    with f:
        for line in f:
            try:
                e = json.loads(line)
            except ValueError:
                continue
            if isinstance(e, dict) and "kind" in e:
                out.append(e)
    return out


def _hop_table(scrape_text: str) -> dict:
    """pair → (count, sum_ms) from one core's Prometheus scrape."""
    acc: dict = {}
    for line in scrape_text.splitlines():
        m = _SCRAPE_RE.match(line)
        if m is None:
            continue
        stat, labels_s, val = m.group(1), m.group(2), float(m.group(3))
        labels = dict(_LABEL_RE.findall(labels_s))
        pair = labels.get("pair")
        if pair is None:
            continue
        count, total = acc.get(pair, (0.0, 0.0))
        if stat == "count":
            count += val
        else:
            total += val
        acc[pair] = (count, total)
    return acc


def _fmt_entry(e: dict) -> str:
    labels = " ".join(f"{k}={v}" for k, v in
                      sorted((e.get("labels") or {}).items()))
    epoch = e.get("epoch")
    return (f"e{epoch if epoch is not None else '-'} "
            f"[{e.get('id')}] {e.get('kind')}  {labels}")


def diagnose(bundle_dir: str) -> dict:
    """Parse the bundle into a triage dict (the printable report's
    data source — tests and the net_smoke gate assert on this)."""
    report: dict = {"cores": {}, "hop_pairs": [], "slo_burn": [],
                    "migrations": [], "anomalies": [], "lint": None}
    anomalies = report["anomalies"]
    manifest = _load_json(os.path.join(bundle_dir, "manifest.json")) or {}
    # static-contract status of the build that captured the bundle
    # (admin bundle runs `fluidlint --json` when the repo is present):
    # a dirty tree in production is itself an incident signal
    lint = _load_json(os.path.join(bundle_dir, "lint.json"))
    report["lint"] = lint
    anomalies.extend(rules.lint_anomalies(lint))
    placement = _load_json(os.path.join(bundle_dir, "placement.json"))
    cores_dir = os.path.join(bundle_dir, "cores")
    owners = (sorted(os.listdir(cores_dir))
              if os.path.isdir(cores_dir) else [])

    hop_acc: dict = {}
    per_core_journals = []
    for owner in owners:
        cdir = os.path.join(cores_dir, owner)
        row = dict(manifest.get("cores", {}).get(owner, {}))
        report["cores"][owner] = row
        anomalies.extend(rules.capture_error_anomalies(owner, row))
        scrape_path = os.path.join(cdir, "scrape.prom")
        try:
            with open(scrape_path) as f:
                scrape = f.read()
        except OSError:
            scrape = ""
        for pair, (count, total) in _hop_table(scrape).items():
            c, t = hop_acc.get(pair, (0.0, 0.0))
            hop_acc[pair] = (c + count, t + total)
        anomalies.extend(rules.scrape_anomalies(owner, scrape))
        journal = _load_journal(os.path.join(cdir, "journal.jsonl"))
        per_core_journals.append(journal)
        anomalies.extend(
            rules.journal_disarmed_anomalies(owner, row, journal))
        err = sum(1 for e in journal if e.get("kind") == "core.recover")
        if err:
            row["recoveries"] = err
        slo = _load_json(os.path.join(cdir, "slo.json")) or {}
        report["slo_burn"].extend(rules.slo_burn_rows(owner, slo))
        # cold-start surface: rehydration progress at capture time
        boot = _load_json(os.path.join(cdir, "boot.json"))
        if boot is not None:
            ex = boot.get("executor") or {}
            booted = sum(p.get("docs_booted", 0)
                         for p in boot.get("parts", []))
            pending = sum(p.get("docs_pending", 0)
                          for p in boot.get("parts", []))
            row["boot"] = {"booted": booted, "pending": pending,
                           "parked": ex.get("parked", 0)}
            anomalies.extend(rules.boot_anomalies(owner, boot))
        anomalies.extend(
            rules.suppression_storm_anomalies(owner, journal))

    report["hop_pairs"] = sorted(
        ((pair, count, total / count if count else 0.0, total)
         for pair, (count, total) in hop_acc.items()),
        key=lambda r: -r[3])

    merged = merge_entries(per_core_journals)
    report["journal_merged"] = merged
    # cross-host epoch regressions, then wedged migrations (a fence
    # with no commit/fail while the journal kept moving) — both over
    # the wall-clock-merged fleet journal
    anomalies.extend(rules.epoch_regression_anomalies(merged))
    anomalies.extend(rules.fence_without_commit_anomalies(merged))
    for e in merged:
        if e.get("kind") in ("migration.commit", "migration.fail"):
            report["migrations"].append(
                {"entry": e, "chain": causal_chain(merged, e["id"])})
            if e["kind"] == "migration.fail":
                anomalies.append(rules.migration_fail_anomaly(e))
    report["migrations"] = report["migrations"][-5:]

    anomalies.extend(
        rules.placement_anomalies(placement, report["cores"]))
    return report


def print_report(report: dict) -> None:
    print("== fleet ==")
    for owner, row in sorted(report["cores"].items()):
        extra = ""
        if row.get("recoveries"):
            extra += f"  recoveries={row['recoveries']}"
        if row.get("boot"):
            b = row["boot"]
            extra += (f"  boot={b['booted']}/"
                      f"{b['booted'] + b['pending']}")
        if row.get("error"):
            extra += "  CAPTURE-ERROR"
        print(f"  core {owner} @ {row.get('addr', '?')}"
              f"  journal={'armed' if row.get('journal_armed') else 'off'}"
              f"{extra}")
    print("\n== slowest hop pairs (fleet, by total ms) ==")
    if not report["hop_pairs"]:
        print("  (no hop observations in any scrape)")
    for pair, count, mean, total in report["hop_pairs"][:8]:
        print(f"  {pair:<22} n={int(count):<8} mean {mean:8.3f} ms  "
              f"total {total:10.1f} ms")
    print("\n== SLO burn ==")
    if not report["slo_burn"]:
        print("  all specs ok")
    for r in report["slo_burn"]:
        print(f"  [{r['state'].upper()}] {r['slo']} on {r['core']}: "
              f"p99 {r['p99_ms']}ms / budget {r['budget_ms']}ms "
              f"(burn {r['burn']}/{r['burn_ticks']})")
    print("\n== recent migrations (causal chains, root first) ==")
    if not report["migrations"]:
        print("  none in the journal window")
    for m in report["migrations"]:
        print(f"  {_fmt_entry(m['entry'])}")
        for link in m["chain"]:
            print(f"    {_fmt_entry(link)}")
    print("\n== static contracts (capturing build) ==")
    lint = report.get("lint")
    if lint is None:
        print("  (no lint.json in bundle — captured without the repo)")
    elif lint.get("clean"):
        waived = lint.get("waived", [])
        print(f"  clean ({len(lint.get('passes', []))} passes"
              f", {len(waived)} waived concurrency finding(s))")
    else:
        print(f"  DIRTY: {len(lint.get('violations', []))} "
              "violation(s) — see anomalies")
    print("\n== anomalies ==")
    if not report["anomalies"]:
        print("  none — healthy")
    for a in report["anomalies"]:
        print(f"  ! {a}")


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1 or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 2
    bundle_dir = argv[0]
    if not os.path.isdir(bundle_dir):
        print(f"not a bundle directory: {bundle_dir}")
        return 2
    report = diagnose(bundle_dir)
    print_report(report)
    return 1 if report["anomalies"] or report["slo_burn"] else 0


if __name__ == "__main__":
    sys.exit(main())
