#!/usr/bin/env bash
# Repo lint gate: the static contract checker + a pytest collection
# smoke test (import errors surface here, not mid-CI).
#
#   tools/lint.sh              # all fluidlint passes + collection check
#   tools/lint.sh layers       # just one fluidlint pass
#   tools/lint.sh --fix-order  # print the canonical lock order table
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

if [ "${1:-}" = "--fix-order" ]; then
    exec python -m tools.fluidlint --fix-order
fi

if [ "$#" -gt 0 ]; then
    args=()
    for p in "$@"; do args+=(--pass "$p"); done
    python -m tools.fluidlint "${args[@]}"
    exit 0
fi

python -m tools.fluidlint

echo "--- pytest collection check"
python -m pytest tests/ -q --collect-only -p no:cacheprovider >/dev/null
echo "collection: ok"
