#!/usr/bin/env bash
# CI gate: static contracts, import health, and a deterministic chaos
# smoke — everything a commit must survive before the full test run.
#
#   tools/ci.sh              # fluidlint + collection + net smoke + soak
#   tools/ci.sh --no-soak    # skip the soak (doc-only changes)
#
# The soak runs the seeded fault campaign at a FIXED seed so a CI
# failure reproduces exactly with the same command locally:
#   python -m fluidframework_tpu.chaos.soak --seed 0 --quick
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

run_soak=1
if [ "${1:-}" = "--no-soak" ]; then
    run_soak=0
fi

echo "--- fluidlint (static contracts)"
python -m tools.fluidlint

# strict concurrency gate, run on its own so the CI log carries the
# waiver ledger (every sanctioned crossing + its one-line argument)
# as a first-class record: the commit fails on ANY unwaivered
# cross-affinity call, loop-blocking reach, unfenced shared write, or
# lock-order inversion — and on any stale waiver, so the exception
# table cannot outlive the code it excuses
echo "--- fluidlint concurrency pass (strict: zero unwaivered findings)"
python -m tools.fluidlint --pass concurrency

echo "--- pytest collection check"
python -m pytest tests/ -q --collect-only -p no:cacheprovider >/dev/null
echo "collection: ok"

echo "--- socket-tier batching smoke"
python -m tools.net_smoke

echo "--- multichip mesh smoke (8 forced host devices)"
# counter-asserts the mesh lane's structural claims: per-wave staged
# bytes scale with ACTIVE shards (never O(max_docs)), the sharded step
# compiles exactly once per wave shape, and pipelined waves drive
# applier.stage.overlap_ratio positive (the stage/execute overlap
# really overlapped)
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m tools.bench_multichip --smoke

if [ "$run_soak" = 1 ]; then
    # three seeds so the overlap-window crash seams (wave N in flight /
    # wave N+1 staged, both orders) land at different pipeline phases
    for seed in 0 7 42; do
        echo "--- chaos soak (seed $seed, quick)"
        python -m fluidframework_tpu.chaos.soak --seed "$seed" --quick
        echo "soak seed $seed: ok"
    done
    echo "--- chaos soak, 2-shard mesh applier (fixed seed, quick)"
    python -m fluidframework_tpu.chaos.soak --seed 0 --quick --phases a \
        --mesh-shards 2
    echo "mesh soak: ok"
    echo "--- noisy-neighbor overload scenario (fixed seed, quick)"
    python -m fluidframework_tpu.chaos.noisy --seed 0 --quick
    echo "noisy: ok"
    echo "--- chaos migration campaign (fixed seed, quick)"
    python -m fluidframework_tpu.chaos.migrate --seed 0 --quick
    echo "migrate: ok"
    echo "--- chaos rebalance campaign (fixed seed, quick)"
    # hotspot storm + flap bait + elastic 2->4->2 against the armed
    # self-driving placement loop; full-mode seeds 0/7/42 add the
    # core kill -9 + auto-heal phase (run manually before release)
    python -m fluidframework_tpu.chaos.rebalance --seed 0 --quick
    echo "rebalance: ok"
    echo "--- chaos cold-start campaign (fixed seed, quick)"
    # full-cluster kill -9 mid-traffic, restart twice from the same
    # topology spec (once with the rehydration crash seam armed),
    # exact-once token audit + boot.part.full_replay == 0 fleet-wide;
    # full-mode seeds 0/7/42 run manually before release
    python -m fluidframework_tpu.chaos.coldstart --seed 0 --quick
    echo "coldstart: ok"
fi

echo "ci: all gates passed"
