"""The frozen registries, in one manifest.

Each fluidlint pass used to hand-roll its own loader for the contract
table it checks against — the journal pass parsed ``KINDS`` out of
``obs/journal.py``, the metric pass carried ``LOCKED_FAMILIES`` inline,
the wire pass knew the codec files but not the frame-id inventory, and
the hop taxonomy lived only in ``utils/telemetry.py``. This module is
the single home: every registry that is a WIRE or ALERT contract (ids
and names other builds/dashboards key on) loads or lives here, so a
pass that needs one imports it instead of re-parsing, and a human
auditing "what is frozen in this tree" reads one file.

Registries:

- :func:`load_journal_kinds` — the audit journal's closed kind
  vocabulary (``obs/journal.py`` ``KINDS``; must stay a pure literal).
- :func:`load_hops` — the hop taxonomy (``utils/telemetry.py``
  ``HOPS``): ids 0–8 are FROZEN wire values stamped into trace tails.
- :data:`LOCKED_FAMILIES` — metric families whose exact member sets
  are alert-surface contracts (moved here from metrics_check).
- :func:`load_frame_types` — the binary codec's ``FT_*`` frame ids
  (``protocol/binwire.py``); ids are frozen wire values.
- :data:`FT_CODECS` — frame type → (encoder, decoder) pairing: every
  frame id on the wire must have both halves, checked by wire_check.
- :data:`LOCK_ORDER` / :data:`LOCK_DOC` — the single global lock
  acquisition order the concurrency pass enforces (outermost first).
"""

from __future__ import annotations

import ast
import os
from typing import Optional

#: Declaring modules (repo-relative).
JOURNAL_KINDS_HOME = os.path.join("fluidframework_tpu", "obs",
                                  "journal.py")
HOPS_HOME = os.path.join("fluidframework_tpu", "utils", "telemetry.py")
BINWIRE_HOME = os.path.join("fluidframework_tpu", "protocol",
                            "binwire.py")


def _repo_root() -> str:
    return os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", ".."))


def _module_literal(path: str, name: str):
    """The value of a module-level ``name = <pure literal>`` assignment,
    or None when missing / not a literal."""
    try:
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return None
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == name):
            try:
                return ast.literal_eval(node.value)
            except ValueError:
                return None
    return None


# ---------------------------------------------------------- journal kinds

def load_journal_kinds(repo_root: Optional[str] = None
                       ) -> Optional[frozenset]:
    """The declared journal kind set, or None when the KINDS table is
    missing or not a pure literal (the journal pass reports that)."""
    repo_root = repo_root or _repo_root()
    kinds = _module_literal(
        os.path.join(repo_root, JOURNAL_KINDS_HOME), "KINDS")
    if isinstance(kinds, dict):
        return frozenset(kinds)
    return None


# ------------------------------------------------------------ hop taxonomy

def load_hops(repo_root: Optional[str] = None) -> Optional[tuple]:
    """The hop taxonomy as ((service, action, short), ...) — index IS
    the frozen wire id. None when HOPS is missing or not a literal."""
    repo_root = repo_root or _repo_root()
    hops = _module_literal(os.path.join(repo_root, HOPS_HOME), "HOPS")
    if (isinstance(hops, tuple)
            and all(isinstance(h, tuple) and len(h) == 3 for h in hops)):
        return hops
    return None


# -------------------------------------------------------- metric families

#: prefix -> exact member set. These families are overload-control
#: alert surfaces (SLO dashboards, the overload bench's gates, the
#: noisy-neighbor scenario); a name under one of these prefixes that
#: is not in the set is either a typo or an unreviewed contract change.
LOCKED_FAMILIES = {
    "obs.slo.": frozenset({"obs.slo.state", "obs.slo.violations"}),
    # the live health plane (obs/probe.py + obs/health.py): the
    # net-smoke health gate, `admin health --fleet`, and the rolling-
    # upgrade wait_healthy primitive all key on these exact names —
    # probe.ms{door} is the canary's per-door latency window,
    # engine.state is the per-component ok/degraded/critical gauge
    "health.": frozenset({"health.probe.ms", "health.probe.failures",
                          "health.engine.state"}),
    "net.admission.": frozenset({"net.admission.shed",
                                 "net.admission.delayed"}),
    # the snapshot fast-boot plane: the net-smoke catch-up gate, the
    # join-storm bench, and the chaos soak all key on these exact names;
    # boot.part.* witness the fleet cold-start contract (lazy == every
    # existing doc booted O(snapshot+tail), full_replay == the count the
    # cold-storm bench and net_smoke gate assert ZERO) and
    # boot.parked.retries is the driver's storm-admission retry lane
    # (service/rehydrate.py, service/local_orderer.py)
    "boot.": frozenset({"boot.snapshot.used", "boot.snapshot.fallback",
                        "boot.snapshot.reanchor", "boot.backfill.bounded",
                        "boot.backfill.full", "boot.chunks.fetched",
                        "boot.chunks.cached",
                        "boot.part.lazy", "boot.part.full_replay",
                        "boot.part.fresh", "boot.part.parked",
                        "boot.parked.retries"}),
    # the topology spec / fleet launcher (service/topology.py): the
    # cold-storm bench and the coldstart chaos drill key on these to
    # prove restarts really went through the one declarative spec
    # topology.fleet.host_kills / host_starts witness whole-host-group
    # chaos (chaos/multihost.py kill -9's one host's process group and
    # resurrects it through the same spec)
    "topology.": frozenset({"topology.fleet.starts",
                            "topology.fleet.restarts",
                            "topology.fleet.kills",
                            "topology.fleet.host_kills",
                            "topology.fleet.host_starts",
                            "topology.core.spawns"}),
    "storage.snapshot.": frozenset({"storage.snapshot.encodes",
                                    "storage.snapshot.cache_hits",
                                    "storage.snapshot.served",
                                    "storage.snapshot.legacy_tree",
                                    "storage.snapshot.chunks_written",
                                    "storage.snapshot.chunks_reused"}),
    # the device-dispatch pipeline: MULTICHIP's smoke gate counter-
    # asserts overlap_ratio, profile_applier prints the stage/execute
    # split, and the r7+ plateau analysis keys on these exact names
    # (service/tpu_applier.py)
    "applier.": frozenset({"applier.kernel.recompiled",
                           "applier.stage.seconds",
                           "applier.stage.bytes",
                           "applier.stage.overlap_ratio",
                           "applier.exec.seconds"}),
    # the placement control plane: the net-smoke migration gate, the
    # admin CLI, and the chaos migration campaign key on these exact
    # names (service/placement_plane.py); placement.heat.* are the
    # rebalancer's windowed per-partition load series and
    # placement.rebalance.* count the self-driving loop's decisions —
    # the storm bench's flap-free gate keys on them.
    # placement.table.* are the networked table plane's client-side
    # counters (service/table_client.py): the multi-host bench publishes
    # cache_hits/rpc_reads as the coherence-protocol hit rate, and the
    # doctor flags stale_rejections > 0 as a fenced zombie writer;
    # heat.scrape_timeouts counts peers dropped from a fleet heat
    # fan-out by the per-peer dial deadline (service/rebalancer.py)
    "placement.": frozenset({"placement.epoch.bumps",
                             "placement.epoch.stale_nacks",
                             "placement.cache.hits",
                             "placement.cache.refreshes",
                             "placement.cache.invalidations",
                             "placement.submits.redirected",
                             "placement.migration.fences",
                             "placement.migration.committed",
                             "placement.migration.failed",
                             "placement.migration.adopted",
                             "placement.heat.ops",
                             "placement.heat.bytes",
                             "placement.heat.scrape_timeouts",
                             "placement.table.rpc_reads",
                             "placement.table.rpc_writes",
                             "placement.table.cache_hits",
                             "placement.table.stale_rejections",
                             "placement.rebalance.ticks",
                             "placement.rebalance.plans",
                             "placement.rebalance.migrations_issued",
                             "placement.rebalance.suppressed_hysteresis",
                             "placement.rebalance.suppressed_budget"}),
    # the read-scale fan-out tier (ISSUE 12): the net-smoke relay gate
    # counter-asserts splices > 0 and encodes == 0 above the first
    # gateway level, and the read-storm bench keys on upstream bytes —
    # these exact names are the relay tree's perf contract
    # (service/gateway.py). NOTE: "fanout." does not collide with the
    # front end's "net.fanout.*" cache counters — prefixes match from
    # the name's start.
    # fanout.upstream.same_host / cross_host split route resolutions by
    # host locality (ISSUE 19): the multi-host bench's locality hit
    # rate is same_host / (same_host + cross_host)
    "fanout.": frozenset({"fanout.relay.splices",
                          "fanout.relay.encodes",
                          "fanout.upstream.frames",
                          "fanout.upstream.bytes",
                          "fanout.upstream.same_host",
                          "fanout.upstream.cross_host"}),
    # the ephemeral presence lane: the soak's drop/dup rules prove loss
    # is invisible BECAUSE coalescing happens, which only these names
    # witness (service/presence.py)
    "presence.": frozenset({"presence.lane.signals",
                            "presence.lane.coalesced",
                            "presence.lane.flushes",
                            "presence.lane.delivered"}),
    "session.readonly.": frozenset({"session.readonly.connects"}),
    # the control-plane audit journal's own health counters: the bench
    # journal A/B and the doctor's write-error triage key on these
    # exact names (obs/journal.py)
    "obs.journal.": frozenset({"obs.journal.entries",
                               "obs.journal.bytes",
                               "obs.journal.errors",
                               "obs.journal.rotations"}),
    # the doc history plane: the net-smoke history gate and the
    # fork-storm bench counter-assert fork boots / replay reads /
    # integrate ops on these exact names (service/history_plane.py;
    # history.replay.legacy is the replay tool's whole-log-replay
    # deprecation gauge, replay/tool.py)
    "history.": frozenset({"history.commit.records",
                           "history.fork.boots",
                           "history.fork.tail_ops",
                           "history.replay.reads",
                           "history.replay.log_scans",
                           "history.replay.legacy",
                           "history.integrate.sessions",
                           "history.integrate.ops",
                           "history.gc.scanned",
                           "history.gc.pinned",
                           "history.gc.deleted",
                           "history.ref.recovered"}),
}


# ----------------------------------------------------------- frame types

def load_frame_types(repo_root: Optional[str] = None) -> dict:
    """Module-level ``FT_* = <int>`` assignments from the binary codec:
    {name: (id, lineno)}. Ids are frozen wire values."""
    repo_root = repo_root or _repo_root()
    path = os.path.join(repo_root, BINWIRE_HOME)
    out: dict = {}
    try:
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return out
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id.startswith("FT_")
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, int)):
            out[node.targets[0].id] = (node.value.value, node.lineno)
    return out


#: frame type -> (encoder fn, decoder fn) in protocol/binwire.py. Both
#: halves must exist for every id on the wire: a frame a peer can send
#: that this build cannot read (or the reverse) is version skew baked
#: into one binary. wire_check asserts the manifest covers every FT_*
#: assignment and that both named functions are defined.
FT_CODECS = {
    "FT_SUBMIT": ("encode_submit", "decode_submit"),
    "FT_OPS": ("encode_ops", "decode_ops"),
    "FT_FSUBMIT": ("encode_submit", "decode_submit"),
    "FT_FOPS": ("encode_ops", "decode_ops"),
    "FT_COLS_SUBMIT": ("encode_submit_columns", "decode_submit_columns"),
    "FT_COLS_FSUBMIT": ("encode_submit_columns",
                        "decode_submit_columns"),
    "FT_COLS_OPS": ("stamp_cols_ops", "decode_cols_ops"),
    "FT_COLS_FOPS": ("stamp_cols_ops", "decode_cols_ops"),
    "FT_COLS_DELTAS": ("cols_deltas_body", "read_cols_deltas"),
    "FT_COLS_SNAP": ("snap_chunk_body", "read_snap_chunk"),
    "FT_PRESENCE": ("encode_presence", "decode_presence"),
    "FT_FPRESENCE": ("encode_presence", "decode_presence"),
    "FT_HISTORY": ("encode_history_commit", "decode_history_commit"),
}


# ------------------------------------------------------------- lock order

#: THE global lock acquisition order, outermost first. A function that
#: acquires a later lock may not then acquire an earlier one — the
#: concurrency pass enforces this over `with` nesting and @holds_lock
#: annotations, so an epoch-table↔lease deadlock cannot land silently
#: as multi-host fleet ops add acquirers. `tools/lint.sh --fix-order`
#: prints this table.
LOCK_ORDER = (
    "epoch_table_flock",      # placement_plane._flock(table.lock)
    "partition_claim_flock",  # placement.PlacementDir._lock(k)
    "applier_lock",           # tpu_applier.TpuDocumentApplier._lock
    "journal_lock",           # obs.journal.Journal._lock
)

#: lock name -> what it guards (printed by --fix-order and the report).
LOCK_DOC = {
    "epoch_table_flock": "the fleet epoch table file "
                         "(service/placement_plane.py _flock)",
    "partition_claim_flock": "per-partition lease files "
                             "(service/placement.py PlacementDir._lock)",
    "applier_lock": "the applier's staging double-buffer "
                    "(service/tpu_applier.py, worker vs ingest)",
    "journal_lock": "the audit journal's append stream "
                    "(obs/journal.py Journal._lock)",
}

LOCK_RANK = {name: i for i, name in enumerate(LOCK_ORDER)}
