"""CLI: ``python -m tools.fluidlint [--pass NAME]... [--emit-packages-md]``.

Exit codes: 0 clean, 1 violations found, 2 internal error.
"""

from __future__ import annotations

import argparse
import os
import sys

PASSES = ("layers", "jaxpr", "wire", "hygiene", "metric-name", "storage",
          "journal-kind")


def run(passes, repo_root: str) -> list:
    from . import (hygiene, jaxpr_check, journal_check, layers,
                   metrics_check, storage_check, wire_check)

    violations = []
    if "layers" in passes:
        violations += layers.check_layers(repo_root=repo_root)
        violations += layers.check_classified(repo_root=repo_root)
        violations += layers.check_packages_md(repo_root=repo_root)
    if "jaxpr" in passes:
        violations += jaxpr_check.check_kernels()
    if "wire" in passes:
        violations += wire_check.check_wire(repo_root=repo_root)
    if "hygiene" in passes:
        violations += hygiene.check_hygiene(repo_root=repo_root)
    if "metric-name" in passes:
        violations += metrics_check.check_metrics(repo_root=repo_root)
    if "storage" in passes:
        violations += storage_check.check_storage(repo_root=repo_root)
    if "journal-kind" in passes:
        violations += journal_check.check_journal_kinds(
            repo_root=repo_root)
    return violations


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.fluidlint",
        description="static contract checker: layer DAG, TPU hot-path "
                    "jaxpr contracts, wire-format widths")
    ap.add_argument("--pass", dest="passes", action="append",
                    choices=PASSES, metavar="|".join(PASSES),
                    help="run only the named pass (repeatable); "
                         "default: all")
    ap.add_argument("--emit-packages-md", nargs="?", const="PACKAGES.md",
                    metavar="PATH",
                    help="regenerate the layer listing (like the "
                         "reference's generated PACKAGES.md) and exit")
    ap.add_argument("--repo-root", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    repo_root = args.repo_root or os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", ".."))

    if args.emit_packages_md is not None:
        from . import layers

        out_path = args.emit_packages_md
        if not os.path.isabs(out_path):
            out_path = os.path.join(repo_root, out_path)
        content = layers.emit_packages_md(repo_root=repo_root)
        with open(out_path, "w") as f:
            f.write(content)
        print(f"wrote {out_path}")
        return 0

    # the jaxpr pass traces kernels; keep it off any real accelerator
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    passes = tuple(args.passes) if args.passes else PASSES
    violations = run(passes, repo_root)
    for v in violations:
        print(v)
    n = len(violations)
    names = ", ".join(passes)
    if n:
        print(f"\nfluidlint: {n} violation(s) [{names}]")
        return 1
    print(f"fluidlint: clean [{names}]")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except Exception:  # noqa: BLE001 — distinguish crash from findings
        import traceback

        traceback.print_exc()
        sys.exit(2)
