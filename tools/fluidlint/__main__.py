"""CLI: ``python -m tools.fluidlint [--pass NAME]... [--emit-packages-md]``.

Exit codes: 0 clean, 1 violations found, 2 internal error.

``--json`` emits a machine-readable report instead of text —
``tools/doctor.py`` embeds it in debug bundles so a triage reads lint
status next to the journal and metrics history. The concurrency pass
also reports its applied waivers (each with its one-line
justification), so the report always shows which contract crossings
are sanctioned, not just that the tree is "clean".
"""

from __future__ import annotations

import argparse
import json
import os
import sys

PASSES = ("layers", "jaxpr", "wire", "hygiene", "metric-name", "storage",
          "journal-kind", "concurrency")


def run(passes, repo_root: str, waived_out=None) -> list:
    from . import (concurrency_check, hygiene, jaxpr_check, journal_check,
                   layers, metrics_check, storage_check, wire_check)

    violations = []
    if "layers" in passes:
        violations += layers.check_layers(repo_root=repo_root)
        violations += layers.check_classified(repo_root=repo_root)
        violations += layers.check_packages_md(repo_root=repo_root)
    if "jaxpr" in passes:
        violations += jaxpr_check.check_kernels()
    if "wire" in passes:
        violations += wire_check.check_wire(repo_root=repo_root)
    if "hygiene" in passes:
        violations += hygiene.check_hygiene(repo_root=repo_root)
    if "metric-name" in passes:
        violations += metrics_check.check_metrics(repo_root=repo_root)
    if "storage" in passes:
        violations += storage_check.check_storage(repo_root=repo_root)
    if "journal-kind" in passes:
        violations += journal_check.check_journal_kinds(
            repo_root=repo_root)
    if "concurrency" in passes:
        violations += concurrency_check.check_concurrency(
            repo_root=repo_root, waived_out=waived_out)
    return violations


def print_lock_order() -> None:
    """``tools/lint.sh --fix-order``: the canonical lock table."""
    from .registries import LOCK_DOC, LOCK_ORDER

    print("global lock acquisition order (outermost first):")
    for i, name in enumerate(LOCK_ORDER):
        print(f"  {i}. {name:<24} {LOCK_DOC.get(name, '')}")
    print("\na function holding lock N may only acquire locks ranked "
          "after N;\n@holds_lock names must appear here "
          "(tools/fluidlint/registries.py).")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.fluidlint",
        description="static contract checker: layer DAG, TPU hot-path "
                    "jaxpr contracts, wire-format widths, concurrency "
                    "contracts")
    ap.add_argument("--pass", dest="passes", action="append",
                    choices=PASSES, metavar="|".join(PASSES),
                    help="run only the named pass (repeatable); "
                         "default: all")
    ap.add_argument("--json", action="store_true",
                    help="emit a machine-readable JSON report (doctor "
                         "embeds this in debug bundles)")
    ap.add_argument("--fix-order", action="store_true",
                    help="print the canonical lock acquisition order "
                         "table and exit")
    ap.add_argument("--emit-packages-md", nargs="?", const="PACKAGES.md",
                    metavar="PATH",
                    help="regenerate the layer listing (like the "
                         "reference's generated PACKAGES.md) and exit")
    ap.add_argument("--repo-root", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    repo_root = args.repo_root or os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", ".."))

    if args.fix_order:
        print_lock_order()
        return 0

    if args.emit_packages_md is not None:
        from . import layers

        out_path = args.emit_packages_md
        if not os.path.isabs(out_path):
            out_path = os.path.join(repo_root, out_path)
        content = layers.emit_packages_md(repo_root=repo_root)
        with open(out_path, "w") as f:
            f.write(content)
        print(f"wrote {out_path}")
        return 0

    # the jaxpr pass traces kernels; keep it off any real accelerator
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    passes = tuple(args.passes) if args.passes else PASSES
    waived: list = []
    violations = run(passes, repo_root, waived_out=waived)
    n = len(violations)
    if args.json:
        print(json.dumps({
            "clean": not n,
            "passes": list(passes),
            "violations": [
                {"pass": v.pass_name, "path": v.path, "line": v.line,
                 "message": v.message, "suggestion": v.suggestion}
                for v in violations],
            "waived": waived,
        }, indent=2))
        return 1 if n else 0
    for v in violations:
        print(v)
    names = ", ".join(passes)
    if waived:
        print(f"fluidlint: {len(waived)} waived concurrency finding(s):")
        for w in waived:
            print(f"  {w}")
    if n:
        print(f"\nfluidlint: {n} violation(s) [{names}]")
        return 1
    print(f"fluidlint: clean [{names}]")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except Exception:  # noqa: BLE001 — distinguish crash from findings
        import traceback

        traceback.print_exc()
        sys.exit(2)
