"""fluidlint: the repo's static contract checker.

Three passes, mirroring how the reference enforces its architecture
mechanically (tools/build-tools/src/layerCheck + generated PACKAGES.md):

1. **layers** — the package import DAG (`layers.ALLOWED` is the single
   source of truth; `tests/test_layering.py` delegates here) plus the
   generated `PACKAGES.md` staleness check.
2. **jaxpr** — TPU hot-path contracts: every registered kernel
   (`fluidframework_tpu.utils.contracts`) is abstract-evaled and its
   jaxpr checked for forbidden primitives (gather/scatter/dynamic-index
   while bodies), int16 silent promotion, and recompile regressions.
3. **wire** — wire-format widths: int16 packed-wave discipline and
   struct width/endianness in the binary codec; plus repo-wide hygiene
   (bare except, mutable defaults, import-time jnp calls).

Run ``python -m tools.fluidlint`` (exit 1 on any violation); wired into
tier-1 via ``tests/test_fluidlint.py`` and ``tools/lint.sh``.
"""

from .report import Violation  # noqa: F401
