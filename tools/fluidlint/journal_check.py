"""Journal-kind lint: the audit journal's closed vocabulary.

``obs/journal.py`` declares every control-plane event kind in its
``KINDS`` table — the entry schema's contract surface: ``admin journal
--kind`` filters by prefix, the net-smoke migration gate asserts exact
chains, and tools/doctor.py pattern-matches kinds for its triage rules.
An ``emit("migration.sealed", ...)`` typo would journal fine at runtime
on a *disarmed* journal (emit short-circuits before validation) and
only explode in production with the journal armed — precisely the
environment where the audit trail matters most.

This pass closes the loop statically:

- parse the ``KINDS`` dict literal out of ``obs/journal.py`` (it must
  STAY a pure literal — a computed table would be invisible here, so
  that too is a violation);
- walk every ``*.emit(...)`` call in the library package whose first
  argument (or ``kind=`` keyword) is a string literal — including both
  arms of a conditional expression like
  ``emit("core.recover" if seq else "core.start")`` — and require each
  literal to be a declared kind.

Stage backchannel ``emit({dict})`` calls and computed kinds are out of
scope (only literals are checkable), mirroring the metric-name pass.
"""

from __future__ import annotations

import ast
import os
from typing import Iterable, Optional

from .registries import JOURNAL_KINDS_HOME as KINDS_HOME
from .registries import load_journal_kinds
from .report import Violation

#: Swept directories (repo-relative), same scope as the metric pass.
JOURNAL_ROOTS = ("fluidframework_tpu",)


def load_kinds(repo_root: Optional[str] = None) -> Optional[frozenset]:
    """The declared kind set, or None when the KINDS table is missing
    or not a pure literal (reported as a violation by the caller).
    Delegates to the registry manifest (tools/fluidlint/registries.py)."""
    return load_journal_kinds(repo_root or _repo_root())


def _literal_kinds(node: ast.expr) -> Iterable[str]:
    """String literals reachable as the kind argument: a plain constant
    or either arm of a conditional expression."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        yield node.value
    elif isinstance(node, ast.IfExp):
        yield from _literal_kinds(node.body)
        yield from _literal_kinds(node.orelse)


def check_file(path: str, kinds: frozenset,
               repo_root: Optional[str] = None) -> list[Violation]:
    repo_root = repo_root or _repo_root()
    rel = os.path.relpath(path, repo_root)
    with open(path) as f:
        try:
            tree = ast.parse(f.read(), filename=path)
        except SyntaxError:
            return []  # the hygiene pass reports syntax errors
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not (isinstance(func, ast.Attribute) and func.attr == "emit"):
            continue
        kind_arg = node.args[0] if node.args else None
        for kw in node.keywords:
            if kw.arg == "kind":
                kind_arg = kw.value
        if kind_arg is None:
            continue
        for kind in _literal_kinds(kind_arg):
            if kind not in kinds:
                out.append(Violation(
                    pass_name="journal-kind", path=rel,
                    line=node.lineno,
                    message=f'journal kind "{kind}" is not declared in '
                            "obs.journal.KINDS (the closed registry "
                            "admin journal / doctor triage key on)",
                    suggestion="fix the typo, or declare the new kind "
                               "in KINDS in the same change"))
    return out


def check_journal_kinds(repo_root: Optional[str] = None,
                        roots: tuple = JOURNAL_ROOTS) -> list[Violation]:
    repo_root = repo_root or _repo_root()
    kinds = load_kinds(repo_root)
    if kinds is None:
        return [Violation(
            pass_name="journal-kind", path=KINDS_HOME, line=1,
            message="KINDS is missing or not a pure dict literal — the "
                    "journal-kind lint cannot read the registry",
            suggestion="keep KINDS a literal dict of str -> str")]
    out: list[Violation] = []
    for r in roots:
        root = os.path.join(repo_root, r)
        if not os.path.isdir(root):
            continue
        for path in _py_files(root):
            out.extend(check_file(path, kinds, repo_root))
    return out


def _py_files(root: str) -> Iterable[str]:
    for dirpath, dirnames, files in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", "build", "fixtures")]
        for fn in sorted(files):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def _repo_root() -> str:
    return os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", ".."))
