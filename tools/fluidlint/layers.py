"""Pass 1 — layer-check: the package import DAG, as the single source
of truth.

Ref: tools/build-tools/src/layerCheck — the reference CI fails any build
whose packages import across the declared layer boundaries, and its
docs/PACKAGES.md layer listing is GENERATED from the same table, so the
docs can never drift from what CI enforces. This module is that table
for our tree: ``tests/test_layering.py`` delegates here, ``python -m
tools.fluidlint --emit-packages-md`` regenerates ``PACKAGES.md``, and
the default lint run fails when the checked-in listing is stale.

Layering (bottom → top), mirroring SURVEY §1's layer map:

    utils                (L1 base utils / telemetry / kernel contracts)
    protocol             (L0 defs + L2 shared consensus kernel)
    mergetree            (L6 CRDT core)
    ops, parallel        (TPU kernels / sharding over the mergetree model)
    dds                  (L6 DDS catalog)
    runtime              (L5)
    loader               (L4; the loader imports DRIVER interfaces)
    driver               (L3 — may bind to service for the local driver)
    framework            (L7)
    service              (S-layers: its own branch; may use protocol,
                          utils, mergetree-adjacent kernels, driver wire
                          helpers — but never runtime/loader/framework)
    replay, native       (tools / bindings)
"""

from __future__ import annotations

import ast
import os
from typing import Iterable, Optional

from .report import Violation

#: Default package root (repo-relative) the real-tree check walks.
PACKAGE = "fluidframework_tpu"

#: subpackage → the set of sibling subpackages it may import from.
#: An import of a package not in its set is a layering violation.
#: THE single source of truth: tests/test_layering.py asserts over this
#: table, and PACKAGES.md is generated from it.
ALLOWED = {
    "utils": set(),
    # the observability plane sits just above utils: registry + flight
    # recorder; any tier may report INTO it, it imports nothing back
    "obs": {"utils"},
    "protocol": {"utils"},
    "mergetree": {"protocol", "utils"},
    "ops": {"mergetree", "protocol", "utils"},
    "parallel": {"ops", "mergetree", "protocol", "utils"},
    "dds": {"mergetree", "ops", "protocol", "utils"},
    "runtime": {"dds", "mergetree", "ops", "protocol", "utils"},
    "loader": {"runtime", "dds", "mergetree", "protocol", "utils",
               "driver"},
    # drivers bind the loader contracts to a service; the local driver
    # reaches into service (the reference's local-driver does the same —
    # localDocumentService.ts binds straight to LocalDeltaConnectionServer)
    "driver": {"protocol", "utils", "service", "mergetree", "obs"},
    "framework": {"loader", "runtime", "dds", "mergetree", "protocol",
                  "utils"},
    # the service branch: protocol + utils + the TPU kernel stack; the
    # wire helpers live in driver (shared transport), NEVER runtime/loader
    "service": {"protocol", "utils", "ops", "parallel", "mergetree",
                "driver", "native", "obs"},
    "native": {"utils"},
    # obs: the replay tool reports history-first vs legacy whole-log
    # boots into the shared metrics registry (history.replay.legacy)
    "replay": {"loader", "driver", "runtime", "dds", "protocol", "utils",
               "service", "mergetree", "obs"},
    # the fault-injection plane sits beside the service: it may reach the
    # seams it arms (service/driver) and the layers they expose, but NO
    # production layer may import chaos back — the seams stay duck-typed
    # (`fault_plane = None` class attrs / module hooks), so disarmed code
    # has no chaos dependency at all; only tests and the soak import it.
    # loader/runtime: the soak's snapshot campaign boots full containers
    # through the columnar fast-boot plane as its late joiners
    "chaos": {"service", "driver", "mergetree", "protocol", "utils",
              "obs", "loader", "runtime"},
}

#: One-line role per layer, used by the PACKAGES.md generator.
LAYER_DOC = {
    "utils": "base utils: telemetry, metrics, kernel-contract registry",
    "obs": "observability: labeled metrics registry, Prometheus scrape, "
           "flight recorder",
    "protocol": "wire messages, consensus kernel, binary codec",
    "mergetree": "scalar merge-tree CRDT (the readable oracle)",
    "ops": "TPU device kernels: batched apply, doc state, Pallas path",
    "parallel": "mesh construction, doc/segment sharding",
    "dds": "distributed data structure catalog",
    "runtime": "container runtime, datastores, summarizer",
    "loader": "container boot, delta manager, quorum",
    "driver": "local / network / file drivers (wire transport)",
    "framework": "aqueduct: DataObject, undo-redo, interceptions",
    "service": "deli, scriptorium, scribe, TPU applier, front end, "
               "placement control plane",
    "native": "C++ durable op log + chunk store bindings",
    "replay": "replay tool + snapshot-regression corpus",
    "chaos": "deterministic fault injection + convergence invariant monitor",
}


def sibling_imports(path: str, root: str) -> list[tuple[str, int, str]]:
    """Sibling subpackages imported by ``path``: [(pkg, lineno, stmt)].

    ``root`` is the package directory the layering is declared over;
    both absolute ``package.sub`` imports (for the package named by the
    root dir) and relative ``..sub`` imports resolve to ``sub``.
    """
    package_name = os.path.basename(os.path.normpath(root))
    with open(path) as f:
        src = f.read()
    tree = ast.parse(src, filename=path)
    lines = src.splitlines()
    depth_from_root = os.path.relpath(path, root).count(os.sep)
    out = []

    def stmt(node):
        return lines[node.lineno - 1].strip() if node.lineno <= len(lines) \
            else ""

    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.level == 0:
                mod = node.module or ""
                if mod.startswith(package_name + "."):
                    out.append((mod.split(".")[1], node.lineno, stmt(node)))
            else:
                # relative: level 1 inside pkg/x.py = same package;
                # level 2 = the framework root (..sibling)
                if node.level == depth_from_root + 1 and node.module:
                    out.append((node.module.split(".")[0], node.lineno,
                                stmt(node)))
                elif node.level > depth_from_root + 1:
                    out.append(("<outside-package>", node.lineno,
                                stmt(node)))
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.startswith(package_name + "."):
                    out.append((alias.name.split(".")[1], node.lineno,
                                stmt(node)))
    return out


def package_files(root: str, allowed: dict) -> Iterable[tuple[str, str]]:
    """(subpackage, file path) for every .py under a classified layer."""
    for pkg in sorted(allowed):
        pkg_dir = os.path.join(root, pkg)
        if not os.path.isdir(pkg_dir):
            continue
        for dirpath, _, files in os.walk(pkg_dir):
            for fn in sorted(files):
                if fn.endswith(".py"):
                    yield pkg, os.path.join(dirpath, fn)


def _suggest(pkg: str, dep: str, allowed: dict) -> str:
    importers = sorted(layer for layer, deps in allowed.items()
                       if dep in deps)
    if importers:
        return (f"'{dep}' may only be imported from "
                f"{{{', '.join(importers)}}}; move the code there, invert "
                f"the dependency, or (deliberately) widen ALLOWED['{pkg}'] "
                f"in tools/fluidlint/layers.py")
    return (f"no layer may import '{dep}'; invert the dependency or "
            f"(deliberately) widen ALLOWED['{pkg}'] in "
            f"tools/fluidlint/layers.py")


def check_layers(root: Optional[str] = None,
                 allowed: Optional[dict] = None,
                 repo_root: Optional[str] = None) -> list[Violation]:
    """AST import walk over every classified layer; one Violation per
    cross-layer import, with file:line, the offending statement, and the
    layers the import would be legal from."""
    repo_root = repo_root or _repo_root()
    root = root or os.path.join(repo_root, PACKAGE)
    allowed = allowed if allowed is not None else ALLOWED
    violations = []
    for pkg, path in package_files(root, allowed):
        ok = allowed[pkg] | {pkg}
        for dep, lineno, stmt in sibling_imports(path, root):
            # only sibling SUBPACKAGES are layered; top-level modules
            # (config.py — the cross-cutting unified registry) are free
            if dep not in allowed or dep in ok:
                continue
            rel = os.path.relpath(path, repo_root)
            violations.append(Violation(
                pass_name="layers", path=rel, line=lineno,
                message=f"layer '{pkg}' may not import '{dep}' "
                        f"({stmt})",
                suggestion=_suggest(pkg, dep, allowed)))
    return violations


def check_classified(root: Optional[str] = None,
                     allowed: Optional[dict] = None,
                     repo_root: Optional[str] = None) -> list[Violation]:
    """A new subpackage must be placed in the layer map explicitly."""
    repo_root = repo_root or _repo_root()
    root = root or os.path.join(repo_root, PACKAGE)
    allowed = allowed if allowed is not None else ALLOWED
    found = {d for d in os.listdir(root)
             if os.path.isdir(os.path.join(root, d))
             and not d.startswith("__")}
    return [Violation(
        pass_name="layers", path=os.path.relpath(root, repo_root), line=0,
        message=f"subpackage '{d}' missing from the layer map",
        suggestion="add it to ALLOWED in tools/fluidlint/layers.py "
                   "(and to PACKAGES.md via --emit-packages-md)")
        for d in sorted(found - set(allowed))]


def _topo_layers(allowed: dict) -> list[str]:
    """Layers bottom-up (deps before dependents), name-stable."""
    out, placed = [], set()
    pending = dict(allowed)
    while pending:
        ready = sorted(p for p, deps in pending.items()
                       if set(deps) - {p} <= placed)
        if not ready:  # cycle: emit the rest sorted, deterministic
            out.extend(sorted(pending))
            break
        for p in ready:
            out.append(p)
            placed.add(p)
            del pending[p]
    return out


def emit_packages_md(root: Optional[str] = None,
                     allowed: Optional[dict] = None,
                     repo_root: Optional[str] = None) -> str:
    """The generated layer listing (the reference's PACKAGES.md analog).

    Deterministic over (ALLOWED, tree): regenerating on an unchanged
    tree is byte-identical, so CI can diff it against the checked-in
    copy."""
    repo_root = repo_root or _repo_root()
    root = root or os.path.join(repo_root, PACKAGE)
    allowed = allowed if allowed is not None else ALLOWED
    package_name = os.path.basename(os.path.normpath(root))
    modules: dict[str, list[str]] = {pkg: [] for pkg in allowed}
    for pkg, path in package_files(root, allowed):
        rel = os.path.relpath(path, os.path.join(root, pkg))
        if rel != "__init__.py":
            modules[pkg].append(rel.replace(os.sep, "/"))
    lines = [
        "# PACKAGES",
        "",
        "<!-- GENERATED by `python -m tools.fluidlint --emit-packages-md` "
        "from tools/fluidlint/layers.py — do not edit by hand. -->",
        "",
        f"Layer listing for `{package_name}/`, bottom-up. Each layer may "
        "import only the layers listed in its **may import** set; "
        "`python -m tools.fluidlint` (pass 1) fails the build on any "
        "other cross-layer import.",
        "",
    ]
    for pkg in _topo_layers(allowed):
        deps = sorted(allowed[pkg])
        lines.append(f"## {pkg}")
        lines.append("")
        doc = LAYER_DOC.get(pkg)
        if doc:
            lines.append(doc)
            lines.append("")
        lines.append("**may import:** "
                     + (", ".join(f"`{d}`" for d in deps) if deps
                        else "(nothing — bottom layer)"))
        lines.append("")
        mods = sorted(modules.get(pkg, []))
        if mods:
            lines.append("**modules:** "
                         + ", ".join(f"`{m}`" for m in mods))
            lines.append("")
    return "\n".join(lines)


def check_packages_md(md_path: Optional[str] = None,
                      repo_root: Optional[str] = None) -> list[Violation]:
    """Fail when the checked-in PACKAGES.md is stale (or missing)."""
    repo_root = repo_root or _repo_root()
    md_path = md_path or os.path.join(repo_root, "PACKAGES.md")
    want = emit_packages_md(repo_root=repo_root)
    try:
        with open(md_path) as f:
            have = f.read()
    except OSError:
        have = None
    if have == want:
        return []
    state = "missing" if have is None else "stale"
    return [Violation(
        pass_name="layers", path=os.path.relpath(md_path, repo_root),
        line=0,
        message=f"generated layer listing is {state}",
        suggestion="run `python -m tools.fluidlint --emit-packages-md` "
                   "and commit the result")]


def _repo_root() -> str:
    return os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", ".."))
