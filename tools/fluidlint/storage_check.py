"""Storage-tier hot-path lint.

Two checks, both born with the columnar segment store (PR 6):

- **json ban**: the storage hot-path modules (``service/durable_log.py``,
  ``service/segment_store.py``, ``native/oplog.py``) may not import
  ``json`` or call ``json.dumps``/``json.loads``. Per-record JSON codecs
  are exactly the cost the segment store exists to remove; every legacy
  shape lives in ``service/log_compat.py`` (the ONE exempted home, whose
  callers count trips under ``storage.log.legacy_json``). The lint also
  asserts the shim module exists — deleting it without a migration would
  silently re-scatter json across the tier.
- **declared storage metrics**: every literal ``storage.*`` name passed
  to ``.inc(...)``/``.observe(...)`` in the library must be in
  ``STORAGE_METRICS``, and every declared name must appear somewhere.
  Dashboards and the net-smoke gates key on these exact strings; a typo
  ("storage.segment.append") would scrape as a new always-zero series
  while the gate starves.
"""

from __future__ import annotations

import ast
import os
from typing import Optional

from .report import Violation

#: Modules banned from json (repo-relative). log_compat.py is the shim
#: for the log lane; summary_trees.py is the snapshot lane's exempted
#: legacy-tree twin (its callers count under storage.snapshot.legacy_tree).
JSON_BANNED = (
    os.path.join("fluidframework_tpu", "service", "durable_log.py"),
    os.path.join("fluidframework_tpu", "service", "segment_store.py"),
    os.path.join("fluidframework_tpu", "native", "oplog.py"),
    os.path.join("fluidframework_tpu", "protocol", "snapcols.py"),
)

COMPAT_SHIM = os.path.join("fluidframework_tpu", "service", "log_compat.py")

#: The storage tier's metric namespace, declared in one place.
STORAGE_METRICS = frozenset({
    "storage.segment.appends",    # segment blocks appended (both lanes' tears re-append)
    "storage.segment.decodes",    # SEG_COLS payloads decoded (backfill must NOT move this)
    "storage.segment.torn",       # chaos torn-tails left + recovered on a segment stream
    "storage.backfill.byterange", # raw block payloads served by delta_blocks
    "storage.log.legacy_json",    # deltas-lane records still riding the compat shim
    # snapshot fast-boot plane (the net-smoke catch-up gate keys on these)
    "storage.snapshot.encodes",        # framed-chunk cache fills (once per version)
    "storage.snapshot.cache_hits",     # joins served from already-framed bytes
    "storage.snapshot.served",         # snapshot boots served columnar
    "storage.snapshot.legacy_tree",    # whole-tree JSON shim trips (deprecation gauge)
    "storage.snapshot.chunks_written", # chunk blobs uploaded by the summarizer
    "storage.snapshot.chunks_reused",  # content-addressed dedupe across generations
})

_METHODS = ("inc", "observe")


def _check_json_ban(path: str, rel: str, tree: ast.AST) -> list[Violation]:
    out = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            mod = getattr(node, "module", None)
            names = [a.name for a in node.names]
            if mod == "json" or "json" in names:
                out.append(Violation(
                    pass_name="storage", path=rel, line=node.lineno,
                    message="json import in a storage hot-path module",
                    suggestion="route legacy shapes through "
                               "service/log_compat.py (the exempted shim)"))
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            func = node.func
            if (func.attr in ("dumps", "loads")
                    and isinstance(func.value, ast.Name)
                    and func.value.id == "json"):
                out.append(Violation(
                    pass_name="storage", path=rel, line=node.lineno,
                    message=f"json.{func.attr} on the storage hot path",
                    suggestion="use the columnar segment codec or "
                               "service/log_compat.py"))
    return out


def _iter_metric_names(tree: ast.AST):
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _METHODS and node.args):
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                yield node.lineno, arg.value


def check_storage(repo_root: Optional[str] = None) -> list[Violation]:
    repo_root = repo_root or _repo_root()
    out: list[Violation] = []

    if not os.path.exists(os.path.join(repo_root, COMPAT_SHIM)):
        out.append(Violation(
            pass_name="storage", path=COMPAT_SHIM, line=1,
            message="legacy-codec shim module is missing: the json ban "
                    "on the storage tier has nowhere to point",
            suggestion="restore service/log_compat.py (or migrate every "
                       "legacy record shape first)"))

    seen: set[str] = set()
    lib_root = os.path.join(repo_root, "fluidframework_tpu")
    for dirpath, dirnames, files in os.walk(lib_root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", "build", "fixtures")]
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, repo_root)
            with open(path) as f:
                try:
                    tree = ast.parse(f.read(), filename=path)
                except SyntaxError:
                    continue  # the hygiene pass reports syntax errors
            if rel in JSON_BANNED:
                out.extend(_check_json_ban(path, rel, tree))
            for line, name in _iter_metric_names(tree):
                if not name.startswith("storage."):
                    continue
                seen.add(name)
                if name not in STORAGE_METRICS:
                    out.append(Violation(
                        pass_name="storage", path=rel, line=line,
                        message=f'undeclared storage metric "{name}"',
                        suggestion="add it to STORAGE_METRICS in "
                                   "tools/fluidlint/storage_check.py (or "
                                   "fix the typo)"))
    for name in sorted(STORAGE_METRICS - seen):
        out.append(Violation(
            pass_name="storage", path="tools/fluidlint/storage_check.py",
            line=1,
            message=f'declared storage metric "{name}" is never '
                    "incremented anywhere in the library",
            suggestion="wire it up or drop it from STORAGE_METRICS"))
    return out


def _repo_root() -> str:
    return os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", ".."))
