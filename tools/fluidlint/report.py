"""Violation model shared by every fluidlint pass."""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Violation:
    """One finding: where, which pass, what, and how to fix it."""

    pass_name: str  # "layers" | "jaxpr" | "wire" | "hygiene"
    path: str       # repo-relative when possible
    line: int       # 1-based; 0 = whole-file / non-source finding
    message: str
    suggestion: str = ""

    def __str__(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        out = f"{loc}: [{self.pass_name}] {self.message}"
        if self.suggestion:
            out += f"\n    -> {self.suggestion}"
        return out
