"""Pass 3 — wire/width lint: the packed-wave and binary-codec widths.

The int16 packed wave (service/tpu_applier.py) and the struct-packed
socket frames (protocol/binwire.py) are both WIDTH contracts: a field
that silently widens (numpy promotes int16 + python-int to a wider
dtype without complaint) or a struct code whose size is
platform-dependent corrupts the wire without any test noticing until
bytes disagree across hosts. This pass enforces, by AST:

- **int16 discipline**: any name bound to an int16 array (``np.int16``
  / ``astype(int16)`` / a dtype argument / the ``*16`` naming
  convention of the wave format) may not appear as an operand of
  arithmetic — it must be explicitly widened (``.astype(...)``) first.
  The range-checked fallback to the int32 wide path is the sanctioned
  escape hatch; silent promotion is not.
- **struct widths**: every ``struct.Struct`` format in the wire codec
  must be explicitly big-endian (``>``) and use only fixed-width codes
  — native-size codes (``l``, ``L``, ``n``, ``P``, or a bare native
  prefix) change width across platforms.

The dtype-level twin of the int16 rule runs in pass 2: the registered
packed-wave kernel's jaxpr must contain no arithmetic primitive
consuming an int16 operand (``no_int16_arithmetic``).
"""

from __future__ import annotations

import ast
import os
from typing import Optional

from .registries import BINWIRE_HOME, FT_CODECS, load_frame_types
from .report import Violation

#: Files the wire pass covers on the real tree (repo-relative).
WIRE_FILES = (
    "fluidframework_tpu/protocol/binwire.py",
    "fluidframework_tpu/service/tpu_applier.py",
)

#: struct format codes whose width is fixed and identical everywhere.
_FIXED_WIDTH_CODES = set("xbBhHiIqQefds")

_ARITH_OPS = (ast.Add, ast.Sub, ast.Mult, ast.Div, ast.FloorDiv, ast.Mod,
              ast.Pow, ast.LShift, ast.RShift)


def _dotted(node: ast.AST) -> str:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_int16_dtype_expr(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant) and node.value == "int16":
        return True
    return _dotted(node) in ("np.int16", "numpy.int16", "jnp.int16",
                             "jax.numpy.int16")


def _makes_int16(node: ast.AST) -> bool:
    """Does this expression evaluate to an int16 array/scalar?"""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if _is_int16_dtype_expr(f):           # np.int16(x)
        return True
    if isinstance(f, ast.Attribute) and f.attr == "astype":
        return any(_is_int16_dtype_expr(a) for a in node.args) or any(
            _is_int16_dtype_expr(k.value) for k in node.keywords)
    # np.zeros(shape, np.int16) / np.empty(..., dtype=np.int16) / ...
    args = list(node.args) + [k.value for k in node.keywords]
    return any(_is_int16_dtype_expr(a) for a in args)


class _Int16Scope(ast.NodeVisitor):
    """One function (or module) scope: track int16-tainted names and
    flag arithmetic whose operand is tainted."""

    def __init__(self, path: str, violations: list):
        self.path = path
        self.violations = violations
        self.tainted: set[str] = set()

    # -- taint sources ----------------------------------------------------
    def visit_Assign(self, node: ast.Assign) -> None:
        if _makes_int16(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.tainted.add(t.id)
        elif isinstance(node.value, ast.Name) \
                and node.value.id in self.tainted:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.tainted.add(t.id)
        else:
            # rebinding a tainted name to something else clears it
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.tainted.discard(t.id)
        self.generic_visit(node)

    def add_params(self, fnode) -> None:
        # the wire format's naming convention: wave16, w16, ... params
        # carry packed int16 payloads
        for a in list(fnode.args.args) + list(fnode.args.kwonlyargs):
            if a.arg.endswith("16"):
                self.tainted.add(a.arg)

    # -- nested functions get their own scope -----------------------------
    def visit_FunctionDef(self, node) -> None:
        _check_scope(node, self.path, self.violations)

    visit_AsyncFunctionDef = visit_FunctionDef

    # -- sinks ------------------------------------------------------------
    def _operand_taint(self, node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Name) and node.id in self.tainted:
            return node.id
        if isinstance(node, ast.Subscript):
            base = node.value
            if isinstance(base, ast.Name) and base.id in self.tainted:
                return base.id
        return None

    def visit_BinOp(self, node: ast.BinOp) -> None:
        if isinstance(node.op, _ARITH_OPS):
            for side in (node.left, node.right):
                name = self._operand_taint(side)
                if name is not None:
                    self.violations.append(Violation(
                        pass_name="wire", path=self.path, line=node.lineno,
                        message=f"arithmetic on int16 array '{name}' "
                                "without an explicit width cast",
                        suggestion="widen first (`x.astype(np.int32)`) or "
                                   "route out-of-range values to the "
                                   "range-checked int32 wide path"))
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        if isinstance(node.op, _ARITH_OPS):
            name = self._operand_taint(node.target) \
                or self._operand_taint(node.value)
            if name is not None:
                self.violations.append(Violation(
                    pass_name="wire", path=self.path, line=node.lineno,
                    message=f"in-place arithmetic on int16 array '{name}' "
                            "without an explicit width cast",
                    suggestion="widen first (`x.astype(np.int32)`)"))
        self.generic_visit(node)


def _check_scope(scope_node, path: str, violations: list) -> None:
    scope = _Int16Scope(path, violations)
    if isinstance(scope_node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        scope.add_params(scope_node)
        for stmt in scope_node.body:
            scope.visit(stmt)
    else:
        for stmt in scope_node.body:
            scope.visit(stmt)


def check_int16_discipline(path: str,
                           repo_root: Optional[str] = None
                           ) -> list[Violation]:
    """Flag arithmetic on int16-typed names without explicit widening."""
    repo_root = repo_root or _repo_root()
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    violations: list[Violation] = []
    _check_scope(tree, os.path.relpath(path, repo_root), violations)
    return violations


def check_struct_widths(path: str,
                        repo_root: Optional[str] = None) -> list[Violation]:
    """Every struct format: explicit big-endian, fixed-width codes only."""
    repo_root = repo_root or _repo_root()
    rel = os.path.relpath(path, repo_root)
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if _dotted(node.func) not in ("struct.Struct", "Struct"):
            continue
        if not node.args or not isinstance(node.args[0], ast.Constant) \
                or not isinstance(node.args[0].value, str):
            out.append(Violation(
                pass_name="wire", path=rel, line=node.lineno,
                message="struct.Struct format is not a string literal "
                        "(width unverifiable)"))
            continue
        fmt = node.args[0].value
        if not fmt.startswith(">"):
            out.append(Violation(
                pass_name="wire", path=rel, line=node.lineno,
                message=f"struct format {fmt!r} is not explicitly "
                        "big-endian",
                suggestion="wire structs must start with '>' — native "
                           "order/size varies by platform"))
            continue
        bad = sorted({c for c in fmt[1:]
                      if not c.isdigit() and c not in _FIXED_WIDTH_CODES})
        if bad:
            out.append(Violation(
                pass_name="wire", path=rel, line=node.lineno,
                message=f"struct format {fmt!r} uses non-fixed-width "
                        f"code(s) {bad}",
                suggestion="use b/B h/H i/I q/Q e/f/d/s/x only"))
    return out


def check_frame_registry(repo_root: Optional[str] = None
                         ) -> list[Violation]:
    """FT_* frame ids unique, and every id paired with both codec
    halves (registries.FT_CODECS). A frame a peer can send that this
    build cannot decode — or an id silently reused — is version skew
    baked into one binary."""
    repo_root = repo_root or _repo_root()
    frames = load_frame_types(repo_root)
    out: list[Violation] = []
    if not frames:
        return [Violation(
            pass_name="wire", path=BINWIRE_HOME, line=1,
            message="no FT_* frame-id assignments found — the frame "
                    "registry check cannot read the codec",
            suggestion="keep FT_* module-level int literals in "
                       "protocol/binwire.py")]
    by_id: dict[int, str] = {}
    for name, (fid, lineno) in sorted(frames.items(),
                                      key=lambda kv: kv[1][0]):
        if fid in by_id:
            out.append(Violation(
                pass_name="wire", path=BINWIRE_HOME, line=lineno,
                message=f"frame id {fid} is assigned to both "
                        f"{by_id[fid]} and {name} — wire ids must be "
                        "unique",
                suggestion="pick the next unused id; existing ids are "
                           "frozen wire values"))
        by_id.setdefault(fid, name)
        if name not in FT_CODECS:
            out.append(Violation(
                pass_name="wire", path=BINWIRE_HOME, line=lineno,
                message=f"{name} has no (encoder, decoder) entry in "
                        "the codec manifest",
                suggestion="declare the pair in FT_CODECS in "
                           "tools/fluidlint/registries.py in the same "
                           "change"))
    # both halves of every declared pair must exist as module functions
    path = os.path.join(repo_root, BINWIRE_HOME)
    try:
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
        defined = {n.name for n in tree.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
    except (OSError, SyntaxError):
        defined = set()
    for name, (enc, dec) in sorted(FT_CODECS.items()):
        if name not in frames:
            out.append(Violation(
                pass_name="wire", path=BINWIRE_HOME, line=1,
                message=f"FT_CODECS declares {name} but the codec "
                        "defines no such frame id",
                suggestion="remove the stale manifest entry or restore "
                           "the frame id"))
            continue
        lineno = frames[name][1]
        for role, fn in (("encoder", enc), ("decoder", dec)):
            if fn not in defined:
                out.append(Violation(
                    pass_name="wire", path=BINWIRE_HOME, line=lineno,
                    message=f"{name} names {role} {fn}() which is not "
                            "defined in the codec — every frame id "
                            "needs both halves",
                    suggestion="define it, or fix the FT_CODECS pair"))
    return out


def check_wire(paths: Optional[tuple] = None,
               repo_root: Optional[str] = None) -> list[Violation]:
    repo_root = repo_root or _repo_root()
    out: list[Violation] = []
    if paths is None:
        paths = tuple(os.path.join(repo_root, p) for p in WIRE_FILES)
        out.extend(check_frame_registry(repo_root))
    for p in paths:
        out.extend(check_struct_widths(p, repo_root))
        out.extend(check_int16_discipline(p, repo_root))
    return out


def _repo_root() -> str:
    return os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", ".."))
