"""The concurrency pass's intentional exceptions.

Each waiver is ``(rule, qualname, detail, justification)``:

- ``rule`` — the violation class (``CROSS-AFFINITY``,
  ``BLOCKING-ON-LOOP``, ``UNFENCED-SHARED-STATE``, ``LOCK-ORDER``);
- ``qualname`` — the function (or ``Class.attr`` for shared state) the
  violation message names;
- ``detail`` — an extra substring to pin the match (the specific
  blocker / attribute), or ``""`` to match any finding on the qualname;
- ``justification`` — ONE line, printed by the report. A waiver is an
  argument, not an escape hatch: it must say why the crossing is sound.

A waiver that stops matching anything is flagged as stale by the pass
itself, so this table cannot silently outlive the code it excuses.
"""

from __future__ import annotations

WAIVERS: tuple = (
    ("CROSS-AFFINITY",
     "service.rebalancer.Rebalancer.tick",
     "MigrationEngine.migrate",
     "in-proc actuation fallback: tests and the chaos bench drive "
     "tick() on the caller's thread; a deployed core always actuates "
     "through the loopback admin_migrate_part RPC"),

    ("BLOCKING-ON-LOOP",
     "service.front_end._ClientSession._handle_admin",
     "log.flush",
     "admin_summarize flushes before summarizing so the summary sees "
     "every acked op — a bounded page-cache flush, and the admin door "
     "is cold by contract"),

    ("BLOCKING-ON-LOOP",
     "service.front_end.NetworkFrontEnd._summarize_loop",
     "log.flush",
     "the summary tick's visibility barrier: a bounded page-cache "
     "flush (no fsync) once per summarize interval, not per frame"),

    ("BLOCKING-ON-LOOP",
     "service.front_end.NetworkFrontEnd._poll_backchannels",
     "log.flush",
     "the backchannel drain's visibility barrier — same bounded "
     "page-cache flush as the summary tick, once per poll"),

    ("BLOCKING-ON-LOOP",
     "service.placement_plane.MigrationEngine._rpc_adopt",
     "admin_rpc",
     "the handoff RPC blocks the loop BY DESIGN: nothing may be "
     "sequenced on this core while the target adopts the partition "
     "(deli's epoch fence covers the rest)"),

    ("BLOCKING-ON-LOOP",
     "service.placement_plane.MigrationEngine._ship_log",
     "admin_rpc",
     "the cross-host log upload rides the same sealed window as "
     "_rpc_adopt: the partition is sealed + revoked, nothing may be "
     "sequenced here until the target owns it, so the storage RPC's "
     "synchrony IS the design"),

    ("BLOCKING-ON-LOOP",
     "service.placement_plane.MigrationEngine._fetch_log",
     "admin_rpc",
     "the target side of the ship: adopt replaces the log dir BEFORE "
     "building the partition server, on the loop by design — serving "
     "ops for a partition whose log is mid-replace would be the race"),
)
