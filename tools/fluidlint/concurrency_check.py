"""Pass 8 — concurrency contracts: thread affinity, lock discipline,
blocking calls, shared state.

Every "found the hard way" bug in PRs 10–13 was a concurrency-
discipline violation, not a logic error: CPU donation silently
serializing dispatch behind ``block_until_ready`` (PR 11), staging-
buffer refills racing in-flight executions until the rotation fence was
keyed to the consuming execution (PR 11), ``tier_counters`` weakrefs
dying under the ticker thread (PR 13), and the rebalancer needing a
loopback ``admin_migrate_part`` RPC because migrations are only sound
on the core's event loop (PR 13). The reference enforces its
architecture with a build-time layer check but has nothing for thread
discipline; this pass is the RacerD / Clang ``-Wthread-safety`` analog
for our tree — annotate the boundaries
(``fluidframework_tpu/utils/affinity.py``), build a package-wide call
graph, propagate execution contexts from every spawn site, and flag
the crossings.

**Contexts** (strings propagated along the call graph):

- ``loop:<name>`` — an asyncio event-loop thread. Seeds: ``async def``
  bodies (``loop:?``), ``call_soon`` / ``call_soon_threadsafe`` /
  ``add_done_callback`` callbacks, and ``@loop_only(name)``
  annotations.
- ``ticker:<name>`` — a daemon ticker thread (``@ticker_thread``).
- ``thread:<name>`` — a ``threading.Thread(target=..., name=...)``.
- ``executor`` — a ``run_in_executor`` offload.

Propagation is conservative: an edge exists only when the callee
resolves unambiguously — ``self.m()`` within the enclosing class (and
package-local bases), bare names via module scope and ``from``
imports, typed receivers (``x = ClassName(...)`` locals and
``self.attr = ClassName(...)`` instance attrs), and otherwise an
attribute call resolves only when exactly ONE function in the package
bears that name. Ambiguity means no edge — this pass must hold a
zero-false-positive bar on the real tree; fixtures are small enough to
resolve fully. Seam calls (``call_soon_threadsafe``,
``run_in_executor``, ``Thread(target=...)``) TRANSFER context to their
callback instead of propagating the caller's, and the loopback
``admin_rpc`` breaks the graph at the socket the way it breaks the
thread coupling at runtime.

**Violation classes:**

- ``CROSS-AFFINITY`` — a ticker/thread/executor context reaches a
  ``@loop_only`` function without going through a registered seam.
- ``BLOCKING-ON-LOOP`` — a blocking call (socket ``sendall``/``recv``,
  ``fcntl.flock``, ``time.sleep``, ``block_until_ready``, subprocess
  waits, the durable log's mmap flush, or any ``@blocking``-annotated
  function) reachable from an event-loop context. Each blocker carries
  the PR that made it load-bearing.
- ``UNFENCED-SHARED-STATE`` — an instance attribute written from ≥2
  distinct concrete contexts with no common lock fence (lexical
  ``with <lock>:`` or ``@holds_lock``) and no waiver.
- ``LOCK-ORDER`` — registered locks must be acquired in the single
  global order (``registries.LOCK_ORDER``), checked over lexical
  ``with`` nesting and ``@holds_lock`` call edges.

Intentional exceptions live in ``concurrency_waivers.py`` — each with
a one-line justification the report prints, and a waiver that stops
matching anything is itself flagged as stale.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Iterable, Optional

from .registries import LOCK_ORDER, LOCK_RANK
from .report import Violation

#: Swept package roots (repo-relative), same scope as the other passes.
PACKAGE_ROOTS = ("fluidframework_tpu",)

# ------------------------------------------------------------ blockers

#: dotted call -> provenance (the PR that made the blocker load-bearing
#: on a near-loop path; the report prints it so a reader knows which
#: hard-way bug the rule encodes).
BLOCKING_DOTTED = {
    "time.sleep": "thread pacing (PR 2 chaos delays, PR 13 tickers) — "
                  "on a loop use `await asyncio.sleep`",
    "socket.create_connection": "synchronous dial (PR 10's loopback "
                                "admin_rpc made these load-bearing)",
    "subprocess.run": "subprocess wait",
    "subprocess.check_output": "subprocess wait",
    "subprocess.check_call": "subprocess wait",
    "fcntl.flock": "file-lock wait (PR 10 epoch-table flock)",
}

#: attribute name (any receiver) -> provenance.
BLOCKING_ATTRS = {
    "sendall": "synchronous socket write (PR 10 admin_rpc)",
    "recv": "synchronous socket read (PR 10 admin_rpc)",
    "block_until_ready": "device sync — PR 11's donation-on-CPU bug "
                         "serialized dispatch exactly here",
    "communicate": "subprocess wait",
}

#: dotted-suffix -> provenance: the durable log's mmap-backed surface.
BLOCKING_SUFFIXES = {
    ".log.flush": "durable-log mmap flush (PR 6 columnar storage; "
                  "PR 11 made flushes per-batch, not per-frame)",
}

#: Callees that TRANSFER context rather than running the callback in
#: the caller's context: the function argument is seeded separately.
SEAM_CALLS = frozenset({
    "call_soon", "call_soon_threadsafe", "run_coroutine_threadsafe",
    "run_in_executor", "create_task", "add_done_callback",
    "ensure_future",
})

#: Registered loopback RPC seams: a ticker actuating through one of
#: these reaches the loop over a socket, not a call edge — named here
#: so CROSS-AFFINITY suggestions can point at the sanctioned pattern.
LOOPBACK_SEAMS = ("service.placement_plane.admin_rpc",)

# ------------------------------------------------------- lock site maps

#: `with <fn>(...)` call names -> registered lock.
WITH_CALL_LOCKS = {"_flock": "epoch_table_flock"}

#: `with self.<method>(...)` per class -> registered lock (the lease
#: lock is a contextmanager METHOD, the others are Lock attributes).
CLASS_CALL_LOCKS = {("PlacementDir", "_lock"): "partition_claim_flock"}

#: `with self.<attr>:` per class -> registered lock.
CLASS_ATTR_LOCKS = {
    ("Journal", "_lock"): "journal_lock",
    ("TpuDocumentApplier", "_lock"): "applier_lock",
}

_AFFINITY_DECOS = ("loop_only", "ticker_thread", "any_thread",
                   "holds_lock", "blocking")


# ----------------------------------------------------------- collection

@dataclass
class _Fn:
    qual: str                    # module.Class.fn / module.fn
    module: str                  # dotted module (package-relative)
    cls: Optional[str]
    name: str
    path: str                    # repo-relative
    lineno: int
    is_async: bool = False
    affinity: Optional[tuple] = None      # ("loop"|"ticker"|"any", name)
    holds: tuple = ()                     # @holds_lock names
    blocking: Optional[str] = None        # @blocking reason
    calls: list = field(default_factory=list)    # (ref, line, held)
    blocker_hits: list = field(default_factory=list)  # (line, what, why)
    writes: list = field(default_factory=list)   # (attr, line, fences)
    acquires: list = field(default_factory=list)  # (lock, line, held)
    seam_args: set = field(default_factory=set)  # callback names seamed
    contexts: set = field(default_factory=set)
    seeds: dict = field(default_factory=dict)    # ctx -> seed reason


def _dotted(node: ast.AST) -> str:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _py_files(root: str) -> Iterable[str]:
    for dirpath, dirnames, files in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", "build", "fixtures")]
        for fn in sorted(files):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def _affinity_of(deco_list):
    """(affinity, holds, blocking) from the decorator list — matched by
    name, not import, so un-imported fixture trees are checkable."""
    affinity, holds, blocking = None, [], None
    for d in deco_list:
        call_args = []
        if isinstance(d, ast.Call):
            call_args = [a.value for a in d.args
                         if isinstance(a, ast.Constant)]
            d = d.func
        name = _dotted(d).rsplit(".", 1)[-1]
        if name not in _AFFINITY_DECOS:
            continue
        if name == "loop_only":
            affinity = ("loop", call_args[0] if call_args else "core")
        elif name == "ticker_thread":
            affinity = ("ticker", call_args[0] if call_args else "?")
        elif name == "any_thread":
            affinity = ("any", "")
        elif name == "holds_lock" and call_args:
            holds.append(call_args[0])
        elif name == "blocking":
            blocking = call_args[0] if call_args else "blocking I/O"
    return affinity, tuple(holds), blocking


class _Package:
    """The parsed package: functions, classes, imports, spawn seeds."""

    def __init__(self):
        self.fns: dict[str, _Fn] = {}
        self.by_name: dict[str, list] = {}
        self.mod_scope: dict[str, dict] = {}     # module -> name -> qual
        self.mod_classes: dict[str, dict] = {}   # module -> cls -> meths
        self.class_bases: dict[tuple, list] = {}  # (mod, cls) -> [names]
        self.attr_types: dict[tuple, dict] = {}  # (mod, cls) -> attr->cls
        self.imports: dict[str, dict] = {}       # module -> local -> tgt
        self.spawns: list = []                   # (ref, ctx, reason, fn)

    def add_fn(self, fn: _Fn):
        self.fns[fn.qual] = fn
        self.by_name.setdefault(fn.name, []).append(fn.qual)


def _class_name_of(node) -> Optional[str]:
    """`ClassName(...)` constructor calls: the (unqualified) class."""
    if isinstance(node, ast.Call):
        name = _dotted(node.func).rsplit(".", 1)[-1]
        if name and name[0].isupper():
            return name
    return None


class _BodyWalk:
    """One function body: calls with held-lock sets, self-writes with
    fence sets, direct blocker hits, spawn/seam seeds, nested defs."""

    def __init__(self, fn: _Fn, pkg: _Package, cls: Optional[str]):
        self.fn = fn
        self.pkg = pkg
        self.cls = cls
        self.held: list[str] = list(fn.holds)
        self.fences: list[str] = list(fn.holds)
        self.local_types: dict[str, str] = {}
        self.nested: list = []

    # -- lock naming -------------------------------------------------
    def _lock_of_with_item(self, expr) -> Optional[str]:
        if isinstance(expr, ast.Call):
            d = _dotted(expr.func)
            base = d.rsplit(".", 1)[-1]
            if base in WITH_CALL_LOCKS:
                return WITH_CALL_LOCKS[base]
            if d.startswith("self.") and self.cls:
                return CLASS_CALL_LOCKS.get((self.cls, base))
            return None
        d = _dotted(expr)
        if d.startswith("self.") and d.count(".") == 1 and self.cls:
            attr = d.split(".", 1)[1]
            hit = CLASS_ATTR_LOCKS.get((self.cls, attr))
            if hit:
                return hit
            low = attr.lower()
            if any(k in low for k in ("lock", "cv", "cond", "wake")):
                return f"{self.cls}.{attr}"
        return None

    # -- traversal ---------------------------------------------------
    def walk(self, body) -> None:
        for stmt in body:
            self._visit(stmt)

    def _visit(self, node) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.nested.append(node)
            return
        if isinstance(node, ast.Lambda):
            # opaque: a lambda is usually a callback — attributing its
            # body's calls to the enclosing function would claim the
            # wrong execution context (e.g. executor work built inline)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            self._with(node)
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            self._assign(node)
            return
        if isinstance(node, ast.Call):
            self._call(node)
            return
        for child in ast.iter_child_nodes(node):
            self._visit(child)

    def _with(self, node) -> None:
        locks = []
        for it in node.items:
            lk = self._lock_of_with_item(it.context_expr)
            if lk:
                locks.append(lk)
            self._visit(it.context_expr)
        for lk in locks:
            if lk in LOCK_RANK:
                held_reg = tuple(h for h in self.held if h in LOCK_RANK)
                self.fn.acquires.append((lk, node.lineno, held_reg))
            self.held.append(lk)
            self.fences.append(lk)
        self.walk(node.body)
        for lk in locks:
            self.held.pop()
            self.fences.pop()

    def _assign(self, node) -> None:
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        value = getattr(node, "value", None)
        cls_of_value = _class_name_of(value) if value is not None else None
        for t in targets:
            d = _dotted(t)
            if d.startswith("self.") and d.count(".") == 1:
                attr = d.split(".", 1)[1]
                self.fn.writes.append(
                    (attr, node.lineno, frozenset(self.fences)))
                if cls_of_value and self.fn.name == "__init__" \
                        and self.cls:
                    key = (self.fn.module, self.cls)
                    self.pkg.attr_types.setdefault(key, {})[attr] = \
                        cls_of_value
            elif isinstance(t, ast.Name) and cls_of_value:
                self.local_types[t.id] = cls_of_value
            else:
                self._visit(t)
        if value is not None:
            self._visit(value)

    # -- calls -------------------------------------------------------
    def _fn_ref(self, node) -> Optional[tuple]:
        """A *reference* to a function (callback position): a
        resolution request tuple, or None."""
        d = _dotted(node)
        if not d:
            return None
        if d.startswith("self.") and d.count(".") == 1:
            return ("self", d.split(".", 1)[1], d)
        if "." not in d:
            return ("name", d, d)
        return ("attr", d.rsplit(".", 1)[-1], d)

    def _call(self, node: ast.Call) -> None:
        d = _dotted(node.func)
        base = d.rsplit(".", 1)[-1] if d else ""
        handled = False

        if base == "Thread":
            target, tname = None, None
            for kw in node.keywords:
                if kw.arg == "target":
                    target = self._fn_ref(kw.value)
                elif kw.arg == "name" and isinstance(kw.value,
                                                    ast.Constant):
                    tname = str(kw.value.value)
            if target is not None:
                ctx = f"thread:{tname or target[1]}"
                self.pkg.spawns.append(
                    (target, ctx,
                     f"threading.Thread in {self.fn.qual}", self.fn))
                self.fn.seam_args.add(target[1])
                handled = True
        elif base in SEAM_CALLS:
            cb_args = list(node.args)
            if base == "run_in_executor":
                cb_args = cb_args[1:2]  # (executor, fn, *args)
                ctx, why = "executor", "run_in_executor offload"
            else:
                cb_args = cb_args[:1]
                ctx, why = "loop:?", f"{base} callback"
            for a in cb_args:
                ref = self._fn_ref(a)
                if ref is None and isinstance(a, ast.Call):
                    # create_task(coro(...)): seed the coroutine fn
                    ref = self._fn_ref(a.func)
                if ref is not None:
                    self.pkg.spawns.append(
                        (ref, ctx, f"{why} in {self.fn.qual}", self.fn))
                    self.fn.seam_args.add(ref[1])
            handled = True

        if not handled and d:
            if self.fn.blocking is None:
                if d in BLOCKING_DOTTED:
                    self.fn.blocker_hits.append(
                        (node.lineno, f"{d}()", BLOCKING_DOTTED[d]))
                elif base in BLOCKING_ATTRS and "." in d:
                    self.fn.blocker_hits.append(
                        (node.lineno, f".{base}()", BLOCKING_ATTRS[base]))
                else:
                    for suffix, why in BLOCKING_SUFFIXES.items():
                        if d.endswith(suffix):
                            self.fn.blocker_hits.append(
                                (node.lineno, d, why))
            ref = self._fn_ref(node.func)
            if ref is not None:
                kind, name, dotted = ref
                if kind == "attr":
                    parts = dotted.split(".")
                    recv_cls = None
                    if parts[0] == "self" and len(parts) == 3:
                        recv_cls = self.pkg.attr_types.get(
                            (self.fn.module, self.cls), {}).get(parts[1])
                    elif len(parts) == 2:
                        recv_cls = self.local_types.get(parts[0])
                    if recv_cls is not None:
                        ref = ("typed", name, recv_cls)
                held_reg = tuple(h for h in self.held if h in LOCK_RANK)
                self.fn.calls.append((ref, node.lineno, held_reg))

        for a in node.args:
            self._visit(a)
        for kw in node.keywords:
            self._visit(kw.value)
        if isinstance(node.func, ast.Attribute):
            self._visit(node.func.value)  # receivers can contain calls


def _collect_module(pkg: _Package, path: str, rel: str, module: str,
                    root_pkg: str, is_pkg: bool) -> None:
    with open(path) as f:
        try:
            tree = ast.parse(f.read(), filename=path)
        except SyntaxError:
            return  # the hygiene pass reports syntax errors
    pkg.mod_scope.setdefault(module, {})
    pkg.imports.setdefault(module, {})

    # function-level imports count too (the tree defers several to the
    # call site to break import cycles); last alias binding wins, which
    # is conservative enough at package scope
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            _collect_import(pkg, module, node, root_pkg, is_pkg)

    def collect_fn(node, cls: Optional[str], parent: Optional[_Fn]):
        if parent is not None:
            qual = f"{parent.qual}.<locals>.{node.name}"
        elif cls:
            qual = f"{module}.{cls}.{node.name}"
        else:
            qual = f"{module}.{node.name}"
        affinity, holds, blocking = _affinity_of(node.decorator_list)
        fn = _Fn(qual=qual, module=module, cls=cls, name=node.name,
                 path=rel, lineno=node.lineno,
                 is_async=isinstance(node, ast.AsyncFunctionDef),
                 affinity=affinity, holds=holds, blocking=blocking)
        pkg.add_fn(fn)
        walker = _BodyWalk(fn, pkg, cls)
        walker.walk(node.body)
        for stmt in walker.nested:
            child = collect_fn(stmt, cls, fn)
            if stmt.name not in fn.seam_args:
                # a nested def runs in the parent's context unless it
                # was handed to a seam (Thread / executor / call_soon)
                fn.calls.append(
                    (("exact", child.qual, child.qual), stmt.lineno, ()))
        return fn

    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn = collect_fn(node, None, None)
            pkg.mod_scope[module][node.name] = fn.qual
        elif isinstance(node, ast.ClassDef):
            pkg.mod_scope[module][node.name] = f"{module}.{node.name}"
            methods = {}
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    fn = collect_fn(sub, node.name, None)
                    methods[sub.name] = fn.qual
            pkg.mod_classes.setdefault(module, {})[node.name] = methods
            pkg.class_bases[(module, node.name)] = [
                _dotted(b).rsplit(".", 1)[-1] for b in node.bases]


def _collect_import(pkg: _Package, module: str, node: ast.ImportFrom,
                    root_pkg: str, is_pkg: bool) -> None:
    """Map `from X import f` locals to (target_module, original_name),
    for relative imports and absolute in-package ones."""
    table = pkg.imports[module]
    if node.level > 0:
        parts = module.split(".") if module != root_pkg else []
        # a package __init__ resolves level 1 against itself
        strip = node.level - 1 if is_pkg else node.level
        if strip > len(parts):
            return
        base = parts[:len(parts) - strip] if strip else parts
        target = ".".join(base + (node.module.split(".")
                                  if node.module else []))
    else:
        target = node.module or ""
        if target == root_pkg:
            target = ""
        elif target.startswith(root_pkg + "."):
            target = target[len(root_pkg) + 1:]
        else:
            return  # external import
    for alias in node.names:
        table[alias.asname or alias.name] = (target, alias.name)


# ---------------------------------------------------------- resolution

def _resolve(pkg: _Package, fn: _Fn, ref) -> Optional[str]:
    kind, name, extra = ref
    if kind == "exact":
        return extra if extra in pkg.fns else None
    if kind == "self":
        return _resolve_method(pkg, fn.module, fn.cls, name)
    if kind == "typed":
        for mod, classes in pkg.mod_classes.items():
            if extra in classes:
                hit = _resolve_method(pkg, mod, extra, name)
                if hit:
                    return hit
        return None
    if kind == "name":
        hit = pkg.mod_scope.get(fn.module, {}).get(name)
        if hit and hit in pkg.fns:
            return hit
        imp = pkg.imports.get(fn.module, {}).get(name)
        if imp:
            tgt_mod, orig = imp
            hit = pkg.mod_scope.get(tgt_mod, {}).get(orig)
            if hit and hit in pkg.fns:
                return hit
        return None
    # attr: the unique-name rule over the whole package
    quals = pkg.by_name.get(name, ())
    if len(quals) == 1:
        return quals[0]
    return None


def _resolve_method(pkg: _Package, module: str, cls: Optional[str],
                    name: str) -> Optional[str]:
    seen = set()
    stack = [(module, cls)]
    while stack:
        mod, c = stack.pop()
        if c is None or (mod, c) in seen:
            continue
        seen.add((mod, c))
        hit = pkg.mod_classes.get(mod, {}).get(c, {}).get(name)
        if hit:
            return hit
        for base in pkg.class_bases.get((mod, c), ()):
            if base in pkg.mod_classes.get(mod, {}):
                stack.append((mod, base))
            else:
                homes = [m for m, cs in pkg.mod_classes.items()
                         if base in cs]
                if len(homes) == 1:
                    stack.append((homes[0], base))
    return None


# --------------------------------------------------------- propagation

def _loopish(ctx: str) -> bool:
    return ctx.startswith("loop:")


def _concrete(ctx: str) -> Optional[str]:
    """Collapse for shared-state grouping: all loop contexts are one
    (a tier runs one loop thread)."""
    if _loopish(ctx):
        return "loop"
    if ctx.startswith(("ticker:", "thread:")) or ctx == "executor":
        return ctx
    return None


def _propagate(pkg: _Package):
    """Flow contexts from seeds along resolved edges; record the first
    parent of each (fn, ctx) for witness paths."""
    parent: dict[tuple, tuple] = {}
    work: list[str] = []

    def seed(fn: _Fn, ctx: str, reason: str):
        if ctx not in fn.contexts:
            fn.contexts.add(ctx)
            fn.seeds.setdefault(ctx, reason)
            work.append(fn.qual)

    for fn in pkg.fns.values():
        if fn.affinity:
            kind, name = fn.affinity
            if kind == "loop":
                seed(fn, f"loop:{name}", f"@loop_only({name!r})")
            elif kind == "ticker":
                seed(fn, f"ticker:{name}", f"@ticker_thread({name!r})")
        elif fn.is_async:
            seed(fn, "loop:?", "async def — coroutine bodies run on "
                               "the owning tier's event loop")
    for ref, ctx, reason, src in pkg.spawns:
        tgt = _resolve(pkg, src, ref)
        if tgt is None:
            continue
        fn = pkg.fns[tgt]
        if fn.affinity and fn.affinity[0] in ("loop", "ticker"):
            continue  # declared affinity names the SAME thread; seeding
            # both would double-count one execution context
        seed(fn, ctx, reason)

    edges: dict[str, list] = {}
    for fn in pkg.fns.values():
        for ref, line, _held in fn.calls:
            tgt = _resolve(pkg, fn, ref)
            if tgt is not None:
                edges.setdefault(fn.qual, []).append((tgt, line))

    while work:
        qual = work.pop()
        fn = pkg.fns[qual]
        for tgt, line in edges.get(qual, ()):
            callee = pkg.fns[tgt]
            if callee.blocking is not None:
                continue  # blocker leaf: checked, never entered
            if callee.is_async:
                continue  # calling a coroutine fn just builds the coro
            if callee.affinity and callee.affinity[0] in ("loop",
                                                          "ticker"):
                # declared affinity wins; crossings are reported as
                # CROSS-AFFINITY instead of cascading contexts through
                continue
            grew = False
            for ctx in fn.contexts:
                if ctx not in callee.contexts:
                    callee.contexts.add(ctx)
                    parent.setdefault((tgt, ctx), (qual, line))
                    grew = True
            if grew:
                work.append(tgt)
    return parent, edges


def _witness(pkg: _Package, parent: dict, qual: str, ctx: str) -> str:
    chain = [qual]
    seen = {qual}
    cur = qual
    while (cur, ctx) in parent:
        cur, _line = parent[(cur, ctx)]
        if cur in seen:
            break
        seen.add(cur)
        chain.append(cur)
    chain.reverse()
    root = pkg.fns.get(chain[0])
    seed_why = root.seeds.get(ctx, "") if root else ""
    path = " -> ".join(chain)
    return f"[{ctx}; {seed_why}] {path}" if seed_why else \
        f"[{ctx}] {path}"


# --------------------------------------------------------------- checks

def _check(pkg: _Package, parent: dict, edges: dict) -> list[Violation]:
    out: list[Violation] = []

    # CROSS-AFFINITY --------------------------------------------------
    for fn in pkg.fns.values():
        for tgt, line in edges.get(fn.qual, ()):
            callee = pkg.fns[tgt]
            if not (callee.affinity and callee.affinity[0] == "loop"):
                continue
            for ctx in sorted(fn.contexts):
                if _loopish(ctx) or _concrete(ctx) is None:
                    continue
                out.append(Violation(
                    pass_name="concurrency", path=fn.path, line=line,
                    message=f"CROSS-AFFINITY: {callee.qual} is "
                            f"@loop_only({callee.affinity[1]!r}) but is "
                            f"called from {ctx} — "
                            f"{_witness(pkg, parent, fn.qual, ctx)}",
                    suggestion="route through a loopback seam "
                               f"({', '.join(LOOPBACK_SEAMS)}) or "
                               "call_soon_threadsafe, or waive in "
                               "tools/fluidlint/concurrency_waivers.py"))

    # BLOCKING-ON-LOOP ------------------------------------------------
    for fn in pkg.fns.values():
        loop_ctxs = sorted(c for c in fn.contexts if _loopish(c))
        if not loop_ctxs:
            continue
        ctx = loop_ctxs[0]
        for line, what, why in fn.blocker_hits:
            out.append(Violation(
                pass_name="concurrency", path=fn.path, line=line,
                message=f"BLOCKING-ON-LOOP: {what} in {fn.qual} is "
                        f"reachable from the event loop ({why}) — "
                        f"{_witness(pkg, parent, fn.qual, ctx)}",
                suggestion="move it behind run_in_executor / a drain "
                           "seam, or waive with a justification"))
        for tgt, line in edges.get(fn.qual, ()):
            callee = pkg.fns[tgt]
            if callee.blocking is not None:
                out.append(Violation(
                    pass_name="concurrency", path=fn.path, line=line,
                    message=f"BLOCKING-ON-LOOP: {fn.qual} calls "
                            f"@blocking {callee.qual} "
                            f"({callee.blocking}) on the event loop — "
                            f"{_witness(pkg, parent, fn.qual, ctx)}",
                    suggestion="move it behind run_in_executor / a "
                               "drain seam, or waive with a "
                               "justification"))

    # UNFENCED-SHARED-STATE -------------------------------------------
    shared: dict[tuple, list] = {}
    for fn in pkg.fns.values():
        if fn.name in ("__init__", "__post_init__"):
            continue
        if fn.affinity and fn.affinity[0] == "any":
            continue  # the author asserts internal synchronization
        ctxs = {_concrete(c) for c in fn.contexts}
        ctxs.discard(None)
        if not ctxs:
            continue
        for attr, line, fences in fn.writes:
            shared.setdefault((fn.module, fn.cls, attr), []).append(
                (fn, line, fences, frozenset(ctxs)))
    for (module, cls, attr), writers in sorted(
            shared.items(), key=lambda kv: str(kv[0])):
        if cls is None:
            continue
        all_ctxs = set()
        for _fn, _line, _fences, ctxs in writers:
            all_ctxs |= ctxs
        if len(all_ctxs) < 2:
            continue
        common = None
        for _fn, _line, fences, _ctxs in writers:
            common = set(fences) if common is None else common & fences
        if common:
            continue  # every write holds a shared fence
        fn0, line0 = writers[0][0], writers[0][1]
        who = ", ".join(sorted({
            f"{w[0].name} ({'/'.join(sorted(w[3]))})" for w in writers}))
        out.append(Violation(
            pass_name="concurrency", path=fn0.path, line=line0,
            message=f"UNFENCED-SHARED-STATE: {cls}.{attr} is written "
                    f"from {len(all_ctxs)} contexts "
                    f"({', '.join(sorted(all_ctxs))}) with no common "
                    f"lock fence — writers: {who}",
            suggestion="guard every write with one shared lock "
                       "(`with self._lock:` / @holds_lock), or waive "
                       "as documented single-writer"))

    # LOCK-ORDER ------------------------------------------------------
    def order_check(fn, line, held, acquiring):
        for h in held:
            if LOCK_RANK[h] > LOCK_RANK[acquiring]:
                out.append(Violation(
                    pass_name="concurrency", path=fn.path, line=line,
                    message=f"LOCK-ORDER: {fn.qual} acquires "
                            f"'{acquiring}' while holding '{h}' — the "
                            "global order is "
                            f"{' -> '.join(LOCK_ORDER)}",
                    suggestion="restructure so acquisition follows the "
                               "order table (tools/lint.sh --fix-order "
                               "prints it)"))

    for fn in pkg.fns.values():
        for name in fn.holds:
            # dotted names ("MetricsRegistry._lock") are instance-lock
            # fences — REQUIRES()-style caller preconditions, not part
            # of the global order; bare names must be registered
            if "." not in name and name not in LOCK_RANK:
                out.append(Violation(
                    pass_name="concurrency", path=fn.path,
                    line=fn.lineno,
                    message=f"@holds_lock({name!r}) on {fn.qual} names "
                            "a lock missing from the global order "
                            "table",
                    suggestion="register it in LOCK_ORDER in "
                               "tools/fluidlint/registries.py (order "
                               "matters: outermost first)"))
        for lock, line, held in fn.acquires:
            order_check(fn, line, held, lock)
        for ref, line, held in fn.calls:
            if not held:
                continue
            tgt = _resolve(pkg, fn, ref)
            if tgt is None:
                continue
            for lock in pkg.fns[tgt].holds:
                if lock in LOCK_RANK:
                    order_check(fn, line, held, lock)
    return out


# ------------------------------------------------------------- waivers

def _apply_waivers(violations, waivers, waived_out: Optional[list]):
    kept = []
    used = [False] * len(waivers)
    for v in violations:
        hit = None
        for i, w in enumerate(waivers):
            rule, qual, detail, why = w
            if not v.message.startswith(rule + ":"):
                continue
            if qual not in v.message:
                continue
            if detail and detail not in v.message:
                continue
            hit = i
            break
        if hit is None:
            kept.append(v)
        else:
            used[hit] = True
            if waived_out is not None:
                rule, qual, detail, why = waivers[hit]
                waived_out.append(
                    f"waived [{rule}] {qual}"
                    + (f" ({detail})" if detail else "")
                    + f" -- {why}")
    return kept, used


def check_concurrency(repo_root: Optional[str] = None,
                      roots: tuple = PACKAGE_ROOTS,
                      waivers: Optional[tuple] = None,
                      waived_out: Optional[list] = None
                      ) -> list[Violation]:
    """Run the whole-package pass. ``waivers`` defaults to the
    checked-in table; pass ``()`` to see everything (the self-tests
    do). An unused waiver is itself a violation — a waiver that no
    longer matches anything is stale documentation."""
    repo_root = repo_root or _repo_root()
    if waivers is None:
        from .concurrency_waivers import WAIVERS
        waivers = WAIVERS
    pkg = _Package()
    for r in roots:
        root = os.path.join(repo_root, r)
        if not os.path.isdir(root):
            continue
        root_pkg = os.path.basename(os.path.normpath(root))
        for path in _py_files(root):
            rel = os.path.relpath(path, repo_root)
            mod_rel = os.path.relpath(path, root)[:-3]
            is_pkg = os.path.basename(path) == "__init__.py"
            parts = [p for p in mod_rel.split(os.sep)
                     if p != "__init__"]
            module = ".".join(parts) if parts else root_pkg
            _collect_module(pkg, path, rel, module, root_pkg, is_pkg)
    parent, edges = _propagate(pkg)
    violations = _check(pkg, parent, edges)
    kept, used = _apply_waivers(violations, waivers, waived_out)
    for w, u in zip(waivers, used):
        if not u:
            rule, qual, detail, why = w
            kept.append(Violation(
                pass_name="concurrency",
                path=os.path.join("tools", "fluidlint",
                                  "concurrency_waivers.py"),
                line=1,
                message=f"stale waiver: [{rule}] {qual} "
                        f"({detail or 'any'}) matches no finding",
                suggestion="delete it — the exception it documented is "
                           "gone"))
    return kept


def _repo_root() -> str:
    return os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", ".."))
