"""Pass 2 — jaxpr contracts: the TPU hot-path claims, checked by trace.

Every registered kernel (``fluidframework_tpu.utils.contracts``) is
abstract-evaled on its declared example shapes and its jaxpr walked
recursively — through scan/while/cond bodies, pjit calls, and
``pallas_call`` kernel jaxprs — so a forbidden primitive cannot hide
inside a nested program. Checks:

- forbidden primitives: ``gather`` / ``scatter*`` when the contract bans
  them, budgets (``max_gathers``, ``max_dynamic_slices``) otherwise;
- dynamic-index ``while`` bodies: a ``gather``/``dynamic_slice`` inside
  a ``while`` is flagged even under a budget — a computed-index read in
  a device loop body is the K-amplified slow path by construction;
- int16 silent promotion: no arithmetic primitive may consume an int16
  operand (packed-wave fields must be explicitly widened first);
- recompile regressions (``single_jit``): the kernel runs twice with
  same-shape inputs and the pjit compilation-cache size must not grow
  on the second call.

The hot-path kernels named in ``REQUIRED_KERNELS`` must stay
registered — removing a ``@kernel_contract`` registration is itself a
violation, so coverage cannot silently decay.
"""

from __future__ import annotations

import functools
import importlib
import inspect
import os
import warnings
from collections import Counter
from typing import Iterator, Optional

from .report import Violation

#: Modules whose import populates the contract registry.
KERNEL_MODULES = (
    "fluidframework_tpu.ops.apply",
    "fluidframework_tpu.ops.pallas_apply",
    "fluidframework_tpu.parallel.sharded_apply",
    "fluidframework_tpu.service.tpu_applier",
)

#: The hot-path entry points that must stay under contract.
REQUIRED_KERNELS = (
    "ops.apply_ops_batch",
    "ops.pallas_apply_ops_batch",
    "parallel.sharded_step",
    "parallel.sharded_step_packed",
    "parallel.sharded_step_packed_pallas",
    "service.dense_step_packed",
    "service.dense_step_packed_pallas",
)

#: Primitives that do arithmetic (an int16 operand here = silent
#: promotion risk); layout/convert/compare primitives are exempt.
_ARITHMETIC_PRIMS = frozenset({
    "add", "sub", "mul", "div", "rem", "neg", "pow", "integer_pow",
    "max", "min", "dot_general", "shift_left", "shift_right_logical",
    "shift_right_arithmetic", "cumsum", "cumprod", "reduce_sum",
    "reduce_prod", "reduce_max", "reduce_min", "abs", "sign",
})


def load_registry() -> dict:
    """Import the kernel modules and return the populated registry."""
    for mod in KERNEL_MODULES:
        importlib.import_module(mod)
    from fluidframework_tpu.utils.contracts import registered_contracts

    return registered_contracts()


def _subjaxprs(params: dict) -> Iterator:
    for v in params.values():
        vals = v if isinstance(v, (list, tuple)) else (v,)
        for x in vals:
            if hasattr(x, "jaxpr"):       # ClosedJaxpr
                yield x.jaxpr
            elif hasattr(x, "eqns"):      # raw Jaxpr (pallas_call kernel)
                yield x


def walk_eqns(jaxpr, *, in_while: bool = False
              ) -> Iterator[tuple[object, bool]]:
    """Every equation in ``jaxpr`` and its nested jaxprs, tagged with
    whether it sits inside a ``while`` body."""
    for eqn in jaxpr.eqns:
        yield eqn, in_while
        child_in_while = in_while or eqn.primitive.name == "while"
        for sub in _subjaxprs(eqn.params):
            yield from walk_eqns(sub, in_while=child_in_while)


def primitive_counts(jaxpr) -> Counter:
    return Counter(eqn.primitive.name for eqn, _ in walk_eqns(jaxpr))


def _contract_site(fn) -> tuple[str, int]:
    """Best-effort (path, line) for a contract's kernel function."""
    try:
        target = inspect.unwrap(fn)
        target = getattr(target, "__wrapped__", target)
        path = inspect.getsourcefile(target) or "<unknown>"
        _, line = inspect.getsourcelines(target)
        try:
            path = os.path.relpath(path, _repo_root())
        except ValueError:
            pass
        return path, line
    except (TypeError, OSError):
        return "<unknown>", 0


def _trace(fn, args, kwargs):
    import jax

    return jax.make_jaxpr(functools.partial(fn, **kwargs))(*args)


def check_contract(contract) -> list[Violation]:
    """Abstract-eval one registered kernel and enforce its invariants."""
    name = contract.name

    def v(message, path="<registry>", line=0, suggestion=""):
        return Violation(pass_name="jaxpr", path=path, line=line,
                         message=f"kernel '{name}': {message}",
                         suggestion=suggestion)

    try:
        fn, example = contract.build()
    except Exception as e:  # noqa: BLE001 — any build failure is a finding
        return [v(f"contract build failed: {type(e).__name__}: {e}")]
    path, line = _contract_site(fn)
    try:
        args, kwargs = example()
        closed = _trace(fn, args, kwargs)
    except Exception as e:  # noqa: BLE001
        return [v(f"abstract eval failed: {type(e).__name__}: {e}",
                  path, line)]

    out: list[Violation] = []
    counts: Counter = Counter()
    int16_hits: list[str] = []
    while_hits: list[str] = []
    import numpy as np

    for eqn, in_while in walk_eqns(closed.jaxpr):
        prim = eqn.primitive.name
        counts[prim] += 1
        if in_while and prim in ("gather", "dynamic_slice"):
            while_hits.append(prim)
        if contract.no_int16_arithmetic and prim in _ARITHMETIC_PRIMS:
            for var in eqn.invars:
                aval = getattr(var, "aval", None)
                if aval is not None and \
                        getattr(aval, "dtype", None) == np.int16:
                    int16_hits.append(prim)
                    break

    gathers = counts.get("gather", 0)
    scatters = sum(n for p, n in counts.items() if p.startswith("scatter"))
    dyn = counts.get("dynamic_slice", 0)

    if contract.no_gather and gathers:
        out.append(v(
            f"jaxpr contains {gathers} gather primitive(s) but the "
            "contract declares no_gather",
            path, line,
            "computed-index gathers are the TPU slow path (~6x the whole "
            "apply per 64k rows); rewrite as one-hot masked sums / "
            "rolls+selects like ops/apply._apply_core"))
    elif contract.max_gathers is not None and gathers > contract.max_gathers:
        out.append(v(
            f"jaxpr contains {gathers} gather primitive(s), over the "
            f"budget of {contract.max_gathers}",
            path, line,
            "a new computed-index gather crept in; keep gathers confined "
            "to the once-per-wave compaction repack"))
    if contract.no_scatter and scatters:
        out.append(v(
            f"jaxpr contains {scatters} scatter primitive(s) but the "
            "contract declares no_scatter",
            path, line,
            "scatter is the TPU slow path; use jnp.where onto a "
            "precomputed mask instead"))
    if contract.max_dynamic_slices is not None and \
            dyn > contract.max_dynamic_slices:
        out.append(v(
            f"jaxpr contains {dyn} dynamic_slice equation(s), over the "
            f"budget of {contract.max_dynamic_slices}",
            path, line))
    if while_hits:
        out.append(v(
            f"dynamic-index read(s) inside a while body: "
            f"{sorted(set(while_hits))}",
            path, line,
            "a computed-index read in a device loop is K-amplified; "
            "hoist it or use a static roll/select form"))
    if int16_hits:
        out.append(v(
            f"arithmetic on int16 operands: {sorted(set(int16_hits))}",
            path, line,
            "widen explicitly with .astype(jnp.int32) before math — "
            "silent promotion hides wire-width bugs (see the packed-wave "
            "unpack in service/tpu_applier.py)"))
    if contract.single_jit:
        out.extend(_check_single_jit(contract, fn, example, path, line, v))
    return out


def _check_single_jit(contract, fn, example, path, line, v
                      ) -> list[Violation]:
    """Run the kernel twice with same-shape inputs; the compilation
    cache must grow by at most one entry total (one compile, no
    recompile on the second call)."""
    import jax

    jf = fn if hasattr(fn, "_cache_size") else jax.jit(fn)
    try:
        with warnings.catch_warnings():
            # CPU ignores buffer donation; that warning is not a finding
            warnings.simplefilter("ignore")
            args, kwargs = example()
            jax.block_until_ready(jf(*args, **kwargs))
            after_first = jf._cache_size()
            args, kwargs = example()
            jax.block_until_ready(jf(*args, **kwargs))
            after_second = jf._cache_size()
    except Exception as e:  # noqa: BLE001
        return [v(f"single_jit execution failed: {type(e).__name__}: {e}",
                  path, line)]
    if after_second != after_first:
        return [v(
            f"recompile on same-shape inputs: compilation cache grew "
            f"{after_first} -> {after_second} across two identical calls",
            path, line,
            "look for unhashable/py-object statics, weak-type churn, or "
            "a closure rebuilt per call — 'everything under one jit' is "
            "a load-bearing claim (ARCHITECTURE.md)")]
    return []


def check_kernels(registry: Optional[dict] = None,
                  required: tuple = REQUIRED_KERNELS) -> list[Violation]:
    """The full pass: registry coverage + every contract's invariants."""
    if registry is None:
        registry = load_registry()
    out = []
    for name in required:
        if name not in registry:
            out.append(Violation(
                pass_name="jaxpr", path="fluidframework_tpu", line=0,
                message=f"required hot-path kernel '{name}' is not "
                        "registered under a kernel contract",
                suggestion="restore its @kernel_contract / "
                           "register_kernel_contract registration"))
    for name in sorted(registry):
        out.extend(check_contract(registry[name]))
    return out


def _repo_root() -> str:
    return os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", ".."))
