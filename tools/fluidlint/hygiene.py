"""Repo-wide hygiene lints (ride along with the wire pass).

Three checks, each of which has bitten a JAX service codebase before:

- **bare ``except:``** — swallows ``KeyboardInterrupt``/``SystemExit``
  and masks device errors as empty state; always name the exception.
- **mutable default args** — ``def f(x=[])`` shares one list across
  calls; in a long-lived service process that is cross-request state.
- **``jnp`` calls at module import time** — a module-scope
  ``jnp.zeros(...)`` initializes the JAX backend as a side effect of
  ``import``, which on a TPU host grabs the device (and ~seconds of
  startup) for every process that merely imports the library. The
  library package must stay import-silent; build arrays lazily.
"""

from __future__ import annotations

import ast
import os
from typing import Iterable, Optional

from .report import Violation

#: Directories swept for bare-except / mutable-default (repo-relative).
HYGIENE_ROOTS = ("fluidframework_tpu", "tools", "examples", "tests")

#: The library package: also checked for import-time jnp calls.
IMPORT_SILENT_ROOTS = ("fluidframework_tpu",)


def _py_files(root: str) -> Iterable[str]:
    for dirpath, dirnames, files in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", "build", "fixtures")]
        for fn in sorted(files):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def _module_level_calls(tree: ast.Module) -> Iterable[ast.Call]:
    """Call nodes that execute at import time: anything not inside a
    function/lambda body (class bodies DO execute at import)."""

    def walk(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, ast.Call):
                yield child
            yield from walk(child)

    yield from walk(tree)


def check_file(path: str, repo_root: Optional[str] = None,
               import_silent: bool = False) -> list[Violation]:
    repo_root = repo_root or _repo_root()
    rel = os.path.relpath(path, repo_root)
    with open(path) as f:
        try:
            tree = ast.parse(f.read(), filename=path)
        except SyntaxError as e:
            return [Violation(pass_name="hygiene", path=rel,
                              line=e.lineno or 0,
                              message=f"syntax error: {e.msg}")]
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            out.append(Violation(
                pass_name="hygiene", path=rel, line=node.lineno,
                message="bare `except:` swallows KeyboardInterrupt and "
                        "masks device errors",
                suggestion="catch `Exception` (or the specific error) "
                           "instead"))
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defaults = list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None]
            for d in defaults:
                if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                    out.append(Violation(
                        pass_name="hygiene", path=rel, line=node.lineno,
                        message=f"mutable default argument in "
                                f"`{node.name}` is shared across calls",
                        suggestion="default to None and construct inside "
                                   "the function"))
    if import_silent:
        for call in _module_level_calls(tree):
            name = _dotted(call.func)
            if name.startswith("jnp.") or name.startswith("jax.numpy."):
                out.append(Violation(
                    pass_name="hygiene", path=rel, line=call.lineno,
                    message=f"`{name}(...)` at module import time "
                            "initializes the JAX backend on import",
                    suggestion="build device arrays lazily (inside a "
                               "function, or a cached builder)"))
    return out


def check_hygiene(repo_root: Optional[str] = None,
                  roots: tuple = HYGIENE_ROOTS,
                  import_silent_roots: tuple = IMPORT_SILENT_ROOTS
                  ) -> list[Violation]:
    repo_root = repo_root or _repo_root()
    out: list[Violation] = []
    for r in roots:
        root = os.path.join(repo_root, r)
        if not os.path.isdir(root):
            continue
        silent = r in import_silent_roots
        for path in _py_files(root):
            out.extend(check_file(path, repo_root, import_silent=silent))
    # top-level scripts (bench.py, __graft_entry__.py, ...)
    for fn in sorted(os.listdir(repo_root)):
        if fn.endswith(".py"):
            out.extend(check_file(os.path.join(repo_root, fn), repo_root))
    return out


def _dotted(node: ast.AST) -> str:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _repo_root() -> str:
    return os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", ".."))
