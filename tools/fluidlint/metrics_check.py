"""Metric-name lint: the dotted ``tier.noun.verb`` convention.

Two checks over the library package:

- **metric names**: every string-literal first argument to ``.inc(...)``,
  ``.observe(...)``, ``.set_gauge(...)`` or ``.observe_windowed(...)``
  (Counters or MetricsRegistry, same surface) must be dotted lowercase
  with 3–4 segments — ``driver.submit.coalesced``,
  ``chaos.recovered.orderer_restart``. A scrape namespace where half the
  names are ``opsDone`` and half are ``driver.ops.done`` cannot be
  queried; the convention is only worth having if it is total. F-strings
  and computed names are skipped (the detailed per-point chaos counters
  compose their suffix at runtime).
- **locked families**: the ``obs.slo.*`` and ``net.admission.*``
  namespaces are alert-surface contracts — dashboards and the overload
  bench key on the exact member set. A new name under a locked prefix
  must be added to ``LOCKED_FAMILIES`` in
  ``tools/fluidlint/registries.py`` in the same change, or
  the lint refuses it (spelling drift like ``net.admission.dropped`` vs
  the canonical ``net.admission.shed`` is exactly the bug this catches).
- **Counters construction**: ``Counters(...)`` may only be constructed
  in ``utils/telemetry.py`` (its home) and ``obs/metrics.py`` (the
  registry factory). Everywhere else must go through
  ``obs.tier_counters(tier)`` so the instance lands in the process-wide
  scrape — a bare ``Counters()`` is telemetry the scrape silently never
  sees.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterable, Optional

from .registries import LOCKED_FAMILIES  # noqa: F401 — re-exported
from .report import Violation

#: Swept directories (repo-relative). Tests and tools construct Counters
#: to exercise the mechanism itself and are deliberately out of scope.
METRIC_ROOTS = ("fluidframework_tpu",)

#: Files allowed to construct Counters directly.
COUNTERS_HOMES = (
    os.path.join("fluidframework_tpu", "utils", "telemetry.py"),
    os.path.join("fluidframework_tpu", "obs", "metrics.py"),
)

#: dotted lowercase, 3–4 segments: tier.noun.verb(.qualifier)
NAME_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z0-9_]+){2,3}$")

_METHODS = ("inc", "observe", "set_gauge", "observe_windowed")


def _py_files(root: str) -> Iterable[str]:
    for dirpath, dirnames, files in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", "build", "fixtures")]
        for fn in sorted(files):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def check_file(path: str, repo_root: Optional[str] = None
               ) -> list[Violation]:
    repo_root = repo_root or _repo_root()
    rel = os.path.relpath(path, repo_root)
    with open(path) as f:
        try:
            tree = ast.parse(f.read(), filename=path)
        except SyntaxError:
            return []  # the hygiene pass reports syntax errors
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if (isinstance(func, ast.Attribute) and func.attr in _METHODS
                and node.args):
            arg = node.args[0]
            # only literal names are checkable; f-strings / computed
            # names (the per-point chaos counters) are skipped
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                name = arg.value
                if not NAME_RE.match(name):
                    out.append(Violation(
                        pass_name="metric-name", path=rel,
                        line=node.lineno,
                        message=f'metric name "{name}" breaks the dotted '
                                "tier.noun.verb convention (3-4 lowercase "
                                "segments)",
                        suggestion="rename to e.g. "
                                   '"driver.submit.coalesced"'))
                else:
                    for prefix, members in LOCKED_FAMILIES.items():
                        if name.startswith(prefix) and name not in members:
                            out.append(Violation(
                                pass_name="metric-name", path=rel,
                                line=node.lineno,
                                message=f'"{name}" is not a registered '
                                        f"member of the locked "
                                        f'"{prefix}*" family '
                                        f"(members: "
                                        f"{', '.join(sorted(members))})",
                                suggestion="add it to LOCKED_FAMILIES in "
                                           "tools/fluidlint/"
                                           "registries.py if the "
                                           "contract change is "
                                           "intentional"))
        if (isinstance(func, ast.Name) and func.id == "Counters"
                and rel not in COUNTERS_HOMES):
            out.append(Violation(
                pass_name="metric-name", path=rel, line=node.lineno,
                message="bare Counters() construction bypasses the "
                        "metrics registry (invisible to the scrape)",
                suggestion="use obs.tier_counters(tier) so the instance "
                           "is labeled and scraped"))
    return out


def check_metrics(repo_root: Optional[str] = None,
                  roots: tuple = METRIC_ROOTS) -> list[Violation]:
    repo_root = repo_root or _repo_root()
    out: list[Violation] = []
    for r in roots:
        root = os.path.join(repo_root, r)
        if not os.path.isdir(root):
            continue
        for path in _py_files(root):
            out.extend(check_file(path, repo_root))
    return out


def _repo_root() -> str:
    return os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", ".."))
