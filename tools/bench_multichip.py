"""Per-device scaling bench for the multi-chip doc mesh (ISSUE 9).

Sweeps the 'docs' mesh axis 1 → 2 → 4 → 8 (forced host devices when the
real platform has fewer) in WEAK-scaling geometry — 64 docs per shard,
K=32 ops per doc per wave — and publishes per rung: ops/s, scaling
efficiency vs the 1-shard rung, host staging cost per wave (the per-shard
wave-build + pre-partitioned transfer path), and staged bytes per wave.
The 1-shard rung is also raced against the LOCAL dense lane at the same
geometry: the mesh lane is only "the fast lane" if the mesh tax at
n_shards=1 is noise.

On this bench host every "device" is a forced host-platform virtual
device time-slicing ONE core, so ops/s cannot rise with the axis; the
artifact carries ``forced_host: true`` and the efficiency column is the
honest transfer-and-dispatch overhead curve, not an ICI scaling claim.

``--smoke`` (the ci.sh gate) skips the timing sweep and counter-asserts
the tentpole's structural claims instead:
  * per-wave staged bytes scale with ACTIVE shards, never with max_docs
    (the pre-refactor dense wave was O(max_docs) on every wave);
  * the sharded step compiles exactly once per wave shape.

Artifact schema v2 (MULTICHIP_r06+)::

    {"schema": 2, "platform": ..., "n_devices": 8, "forced_host": true,
     "rungs": [{"docs_axis": n, "n_docs": D, "ops_per_sec": ...,
                "scaling_efficiency": ..., "staging_ms_per_wave": ...,
                "staged_bytes_per_wave": ...}, ...],
     "local_dense_ops_per_sec": ..., "mesh_vs_local_1shard": ...,
     "ok": true, "rc": 0}

``read_multichip`` also accepts the pre-r06 dryrun schema
({n_devices, rc, ok, skipped, tail}) and normalizes it to v2 shape with
an empty rung list, so dashboards can fold the whole r01..rNN series.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import types


def read_multichip(path: str) -> dict:
    """Load a MULTICHIP artifact of ANY generation as schema v2."""
    with open(path) as f:
        raw = json.load(f)
    if raw.get("schema", 1) >= 2:
        return raw
    # r01..r05 dryrun schema: presence/absence of a multi-device compile,
    # no throughput rungs
    return {
        "schema": 2,
        "platform": None,
        "n_devices": raw.get("n_devices"),
        "forced_host": None,
        "rungs": [],
        "local_dense_ops_per_sec": None,
        "mesh_vs_local_1shard": None,
        "ok": bool(raw.get("ok")) and not raw.get("skipped"),
        "rc": raw.get("rc"),
    }


def _msg(seq: int) -> types.SimpleNamespace:
    return types.SimpleNamespace(
        sequence_number=seq,
        reference_sequence_number=max(seq - 1, 0),
        minimum_sequence_number=max(seq - 4, 0),
        client_id="bench",
    )


_INS = {"type": 0, "pos": 0, "text": "x"}
_REM = {"type": 1, "start": 0, "end": 1}


def _stage_wave(applier, docs, seqs, k: int) -> int:
    """Stage k ops per doc (insert/remove pairs at the head, so live
    segments stay flat and zamboni has work every wave). Returns the op
    count staged."""
    for d in docs:
        for _ in range(k // 2):
            seqs[d] += 1
            applier.ingest("t", d, _msg(seqs[d]), _INS)
            seqs[d] += 1
            applier.ingest("t", d, _msg(seqs[d]), _REM)
    return len(docs) * (k // 2) * 2


def _fence(applier) -> None:
    import numpy as np

    np.asarray(applier.state.count)


def _time_applier(applier, docs, k: int, warmup: int = 2,
                  timed: int = 8) -> dict:
    """Ops/s over `timed` full waves (ingest excluded: the bench isolates
    the wave-build → transfer → dispatch lane, and the host staging slice
    of it is reported separately from the applier's own counters)."""
    seqs = {d: 0 for d in docs}
    for _ in range(warmup):
        _stage_wave(applier, docs, seqs, k)
        applier.flush()
    _fence(applier)
    stage_s0 = applier.mesh_stage_seconds
    waves0 = applier.mesh_waves
    bytes0 = applier.mesh_staged_bytes
    total_ops = 0
    elapsed = 0.0
    for _ in range(timed):
        total_ops += _stage_wave(applier, docs, seqs, k)
        t0 = time.perf_counter()
        applier.flush()
        _fence(applier)
        elapsed += time.perf_counter() - t0
    waves = applier.mesh_waves - waves0
    return {
        "ops_per_sec": round(total_ops / elapsed, 1),
        "staging_ms_per_wave": (
            round((applier.mesh_stage_seconds - stage_s0) / waves * 1e3, 4)
            if waves else None),
        "staged_bytes_per_wave": (
            (applier.mesh_staged_bytes - bytes0) // waves if waves else None),
    }


DOCS_PER_SHARD = 64
K = 32


def run_sweep(axes=(1, 2, 4, 8)) -> dict:
    import jax

    from fluidframework_tpu.parallel.mesh import make_mesh
    from fluidframework_tpu.service.tpu_applier import TpuDocumentApplier

    rungs = []
    for n in axes:
        D = DOCS_PER_SHARD * n
        applier = TpuDocumentApplier(
            max_docs=D, max_slots=64, ops_per_dispatch=K,
            mesh=make_mesh(n, seg_shards=1))
        docs = [f"d{i}" for i in range(D)]
        r = _time_applier(applier, docs, K)
        rungs.append({"docs_axis": n, "n_docs": D, **r})
    base = rungs[0]["ops_per_sec"]
    for r in rungs:
        r["scaling_efficiency"] = round(
            r["ops_per_sec"] / (r["docs_axis"] * base), 3)

    # the mesh tax at n_shards=1: same geometry down the local dense lane
    local = TpuDocumentApplier(max_docs=DOCS_PER_SHARD, max_slots=64,
                               ops_per_dispatch=K)
    docs1 = [f"d{i}" for i in range(DOCS_PER_SHARD)]
    seqs = {d: 0 for d in docs1}
    for _ in range(2):
        _stage_wave(local, docs1, seqs, K)
        local.flush()
    _fence(local)
    ops = elapsed = 0
    for _ in range(8):
        ops += _stage_wave(local, docs1, seqs, K)
        t0 = time.perf_counter()
        local.flush()
        _fence(local)
        elapsed += time.perf_counter() - t0
    local_opsps = round(ops / elapsed, 1)
    return {
        "schema": 2,
        "platform": jax.devices()[0].platform,
        "n_devices": len(jax.devices()),
        "forced_host": jax.devices()[0].platform == "cpu",
        "rungs": rungs,
        "local_dense_ops_per_sec": local_opsps,
        "mesh_vs_local_1shard": round(rungs[0]["ops_per_sec"] / local_opsps,
                                      3),
        "ok": True,
        "rc": 0,
    }


def run_smoke() -> None:
    """The ci.sh gate: structural counter-asserts, no timing."""
    from fluidframework_tpu.ops.apply import OP_FIELDS
    from fluidframework_tpu.parallel.mesh import make_mesh
    from fluidframework_tpu.service.tpu_applier import TpuDocumentApplier

    D, n_shards, k = 64, 8, 8
    applier = TpuDocumentApplier(max_docs=D, max_slots=32,
                                 ops_per_dispatch=k,
                                 mesh=make_mesh(n_shards, seg_shards=1))
    sps = applier.placement.slots_per_shard
    per_shard = sps * k * OP_FIELDS * 2 + sps * 2 * 4  # int16 wave + bases
    dense = D * k * OP_FIELDS * 2 + D * 2 * 4          # the old O(max_docs)

    # one compile per wave shape, measured as growth: the packed step is
    # cached per mesh across applier instances, so an absolute count
    # would see shapes compiled by other users of the same mesh
    packed_fn, wide_fn = applier._sharded_step
    cache0 = packed_fn._cache_size()
    wide0 = wide_fn._cache_size()

    # one active doc → exactly one shard's buffers staged per wave
    seqs = {"d0": 0}
    for _ in range(10):
        _stage_wave(applier, ["d0"], seqs, k)
        applier.flush()
    assert applier.mesh_waves == 10, applier.mesh_waves
    assert applier.mesh_active_shards == 10, applier.mesh_active_shards
    b1 = applier.mesh_staged_bytes // applier.mesh_waves
    assert b1 == per_shard, (b1, per_shard)
    assert b1 * n_shards <= dense, (b1, dense)

    # all shards active → bytes scale with ACTIVE shards (8×), still not
    # with max_docs
    docs = [f"d{i}" for i in range(D)]
    seqs = {d: seqs.get(d, 0) for d in docs}
    w0, by0 = applier.mesh_waves, applier.mesh_staged_bytes
    for _ in range(10):
        _stage_wave(applier, docs, seqs, k)
        applier.flush()
    waves = applier.mesh_waves - w0
    b8 = (applier.mesh_staged_bytes - by0) // waves
    assert b8 == n_shards * per_shard, (b8, n_shards * per_shard)

    # 20 same-shape waves → exactly one new compile on the packed step,
    # none on the wide lane (it never ran)
    assert packed_fn._cache_size() - cache0 <= 1, (cache0,
                                                   packed_fn._cache_size())
    assert wide_fn._cache_size() == wide0, (wide0, wide_fn._cache_size())
    import numpy as np

    assert not np.asarray(applier.state.overflow).any()
    print("bench_multichip --smoke: ok "
          f"(per-wave bytes {b1} x active shards, dense was {dense})")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--smoke", action="store_true",
                    help="structural counter-asserts only (ci.sh gate)")
    ap.add_argument("--out", default=None,
                    help="also write the artifact JSON to this path")
    args = ap.parse_args(argv)
    from fluidframework_tpu.parallel.mesh import force_host_devices

    force_host_devices(args.devices)
    if args.smoke:
        run_smoke()
        return 0
    result = run_sweep()
    line = json.dumps(result)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(json.dumps(result, indent=1) + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
