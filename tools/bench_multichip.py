"""Per-device scaling bench for the multi-chip doc mesh (ISSUE 9).

Sweeps the 'docs' mesh axis 1 → 2 → 4 → 8 (forced host devices when the
real platform has fewer) in WEAK-scaling geometry — 64 docs per shard,
K=32 ops per doc per wave — and publishes per rung: ops/s, scaling
efficiency vs the 1-shard rung, host staging cost per wave (the per-shard
wave-build + pre-partitioned transfer path), and staged bytes per wave.
The 1-shard rung is also raced against the LOCAL dense lane at the same
geometry: the mesh lane is only "the fast lane" if the mesh tax at
n_shards=1 is noise.

On this bench host every "device" is a forced host-platform virtual
device time-slicing ONE core, so ops/s cannot rise with the axis; the
artifact carries ``forced_host: true`` and the efficiency column is the
honest transfer-and-dispatch overhead curve, not an ICI scaling claim.

``--smoke`` (the ci.sh gate) skips the timing sweep and counter-asserts
the tentpole's structural claims instead:
  * per-wave staged bytes scale with ACTIVE shards, never with max_docs
    (the pre-refactor dense wave was O(max_docs) on every wave);
  * the sharded step compiles exactly once per wave shape — with the
    overlap pipeline armed;
  * ``applier.stage.overlap_ratio`` goes positive when waves pipeline
    (staging really ran while the device executed).

Artifact schema v3 (MULTICHIP_r07+) adds the overlap-staged dispatch
split::

    {"schema": 3, "platform": ..., "n_devices": 8, "forced_host": true,
     "host_limited": true, "host_limited_note": ...,
     "overlap": true, "efficiency_basis": "wall",
     "rungs": [{"docs_axis": n, "n_docs": D, "ops_per_sec": ...,
                "pipeline_ops_per_sec": ..., "scaling_efficiency": ...,
                "overlap_ratio": ..., "stage_ms_hidden": ...,
                "kernel_lane": "xla"|"pallas",
                "staging_ms_per_wave": ..., "staged_bytes_per_wave": ...},
               ...],
     "local_dense_ops_per_sec": ..., "mesh_vs_local_1shard": ...,
     "local_dense_ab": {"n_docs": D, "on": {...}, "off": {...},
                        "improvement": ..., "improvement_basis": ...},
     "ok": true, "rc": 0}

``ops_per_sec`` stays wall-clock and ``scaling_efficiency`` is computed
on it (``efficiency_basis: "wall"`` — the number that cannot lie).
``pipeline_ops_per_sec`` divides by the HOST critical path instead:
un-hidden staging time plus the (async) dispatch call — the path the
overlap pipeline shrinks and the throughput predictor for a real mesh.
On forced host-platform devices every "chip" time-slices one core, so
wall throughput arithmetically cannot rise with the axis; the artifact
then carries ``host_limited: true`` with a note, and the overlap
mechanism is evidenced by per-rung ``overlap_ratio`` plus the smoke
gate's counter-asserts.

``read_multichip`` folds all generations: v1 dryruns
({n_devices, rc, ok, skipped, tail}) normalize to an empty rung list;
v2 (r06) rungs gain null overlap fields.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import types


#: per-rung fields added by schema v3 (null when folded from older runs)
_V3_RUNG_FIELDS = ("pipeline_ops_per_sec", "overlap_ratio",
                   "stage_ms_hidden", "kernel_lane")


def read_multichip(path: str) -> dict:
    """Load a MULTICHIP artifact of ANY generation as schema v3."""
    with open(path) as f:
        raw = json.load(f)
    schema = raw.get("schema", 1)
    if schema >= 3:
        return raw
    if schema == 2:
        # r06: real rungs, pre-overlap — the v3 split fields are unknown
        for r in raw.get("rungs", []):
            for f2 in _V3_RUNG_FIELDS:
                r.setdefault(f2, None)
        raw.setdefault("overlap", False)
        raw.setdefault("efficiency_basis", "wall")
        raw.setdefault("host_limited", raw.get("forced_host"))
        raw.setdefault("host_limited_note", None)
        raw.setdefault("local_dense_ab", None)
        raw["schema"] = 3
        return raw
    # r01..r05 dryrun schema: presence/absence of a multi-device compile,
    # no throughput rungs
    return {
        "schema": 3,
        "platform": None,
        "n_devices": raw.get("n_devices"),
        "forced_host": None,
        "host_limited": None,
        "host_limited_note": None,
        "overlap": False,
        "efficiency_basis": "wall",
        "rungs": [],
        "local_dense_ops_per_sec": None,
        "mesh_vs_local_1shard": None,
        "local_dense_ab": None,
        "ok": bool(raw.get("ok")) and not raw.get("skipped"),
        "rc": raw.get("rc"),
    }


def _msg(seq: int) -> types.SimpleNamespace:
    return types.SimpleNamespace(
        sequence_number=seq,
        reference_sequence_number=max(seq - 1, 0),
        minimum_sequence_number=max(seq - 4, 0),
        client_id="bench",
    )


_INS = {"type": 0, "pos": 0, "text": "x"}
_REM = {"type": 1, "start": 0, "end": 1}


def _stage_wave(applier, docs, seqs, k: int) -> int:
    """Stage k ops per doc (insert/remove pairs at the head, so live
    segments stay flat and zamboni has work every wave). Returns the op
    count staged."""
    for d in docs:
        for _ in range(k // 2):
            seqs[d] += 1
            applier.ingest("t", d, _msg(seqs[d]), _INS)
            seqs[d] += 1
            applier.ingest("t", d, _msg(seqs[d]), _REM)
    return len(docs) * (k // 2) * 2


def _fence(applier) -> None:
    import numpy as np

    np.asarray(applier.state.count)


def _time_applier(applier, docs, k: int, warmup: int = 2,
                  timed: int = 8) -> dict:
    """Ops/s over `timed` PIPELINED waves (ingest excluded: the bench
    isolates the stage → transfer → dispatch lane). All timed waves are
    pre-ingested and ONE flush drains them, so wave i+1 stages on the
    host while wave i executes on device — the overlap lane this bench
    exists to measure. (The pre-overlap bench fenced after every wave,
    serializing exactly the path under test.) Works for both lanes: the
    stage/execute split counters are fed by dense and mesh alike."""
    seqs = {d: 0 for d in docs}
    for _ in range(warmup):
        _stage_wave(applier, docs, seqs, k)
        applier.flush()
    _fence(applier)
    stage_s0 = applier.stage_seconds
    hidden_s0 = applier.stage_overlap_seconds
    bytes0 = applier.stage_bytes
    waves0 = applier.waves_staged
    exec_s0 = applier.exec_seconds
    total_ops = 0
    for _ in range(timed):
        total_ops += _stage_wave(applier, docs, seqs, k)
    t0 = time.perf_counter()
    applier.flush()
    _fence(applier)
    elapsed = time.perf_counter() - t0
    stage_s = applier.stage_seconds - stage_s0
    hidden_s = applier.stage_overlap_seconds - hidden_s0
    exec_s = applier.exec_seconds - exec_s0
    waves = applier.waves_staged - waves0
    # the HOST critical path per wave: staging not hidden behind device
    # execution, plus the (async) dispatch call. On a real mesh this
    # path bounds throughput once per-device compute is constant (weak
    # scaling); on forced-host devices one core also runs all the
    # "device" compute, so wall time cannot scale and this is the
    # honest predictor the overlap work moves.
    host_path_s = (stage_s - hidden_s) + exec_s
    return {
        "ops_per_sec": round(total_ops / elapsed, 1),
        "pipeline_ops_per_sec": (round(total_ops / host_path_s, 1)
                                 if host_path_s > 0 else None),
        "staging_ms_per_wave": (round(stage_s / waves * 1e3, 4)
                                if waves else None),
        "stage_ms_hidden": (round(hidden_s / waves * 1e3, 4)
                            if waves else None),
        "overlap_ratio": round(hidden_s / stage_s, 3) if stage_s else None,
        "staged_bytes_per_wave": ((applier.stage_bytes - bytes0) // waves
                                  if waves else None),
        "kernel_lane": applier.kernel_lane,
    }


DOCS_PER_SHARD = 64
K = 32


def run_sweep(axes=(1, 2, 4, 8)) -> dict:
    import jax

    from fluidframework_tpu.parallel.mesh import make_mesh
    from fluidframework_tpu.service.tpu_applier import TpuDocumentApplier

    forced_host = jax.devices()[0].platform == "cpu"
    rungs = []
    for n in axes:
        D = DOCS_PER_SHARD * n
        applier = TpuDocumentApplier(
            max_docs=D, max_slots=64, ops_per_dispatch=K,
            mesh=make_mesh(n, seg_shards=1))
        docs = [f"d{i}" for i in range(D)]
        r = _time_applier(applier, docs, K)
        rungs.append({"docs_axis": n, "n_docs": D, **r})
    # weak-scaling efficiency vs the 1-shard rung, on WALL throughput —
    # the number that cannot lie. pipeline_ops_per_sec per rung shows
    # the host critical path the overlap pipeline shrinks; on forced
    # host devices it goes near-free at rungs the runtime can keep two
    # waves in flight, which would flatter the efficiency column, so it
    # stays informational and the artifact is annotated host_limited.
    base = rungs[0]["ops_per_sec"]
    for r in rungs:
        r["scaling_efficiency"] = round(
            r["ops_per_sec"] / (r["docs_axis"] * base), 3)

    # the mesh tax at n_shards=1: same geometry down the local dense lane
    local = TpuDocumentApplier(max_docs=DOCS_PER_SHARD, max_slots=64,
                               ops_per_dispatch=K)
    docs1 = [f"d{i}" for i in range(DOCS_PER_SHARD)]
    local_opsps = _time_applier(local, docs1, K)["ops_per_sec"]

    # dense-lane A/B at the 4-doc-axis rung's doc count: overlap on vs
    # off over the identical pipelined workload. The design's effect
    # lives on the host critical path (improvement_basis), wall is
    # reported alongside — on a single-core host the two arms do the
    # same total work, so wall improvement there is bounded by the
    # sync-call overhead the off arm pays.
    ab_docs = DOCS_PER_SHARD * 4
    ab = {}
    for arm, overlap in (("on", True), ("off", False)):
        applier = TpuDocumentApplier(max_docs=ab_docs, max_slots=64,
                                     ops_per_dispatch=K, overlap=overlap)
        ab[arm] = _time_applier(applier,
                                [f"d{i}" for i in range(ab_docs)], K)

    def _ratio(key):
        on, off = ab["on"][key], ab["off"][key]
        return round(on / off, 3) if on and off else None

    host_limited_note = (
        "forced host-platform devices time-slice one core: wall "
        "throughput cannot rise with the docs axis, and the CPU runtime "
        "intermittently serializes multi-wave dispatch at the 8-device "
        "rung (overlap_ratio collapses there). The overlap mechanism is "
        "proven by the lower rungs' overlap_ratio and the --smoke gate."
        if forced_host else None)

    return {
        "schema": 3,
        "platform": jax.devices()[0].platform,
        "n_devices": len(jax.devices()),
        "forced_host": forced_host,
        "host_limited": forced_host,
        "host_limited_note": host_limited_note,
        "overlap": True,
        "efficiency_basis": "wall",
        "rungs": rungs,
        "local_dense_ops_per_sec": local_opsps,
        "mesh_vs_local_1shard": round(rungs[0]["ops_per_sec"] / local_opsps,
                                      3),
        "local_dense_ab": {"n_docs": ab_docs, "on": ab["on"],
                           "off": ab["off"],
                           "improvement": _ratio("pipeline_ops_per_sec"),
                           "improvement_basis": "host_pipeline",
                           "improvement_wall": _ratio("ops_per_sec")},
        "ok": True,
        "rc": 0,
    }


def run_smoke() -> None:
    """The ci.sh gate: structural counter-asserts, no timing."""
    from fluidframework_tpu.ops.apply import OP_FIELDS
    from fluidframework_tpu.parallel.mesh import make_mesh
    from fluidframework_tpu.service.tpu_applier import TpuDocumentApplier

    D, n_shards, k = 64, 8, 8
    applier = TpuDocumentApplier(max_docs=D, max_slots=32,
                                 ops_per_dispatch=k,
                                 mesh=make_mesh(n_shards, seg_shards=1))
    sps = applier.placement.slots_per_shard
    per_shard = sps * k * OP_FIELDS * 2 + sps * 2 * 4  # int16 wave + bases
    dense = D * k * OP_FIELDS * 2 + D * 2 * 4          # the old O(max_docs)

    # one compile per wave shape, measured as growth: the packed step is
    # cached per mesh across applier instances, so an absolute count
    # would see shapes compiled by other users of the same mesh
    packed_fn, wide_fn = applier._sharded_step
    cache0 = packed_fn._cache_size()
    wide0 = wide_fn._cache_size()

    # one active doc → exactly one shard's buffers staged per wave
    seqs = {"d0": 0}
    for _ in range(10):
        _stage_wave(applier, ["d0"], seqs, k)
        applier.flush()
    assert applier.mesh_waves == 10, applier.mesh_waves
    assert applier.mesh_active_shards == 10, applier.mesh_active_shards
    b1 = applier.mesh_staged_bytes // applier.mesh_waves
    assert b1 == per_shard, (b1, per_shard)
    assert b1 * n_shards <= dense, (b1, dense)

    # all shards active → bytes scale with ACTIVE shards (8×), still not
    # with max_docs
    docs = [f"d{i}" for i in range(D)]
    seqs = {d: seqs.get(d, 0) for d in docs}
    w0, by0 = applier.mesh_waves, applier.mesh_staged_bytes
    for _ in range(10):
        _stage_wave(applier, docs, seqs, k)
        applier.flush()
    waves = applier.mesh_waves - w0
    b8 = (applier.mesh_staged_bytes - by0) // waves
    assert b8 == n_shards * per_shard, (b8, n_shards * per_shard)

    # overlap: pipeline 10 pre-ingested waves through ONE flush, so the
    # staging of wave i+1 runs while wave i executes. Both the instance
    # counter and the exported gauge must go positive — staging really
    # overlapped device execution, with overlap armed by default.
    for _ in range(10):
        _stage_wave(applier, docs, seqs, k)
    applier.flush()
    _fence(applier)
    ratio = applier.stage_overlap_ratio()
    assert ratio > 0, f"overlap_ratio {ratio} with pipelined waves"
    from fluidframework_tpu.obs import get_registry, parse_prometheus

    scraped = parse_prometheus(get_registry().scrape())
    gauge = scraped.get("fluid_applier_stage_overlap_ratio", {})
    assert gauge and max(gauge.values()) > 0, gauge

    # 30 same-shape waves → exactly one new compile on the packed step,
    # none on the wide lane (it never ran) — including across the
    # pipelined overlap phase above
    assert packed_fn._cache_size() - cache0 <= 1, (cache0,
                                                   packed_fn._cache_size())
    assert wide_fn._cache_size() == wide0, (wide0, wide_fn._cache_size())
    import numpy as np

    assert not np.asarray(applier.state.overflow).any()
    print("bench_multichip --smoke: ok "
          f"(per-wave bytes {b1} x active shards, dense was {dense}; "
          f"overlap_ratio {ratio:.3f} with pipelined waves)")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--smoke", action="store_true",
                    help="structural counter-asserts only (ci.sh gate)")
    ap.add_argument("--out", default=None,
                    help="also write the artifact JSON to this path")
    args = ap.parse_args(argv)
    from fluidframework_tpu.parallel.mesh import force_host_devices

    force_host_devices(args.devices)
    if args.smoke:
        run_smoke()
        return 0
    result = run_sweep()
    line = json.dumps(result)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(json.dumps(result, indent=1) + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
