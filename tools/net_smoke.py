"""Socket-tier batching smoke: fail CI if the coalescing never engages.

``python -m tools.net_smoke`` (wired into tools/ci.sh) runs an
in-process NetworkFrontEnd over a durable log and drives the three
amortization points of the socket tier (see ARCHITECTURE.md
"Socket-tier batching"):

- a driver client submitting a rapid burst through a forced coalescing
  window — ``driver.submit.coalesced`` must rise and the burst must
  ride FEWER frames than ops;
- a raw socket delivering many frames in one TCP wave — the server's
  drain-batched read loop must count ``net.ingress.coalesced``;
- a burst of canonical chanop boxcars — the driver must emit columnar
  frames (``driver.submit.columnar``) and the server must admit them
  through the array lane (``net.ingress.columnar``);
- two subscribers on one doc — the encode-once fan-out must count
  ``net.fanout.cache_hits``;
- a read-only frame after quiescence — ``net.flush.elided`` must rise,
  and the submit batches must have counted ``net.flush.performed``;
- a catch-up client backfilling the full range through the columnar
  door — the sequenced stream must have ridden the segment lane
  (``storage.segment.appends``) and the server must have served raw
  block byte ranges (``storage.backfill.byterange``);
- a summarized doc + a burst of three cold joiners booting through the
  columnar snapshot door — the serving side must frame chunks exactly
  ONCE for the whole burst (``storage.snapshot.encodes`` == 1), serve
  every boot from the framed cache (``storage.snapshot.served`` /
  ``cache_hits``), and every joiner must take the bounded backfill
  (``boot.backfill.bounded``) with zero legacy-tree fallbacks;
- a mini-overload burst with the admission gate + a hair-trigger SLO
  armed — ``net.admission.shed`` must rise, ``obs.slo.state`` must
  appear in the scrape, and the driver's transparent shed retries must
  converge once shedding is disarmed;
- a forced live migration under traffic (two sharded core processes +
  a gateway, ``admin_migrate_doc`` fired mid-stream): every submitted
  op must ack exactly once (zero lost), the source core's
  ``placement.migration.committed`` / ``placement.epoch.bumps``
  counters must be nonzero, the fleet-merged audit journal must show
  the move's CAUSALLY-LINKED chain (operator command → seal → fence →
  checkpoint → adopt → commit, crossing both cores), and an ``admin
  bundle`` of the fleet must be parseable by tools/doctor.py with the
  migration visible in its triage;
- a 2-level relay tree (core ← gw1 ← gw2) with read-only leaf
  subscribers — ``fanout.relay.splices`` must rise at BOTH levels,
  ``presence.lane.coalesced`` and ``session.readonly.connects`` must
  rise at the core, and ``fanout.relay.encodes`` must stay 0 (zero
  re-encode above the first gateway level);
- the doc history plane over sockets: summarize a live doc, fork it
  through the history door, read a historical seq through a read-only
  replay container, edit the fork and integrate the edit back into the
  parent — ``history.fork.boots``, ``history.replay.reads`` and
  ``history.integrate.ops`` must all rise;
- a 2-host-group fleet from one ``multihost_spec`` (subprocess, h1 in
  a DISJOINT working dir on the remote table client) with a forced
  CROSS-HOST migration under traffic: the sealed log must ship through
  storage (``migration.ship`` in the fleet journal), every ack must
  land exactly once, the remote core's ``placement.table.rpc_reads``
  must be nonzero (its placement plane ran through the door), and an
  ``admin bundle`` must triage clean through tools/doctor.py;
- the live health plane (canary probes + streaming doctor + fleet
  gate): a 2-host fleet with probing armed, one host group killed -9
  mid-probe — the survivor's engine must reach ``critical`` with a
  reason NAMING the dead peer, a bundle captured during the outage
  must make tools/doctor.py agree with the live verdict, and after
  the respawn ``Fleet.wait_healthy`` (the rolling-upgrade go/no-go
  gate) must reopen with the doctor quiet again.

``--only GATE`` (repeatable; migration/relay/history/coldstart/
multihost/health) runs just the named process gate(s), skipping the
in-proc batching burst — the dev loop for one subsystem.

Exit 1 names every counter that stayed at zero: a refactor that
silently disengages the batching fails the commit gate, not the next
bench run.
"""

from __future__ import annotations

import json
import os
import socket
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

N_OPS = 200
N_COLS = 64
BURST_FRAMES = 16


def wait_for(pred, timeout: float = 20.0) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.005)
    return bool(pred())


def _frame(obj: dict) -> bytes:
    body = json.dumps(obj, separators=(",", ":")).encode()
    return len(body).to_bytes(4, "big") + body


def _spawn_listening(mod: str, *args: str):
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.Popen(
        [sys.executable, "-m", mod, *args],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd=repo, env=dict(os.environ, JAX_PLATFORMS="cpu"))
    line = proc.stdout.readline().strip()
    assert line.startswith("LISTENING"), line
    return proc, int(line.rsplit(":", 1)[1])


def migration_gate() -> dict:
    """Forced live migration under traffic: two sharded core processes
    + a gateway, a driver client submitting through the migration, the
    ``admin migrate`` RPC fired at the source core mid-stream. Returns
    the placement counter checks; raises AssertionError on a lost or
    duplicated ack (the zero-loss gate)."""
    import tempfile
    import threading

    from fluidframework_tpu.driver.network import (
        NetworkDocumentServiceFactory,
        _Transport,
    )
    from fluidframework_tpu.loader.container import Loader
    from fluidframework_tpu.service.stage_runner import doc_partition

    shard_dir = tempfile.mkdtemp(prefix="net-smoke-mig-")
    cores, core_ports, gw = [], [], None
    writer = reader = None
    try:
        for prefer in ("0", "1"):
            c, p = _spawn_listening(
                "fluidframework_tpu.service.front_end", "--port", "0",
                "--shard-dir", shard_dir, "--shards", "2",
                "--prefer", prefer, "--lease-ttl", "1.5")
            cores.append(c)
            core_ports.append(p)
        gw, gw_port = _spawn_listening(
            "fluidframework_tpu.service.gateway", "--shard-dir",
            shard_dir, "--shards", "2")

        k = doc_partition("smoke", "migdoc", 2)
        src_port = core_ports[k]
        target = f"127.0.0.1:{core_ports[1 - k]}"

        # the supported client posture for a route flip: the gateway
        # drops the doc's sessions on fdropped, the container re-dials
        # and replays its pending ops against the takeover owner
        writer = Loader(NetworkDocumentServiceFactory(
            "127.0.0.1", gw_port), auto_reconnect=True).resolve(
            "smoke", "migdoc")
        sstr = writer.runtime.create_data_store(
            "default").create_channel("text", "shared-string")

        n_ops = 120

        def feed():
            for i in range(n_ops):
                sstr.insert_text(0, f"m{i:03d} ")
                time.sleep(0.005)

        feeder = threading.Thread(target=feed)
        feeder.start()
        try:
            # let traffic establish, then rip the partition out from
            # under it mid-stream — the synchronous RPC returns after
            # the flip, while the feeder keeps submitting through it
            if not wait_for(lambda: len(sstr.get_text()) >= 60):
                raise AssertionError("migration gate: no traffic before "
                                     "the trigger")
            t = _Transport("127.0.0.1", src_port, timeout=30.0)
            try:
                mig = t.request({"t": "admin_migrate_doc",
                                 "tenant": "smoke", "doc": "migdoc",
                                 "target": target})
                assert mig["target"] == target, mig
                place = t.request({"t": "admin_placement"})["placement"]
            finally:
                t.close()
        finally:
            feeder.join()
        # zero lost acks: every edit submitted across the flip must land
        # exactly once (pending-op replay through the takeover owner)
        if not wait_for(lambda: writer.connected
                        and writer.runtime.pending.count == 0,
                        timeout=60.0):
            raise AssertionError(
                f"migration gate: {writer.runtime.pending.count} op(s) "
                "still pending after the flip (acks lost)")
        reader = Loader(NetworkDocumentServiceFactory(
            "127.0.0.1", gw_port)).resolve("smoke", "migdoc")
        if not wait_for(
                lambda: "text" in reader.runtime.get_data_store(
                    "default").channels
                and len(reader.runtime.get_data_store("default")
                        .get_channel("text").get_text())
                == len(sstr.get_text())):
            raise AssertionError(
                "migration gate: reader never converged on the writer's "
                "text after the flip")
        text = reader.runtime.get_data_store(
            "default").get_channel("text").get_text()
        lost = [i for i in range(n_ops) if text.count(f"m{i:03d} ") != 1]
        if lost:
            raise AssertionError(
                f"migration gate: {len(lost)} edit(s) lost or duplicated "
                f"across the flip (first: {lost[:5]})")
        counters = place["counters"]

        # journal gate: both cores run with --shard-dir, so their audit
        # journals armed automatically; the fleet merge must contain the
        # forced move's causal chain, crossing source AND target
        from fluidframework_tpu.obs.journal import (
            causal_chain,
            merge_entries,
        )

        per_core = []
        for p in core_ports:
            t = _Transport("127.0.0.1", p, timeout=10.0)
            try:
                j = t.request({"t": "admin_journal", "n": 1000})["journal"]
                if not j.get("armed"):
                    raise AssertionError(
                        f"journal gate: core on :{p} reports a disarmed "
                        "journal despite --shard-dir")
                per_core.append(j["entries"])
            finally:
                t.close()
        merged = merge_entries(per_core)
        commits = [e for e in merged if e["kind"] == "migration.commit"]
        if not commits:
            raise AssertionError(
                "journal gate: no migration.commit entry in the fleet "
                "journal after the forced move")
        chain = causal_chain(merged, commits[-1]["id"])
        kinds = [e["kind"] for e in chain]
        for want in ("operator.command", "migration.seal",
                     "migration.fence", "migration.checkpoint",
                     "migration.adopt", "migration.commit"):
            if want not in kinds:
                raise AssertionError(
                    f"journal gate: {want} missing from the causal "
                    f"chain (got {kinds})")
        if len({e["core"] for e in chain}) < 2:
            raise AssertionError(
                "journal gate: the chain never crossed cores — the "
                "adopt RPC dropped the journal_cause link "
                f"(chain cores: {sorted({e['core'] for e in chain})})")

        # bundle gate: capture the fleet's debug surface and triage it
        # with the doctor — the forced move must be visible
        import subprocess

        from tools.doctor import diagnose

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        bundle_dir = os.path.join(shard_dir, "bundle")
        out = subprocess.run(
            [sys.executable, "-m", "fluidframework_tpu.admin",
             "--port", str(src_port), "bundle", "--out", bundle_dir],
            capture_output=True, text=True, cwd=repo, timeout=60,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        if out.returncode != 0:
            raise AssertionError(
                f"bundle gate: admin bundle failed:\n{out.stderr}")
        rep = diagnose(bundle_dir)
        if not rep["migrations"]:
            raise AssertionError(
                "bundle gate: tools/doctor.py found no migrations in "
                "the captured bundle")

        return {
            "placement.migration.committed": counters.get(
                "placement.migration.committed", 0),
            "placement.epoch.bumps": counters.get(
                "placement.epoch.bumps", 0),
            "obs.journal.chain_links": len(chain),
            "doctor.bundle_migrations": len(rep["migrations"]),
        }
    finally:
        for cont in (writer, reader):
            if cont is not None:
                try:
                    cont.close()
                except Exception:  # noqa: BLE001
                    pass
        if gw is not None:
            gw.terminate()
        for c in cores:
            c.terminate()
        for c in cores:
            try:
                c.wait(timeout=10)
            except Exception:  # noqa: BLE001
                c.kill()


def relay_gate() -> dict:
    """2-level relay tree, in process: core ← gw1 ← gw2 with read-only
    binary subscribers on the leaf. Counter-asserts the tree's perf
    contract: stamped frames SPLICE down every level
    (``fanout.relay.splices`` nonzero at both), presence coalesces at
    the core (``presence.lane.coalesced``), readers boot without quorum
    membership (``session.readonly.connects``), and nothing re-encodes
    above the first gateway level (``fanout.relay.encodes`` == 0)."""
    import threading

    from fluidframework_tpu.driver import NetworkDocumentServiceFactory
    from fluidframework_tpu.loader import Loader
    from fluidframework_tpu.service import LocalServer, NetworkFrontEnd
    from fluidframework_tpu.service.gateway import Gateway

    front = NetworkFrontEnd(LocalServer()).start_background()
    containers = []
    try:
        gw1 = Gateway("127.0.0.1", front.port)
        threading.Thread(target=gw1.serve_forever, daemon=True).start()
        assert wait_for(lambda: gw1.port != 0), "relay gate: gw1 bind"
        # the leaf's "core" IS gw1 — the --upstream-gateway topology
        gw2 = Gateway("127.0.0.1", gw1.port)
        threading.Thread(target=gw2.serve_forever, daemon=True).start()
        assert wait_for(lambda: gw2.port != 0), "relay gate: gw2 bind"

        writer = Loader(NetworkDocumentServiceFactory(
            "127.0.0.1", front.port)).resolve("smoke", "relaydoc")
        containers.append(writer)
        sstr = writer.runtime.create_data_store(
            "default").create_channel("text", "shared-string")
        sstr.insert_text(0, "seed ")
        readers = []
        for _ in range(3):
            r = Loader(NetworkDocumentServiceFactory(
                "127.0.0.1", gw2.port, readonly=True)).resolve(
                "smoke", "relaydoc")
            containers.append(r)
            readers.append(r)

        def rtext(r):
            return (r.runtime.get_data_store("default")
                    .get_channel("text").get_text())

        for i in range(20):
            sstr.insert_text(len(sstr.get_text()), f"w{i:02d} ")
        want = sstr.get_text()
        if not wait_for(lambda: all(rtext(r) == want for r in readers)):
            raise AssertionError(
                "relay gate: read-only leaf subscribers never converged "
                f"({[len(rtext(r)) for r in readers]} vs {len(want)})")

        # a cursor burst: coalesces ONCE at the core, splices down both
        # levels, and the last write lands at the leaf
        got = []
        readers[0].on_signal = got.append
        for i in range(40):
            writer.submit_signal({"i": i}, type="cursor")
        if not wait_for(lambda: any(s.content == {"i": 39} for s in got)):
            raise AssertionError(
                "relay gate: presence burst never reached the leaf "
                f"({len(got)} signal(s) arrived)")

        fsnap = front.counters.snapshot()
        g1 = gw1.counters.snapshot()
        g2 = gw2.counters.snapshot()
        for level, snap in (("gw1", g1), ("gw2", g2)):
            if snap.get("fanout.relay.encodes", 0):
                raise AssertionError(
                    f"relay gate: {level} re-encoded "
                    f"{snap['fanout.relay.encodes']} frame(s) — the "
                    "splice cache disengaged above the first level")
        return {
            # both levels must splice; min()==0 trips the dead check
            "fanout.relay.splices": min(
                g1.get("fanout.relay.splices", 0),
                g2.get("fanout.relay.splices", 0)),
            "fanout.upstream.frames": g2.get("fanout.upstream.frames", 0),
            "presence.lane.coalesced": fsnap.get(
                "presence.lane.coalesced", 0),
            "session.readonly.connects": fsnap.get(
                "session.readonly.connects", 0),
        }
    finally:
        for c in containers:
            try:
                c.close()
            except Exception:  # noqa: BLE001
                pass
        front.stop()


def history_gate() -> dict:
    """Doc history plane over sockets, in process: fork a live doc at
    its newest commit, time-travel a read into the pre-fork state,
    integrate a fork edit back through the parent's total order. Every
    leg goes through the front end's history doors; the service-tier
    counters must account for the boot, the historical read and the
    integrated op — a refactor that silently reroutes any of them onto
    the whole-log path fails here, not in the next bench run."""
    from fluidframework_tpu.driver import NetworkDocumentServiceFactory
    from fluidframework_tpu.loader import Loader
    from fluidframework_tpu.obs import tier_snapshot
    from fluidframework_tpu.service import LocalServer, NetworkFrontEnd

    front = NetworkFrontEnd(LocalServer()).start_background()
    containers = []
    try:
        factory = NetworkDocumentServiceFactory("127.0.0.1", front.port)
        loader = Loader(factory)
        writer = loader.resolve("smoke", "histdoc")
        containers.append(writer)
        sstr = writer.runtime.create_data_store(
            "default").create_channel("text", "shared-string")
        for i in range(30):
            sstr.insert_text(0, f"h{i:02d} ")
        if not wait_for(lambda: writer.runtime.pending.count == 0):
            raise AssertionError("history gate: writer never quiesced")
        svc = factory.create_document_service("smoke", "histdoc")
        svc._rpc_transport().request(
            {"t": "admin_summarize", "tenant": "smoke", "doc": "histdoc"})
        mid_text = sstr.get_text()
        mid_seq = svc._rpc_transport().request(
            {"t": "admin_status", "tenant": "smoke",
             "doc": "histdoc"})["status"]["seq"]
        for i in range(8):
            sstr.insert_text(0, f"t{i} ")
        if not wait_for(lambda: writer.runtime.pending.count == 0):
            raise AssertionError("history gate: tail edits never acked")
        tail_text = sstr.get_text()

        before = tier_snapshot("service")

        # time-travel: a read-only container at the pre-tail commit must
        # reproduce the doc exactly as it stood at that seq
        hist = loader.resolve_at("smoke", "histdoc", mid_seq)
        containers.append(hist)
        got = (hist.runtime.get_data_store("default")
               .get_channel("text").get_text())
        if got != mid_text:
            raise AssertionError(
                f"history gate: time-travel read at seq {mid_seq} drifted "
                f"({len(got)} chars vs {len(mid_text)})")

        # near-free fork: boots from the parent's chunks, converges on
        # the parent's full tail, then diverges with one edit
        res = svc.history().fork(new_doc="histfork")
        if res.get("shared_chunks", 0) <= 0:
            raise AssertionError(
                "history gate: fork shared no chunks with its parent "
                f"({res})")
        fork = loader.resolve("smoke", "histfork")
        containers.append(fork)
        fstr = fork.runtime.get_data_store("default").get_channel("text")
        if not wait_for(lambda: fstr.get_text() == tail_text):
            raise AssertionError(
                "history gate: fork never converged on the parent's "
                f"tail ({len(fstr.get_text())} vs {len(tail_text)})")
        fstr.insert_text(0, "FORK ")
        if not wait_for(lambda: fstr.get_text().startswith("FORK ")):
            raise AssertionError("history gate: fork edit never acked")

        # integrate: the fork's post-base tail replays through the
        # parent's ordinary total order (CRDT merge, no special path)
        out = factory.create_document_service(
            "smoke", "histfork").history().integrate()
        if out.get("ops") != 1:
            raise AssertionError(
                f"history gate: integrate replayed {out.get('ops')} "
                "op(s), wanted exactly 1")
        if not wait_for(lambda: sstr.get_text().startswith("FORK ")):
            raise AssertionError(
                "history gate: integrated edit never reached the parent")

        after = tier_snapshot("service")

        def _delta(name):
            return after.get(name, 0) - before.get(name, 0)

        return {
            "history.fork.boots": _delta("history.fork.boots"),
            "history.replay.reads": _delta("history.replay.reads"),
            "history.integrate.ops": _delta("history.integrate.ops"),
        }
    finally:
        for c in containers:
            try:
                c.close()
            except Exception:  # noqa: BLE001
                pass
        front.stop()


def coldstart_gate() -> dict:
    """Fleet cold start, in process: a 2-core topology built from ONE
    TopologySpec, killed outright (no checkpoint beyond the last
    ticker-equivalent pass) and restarted from the same spec object
    under live traffic — reconnecting writers ARE the boot storm.
    Counter-asserts the rehydration contract: every summarized doc in
    the restarted generation boots lazily from its snapshot + durable
    tail (``boot.part.lazy`` rises, ``boot.part.full_replay`` stays 0)
    and the topology counters account for the restart."""
    import shutil
    import tempfile

    from fluidframework_tpu.driver.network import (
        NetworkDocumentServiceFactory,
        _Transport,
    )
    from fluidframework_tpu.loader.container import Loader
    from fluidframework_tpu.service.placement_plane import EpochTable
    from fluidframework_tpu.service.rehydrate import boot_counters
    from fluidframework_tpu.service.stage_runner import doc_partition
    from fluidframework_tpu.service.topology import Fleet, default_spec

    n_docs, n_parts = 3, 4
    work = tempfile.mkdtemp(prefix="net-smoke-cold-")
    fl = None
    containers = []
    try:
        spec = default_spec(os.path.join(work, "fleet"), n_cores=2,
                            n_partitions=n_parts, lease_ttl=0.75,
                            summarize_every=10 ** 6)
        fl = Fleet(spec).start()
        fl.wait_claimed()

        def port_for(doc: str) -> int:
            k = doc_partition("smoke", doc, n_parts)
            rec = EpochTable.for_shard_dir(
                spec.shard_dir).read()["parts"][str(k)]
            return int(rec["addr"].rsplit(":", 1)[1])

        def dial(doc: str):
            c = Loader(NetworkDocumentServiceFactory(
                "127.0.0.1", port_for(doc))).resolve("smoke", doc)
            containers.append(c)
            return c

        docs = [f"cold{i}" for i in range(n_docs)]
        texts = {}
        for doc in docs:
            c = dial(doc)
            sstr = c.runtime.create_data_store(
                "default").create_channel("text", "shared-string")
            for i in range(40):
                sstr.insert_text(0, f"{doc}.{i} ")
            if not wait_for(lambda: c.runtime.pending.count == 0):
                raise AssertionError(
                    f"coldstart gate: {doc} never quiesced pre-kill")
            texts[doc] = sstr.get_text()
        for doc in docs:
            t = _Transport("127.0.0.1", port_for(doc))
            try:
                t.request_rid({"t": "admin_summarize", "tenant": "smoke",
                               "doc": doc})
            finally:
                t.close()
        fl.checkpoint_all()

        before = boot_counters().snapshot()
        fl.restart()
        fl.wait_claimed()

        # reconnect UNDER the storm: each resolve is a first route that
        # lazily boots its doc, and fresh edits ride straight in
        for doc in docs:
            c = dial(doc)
            sstr = c.runtime.get_data_store("default").get_channel("text")
            sstr.insert_text(0, "post ")
            if not wait_for(lambda: c.runtime.pending.count == 0
                            and sstr.get_text() == "post " + texts[doc]):
                raise AssertionError(
                    f"coldstart gate: {doc} did not converge on its "
                    f"pre-kill text after the restart "
                    f"({len(sstr.get_text())} vs {len(texts[doc]) + 5})")

        after = boot_counters().snapshot()

        def _delta(name):
            return after.get(name, 0) - before.get(name, 0)

        if _delta("boot.part.full_replay"):
            raise AssertionError(
                "coldstart gate: a summarized + checkpointed doc "
                f"whole-log replayed ({_delta('boot.part.full_replay')} "
                "full replays in the restarted generation)")
        if _delta("boot.part.lazy") < n_docs:
            raise AssertionError(
                f"coldstart gate: expected >= {n_docs} lazy boots after "
                f"the restart, saw {_delta('boot.part.lazy')}")
        return {
            "boot.part.lazy": _delta("boot.part.lazy"),
            "topology.fleet.restarts": _delta("topology.fleet.restarts"),
            "topology.core.spawns": _delta("topology.core.spawns"),
        }
    finally:
        for c in containers:
            try:
                c.close()
            except Exception:  # noqa: BLE001
                pass
        if fl is not None:
            fl.stop()
        shutil.rmtree(work, ignore_errors=True)


def multihost_gate() -> dict:
    """Two host groups under one spec: a subprocess fleet from
    ``multihost_spec`` (h0 = placement host with the storage tier and
    table door, h1 in a DISJOINT dir on ``RemoteTableClient``), a
    driver client writing through a gateway while a FORCED CROSS-HOST
    migration rips the doc's partition onto the other host — the
    sealed log ships through storage (``migration.ship`` in the fleet
    journal), every ack lands exactly once, the remote core's
    ``placement.table.rpc_reads`` prove the door carried its placement
    plane, and an ``admin bundle`` of the fleet triages clean through
    tools/doctor.py with the migration visible."""
    import shutil
    import tempfile
    import threading

    from fluidframework_tpu.driver.network import (
        NetworkDocumentServiceFactory,
        _Transport,
    )
    from fluidframework_tpu.loader.container import Loader
    from fluidframework_tpu.service.stage_runner import doc_partition
    from fluidframework_tpu.service.topology import Fleet, multihost_spec

    work = tempfile.mkdtemp(prefix="net-smoke-mh-")
    fl = None
    writer = reader = None
    try:
        spec = multihost_spec(os.path.join(work, "fleet"), n_hosts=2,
                              cores_per_host=1, n_partitions=2,
                              lease_ttl=1.5)
        fl = Fleet(spec, subprocess=True).start()
        fl.wait_claimed()

        k = doc_partition("smoke", "mhdoc", 2)
        # partitions are pinned round-robin: core k (host h{k}) owns the
        # doc; the migration target is the OTHER host's core — forcing
        # the cross-host path (log shipped through storage, not copied
        # through any shared file)
        src_port = fl.core_ports[k]
        dst_core = 1 - k
        target = f"127.0.0.1:{fl.core_ports[dst_core]}"
        gw_host, gw_port = fl.gateway_addr(0)

        writer = Loader(NetworkDocumentServiceFactory(
            gw_host, gw_port), auto_reconnect=True).resolve(
            "smoke", "mhdoc")
        sstr = writer.runtime.create_data_store(
            "default").create_channel("text", "shared-string")

        n_ops = 120

        def feed():
            for i in range(n_ops):
                sstr.insert_text(0, f"h{i:03d} ")
                time.sleep(0.005)

        feeder = threading.Thread(target=feed)
        feeder.start()
        try:
            if not wait_for(lambda: len(sstr.get_text()) >= 60):
                raise AssertionError("multihost gate: no traffic "
                                     "before the trigger")
            t = _Transport("127.0.0.1", src_port, timeout=30.0)
            try:
                mig = t.request({"t": "admin_migrate_doc",
                                 "tenant": "smoke", "doc": "mhdoc",
                                 "target": target})
                assert mig["target"] == target, mig
            finally:
                t.close()
        finally:
            feeder.join()
        if not wait_for(lambda: writer.connected
                        and writer.runtime.pending.count == 0,
                        timeout=60.0):
            raise AssertionError(
                f"multihost gate: {writer.runtime.pending.count} op(s) "
                "still pending after the cross-host flip (acks lost)")
        reader = Loader(NetworkDocumentServiceFactory(
            gw_host, gw_port)).resolve("smoke", "mhdoc")
        if not wait_for(
                lambda: "text" in reader.runtime.get_data_store(
                    "default").channels
                and len(reader.runtime.get_data_store("default")
                        .get_channel("text").get_text())
                == len(sstr.get_text())):
            raise AssertionError(
                "multihost gate: reader never converged after the "
                "cross-host flip")
        text = reader.runtime.get_data_store(
            "default").get_channel("text").get_text()
        lost = [i for i in range(n_ops) if text.count(f"h{i:03d} ") != 1]
        if lost:
            raise AssertionError(
                f"multihost gate: {len(lost)} edit(s) lost or "
                f"duplicated across the flip (first: {lost[:5]})")

        # the remote core's placement plane ran over the wire: its
        # admin_placement counters must show door round trips
        remote_core = 1  # core1 is h1's — the non-placement group
        t = _Transport("127.0.0.1", fl.core_ports[remote_core],
                       timeout=10.0)
        try:
            place = t.request({"t": "admin_placement"})["placement"]
        finally:
            t.close()
        rc = place["counters"]
        if not rc.get("placement.table.rpc_reads"):
            raise AssertionError(
                "multihost gate: the remote core counted zero "
                "placement.table.rpc_reads — its placement plane did "
                f"not run through the door ({rc})")

        # the fleet journal must witness the cross-host log ship
        from fluidframework_tpu.obs.journal import merge_entries

        per_core = []
        for p in fl.core_ports.values():
            t = _Transport("127.0.0.1", p, timeout=10.0)
            try:
                j = t.request({"t": "admin_journal",
                               "n": 1000})["journal"]
                per_core.append(j["entries"])
            finally:
                t.close()
        merged = merge_entries(per_core)
        ships = [e for e in merged if e["kind"] == "migration.ship"]
        if not ships:
            raise AssertionError(
                "multihost gate: no migration.ship journal entry — "
                "the cross-host move never shipped the sealed log "
                "through storage")

        # bundle + doctor triage: the debug surface must capture the
        # 2-host fleet and the doctor must see the migration
        import subprocess

        from tools.doctor import diagnose

        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        bundle_dir = os.path.join(work, "bundle")
        out = subprocess.run(
            [sys.executable, "-m", "fluidframework_tpu.admin",
             "--port", str(src_port), "bundle", "--out", bundle_dir],
            capture_output=True, text=True, cwd=repo, timeout=60,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        if out.returncode != 0:
            raise AssertionError(
                f"multihost gate: admin bundle failed:\n{out.stderr}")
        rep = diagnose(bundle_dir)
        if not rep["migrations"]:
            raise AssertionError(
                "multihost gate: tools/doctor.py found no migrations "
                "in the captured bundle")
        bad = [a for a in rep.get("anomalies", [])
               if "unreachable host group" in a
               or "epoch regressed" in a]
        if bad:
            raise AssertionError(
                f"multihost gate: doctor flagged a healthy fleet: {bad}")

        return {
            "placement.table.rpc_reads": rc.get(
                "placement.table.rpc_reads", 0),
            "placement.table.rpc_writes": rc.get(
                "placement.table.rpc_writes", 0),
            "obs.journal.migration_ships": len(ships),
            "doctor.multihost_migrations": len(rep["migrations"]),
        }
    finally:
        for cont in (writer, reader):
            if cont is not None:
                try:
                    cont.close()
                except Exception:  # noqa: BLE001
                    pass
        if fl is not None:
            fl.stop()
        shutil.rmtree(work, ignore_errors=True)


def health_gate() -> dict:
    """The live health plane under a real outage: a 2-host subprocess
    fleet with canary probing armed, one host group killed -9 mid-probe.
    The survivor's HealthEngine must reach ``critical`` with a reason
    NAMING the dead peer (the canary route door saw it first), the
    fleet ``admin_health`` verdict must aggregate to critical (the
    unreachable core fails closed), an ``admin bundle`` captured during
    the outage must make tools/doctor.py agree with the live verdict —
    and after the host respawns, ``Fleet.wait_healthy`` (the
    rolling-upgrade go/no-go gate) must reopen, with a fresh bundle
    triaging quiet on the outage rules."""
    import shutil
    import subprocess
    import tempfile

    from fluidframework_tpu.service.placement_plane import admin_rpc
    from fluidframework_tpu.service.topology import Fleet, multihost_spec
    from tools.doctor import diagnose

    work = tempfile.mkdtemp(prefix="net-smoke-health-")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    fl = None
    try:
        # lease_ttl is deliberately LONG: the dead core must still be
        # in the placement membership when the bundle captures it, so
        # the doctor sees the same outage the live engine does
        spec = multihost_spec(
            os.path.join(work, "fleet"), n_hosts=2, cores_per_host=1,
            n_partitions=2, gateway_per_host=False, lease_ttl=8.0,
            health={"probe_tick_s": 0.25, "tick_s": 0.25,
                    "critical_ticks": 2, "probe_fail_critical": 2,
                    "probe_timeout": 2.0})
        fl = Fleet(spec, subprocess=True).start()
        fl.wait_claimed()
        verdicts = fl.wait_healthy(timeout=60.0)
        if sorted(verdicts) != ["core0", "core1"]:
            raise AssertionError(
                f"health gate: wait_healthy returned {sorted(verdicts)}")
        doors_ok = sum(
            1 for h in verdicts.values()
            for d in (h["probes"]["doors"] or {}).values()
            if d.get("ok") and d.get("probes"))

        def live_health(fleet=False):
            frame = {"t": "admin_health"}
            if fleet:
                frame["fleet"] = 1
            return admin_rpc(*fl.core_addr(0), frame,
                             timeout=10.0)["health"]

        dead_addr = f"127.0.0.1:{fl.core_ports[1]}"
        fl.kill_host("h1")

        # the survivor's canary route door fails consecutively → the
        # hard probe signal flips the engine critical within ~1s
        if not wait_for(lambda: live_health()["verdict"] == "critical",
                        timeout=30.0):
            raise AssertionError(
                "health gate: survivor engine never reached critical "
                f"after the host kill (verdict: "
                f"{live_health()['verdict']})")
        h = live_health()
        reasons = [r for c in h["components"].values()
                   for r in c["reasons"]]
        named = [r for r in reasons if dead_addr in r]
        if not named:
            raise AssertionError(
                "health gate: no critical reason names the dead peer "
                f"{dead_addr} (got {reasons})")
        fleet_h = live_health(fleet=True)
        if fleet_h["verdict"] != "critical":
            raise AssertionError(
                "health gate: fleet verdict did not fail closed on the "
                f"unreachable core (got {fleet_h['verdict']})")

        # bundle → doctor must AGREE with the live verdict: the dead
        # host group is an anomaly offline too
        bundle_out = os.path.join(work, "bundle-outage")
        out = subprocess.run(
            [sys.executable, "-m", "fluidframework_tpu.admin",
             "--port", str(fl.core_ports[0]), "bundle",
             "--out", bundle_out],
            capture_output=True, text=True, cwd=repo, timeout=60,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        if out.returncode != 0:
            raise AssertionError(
                f"health gate: admin bundle failed:\n{out.stderr}")
        rep = diagnose(bundle_out)
        outage = [a for a in rep["anomalies"]
                  if "capture error" in a or "host group h1" in a]
        if not outage:
            raise AssertionError(
                "health gate: live verdict is critical but the doctor "
                "found no outage in the bundle — the offline and live "
                f"rules disagree (anomalies: {rep['anomalies']})")

        # respawn: the go/no-go gate must reopen on the SAME primitive
        # the rolling-upgrade loop will use
        fl.start_host("h1")
        recovered = fl.wait_healthy(timeout=60.0)
        if any(h["verdict"] != "ok" for h in recovered.values()):
            raise AssertionError(
                "health gate: fleet never recovered after the respawn "
                f"({ {k: v['verdict'] for k, v in recovered.items()} })")
        bundle_rec = os.path.join(work, "bundle-recovered")
        out = subprocess.run(
            [sys.executable, "-m", "fluidframework_tpu.admin",
             "--port", str(fl.core_ports[0]), "bundle",
             "--out", bundle_rec],
            capture_output=True, text=True, cwd=repo, timeout=60,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        if out.returncode != 0:
            raise AssertionError(
                f"health gate: post-recovery bundle failed:\n{out.stderr}")
        rep2 = diagnose(bundle_rec)
        stale = [a for a in rep2["anomalies"]
                 if "capture error" in a or "host group" in a]
        if stale:
            raise AssertionError(
                "health gate: doctor still flags the outage after "
                f"recovery (live verdict is ok): {stale}")
        return {
            "health.gate.doors_probed_ok": doors_ok,
            "health.gate.critical_reasons": len(named),
            "health.gate.doctor_outage_anomalies": len(outage),
            "health.gate.recovered_cores": len(recovered),
        }
    finally:
        if fl is not None:
            fl.stop()
        shutil.rmtree(work, ignore_errors=True)


GATES = {
    "migration": migration_gate,
    "relay": relay_gate,
    "history": history_gate,
    "coldstart": coldstart_gate,
    "multihost": multihost_gate,
    "health": health_gate,
}


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="socket-tier smoke: batching burst + process gates")
    ap.add_argument("--only", action="append", choices=sorted(GATES),
                    metavar="GATE",
                    help="run ONLY the named gate(s) (repeatable; "
                         f"one of: {', '.join(sorted(GATES))}) — skips "
                         "the in-proc batching burst")
    args = ap.parse_args(argv)
    if args.only:
        checks: dict = {}
        for name in args.only:
            try:
                checks.update(GATES[name]())
            except AssertionError as e:
                print(f"net_smoke: FAIL — {e}", file=sys.stderr)
                return 1
        print(json.dumps({"checks": checks}, indent=2))
        dead = sorted(k for k, v in checks.items() if v == 0)
        if dead:
            print(f"net_smoke: FAIL — counters stayed at zero under "
                  f"load: {dead}", file=sys.stderr)
            return 1
        print("net_smoke: ok")
        return 0
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from fluidframework_tpu.driver.network import (
        NetworkDocumentServiceFactory,
    )
    from fluidframework_tpu.obs import parse_prometheus
    from fluidframework_tpu.protocol.messages import (
        DocumentMessage,
        MessageType,
    )
    from fluidframework_tpu.protocol.serialization import message_to_dict
    from fluidframework_tpu.service.durable_log import DurableLog
    from fluidframework_tpu.service.front_end import NetworkFrontEnd
    from fluidframework_tpu.service.local_server import LocalServer

    def op(cseq: int, i: int) -> DocumentMessage:
        return DocumentMessage(
            client_sequence_number=cseq, reference_sequence_number=0,
            type=MessageType.OPERATION, contents={"i": i})

    def chan_op(cseq: int, i: int) -> DocumentMessage:
        # canonical chanop envelope — eligible for the columnar fast path
        return DocumentMessage(
            client_sequence_number=cseq, reference_sequence_number=0,
            type=MessageType.OPERATION,
            contents={"kind": "chanop", "address": "default",
                      "contents": {"address": "text",
                                   "contents": {"type": 0, "pos": 0,
                                                "text": f"c{i}"}}})

    tmp = tempfile.mkdtemp(prefix="net-smoke-")
    log = DurableLog(os.path.join(tmp, "log"))
    front = NetworkFrontEnd(LocalServer(log=log)).start_background()
    factory = NetworkDocumentServiceFactory("127.0.0.1", front.port)
    conn1 = factory.create_document_service(
        "smoke", "doc").connect_to_delta_stream()
    # force the window on (the adaptive tuner would keep an idle client
    # inline): the smoke asserts the MECHANISM, not the tuner
    conn1.coalesce_window = 0.002
    # arm tracing on every boxcar: the scrape gate below requires each
    # hop leg of the in-proc topology to have counted at least once
    conn1.trace_sample_n = 1
    conn2 = factory.create_document_service(
        "smoke", "doc").connect_to_delta_stream()
    seen1: list = []
    seen2: list = []
    conn1.on_op = seen1.append
    conn2.on_op = seen2.append

    for i in range(N_OPS):
        conn1.submit([op(i + 1, i)])

    def delivered(seen, cid, want):
        return sum(1 for m in seen if m.client_id == cid) >= want

    if not wait_for(lambda: delivered(seen1, conn1.client_id, N_OPS)
                    and delivered(seen2, conn1.client_id, N_OPS)):
        print("net_smoke: FAIL — coalesced burst did not converge "
              f"({len(seen1)}/{len(seen2)} of {N_OPS})", file=sys.stderr)
        return 1

    # columnar burst: canonical chanop boxcars must ride the array lane
    # (driver encodes columns once, server admits without per-op decode)
    for i in range(N_COLS):
        conn1.submit([chan_op(N_OPS + i + 1, i)])
    want = N_OPS + N_COLS
    if not wait_for(lambda: delivered(seen1, conn1.client_id, want)
                    and delivered(seen2, conn1.client_id, want)):
        print("net_smoke: FAIL — columnar burst did not converge "
              f"({len(seen1)}/{len(seen2)} of {want})", file=sys.stderr)
        return 1

    # raw socket: many frames in ONE TCP wave — the drain-batched read
    # loop must serve them as one batch
    s = socket.create_connection(("127.0.0.1", front.port), timeout=10)
    rbuf = b""

    def read_frame() -> dict:
        nonlocal rbuf
        while True:
            if len(rbuf) >= 4:
                n = int.from_bytes(rbuf[:4], "big")
                if len(rbuf) >= 4 + n:
                    body, rbuf = rbuf[4:4 + n], rbuf[4 + n:]
                    return json.loads(body.decode())
            chunk = s.recv(65536)
            if not chunk:
                raise ConnectionError("smoke socket closed")
            rbuf += chunk

    s.sendall(_frame({"t": "connect", "tenant": "smoke", "doc": "doc",
                      "rid": 1, "bin": 0}))
    reply = read_frame()
    while reply.get("rid") != 1:
        reply = read_frame()
    raw_cid = reply["clientId"]
    s.sendall(b"".join(
        _frame({"t": "submit", "ops": [message_to_dict(op(i + 1, i))]})
        for i in range(BURST_FRAMES)))
    if not wait_for(lambda: delivered(seen2, raw_cid, BURST_FRAMES)):
        print("net_smoke: FAIL — raw burst did not converge",
              file=sys.stderr)
        return 1
    # quiescent now: a lone read-only frame must ELIDE the flush
    s.sendall(_frame({"t": "ping"}))
    reply = read_frame()
    while reply.get("t") != "pong":
        reply = read_frame()

    # labeled metrics scrape: must come back as parseable Prometheus
    # text, and every hop leg of the in-proc topology (no gateway, so
    # no relay) must have a non-zero observation count
    s.sendall(_frame({"t": "admin_metrics_scrape", "rid": 2}))
    reply = read_frame()
    while reply.get("rid") != 2:
        reply = read_frame()
    try:
        series = parse_prometheus(reply["scrape"])
    except ValueError as e:
        print(f"net_smoke: FAIL — scrape is not Prometheus text: {e}",
              file=sys.stderr)
        return 1
    hop_counts = {
        dict(k).get("pair"): v
        for k, v in series.get("fluid_obs_hop_ms_count", {}).items()}
    want_pairs = ("submit_to_admit", "admit_to_deli", "deli_to_fanout")
    dead_pairs = sorted(p for p in want_pairs
                        if hop_counts.get(p, 0) <= 0)

    # columnar backfill door: a catch-up client pulls the whole op range
    # through get_deltas_cols — the server must serve raw segment block
    # byte ranges (storage.backfill.byterange), and the stream itself
    # must have ridden the columnar segment lane (storage.segment.appends)
    bf_svc = factory.create_document_service("smoke", "doc")
    bf_stream = bf_svc.connect_to_delta_stream()
    bf_msgs = bf_svc.connect_to_delta_storage().get_deltas(0, 10 ** 9)
    bf_stream.close()
    if not bf_msgs:
        print("net_smoke: FAIL — columnar backfill returned no ops",
              file=sys.stderr)
        return 1

    # snapshot catch-up door: a summarized doc, then a burst of cold
    # joiners booting through the columnar snapshot plane — the server
    # must frame chunks exactly ONCE (encode-once), every joiner must
    # take the bounded backfill, and none may fall back to the tree shim
    from fluidframework_tpu.loader.container import Loader
    from fluidframework_tpu.service.service_summarizer import (
        HostReplicaSource,
        ServiceSummarizer,
    )

    writer = Loader(NetworkDocumentServiceFactory(
        "127.0.0.1", front.port, counters=factory.counters)).resolve(
        "smoke", "snapdoc")
    sstr = writer.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    for i in range(60):
        sstr.insert_text(0, f"w{i} ")
    if not wait_for(lambda: writer.runtime.pending.count == 0):
        print("net_smoke: FAIL — snapshot writer never quiesced",
              file=sys.stderr)
        return 1
    ServiceSummarizer(front.server,
                      HostReplicaSource(front.server)).summarize_doc(
        "smoke", "snapdoc")
    pre_srv = front.counters.snapshot()
    pre_drv = factory.counters.snapshot()
    joiners = []
    for _ in range(3):
        # cold factory per joiner (fresh snapshot/chunk cache), shared
        # driver counters so the deltas below cover the whole burst
        jf = NetworkDocumentServiceFactory("127.0.0.1", front.port,
                                           counters=factory.counters)
        joiners.append(Loader(jf).resolve("smoke", "snapdoc"))
    post_srv = front.counters.snapshot()
    post_drv = factory.counters.snapshot()

    def _delta(post, pre, name):
        return post.get(name, 0) - pre.get(name, 0)

    snap_encodes = _delta(post_srv, pre_srv, "storage.snapshot.encodes")
    if snap_encodes != 1:
        print(f"net_smoke: FAIL — snapshot serving framed chunks "
              f"{snap_encodes} times for a 3-joiner burst (encode-once "
              "requires exactly 1)", file=sys.stderr)
        return 1
    if _delta(post_drv, pre_drv, "boot.snapshot.fallback"):
        print("net_smoke: FAIL — a joiner fell back to the legacy tree "
              "shim during the snapshot catch-up burst", file=sys.stderr)
        return 1
    snap_checks = {
        "storage.snapshot.served": _delta(
            post_srv, pre_srv, "storage.snapshot.served"),
        "storage.snapshot.cache_hits": _delta(
            post_srv, pre_srv, "storage.snapshot.cache_hits"),
        "boot.snapshot.used": _delta(
            post_drv, pre_drv, "boot.snapshot.used"),
        "boot.backfill.bounded": _delta(
            post_drv, pre_drv, "boot.backfill.bounded"),
        "boot.chunks.fetched": _delta(
            post_drv, pre_drv, "boot.chunks.fetched"),
    }
    for j in joiners:
        j.close()
    writer.close()

    # mini-overload burst: arm the admission gate + a hair-trigger SLO
    # (p99 budget 0 on submit_to_admit, manual tick — no ticker race),
    # deplete the smoke tenant's bucket, and prove the loop closes:
    # sheds counted, SLO state in the scrape, and the driver's
    # transparent retries converging once shedding is disarmed
    from fluidframework_tpu.obs import get_registry
    from fluidframework_tpu.obs.slo import SloEngine, SloSpec
    from fluidframework_tpu.service.tenants import TenantManager

    tm = TenantManager()
    tm.set_rate("smoke", 25, burst=200)
    front.server.tenants = tm
    engine = SloEngine([SloSpec(
        name="smoke_admit", pair="submit_to_admit", p99_budget_ms=0.0,
        burn_ticks=1, min_count=1)])
    front.attach_slo(engine)
    base = N_OPS + N_COLS
    for i in range(2):  # fresh traced boxcars keep the window live
        conn1.submit([chan_op(base + i + 1, i)])
    base += 2
    if not wait_for(lambda: delivered(seen1, conn1.client_id, base)):
        print("net_smoke: FAIL — pre-overload ops did not converge",
              file=sys.stderr)
        return 1
    engine.evaluate()
    if not engine.shed_signal:
        print("net_smoke: FAIL — hair-trigger SLO never armed shedding",
              file=sys.stderr)
        return 1
    # one full-budget boxcar empties the bucket (burst tokens)...
    conn1.submit([chan_op(base + i + 1, i) for i in range(200)])
    base += 200
    if not wait_for(lambda: delivered(seen1, conn1.client_id, base)):
        print("net_smoke: FAIL — bucket-depleting boxcar did not "
              "converge", file=sys.stderr)
        return 1
    # ...so the next burst finds it depleted, the SLO violated, and
    # sheds through the nack door
    conn1.submit([chan_op(base + i + 1, i) for i in range(100)])
    base += 100
    reg = get_registry()

    def shed_count() -> float:
        series = parse_prometheus(reg.scrape())
        return sum(series.get("fluid_net_admission_shed", {}).values())

    if not wait_for(lambda: shed_count() > 0, timeout=10.0):
        print("net_smoke: FAIL — overload burst never shed "
              "(net.admission.shed stayed 0)", file=sys.stderr)
        return 1
    s.sendall(_frame({"t": "admin_metrics_scrape", "rid": 3}))
    reply = read_frame()
    while reply.get("rid") != 3:
        reply = read_frame()
    overload_series = parse_prometheus(reply["scrape"])
    if "fluid_obs_slo_state" not in overload_series:
        print("net_smoke: FAIL — obs.slo.state missing from the scrape",
              file=sys.stderr)
        return 1
    # disarm shedding: the held ops soft-admit on the driver's retry
    front.admission.shedding = False
    if not wait_for(lambda: delivered(seen1, conn1.client_id, base)):
        print("net_smoke: FAIL — shed retries never converged after "
              f"disarm ({len(seen1)} of {base})", file=sys.stderr)
        return 1

    drv = factory.counters.snapshot()
    srv = front.counters.snapshot()
    sto = log.counters.snapshot()
    checks = {
        "driver.submit.coalesced": drv.get("driver.submit.coalesced", 0),
        "driver.submit.columnar": drv.get("driver.submit.columnar", 0),
        "net.ingress.coalesced": srv.get("net.ingress.coalesced", 0),
        "net.ingress.columnar": srv.get("net.ingress.columnar", 0),
        "net.fanout.cache_hits": srv.get("net.fanout.cache_hits", 0),
        "net.flush.performed": srv.get("net.flush.performed", 0),
        "net.flush.elided": srv.get("net.flush.elided", 0),
        "storage.segment.appends": sto.get("storage.segment.appends", 0),
        "storage.backfill.byterange": sto.get(
            "storage.backfill.byterange", 0),
        "net.admission.shed": int(sum(
            overload_series.get("fluid_net_admission_shed", {}).values())),
        "driver.submit.shed_retries": drv.get(
            "driver.submit.shed_retries", 0),
        **snap_checks,
    }
    frames = drv.get("driver.submit.frames", 0)
    ops = drv.get("driver.submit.ops", 0)

    conn1.close()
    conn2.close()
    s.close()
    front.stop()

    # forced live migration under traffic (its own 2-core + gateway
    # process topology): zero lost acks, placement counters nonzero
    try:
        checks.update(migration_gate())
    except AssertionError as e:
        print(f"net_smoke: FAIL — {e}", file=sys.stderr)
        return 1

    # 2-level relay tree + read-only leaf subscribers (in-proc): splices
    # nonzero at every level, presence coalesced at the core, and ZERO
    # re-encodes above the first gateway level
    try:
        checks.update(relay_gate())
    except AssertionError as e:
        print(f"net_smoke: FAIL — {e}", file=sys.stderr)
        return 1

    # doc history plane over sockets: fork a live doc, time-travel a
    # read, integrate one fork edit back — all three counters nonzero
    try:
        checks.update(history_gate())
    except AssertionError as e:
        print(f"net_smoke: FAIL — {e}", file=sys.stderr)
        return 1

    # fleet cold start from one topology spec (in-proc 2-core fleet,
    # killed + restarted under live traffic): every summarized doc
    # boots lazily, zero whole-log replays
    try:
        checks.update(coldstart_gate())
    except AssertionError as e:
        print(f"net_smoke: FAIL — {e}", file=sys.stderr)
        return 1

    # two host groups under one spec (subprocess fleet, disjoint dirs):
    # a forced CROSS-HOST migration ships the log through storage, the
    # remote core's placement plane runs through the table door, and
    # the bundle triages clean through the doctor
    try:
        checks.update(multihost_gate())
    except AssertionError as e:
        print(f"net_smoke: FAIL — {e}", file=sys.stderr)
        return 1

    # the live health plane: canary probes, the streaming doctor's
    # critical verdict on a killed host group, the bundle→doctor
    # agreement, and the wait_healthy gate reopening on respawn
    try:
        checks.update(health_gate())
    except AssertionError as e:
        print(f"net_smoke: FAIL — {e}", file=sys.stderr)
        return 1

    print(json.dumps({"checks": checks,
                      "hop_counts": hop_counts,
                      "driver.submit.frames": frames,
                      "driver.submit.ops": ops}, indent=2))
    dead = sorted(k for k, v in checks.items() if v == 0)
    if dead:
        print(f"net_smoke: FAIL — counters stayed at zero under load: "
              f"{dead}", file=sys.stderr)
        return 1
    if dead_pairs:
        print(f"net_smoke: FAIL — hop pairs missing from the scrape: "
              f"{dead_pairs} (got {sorted(hop_counts)})", file=sys.stderr)
        return 1
    if frames >= ops:
        print(f"net_smoke: FAIL — coalescing never reduced frame count "
              f"(frames={frames}, ops={ops})", file=sys.stderr)
        return 1
    print("net_smoke: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
