"""Split the applier feed into its cost components (VERDICT r4 #5).

Measures, on the real device (run WITHOUT JAX_PLATFORMS=cpu):

  pack     — host-side wave assembly (_dispatch_wave's numpy work)
  h2d      — device_put of the packed wave, blocked to completion
  step     — the jitted dense step with the wave already on device
  e2e      — the production dispatch path end to end

and prints the implied bytes/op, link bandwidth, and the ceiling
``bandwidth / bytes_per_op`` that bounds the service-path ops/s on this
rig. Usage:  python tools/profile_applier.py [--docs D] [--k K]

On the r4→r5 ``kernel_ops_per_sec`` drop (1.203M → 1.059M, VERDICT r5
#3): no kernel source changed between the two artifacts (``git log``
shows nothing under ``ops/`` between them), so the −12% is not a code
regression. The evidence points at run environment, not compute: BOTH
lanes fell in the same r5 run (Pallas −12%, the independent XLA scan
−4%), and the r5 bench prepended a heavier network phase before the
kernel timing (the new sharded 2-core row plus cfg4 retries — bench.py
runs network first, so the kernel bench inherits a host still draining
10k-socket teardown) under the new gc-frozen trial posture. The shared
component is device-dispatch weather on the axon tunnel; the
Pallas-specific excess is dispatch-cost sensitivity (its per-step win
over the scan is small, so tunnel jitter moves it more). The honest
bound for regressions is this profile's ``step`` row (device compute
with the wave resident), not the e2e artifact number.

SUPERSEDED for attribution (overlap-staged dispatch): the r4→r5 note
above had to reconstruct the host/device split after the fact because
the production path timed only whole dispatches. The applier now
accounts its own halves per lane — ``applier.stage.seconds`` (host wave
assembly + transfer, with the hidden-behind-execute fraction) vs
``applier.exec.seconds`` (the step dispatch) — so a throughput swing in
a bench artifact is attributable directly from its counters: a stage
swing is host/link weather, an exec swing is device weather or a kernel
change. This profile prints that split below (``stage/execute split``)
for the dense lane and, when the rig has multiple devices, the mesh
lane; the manual pack/h2d/step rows remain the finer microscope.
"""

from __future__ import annotations

import argparse
import sys
import time

sys.path.insert(0, "/root/repo")

import numpy as np  # noqa: E402


def _reset_split(applier) -> None:
    applier.stage_seconds = applier.stage_overlap_seconds = 0.0
    applier.exec_seconds = 0.0
    applier.stage_bytes = 0
    applier.waves_staged = 0


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--docs", type=int, default=1024)
    ap.add_argument("--k", type=int, default=16)
    ap.add_argument("--trials", type=int, default=20)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from fluidframework_tpu.ops.apply import OP_FIELDS, OP_INSERT, make_op
    from fluidframework_tpu.service.tpu_applier import TpuDocumentApplier

    D, K, T = args.docs, args.k, args.trials
    dev = jax.devices()[0]
    print(f"device: {dev.platform} {getattr(dev, 'device_kind', '?')}")

    app = TpuDocumentApplier(max_docs=D, ops_per_dispatch=K,
                             async_dispatch=False)
    # register docs + seed a little text so applies do real work
    for d in range(D):
        app.slot_of("t", f"doc{d}")

    # ---- a full synthetic wave: every doc x K insert rows ----
    def stage_full_wave(seq0: int) -> None:
        for d in range(D):
            rows = np.zeros((K, OP_FIELDS), np.int32)
            for i in range(K):
                rows[i] = make_op(OP_INSERT, pos=0, seq=seq0 + i,
                                  ref_seq=seq0 + i - 1, client=0,
                                  text_len=1, text_start=seq0 + i,
                                  msn=seq0 + i - 1)
            app._push_chunk(d, rows)

    # warm: compile both lanes, then zero the split counters so the
    # stage/execute rows below report steady-state waves, not the
    # compile wave
    stage_full_wave(2)
    app._flush_sync()
    app._sync(0)
    _reset_split(app)

    n_ops = D * K

    # ---- e2e: the production dispatch path ----
    t0 = time.perf_counter()
    for t in range(T):
        stage_full_wave(2 + (t + 1) * K)
        app._flush_sync()
    jax.block_until_ready(app.state.length)
    e2e = (time.perf_counter() - t0) / T

    # ---- pack only: _dispatch_wave minus the device calls ----
    # re-measure by timing the numpy assembly on a staged wave
    stage_full_wave(2 + (T + 1) * K)
    parts = app._take_wave_locked()  # sync mode: no worker, no lock
    all_chunks, slots, lens = [], [], []
    for slot, chunks, count in parts:
        if count:
            all_chunks.extend(chunks)
            slots.append(slot)
            lens.append(count)
    t0 = time.perf_counter()
    for _ in range(T):
        flat = (all_chunks[0] if len(all_chunks) == 1
                else np.concatenate(all_chunks))
        lens_a = np.array(lens)
        starts = np.cumsum(lens_a) - lens_a
        slots_a = np.array(slots, np.int64)
        doc_idx = np.repeat(slots_a, lens_a)
        pos_idx = (np.arange(len(flat), dtype=np.int64)
                   - np.repeat(starts, lens_a))
        wave16 = np.zeros((D, K, OP_FIELDS), np.int16)
        wave16[doc_idx, pos_idx] = flat.astype(np.int16)
    pack = (time.perf_counter() - t0) / T

    # ---- h2d: ship that wave, blocked ----
    t0 = time.perf_counter()
    for _ in range(T):
        jax.block_until_ready(jax.device_put(wave16))
    h2d = (time.perf_counter() - t0) / T
    wave_bytes = wave16.nbytes + D * 2 * 4  # + bases

    # ---- step: wave already on device ----
    wave_dev = jax.block_until_ready(jax.device_put(wave16))
    bases = np.zeros((D, 2), np.int32)
    bases[:, 0] = 2
    bases_dev = jax.block_until_ready(jax.device_put(bases))
    packed_fn, _ = app._dense_step
    state = app.state
    t0 = time.perf_counter()
    for _ in range(T):
        state, _aux = packed_fn(state, wave_dev, bases_dev)
    jax.block_until_ready(state.length)
    step = (time.perf_counter() - t0) / T
    app.state = state

    bw = wave_bytes / h2d
    bpo = wave_bytes / n_ops
    print(f"wave: {D} docs x {K} ops = {n_ops} ops, {wave_bytes} B "
          f"({bpo:.1f} B/op)")
    print(f"pack : {pack*1e3:8.2f} ms  ({n_ops/pack:10.0f} ops/s if alone)")
    print(f"h2d  : {h2d*1e3:8.2f} ms  ({n_ops/h2d:10.0f} ops/s if alone) "
          f"-> link {bw/1e6:.1f} MB/s")
    print(f"step : {step*1e3:8.2f} ms  ({n_ops/step:10.0f} ops/s if alone)")
    print(f"e2e  : {e2e*1e3:8.2f} ms  ({n_ops/e2e:10.0f} ops/s)")
    print(f"ceiling at this link = bw/bytes_per_op = "
          f"{bw/bpo:,.0f} ops/s")

    # ---- stage/execute split: the applier's own per-lane accounting ----
    # (the production path's first-class attribution — see docstring)
    def split_row(lane: str, a) -> None:
        waves = a.waves_staged
        if not waves:
            return
        stage_ms = a.stage_seconds / waves * 1e3
        exec_ms = a.exec_seconds / waves * 1e3
        print(f"  {lane:5s}: stage {stage_ms:7.2f} ms/wave "
              f"({a.stage_overlap_ratio()*100:5.1f}% hidden behind "
              f"execute), exec-call {exec_ms:7.2f} ms/wave, "
              f"kernel={a.kernel_lane}")

    print("stage/execute split:")
    split_row("dense", app)
    if len(jax.devices()) > 1:
        from fluidframework_tpu.parallel.mesh import make_mesh

        n_sh = len(jax.devices())
        mesh_app = TpuDocumentApplier(
            max_docs=D, ops_per_dispatch=K, async_dispatch=False,
            mesh=make_mesh(n_sh, seg_shards=1))
        for d in range(D):
            mesh_app.slot_of("t", f"doc{d}")
        warmed = False
        for t in range(T):
            for d in range(D):
                rows = np.zeros((K, OP_FIELDS), np.int32)
                seq0 = 2 + t * K
                for i in range(K):
                    rows[i] = make_op(OP_INSERT, pos=0, seq=seq0 + i,
                                      ref_seq=seq0 + i - 1, client=0,
                                      text_len=1, text_start=seq0 + i,
                                      msn=seq0 + i - 1)
                mesh_app._push_chunk(d, rows)
            if not warmed:
                # first wave compiles; keep it out of the split rows
                mesh_app._flush_sync()
                jax.block_until_ready(mesh_app.state.length)
                _reset_split(mesh_app)
                warmed = True
        mesh_app._flush_sync()
        jax.block_until_ready(mesh_app.state.length)
        split_row("mesh", mesh_app)

    # ---- recompiles: which kernels traced, how many times ----
    # a kernel-number swing between runs (the r4→r5 note above) is only
    # attributable if the recompile count is in the artifact: a second
    # trace of the same kernel means the run paid compile time mid-trial
    from fluidframework_tpu.obs import get_registry, parse_prometheus

    series = parse_prometheus(get_registry().scrape())
    recompiles = series.get("fluid_applier_kernel_recompiled", {})
    print("recompiles:")
    for key in sorted(recompiles):
        labels = dict(key)
        print(f"  {labels.get('kernel', '?'):16s} "
              f"shape {labels.get('shape', '?'):12s} "
              f"x{recompiles[key]:g}")
    if not recompiles:
        print("  (none recorded — kernels served from the jit cache)")


if __name__ == "__main__":
    main()
