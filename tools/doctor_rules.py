"""The doctor's anomaly rules, factored out of the bundle walk.

One rule = one function over a bundle-shaped artifact (a Prometheus
scrape text, a journal entry list, the placement dict, a core's boot
status) returning a list of anomaly strings. TWO consumers share them
verbatim:

- ``tools/doctor.py`` — the offline bundle triage (unchanged output:
  the doctor now calls these functions in the same order it used to
  run the inline rules, so existing bundle fixtures stay byte-stable);
- ``fluidframework_tpu/obs/health.py`` — the in-process HealthEngine,
  which builds the SAME artifact shapes from the LIVE process (the
  registry's scrape, the journal tail, the epoch table, the prober's
  door verdicts) and evaluates continuously.

Sharing the literal rule code — not a prose spec of it — is the point:
the streaming verdict and the post-incident bundle verdict can never
drift, and the offline/live equivalence test in
``tests/test_health_plane.py`` asserts exactly that.

Pure stdlib on purpose: the package side imports THIS module, never
the other way around, so the rules stay importable from a bare bundle
checkout with no service code on the path.
"""

from __future__ import annotations

import re

#: consecutive rebalance.suppressed entries (no plan between) that
#: count as a storm — the loop wants to move but can't
STORM_THRESHOLD = 10

#: a migration.fence with no commit/fail/adopt for its partition, and
#: the journal still moved on for at least this long after it: the
#: migration wedged between fencing and lease transfer (the partition
#: is sealed and bouncing submits with nobody coming to adopt it)
FENCE_STALL_S = 10.0


def scrape_counter(scrape_text: str, name: str) -> float:
    """Sum every sample of a (possibly labeled) counter in a scrape."""
    total = 0.0
    pat = re.compile(r"^" + re.escape(name) + r'(?:\{[^}]*\})?\s+'
                     r"([0-9.eE+-]+)")
    for line in scrape_text.splitlines():
        m = pat.match(line)
        if m is not None:
            total += float(m.group(1))
    return total


# ---------------------------------------------------------- per-core


def lint_anomalies(lint) -> list:
    """A dirty fluidlint report in the capturing build."""
    out = []
    if lint is not None and not lint.get("clean", True):
        for v in lint.get("violations", []):
            out.append(
                f"lint [{v.get('pass')}]: {v.get('message')} "
                f"({v.get('path')}:{v.get('line')})")
    return out


def capture_error_anomalies(owner: str, row: dict) -> list:
    """A core that could not be reached at bundle/probe time.

    Rows marked ``routed: False`` (members holding no partition when
    the bundle was captured) are skipped: membership never expires, so
    a kill -9'd core's stale row would otherwise read as an outage
    forever after its partitions were re-claimed."""
    if row.get("routed") is False:
        return []
    if row.get("error"):
        return [f"core {owner}: capture error ({row['error']}) — "
                "unreachable or mid-restart at bundle time"]
    return []


def scrape_anomalies(owner: str, scrape_text: str) -> list:
    """Version-skew hop drops and door-fence rejections, from one
    core's Prometheus scrape."""
    out = []
    unknown = scrape_counter(scrape_text, "fluid_obs_trace_unknown_hops")
    if unknown:
        out.append(
            f"core {owner}: {int(unknown)} hop stamp(s) outside "
            "this build's taxonomy (version-skewed client?) — "
            "the breakdown is missing legs")
    rejected = scrape_counter(
        scrape_text, "fluid_placement_table_stale_rejections")
    if rejected:
        out.append(
            f"core {owner}: {int(rejected)} remote-table write(s) "
            "rejected by the door's fence — a zombie ex-owner kept "
            "writing the epoch table after takeover (the fence held, "
            "but that core's lease view is stale: check its host "
            "group's clock and network)")
    return out


def journal_disarmed_anomalies(owner: str, row: dict,
                               journal: list) -> list:
    if row.get("journal_armed") is False and not journal:
        return [f"core {owner}: journal disarmed — no audit trail "
                "from this core"]
    return []


def slo_burn_rows(owner: str, slo: dict) -> list:
    """Specs not in ``ok`` → burn rows (the report's slo_burn table;
    the doctor's exit code and the engine's slo component key on
    these)."""
    return [{"core": owner, **r} for r in (slo or {}).get("slos", [])
            if r.get("state") != "ok"]


def boot_anomalies(owner: str, boot) -> list:
    """Cold-start regressions: paid whole-log replays, or a stalled
    admission storm (parked boots idling against a refilled bucket)."""
    out = []
    if boot is None:
        return out
    ex = boot.get("executor") or {}
    pending = sum(p.get("docs_pending", 0)
                  for p in boot.get("parts", []))
    replays = (boot.get("counters") or {}).get(
        "boot.part.full_replay", 0)
    if replays:
        out.append(
            f"core {owner}: {replays} doc boot(s) paid a "
            "WHOLE-LOG replay — a summary or checkpoint is "
            "missing, so the cold-start bound is gone for "
            "those docs")
    if (pending and ex.get("parked", 0)
            and ex.get("tokens", 0) >= 1):
        out.append(
            f"core {owner}: {pending} doc(s) still pending "
            f"with {ex['parked']} boot(s) parked against a "
            "refilled admission bucket — the storm stalled "
            "(clients gave up retrying, or first routes never "
            "arrived)")
    return out


def suppression_storm_anomalies(owner: str, journal: list) -> list:
    """Longest run of rebalance.suppressed without an actionable plan
    breaking it."""
    run = best = 0
    for e in journal:
        kind = e.get("kind", "")
        if kind == "rebalance.suppressed":
            run += 1
            best = max(best, run)
        elif kind == "rebalance.plan":
            run = 0
    if best >= STORM_THRESHOLD:
        return [f"core {owner}: rebalance suppression storm ({best} "
                "consecutive suppressed ticks) — the loop wants to "
                "move but hysteresis/budget keeps refusing; check "
                "dwell/budget settings vs the heat imbalance"]
    return []


# ------------------------------------------------- merged journal


def epoch_regression_anomalies(merged: list) -> list:
    """Replayed in WALL-CLOCK order, each partition's epoch.bump
    sequence must only move forward — a later bump with a lower epoch
    means two cores wrote the table through different planes (a host
    group split-brained past the fence)."""
    out = []
    last_bump: dict = {}
    for e in sorted((e for e in merged if e.get("kind") == "epoch.bump"),
                    key=lambda e: (e.get("ts", 0.0), e.get("epoch", 0))):
        part = (e.get("labels") or {}).get("part")
        epoch = e.get("epoch")
        if part is None or epoch is None:
            continue
        prev = last_bump.get(part)
        if prev is not None and epoch < prev[0]:
            out.append(
                f"part {part}: epoch regressed e{epoch} on "
                f"{e.get('core')} after e{prev[0]} on {prev[1]} — two "
                "cores wrote the epoch table through different planes "
                "(a remote group bypassing the table door?)")
        if prev is None or epoch > prev[0]:
            last_bump[part] = (epoch, e.get("core"))
    return out


def fence_without_commit_anomalies(merged: list) -> list:
    """A fence that never became a commit (or a fail): the partition
    is sealed at a final seq, submits bounce, and no adopt/commit/fail
    ever followed while the journal kept moving for FENCE_STALL_S past
    the fence — the migration wedged mid-flight."""
    out = []
    fences: dict = {}
    for e in merged:
        kind = e.get("kind")
        part = (e.get("labels") or {}).get("part")
        if part is None:
            continue
        if kind == "migration.fence":
            fences[part] = e
        elif kind in ("migration.adopt", "migration.commit",
                      "migration.fail"):
            fences.pop(part, None)
    if not fences:
        return out
    horizon = max((e.get("ts", 0.0) for e in merged), default=0.0)
    for part in sorted(fences, key=str):
        e = fences[part]
        stalled_s = horizon - e.get("ts", 0.0)
        if stalled_s >= FENCE_STALL_S:
            out.append(
                f"part {part}: fenced on {e.get('core')} "
                f"[{e.get('id')}] with no commit/fail "
                f"{stalled_s:.0f}s later — the migration wedged "
                "after sealing (submits are bouncing with nobody "
                "coming to adopt; check the target core and the "
                "lease plane)")
    return out


def migration_fail_anomaly(e: dict) -> str:
    """One migration.fail entry → its anomaly line."""
    return (f"migration of part "
            f"{(e.get('labels') or {}).get('part')} FAILED on "
            f"{e.get('core')}: "
            f"{(e.get('labels') or {}).get('error')}")


# ------------------------------------------------------- placement


def placement_anomalies(placement, core_rows: dict) -> list:
    """Orphaned partitions, draining-but-owning cores, and the
    unreachable-host-group rule. ``core_rows`` maps owner → the
    capture row (the doctor's manifest rows; the engine's probe-backed
    peer reachability rows) — only its ``error`` field is read."""
    out = []
    if placement is None:
        return out
    member_states = {owner: row.get("state")
                     for owner, row in
                     (placement.get("cores") or {}).items()}
    owned_by: dict = {}
    for k, part in (placement.get("parts") or {}).items():
        owned_by.setdefault(part.get("owner"), []).append(k)
        if member_states and part.get("owner") not in member_states:
            out.append(
                f"part {k}: owner {part.get('owner')} is not in "
                "the core membership — orphaned routing entry "
                "(stale lease / dead core?)")
    for owner, state in member_states.items():
        if state in ("draining", "drained") and owned_by.get(owner):
            out.append(
                f"core {owner} is {state} but still owns parts "
                f"{sorted(owned_by[owner])} — evacuation stuck?")
    # unreachable host group: every core a host id advertises in the
    # membership failed capture — that is a machine (or its network)
    # down, not a core restarting; triage the host first
    by_host: dict = {}
    for owner, row in (placement.get("cores") or {}).items():
        host = row.get("host")
        if host is not None:
            by_host.setdefault(host, []).append(owner)
    for host, members in sorted(by_host.items()):
        # unrouted rows (no partitions at capture) carry no liveness
        # signal — same exclusion as capture_error_anomalies
        captured = [o for o in members if o in core_rows
                    and core_rows[o].get("routed") is not False]
        if captured and all(core_rows[o].get("error")
                            for o in captured):
            out.append(
                f"host group {host}: all {len(captured)} core(s) "
                f"({', '.join(sorted(captured))}) unreachable at "
                "capture — the whole host group is down or "
                "partitioned from the entry core")
    return out
